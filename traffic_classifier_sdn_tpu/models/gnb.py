"""Gaussian naive Bayes predict as closed-form batched log-probability.

Replaces sklearn's ``GaussianNB.predict`` (reference checkpoint
``models/GaussianNB``, fitted in notebook ``5_GaussianNB.ipynb``; loaded at
traffic_classifier.py:238-239). Joint log likelihood per class c:

    log P(c) − ½ Σ_f [ log(2π σ²_cf) + (x_f − θ_cf)² / σ²_cf ]

(SURVEY.md §2.2). The per-class constant ½Σ log(2πσ²) and the reciprocal
variances are folded at import time, so predict is two broadcast multiplies
and a reduction — fully fused by XLA.
"""

from __future__ import annotations

import math
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class Params(NamedTuple):
    theta: jax.Array  # (C, F) per-class feature means
    inv_var: jax.Array  # (C, F) 1/σ²
    log_const: jax.Array  # (C,)  log prior − ½ Σ log(2π σ²)


def from_numpy(d: dict, dtype=jnp.float32) -> Params:
    theta = np.asarray(d["theta"], dtype=np.float64)
    var = np.asarray(d["var"], dtype=np.float64)
    prior = np.asarray(d["class_prior"], dtype=np.float64)
    # Absent classes (zero prior — reachable when a fit sees no rows of a
    # class, e.g. the distributed fit's padded class count) are made inert
    # explicitly: zero mean/precision and a -inf score, so they can never
    # win the argmax and their NaN moments can't poison present classes.
    present = prior > 0.0
    safe_prior = np.where(present, prior, 1.0)
    safe_var = np.where(present[:, None], var, 1.0)
    log_const = np.where(
        present,
        np.log(safe_prior)
        - 0.5 * np.sum(np.log(2.0 * math.pi * safe_var), axis=1),
        -np.inf,
    )
    return Params(
        theta=jnp.asarray(np.where(present[:, None], theta, 0.0), dtype=dtype),
        inv_var=jnp.asarray(
            np.where(present[:, None], 1.0 / safe_var, 0.0), dtype=dtype
        ),
        log_const=jnp.asarray(log_const, dtype=dtype),
    )


def scores(params: Params, X: jax.Array) -> jax.Array:
    """Joint log likelihood, (N, C)."""
    diff = X[:, None, :] - params.theta[None, :, :]  # (N, C, F)
    quad = jnp.sum(diff * diff * params.inv_var[None, :, :], axis=-1)
    return params.log_const[None, :] - 0.5 * quad


def predict(params: Params, X: jax.Array) -> jax.Array:
    return jnp.argmax(scores(params, X), axis=-1).astype(jnp.int32)


def predict_scores(params: Params, X: jax.Array) -> tuple[jax.Array, jax.Array]:
    """(labels, log-likelihood scores) from ONE score computation —
    the open-set serving surface (models/base.py protocol);
    ``argmax(scores) == predict`` by construction."""
    s = scores(params, X)
    return jnp.argmax(s, axis=-1).astype(jnp.int32), s
