"""Random-forest predict via tensorized lockstep tree traversal.

Replaces sklearn's ``RandomForestClassifier.predict`` (reference checkpoint
``models/RandomForestClassifier``: 100 gini trees, node counts 25-101, depth
5-14, fitted in ``3_RandomForest.ipynb``; loaded at
traffic_classifier.py:241-243 — the reference's most accurate model at
99.87%, SURVEY.md §6). Prediction is argmax of the mean per-tree class
distribution, computed by ops/tree_eval.py's gather-based traversal.

Trees shard across chips for big ensembles — parallel/forest_sharded.py
psums the per-chip distribution sums.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from ..ops import tree_eval


class Params(struct.PyTreeNode):
    left: jax.Array  # (T, M) int32
    right: jax.Array  # (T, M) int32
    feature: jax.Array  # (T, M) int32
    threshold: jax.Array  # (T, M)
    values: jax.Array  # (T, M, C) leaf class counts
    max_depth: int = struct.field(pytree_node=False)  # static under jit


def from_numpy(d: dict, dtype=jnp.float32) -> Params:
    import numpy as np

    from ..io.sklearn_import import f32_safe_thresholds

    thr = np.asarray(d["threshold"], np.float64)
    if dtype == jnp.float32:
        # sklearn compares f32 features against f64 midpoint thresholds;
        # round-down keeps every decision identical in pure f32.
        thr = f32_safe_thresholds(thr)
    return Params(
        left=jnp.asarray(d["left"]),
        right=jnp.asarray(d["right"]),
        feature=jnp.asarray(d["feature"]),
        threshold=jnp.asarray(thr, dtype=dtype),
        values=jnp.asarray(d["values"], dtype=dtype),
        max_depth=int(d["max_depth"]),
    )


def scores(params: Params, X: jax.Array) -> jax.Array:
    """Ensemble-averaged class probabilities, (N, C)."""
    return tree_eval.forest_proba(
        params.left,
        params.right,
        params.feature,
        params.threshold,
        params.values,
        X,
        params.max_depth,
    )


def predict(params: Params, X: jax.Array) -> jax.Array:
    return jnp.argmax(scores(params, X), axis=-1).astype(jnp.int32)


def predict_scores(params: Params, X: jax.Array) -> tuple[jax.Array, jax.Array]:
    """(labels, ensemble vote-mass scores) from ONE traversal — the
    open-set serving surface (models/base.py protocol);
    ``argmax(scores) == predict`` by construction. The native C++
    walk exposes the same surface as ``NativeForest.predict_proba``."""
    s = scores(params, X)
    return jnp.argmax(s, axis=-1).astype(jnp.int32), s
