"""k-nearest-neighbors predict as brute-force batched L2 + top-k.

Replaces sklearn's ``KNeighborsClassifier.predict`` (reference checkpoint
``models/KNeighbors``: k=5, Euclidean, KDTree; loaded at
traffic_classifier.py:234-236). TPUs have no KDTree; the idiomatic
replacement is a dense (N, S) distance computation — one MXU matmul —
followed by ``lax.top_k`` and a one-hot vote reduction (SURVEY.md §2.3).
Majority vote ties resolve to the lowest class index, matching numpy/scipy
mode semantics used by sklearn.

Numerical design (measured — see models/svc.py notes): features reach ~8e8,
so the dot-product expansion ``x·s − ½‖s‖²`` can cancel catastrophically in
float32 when two neighbors of different classes are nearly equidistant. The
fast path keeps the matmul form (with precision='highest'); passing ``X_lo``
(from ``svc.split_hilo``) switches to the exact two-float difference form
for parity-critical use.

The training matrix shards across chips for large corpora — see
parallel/knn_sharded.py for the all_gather-merged global top-k.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct
from jax import lax


class Params(struct.PyTreeNode):
    fit_X: jax.Array  # (S, F) training matrix, hi part in f32 mode
    fit_X_lo: jax.Array  # (S, F) two-float residual (zeros in f64 mode)
    fit_y: jax.Array  # (S,) int32 class indices
    half_sq_norms: jax.Array  # (S,) ½‖x_s‖²
    n_neighbors: int = struct.field(pytree_node=False)  # static under jit
    n_classes: int = struct.field(pytree_node=False)  # static under jit


def from_numpy(d: dict, dtype=jnp.float32) -> Params:
    from .svc import split_hilo  # shared two-float helper

    fit_hi, fit_lo = split_hilo(d["fit_X"], dtype=dtype)
    return Params(
        fit_X=fit_hi,
        fit_X_lo=fit_lo,
        fit_y=jnp.asarray(d["y"], dtype=jnp.int32),
        half_sq_norms=0.5 * jnp.sum(fit_hi * fit_hi, axis=1),
        n_neighbors=int(d["n_neighbors"]),
        n_classes=int(len(d["classes"])),
    )


def _dot_expansion_sim(X: jax.Array, fit_X: jax.Array,
                       half_sq_norms: jax.Array) -> jax.Array:
    """(N, S) fast-path similarity: argmin_s ‖x−s‖² == argmax_s
    (x·s − ½‖s‖²); ‖x‖² is row-constant. precision='highest': default
    matmul precision on this XLA build is bf16-like (see models/svc.py
    numerical notes). The ONE place the expression lives — the full
    matrix, the big-corpus scan slices, and the sharded local top-k all
    call it, so a precision change applies everywhere."""
    return (
        jnp.matmul(X, fit_X.T, precision=lax.Precision.HIGHEST)
        - half_sq_norms[None, :]
    )


def _neighbor_sim(params: Params, X: jax.Array, X_lo=None) -> jax.Array:
    """(N, S) similarity whose argmax order is ascending-distance order."""
    if X_lo is None:
        return _dot_expansion_sim(X, params.fit_X, params.half_sq_norms)
    # Exact two-float difference form.
    diff = (X[:, None, :] - params.fit_X[None, :, :]) + (
        X_lo[:, None, :] - params.fit_X_lo[None, :, :]
    )
    return -jnp.sum(diff * diff, axis=-1)


def neighbor_votes(params: Params, X: jax.Array, X_lo=None,
                   top_k_impl: str = "sort") -> jax.Array:
    """(N, C) neighbor counts per class from the k nearest training points.

    ``top_k_impl``: "sort" uses ``lax.top_k`` (a partial sort network over
    all S corpus columns); "argmax" runs k iterative max+mask passes —
    O(k·S) elementwise VPU work instead of the sort network, exact
    including ties (each pass takes the FIRST maximum, i.e. the lowest
    corpus index — the same tie order sklearn's KDTree/brute force and
    ``lax.top_k`` produce). The bench races both on real hardware."""
    sim = _neighbor_sim(params, X, X_lo)
    if top_k_impl == "argmax":
        nbr_idx = _topk_argmax_idx(sim, params.n_neighbors)
    elif top_k_impl.startswith("hier"):
        # "hier" (group=128) or "hier<group>" e.g. "hier512" — the group
        # width is a hardware tuning knob the bench sweeps on chip;
        # every width is exact (same merge argument)
        group = int(top_k_impl[4:] or 128)
        nbr_idx = _topk_hier_idx(sim, params.n_neighbors, group=group)
    elif top_k_impl.startswith("screened"):
        # "screened" (group=32 — the measured CPU winner at batch 16k)
        # or "screened<group>" — bound-screened group selection; every
        # width is exact (proof on the fn)
        group = int(top_k_impl[8:] or 32)
        nbr_idx = _topk_screened_idx(sim, params.n_neighbors, group=group)
    elif top_k_impl == "sort":
        _, nbr_idx = lax.top_k(sim, params.n_neighbors)  # (N, k)
    else:
        raise ValueError(f"unknown top_k_impl {top_k_impl!r}")
    return _count_votes(params, nbr_idx)


def count_votes(fit_y: jax.Array, n_classes: int,
                nbr_idx: jax.Array) -> jax.Array:
    """(N, C) class counts for the given (N, k) neighbor indices — the ONE
    home of the vote semantics (ops/pallas_knn.py shares it)."""
    nbr_y = fit_y[nbr_idx]  # (N, k)
    return jnp.sum(
        jax.nn.one_hot(nbr_y, n_classes, dtype=jnp.int32), axis=1
    )


def _count_votes(params: Params, nbr_idx: jax.Array) -> jax.Array:
    return count_votes(params.fit_y, params.n_classes, nbr_idx)


def _topk_argmax_idx(sim: jax.Array, k: int) -> jax.Array:
    """(N, k) indices of the k largest columns, descending, ties to the
    lowest index — k argmax+mask passes.

    Precondition: every entry of ``sim`` is FINITE (true for
    ``-sum(diff**2)`` over finite features, which is the only producer).
    Under that precondition the ordering is bitwise-identical to
    ``lax.top_k``. If a row held fewer than k finite entries the -inf
    mask would make later passes return duplicate index 0 where
    ``lax.top_k`` returns distinct indices — unreachable here; parity
    is asserted by tests/test_model_parity.py
    (test_knn_argmax_topk_matches_sort_topk)."""
    idxs = []
    for _ in range(k):
        best = jnp.argmax(sim, axis=1)  # first (lowest-index) maximum
        idxs.append(best)
        sim = jnp.where(
            jax.nn.one_hot(best, sim.shape[1], dtype=bool), -jnp.inf, sim
        )
    return jnp.stack(idxs, axis=1)


def _topk_hier_idx(sim: jax.Array, k: int, group: int = 128) -> jax.Array:
    """(N, k) indices of the k largest columns — hierarchical selection:
    per-group ``lax.top_k`` over ``group``-column tiles, then a final
    ``lax.top_k`` over the G·k surviving candidates.

    Why: one ``lax.top_k`` over all S columns is a sort network whose
    cost scales with S (4448 for the reference corpus) per output row —
    the measured KNN floor in round 3. The hierarchy reads the (N, S)
    similarity once, runs the sort network over 128-wide tiles, and
    merges G·k ≈ 175 survivors — an exact algebraic identity (the true
    top-k of a union is the top-k of the per-part top-ks).

    Tie order is bitwise-identical to ``lax.top_k`` over the full row:
    groups are CONTIGUOUS index ranges, per-group top_k orders equal
    values by ascending index, and the merge sees candidates in
    (group, rank) position order — so equal values resolve to the lowest
    global index at every level. Padding columns get -inf and lose every
    comparison (S >= k real columns always exist)."""
    n, S = sim.shape
    if k > group:
        raise ValueError(f"k={k} must be <= group={group}")
    G = -(-S // group)
    pad = G * group - S
    if pad:
        sim = jnp.pad(sim, ((0, 0), (0, pad)), constant_values=-jnp.inf)
    vals_g, idx_g = lax.top_k(sim.reshape(n, G, group), k)  # (N, G, k)
    base = (jnp.arange(G, dtype=jnp.int32) * group)[None, :, None]
    gidx = (idx_g.astype(jnp.int32) + base).reshape(n, G * k)
    _, sel = lax.top_k(vals_g.reshape(n, G * k), k)  # (N, k) positions
    return jnp.take_along_axis(gidx, sel, axis=1)


def _topk_screened_idx(sim: jax.Array, k: int, group: int = 32) -> jax.Array:
    """(N, k) indices of the k largest columns — bound-screened group
    selection: a cheap per-group MAX pass (the group's upper bound — in
    distance terms, a triangle-style lower bound on every member's
    distance) selects the k survivor groups per row, and the exact
    ranking runs only over their k·group gathered columns. This is the
    XLA mirror of the native evaluator's whole-chunk screening: the
    bound pass costs one max-reduce over (N, S) plus a top-k over the
    G = ⌈S/group⌉ group maxima instead of ``lax.top_k``'s sort network
    over all S columns.

    Exactness incl. tie order (bitwise-identical to ``lax.top_k`` over
    the full row): (1) every true top-k element lives in one of the
    top-k groups by (group max desc, group index asc) — if element e
    (value v, group g) had k groups ranked above g, each contributes a
    distinct element that outranks e: strictly larger max, or an equal
    max in a lower-indexed group, whose element (groups are CONTIGUOUS
    index ranges) has a globally lower index; k such elements
    contradict e being in the top-k. ``lax.top_k`` over the maxima
    produces exactly that (max desc, index asc) group ranking.
    (2) The selected group ids are re-sorted ASCENDING before the
    gather, so gathered position order equals global index order and
    the final ``lax.top_k``'s lowest-position tie rule resolves to the
    lowest global index — the full-row rule. Padding columns get -inf
    and lose every comparison (each group holds ≥ 1 real column and
    k selected groups hold ≥ k real columns; sim is finite — the
    ``_topk_argmax_idx`` precondition). Rows with fewer than k groups
    degrade to the plain sort network (still exact)."""
    n, S = sim.shape
    G = -(-S // group)
    if G < k:  # too few groups to screen — the sort network is exact
        _, nbr_idx = lax.top_k(sim, k)
        return nbr_idx
    pad = G * group - S
    if pad:
        sim = jnp.pad(sim, ((0, 0), (0, pad)), constant_values=-jnp.inf)
    gmax = jnp.max(sim.reshape(n, G, group), axis=2)  # (N, G) bounds
    _, gsel = lax.top_k(gmax, k)  # (N, k) survivor groups
    gsel = jnp.sort(gsel, axis=1)  # ascending → global-index tie order
    cand_idx = (
        gsel[:, :, None] * group
        + jnp.arange(group, dtype=gsel.dtype)[None, None, :]
    ).reshape(n, k * group)
    cand_val = jnp.take_along_axis(sim, cand_idx, axis=1)
    _, sel = lax.top_k(cand_val, k)
    return jnp.take_along_axis(cand_idx, sel, axis=1).astype(jnp.int32)


def scores(params: Params, X: jax.Array, X_lo=None) -> jax.Array:
    return neighbor_votes(params, X, X_lo)


def predict(params: Params, X: jax.Array, X_lo=None,
            top_k_impl: str = "sort") -> jax.Array:
    return jnp.argmax(
        neighbor_votes(params, X, X_lo, top_k_impl=top_k_impl), axis=-1
    ).astype(jnp.int32)


def predict_scores(
    params: Params, X: jax.Array, X_lo=None, top_k_impl: str = "sort",
) -> tuple[jax.Array, jax.Array]:
    """(labels, neighbor-vote scores) from ONE vote computation — the
    open-set serving surface (models/base.py protocol);
    ``argmax(scores) == predict`` by construction (same votes, same
    first-max tie order). The native C++ evaluator exposes the same
    surface as ``NativeKnn.votes``."""
    votes = neighbor_votes(params, X, X_lo, top_k_impl=top_k_impl)
    return jnp.argmax(votes, axis=-1).astype(jnp.int32), votes


def predict_chunked(
    params: Params, X: jax.Array, X_lo=None, row_chunk: int = 65536,
    top_k_impl: str = "sort",
) -> jax.Array:
    """``predict`` for batches whose (N, S) similarity matrix would blow
    HBM (2²⁰ rows × the reference's 4448-row corpus ≈ 18.6 GB f32):
    rows stream through the shared ``ops.chunking.chunked_predict``
    dispatch, exactly like the SVC and forest GEMM paths."""
    from ..ops.chunking import chunked_predict

    return chunked_predict(
        lambda xc, xlo=None: predict(params, xc, xlo, top_k_impl=top_k_impl),
        row_chunk, X, X_lo,
    )


def neighbor_votes_big_corpus(
    params: Params, X: jax.Array, corpus_chunk: int = 65536
) -> jax.Array:
    """(N, C) neighbor votes for corpora too large to materialize the
    (N, S) similarity matrix on ONE device — the single-chip complement
    of the state-sharded path (parallel/knn_sharded.py shards S across
    chips; this streams S through one chip's HBM).

    A ``lax.scan`` walks the corpus in ``corpus_chunk``-column slices:
    each step computes the slice's similarities (one MXU matmul), takes
    a local top-k, and merges it into the running top-k carry. Exactness
    incl. tie order: slices are CONTIGUOUS ascending index ranges and
    the merge concatenates (carry, slice) in that order, so equal values
    sit in ascending-global-index position order at every merge — the
    same argument as ``_topk_hier_idx``, giving bitwise-identical
    results to one ``lax.top_k`` over the full row (asserted in
    tests/test_model_parity.py). Peak memory is O(N·corpus_chunk)
    instead of O(N·S).

    Uses the fast dot-expansion similarity (the ``_neighbor_sim``
    expression and its f32 caveat, inlined per slice); the corpus pads
    to a slice multiple with +inf half-norms, which lose every
    comparison."""
    S = params.fit_X.shape[0]
    k = params.n_neighbors
    n = X.shape[0]
    if S < k:
        raise ValueError(f"corpus has {S} rows < n_neighbors={k}")
    if corpus_chunk < k:
        raise ValueError(
            f"corpus_chunk={corpus_chunk} must be >= n_neighbors={k}"
        )
    n_slices = -(-S // corpus_chunk)
    pad = n_slices * corpus_chunk - S
    fit_X = params.fit_X
    half = params.half_sq_norms
    if pad:
        fit_X = jnp.concatenate(
            [fit_X, jnp.zeros((pad, fit_X.shape[1]), fit_X.dtype)]
        )
        half = jnp.concatenate(
            [half, jnp.full((pad,), jnp.inf, half.dtype)]
        )
    fit_slices = fit_X.reshape(n_slices, corpus_chunk, -1)
    half_slices = half.reshape(n_slices, corpus_chunk)
    sim_dtype = jnp.result_type(X.dtype, fit_X.dtype)

    def step(carry, sl):
        c_val, c_idx = carry
        fit_s, half_s, base = sl
        sim = _dot_expansion_sim(X, fit_s, half_s)
        v, i = lax.top_k(sim, k)  # local: ties to lowest in-slice index
        gidx = i.astype(jnp.int32) + base
        # (carry, slice) concat order == ascending global index for ties
        mv = jnp.concatenate([c_val, v], axis=1)
        mi = jnp.concatenate([c_idx, gidx], axis=1)
        nv, sel = lax.top_k(mv, k)
        return (nv, jnp.take_along_axis(mi, sel, axis=1)), None

    init = (
        jnp.full((n, k), -jnp.inf, sim_dtype),
        jnp.zeros((n, k), jnp.int32),
    )
    bases = (jnp.arange(n_slices, dtype=jnp.int32) * corpus_chunk)
    (_, nbr_idx), _ = lax.scan(
        step, init, (fit_slices, half_slices, bases)
    )
    return _count_votes(params, nbr_idx)


def predict_big_corpus(
    params: Params, X: jax.Array, corpus_chunk: int = 65536
) -> jax.Array:
    return jnp.argmax(
        neighbor_votes_big_corpus(params, X, corpus_chunk), axis=-1
    ).astype(jnp.int32)
