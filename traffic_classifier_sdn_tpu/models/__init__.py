"""The six classifier families of the reference, as pure JAX functions.

Registry keys mirror the reference's CLI subcommands
(traffic_classifier.py:189: logistic, kmeans, knearest, svm, Randomforest,
gaussiannb) under normalized names; ``load_reference_model`` is the TPU-era
replacement for the pickle-loading if-chain at traffic_classifier.py:229-243
(including fixing the knearest/kneighbors dispatch bug noted in SURVEY.md §2).
"""

from __future__ import annotations

from dataclasses import dataclass
from collections.abc import Callable
from typing import Any

import jax.numpy as jnp

from ..io import sklearn_import
from . import forest, gnb, kmeans, knn, logreg, svc
from .base import ClassList

MODEL_MODULES = {
    "logreg": logreg,
    "gnb": gnb,
    "kmeans": kmeans,
    "knn": knn,
    "svc": svc,
    "forest": forest,
}

# the --knn-topk / TCSDN_KNN_TOPK menu (usage text shared by the CLI
# flag error and resolve_knn_topk's ValueError)
KNN_TOPK_CHOICES = (
    "sort, argmax, hier[<group>], screened[<group>], pallas, native, "
    "ivf[<nprobe>]"
)
_KNN_TOPK_WARNED: set[str] = set()


def resolve_knn_topk(value: str | None = None) -> str:
    """Resolve and validate the serving KNN top-k implementation: an
    explicit value (the ``--knn-topk`` flag) wins, else the
    ``TCSDN_KNN_TOPK`` env fallback, else ``sort``. Unknown names raise
    ``ValueError`` with the menu (cli.py surfaces it as a clean usage
    error, not a traceback); numeric-suffix forms are checked for shape
    here and for corpus-dependent bounds (hier's group ≥ n_neighbors)
    at serving-path build time.

    This is the ONE resolution point, so the serving-semantics warnings
    fire here — once per process per implementation, not once per
    serving-path build (drift promotions rebuild the path on every
    swap): ``native`` ranks by exact f64 distances and can diverge from
    the default f32 device ranking on near-ties (ADVICE r5, no same-run
    parity gate at serving time); ``ivf`` is the APPROXIMATE tier — an
    explicit opt-in served with a measured recall artifact
    (docs/artifacts/knn_ivf_recall_cpu.json), never a silent
    substitute."""
    import os
    import sys

    impl = value if value is not None else os.environ.get(
        "TCSDN_KNN_TOPK", "sort"
    )
    if impl not in ("sort", "argmax", "pallas", "native", "hier",
                    "screened", "ivf"):
        for prefix in ("screened", "hier", "ivf"):
            suffix = impl[len(prefix):]
            # a zero suffix (group/nprobe) is never valid for ANY
            # corpus — reject at resolve time so the CLI's usage-error
            # contract holds (corpus-dependent bounds still land at
            # serving-path build)
            if (impl.startswith(prefix) and suffix.isdecimal()
                    and int(suffix) >= 1):
                break
        else:
            raise ValueError(
                f"unknown KNN top-k implementation {impl!r} "
                f"(--knn-topk / TCSDN_KNN_TOPK; choose from: "
                f"{KNN_TOPK_CHOICES})"
            )
    if impl == "native" and "native" not in _KNN_TOPK_WARNED:
        _KNN_TOPK_WARNED.add("native")
        print(
            "NOTE: TCSDN_KNN_TOPK=native ranks by exact f64 "
            "distances; labels can differ from the default f32 "
            "device ranking on near-ties (no same-run parity gate "
            "at serving time)",
            file=sys.stderr,
        )
    if impl.startswith("ivf") and "ivf" not in _KNN_TOPK_WARNED:
        _KNN_TOPK_WARNED.add("ivf")
        print(
            "NOTE: --knn-topk ivf serves the APPROXIMATE cluster-probed "
            "tier: true neighbors outside the probed lists are missed "
            "(measured recall: docs/artifacts/knn_ivf_recall_cpu.json); "
            "exact tiers: sort/argmax/hier/screened/native",
            file=sys.stderr,
        )
    return impl

# reference CLI subcommand → normalized model name (traffic_classifier.py:189;
# both 'knearest' and 'kneighbors' accepted — the reference advertises the
# former but dispatches on the latter, a defect we fix rather than replicate).
SUBCOMMAND_ALIASES = {
    "logistic": "logreg",
    "kmeans": "kmeans",
    "knearest": "knn",
    "kneighbors": "knn",
    "svm": "svc",
    "Randomforest": "forest",
    "randomforest": "forest",
    "gaussiannb": "gnb",
}


def _build_serving_path(name: str, params) -> tuple[Callable, Any]:
    """(predict_fn, params) for full-table serving at 2²⁰ capacity:
    forest swaps the gather traversal for an MXU kernel (~1000× on TPU),
    KNN/SVC swap in the row-chunked predict (their (N, S) matrices
    exceed HBM at 1M rows); everything else serves with its canonical
    predict.

    Raced-kernel selection (so a ``bench.py`` chip-race winner can be
    promoted to the live serving path without code changes):

    - ``TCSDN_FOREST_KERNEL`` ∈ ``gemm`` (default, size-bucketed v1) |
      ``gemm_v2_dot`` | ``gemm_v2_gather`` (ops/tree_gemm v2 layouts) |
      ``pallas`` | ``pallas_fast`` (the fused kernel; TPU-only —
      Mosaic does not compile on CPU hosts) | ``native`` (the C++
      host-spine walk as a plain host call for accelerator-less hosts;
      marked ``host_native`` — callers must NOT jit or shard_map it).
    - ``TCSDN_SVC_KERNEL`` ∈ ``chunked`` (default, two-float exact
      difference form) | ``dot`` (dot-expansion RBF — one matmul, no
      (N, S, F) difference tensor; ~3.6× on CPU hosts).
    - ``TCSDN_KNN_TOPK`` (the ``--knn-topk`` CLI flag wins over the env
      var; both resolve through ``resolve_knn_topk``) ∈ ``sort``
      (default) | ``argmax`` | ``hier`` or ``hier<group>`` (e.g.
      ``hier512``; group in [n_neighbors, 65536]) | ``screened`` or
      ``screened<group>`` (bound-screened group selection — the cheap
      group-max pass picks the k survivor groups, exact ranking runs
      over their columns only; bitwise lax.top_k tie order, see
      models/knn._topk_screened_idx) | ``pallas`` (ops/pallas_knn fused
      distance+top-k kernel; TPU-only — Mosaic does not compile on CPU
      hosts) | ``native`` (the C++ host-spine cluster-pruned exact
      search for accelerator-less hosts; ``host_native`` — callers must
      NOT jit or shard_map it) | ``ivf`` or ``ivf<nprobe>`` (the
      APPROXIMATE cluster-probed tier, ops/knn_ivf.py — explicit opt-in
      only, measured recall artifact, never promoted by the bench).
      Numerics note: ``native`` ranks by exact float64 squared
      distances while the default XLA path ranks by float32
      dot-expansion similarity, so labels can differ wherever f32
      rounding makes or breaks a near-tie — a documented divergence
      (ADVICE r5), warned once at resolve time; unlike bench promotion
      there is no same-run parity gate at serving (only the
      reference-corpus parity in tests/test_native_knn.py).

    Every EXACT option is argmax-parity-gated against the same oracles
    by tests and by the bench before promotion; exact selection never
    changes semantics, only speed. ``ivf`` is the one option that
    trades semantics for speed, which is why it is opt-in."""
    import functools
    import os

    mod = MODEL_MODULES[name]
    if name == "knn":
        impl = resolve_knn_topk()
        if impl == "pallas":
            from ..ops import pallas_knn

            return pallas_knn.predict_chunked, pallas_knn.compile_knn(params)
        if impl.startswith("ivf"):
            # the APPROXIMATE cluster-probed tier (ops/knn_ivf.py) —
            # this branch is only reachable through the explicit
            # --knn-topk ivf / TCSDN_KNN_TOPK=ivf opt-in (the warning
            # fired at resolve time); the coarse quantizer fits HERE,
            # at params-build time, on the already-device-resident
            # KMeans kernel
            from ..ops import knn_ivf

            suffix = impl[3:]
            nprobe = int(suffix) if suffix else knn_ivf.DEFAULT_NPROBE
            if nprobe < 1:
                raise ValueError(
                    f"TCSDN_KNN_TOPK={impl!r}: nprobe must be >= 1"
                )
            ivf = knn_ivf.build(params, nprobe=nprobe)
            from ..native import knn as native_knn

            if native_knn.available():
                # serve the NATIVE mirror of the same quantizer — on
                # CPU hosts the XLA tier's per-row candidate gathers
                # cost more than the sort network they avoid, while
                # the native tier probes at 4-6x the full scan
                # (knn_ivf_recall_cpu.json); host_native contract as
                # the native branch below
                import numpy as np

                from ..utils.metrics import global_metrics as _gm

                hk = native_knn.NativeKnn({
                    "fit_X": np.asarray(params.fit_X),
                    "y": np.asarray(params.fit_y),
                    "n_neighbors": params.n_neighbors,
                    "classes": np.arange(params.n_classes),
                })
                # the same partition build() just computed — O(S)
                # list inversion, no second assignment pass (NativeKnn
                # construction still pays its exact-tier Lloyd index;
                # a rebuild is rare — boot and drift promotions — and
                # ~tens of ms at reference scale)
                hk.build_ivf(
                    np.asarray(ivf.centers), knn_ivf.assignments_of(ivf)
                )
                nprobe_eff = ivf.nprobe
                last = {"screened": 0, "abandoned": 0}

                def native_ivf_predict(_params, X):
                    out = hk.predict_ivf(
                        np.asarray(X, np.float32), nprobe_eff
                    )
                    scr, ab, _q = hk.screen_stats()
                    _gm.inc("knn_candidates_screened",
                            scr - last["screened"])
                    _gm.inc("knn_candidates_abandoned",
                            ab - last["abandoned"])
                    last["screened"], last["abandoned"] = scr, ab
                    return jnp.asarray(out)

                native_ivf_predict.host_native = True
                return native_ivf_predict, None
            # no C++ on this host: the XLA tier (the device-side
            # implementation — the TPU artifact measures it)
            return knn_ivf.predict_chunked, ivf
        if impl == "native":
            # host-spine C++ cluster-pruned exact search
            # (native/knn_eval.cpp) for accelerator-less hosts;
            # host_native contract as the forest branch below — a plain
            # host call, never jitted/shard_mapped. (The f64-vs-f32
            # divergence NOTE fired once at resolve time.)
            import numpy as np

            from ..native import knn as native_knn
            from ..utils.metrics import global_metrics as _gm

            hk = native_knn.NativeKnn({
                "fit_X": np.asarray(params.fit_X),  # the f32 hi corpus,
                # exactly the fast path's operand
                "y": np.asarray(params.fit_y),
                "n_neighbors": params.n_neighbors,
                "classes": np.arange(params.n_classes),
            })
            # screen accounting: the evaluator's cumulative totals diff
            # into the serving counters each call (one caller per serve
            # — the device-stage worker — so the stateful diff is safe)
            last = {"screened": 0, "abandoned": 0}

            def native_knn_predict(_params, X):
                out = hk.predict(np.asarray(X, np.float32))
                scr, ab, _q = hk.screen_stats()
                _gm.inc("knn_candidates_screened",
                        scr - last["screened"])
                _gm.inc("knn_candidates_abandoned",
                        ab - last["abandoned"])
                last["screened"], last["abandoned"] = scr, ab
                return jnp.asarray(out)

            native_knn_predict.host_native = True
            return native_knn_predict, None
        if impl not in ("sort", "argmax"):
            # hier[<group>] / screened[<group>]: the NAME was validated
            # at resolve time; the corpus-dependent group bounds land
            # here (hier's final merge needs group >= n_neighbors; the
            # screened bound pass only needs a nonzero width)
            prefix = "hier" if impl.startswith("hier") else "screened"
            suffix = impl[len(prefix):]
            group = int(suffix) if suffix else (
                128 if prefix == "hier" else 32
            )
            lo = params.n_neighbors if prefix == "hier" else 1
            if group < lo or group > (1 << 16):
                raise ValueError(
                    f"TCSDN_KNN_TOPK={impl!r}: group must be in "
                    f"[{lo}, 65536]"
                )
        return functools.partial(mod.predict_chunked, top_k_impl=impl), params
    if name == "svc":
        svc_kernel = os.environ.get("TCSDN_SVC_KERNEL", "chunked")
        if svc_kernel == "dot":
            # dot-expansion RBF (no (N, S, F) difference tensor —
            # ~3.6× on CPU hosts, measured; numerics note on
            # svc.rbf_kernel_dot)
            return mod.predict_dot_chunked, params
        if svc_kernel != "chunked":
            raise ValueError(f"TCSDN_SVC_KERNEL={svc_kernel!r} unknown")
        return mod.predict_chunked, params
    if name == "forest":
        import numpy as np

        from ..core.features import NUM_FEATURES
        from ..ops import tree_gemm

        node_arrays = {
            k: np.asarray(getattr(params, k))
            for k in ("left", "right", "feature", "threshold", "values")
        }
        # serving feature width is the framework's fixed 12-column matrix
        # (a forest whose trees never split on the last feature must still
        # compile a full-width selector)
        kernel = os.environ.get("TCSDN_FOREST_KERNEL", "gemm")
        if kernel in ("gemm_v2_dot", "gemm_v2_gather"):
            return tree_gemm.predict_v2, tree_gemm.compile_forest_v2(
                node_arrays, n_features=NUM_FEATURES,
                stage3=kernel.rsplit("_", 1)[1],
            )
        if kernel in ("pallas", "pallas_fast"):
            from ..ops import pallas_forest

            return pallas_forest.predict, pallas_forest.compile_forest(
                node_arrays, n_buckets=8, n_features=NUM_FEATURES,
                fast_stages=kernel == "pallas_fast",
            )
        if kernel == "native":
            # host-spine C++ walk (native/forest_eval.cpp) for
            # accelerator-less serving hosts — it beats sklearn's Cython
            # walk ~2× on one core. Marked ``host_native``: a plain host
            # function, NEVER jitted (callers check the flag). It is
            # deliberately NOT a jax.pure_callback: callback custom-calls
            # — jitted OR eager — dispatch asynchronously through the XLA
            # CPU runtime, and in a pipelined serving loop the callback
            # can queue on the thread pool BEHIND its own input's
            # producer, a deterministic deadlock on a single-core host at
            # the second tick (observed; a single-shot call works, which
            # is why a one-call test cannot catch it). np.asarray(X) here
            # is a real synchronous wait on X's producer; the result
            # re-enters jax so the device render path composes unchanged.
            from ..native import forest as native_forest

            nf = native_forest.NativeForest(node_arrays)

            def native_predict(_params, X):
                return jnp.asarray(
                    nf.predict(np.asarray(X, np.float32))
                )

            native_predict.host_native = True
            return native_predict, None
        if kernel != "gemm":
            raise ValueError(f"TCSDN_FOREST_KERNEL={kernel!r} unknown")
        return tree_gemm.predict, tree_gemm.compile_forest(
            node_arrays, n_features=NUM_FEATURES
        )
    return mod.predict, params


@dataclass(frozen=True)
class LoadedModel:
    name: str
    params: Any
    classes: ClassList
    predict: Callable
    scores: Callable
    # lazily resolved serving pair — see serving_path()
    serve_params: Any = None
    serve_predict: Callable | None = None

    def serving_path(self) -> tuple[Callable, Any]:
        """The serving-optimized ``(predict_fn, params)`` pair, resolved
        as ONE unit (the two are only valid together) and built lazily —
        loaders that never serve (checkpoint round-trips, eval) skip the
        forest GEMM compilation cost. ``params``/``predict`` remain the
        canonical checkpoint-portable pair."""
        if self.serve_predict is None:
            fn, p = _build_serving_path(self.name, self.params)
            object.__setattr__(self, "serve_predict", fn)
            object.__setattr__(self, "serve_params", p)
        return self.serve_predict, self.serve_params


def jit_serving_fn(serve_fn: Callable) -> Callable:
    """The one correct way to jit a serving predict fn: jit device
    kernels, return host-native kernels untouched. The ``host_native``
    contract (see _build_serving_path's native branches) forbids
    jitting: a jitted host callback queues on the XLA CPU pool behind
    its own input's producer — a deterministic deadlock on single-core
    hosts at the second pipelined tick. Shared by cli.py and
    tools/bench_serve.py so neither re-derives the rule."""
    import jax

    if getattr(serve_fn, "host_native", False):
        return serve_fn
    return jax.jit(serve_fn)


@dataclass(frozen=True)
class ServingFallback:
    """A degraded-rung predict: ``predict(X) -> labels`` as a plain host
    call (params baked in — the ladder has no second params slot), plus
    the kind string the flight recorder / /healthz report.

    ``scores(X) -> (N, C)`` is the rung's score surface — the same
    per-class scores the family's ``predict_scores`` exposes on the
    device path (native C++: ``NativeForest.predict_proba`` /
    ``NativeKnn.votes``), so open-set tooling keeps a score view even
    while the serve is degraded. ``argmax(scores) == predict`` holds on
    every rung (pinned in tests/test_model_parity.py)."""

    predict: Callable
    kind: str
    scores: Callable | None = None


def resolve_fallback(name: str, params) -> ServingFallback | None:
    """The degradation ladder's per-family fallback (serving/degrade.py):
    what still classifies when the device kernel is wedged or erroring.

    - forest / knn → the host-native C++ evaluators
      (native/forest_eval.cpp, native/knn_eval.cpp) under the same
      ``host_native`` contract as the ``TCSDN_*=native`` serving
      kernels — plain host calls, never jitted;
    - everything else (gnb, logreg, svc, kmeans) — and forest/knn on
      hosts whose C++ engine won't build — an eager-CPU jax predict
      with params pre-staged on the CPU backend, so a sick accelerator
      is never re-entered. SVC/KNN use their row-chunked forms (the
      full (N, S) intermediate would blow host RAM at capacity 2²⁰).

    The residual dependency is honest and documented
    (docs/ROBUSTNESS.md): the feature matrix itself still comes from
    the device flow table, so a TOTAL device loss (not the observed
    mid-kernel wedge class) also stalls feature reads — that failure
    needs the process-level ladder (checkpoint restore on a new host),
    not this in-process one."""
    import numpy as np

    if name == "forest":
        from ..native import forest as native_forest

        if native_forest.available():
            from ..core.features import NUM_FEATURES

            node_arrays = {
                k: np.asarray(getattr(params, k))
                for k in ("left", "right", "feature", "threshold",
                          "values")
            }
            nf = native_forest.NativeForest(
                dict(node_arrays, n_features=NUM_FEATURES)
            )
            return ServingFallback(
                lambda X: nf.predict(np.asarray(X, np.float32)),
                "native-forest",
                scores=lambda X: nf.predict_proba(
                    np.asarray(X, np.float32)
                ),
            )
    if name == "knn":
        from ..native import knn as native_knn

        if native_knn.available():
            hk = native_knn.NativeKnn({
                "fit_X": np.asarray(params.fit_X),
                "y": np.asarray(params.fit_y),
                "n_neighbors": params.n_neighbors,
                "classes": np.arange(params.n_classes),
            })
            return ServingFallback(
                lambda X: hk.predict(np.asarray(X, np.float32)),
                "native-knn",
                scores=lambda X: hk.votes(np.asarray(X, np.float32)),
            )

    import jax
    import jax.numpy as jnp_mod

    mod = MODEL_MODULES[name]
    cpu_devices = jax.devices("cpu")
    if not cpu_devices:
        return None
    cpu = cpu_devices[0]
    cpu_params = jax.device_put(params, cpu)
    chunked = getattr(mod, "predict_chunked", None)

    def eager_cpu(X):
        # np.asarray first: a device array operand must cross to host
        # HERE (one sync against the feature producer), not be consumed
        # by a CPU-placed computation that would keep a handle into the
        # sick backend
        with jax.default_device(cpu):
            Xc = jnp_mod.asarray(np.asarray(X), jnp_mod.float32)
            fn = chunked if chunked is not None else mod.predict
            return np.asarray(fn(cpu_params, Xc))

    def eager_cpu_scores(X):
        # the rung's score surface; ``scores`` is unchunked — acceptable
        # for the eval/ops consumers this serves (the hot path rejects
        # on feature-space statistics, serving/openset.py)
        with jax.default_device(cpu):
            Xc = jnp_mod.asarray(np.asarray(X), jnp_mod.float32)
            return np.asarray(mod.scores(cpu_params, Xc))

    return ServingFallback(eager_cpu, "eager-cpu", scores=eager_cpu_scores)


def make_loaded_model(name: str, params, classes) -> LoadedModel:
    """Assemble a LoadedModel — shared by the sklearn-pickle importer and
    the native checkpoint loader (io/checkpoint.load_model)."""
    mod = MODEL_MODULES[name]
    return LoadedModel(
        name=name,
        params=params,
        classes=classes,
        predict=mod.predict,
        scores=mod.scores,
    )


def load_reference_model(
    name: str, checkpoint_path: str, dtype=jnp.float32
) -> LoadedModel:
    """Import a reference sklearn pickle and return params + predict fns."""
    name = SUBCOMMAND_ALIASES.get(name, name)
    mod = MODEL_MODULES[name]
    raw = sklearn_import.IMPORTERS[name](checkpoint_path)
    params = mod.from_numpy(raw, dtype=dtype)
    if name == "kmeans":
        classes = ClassList(kmeans.CLUSTER_LABELS_CHECKPOINT)
    else:
        classes = ClassList.from_array(raw["classes"])
    return make_loaded_model(name, params, classes)
