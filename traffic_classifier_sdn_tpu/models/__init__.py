"""The six classifier families of the reference, as pure JAX functions.

Registry keys mirror the reference's CLI subcommands
(traffic_classifier.py:189: logistic, kmeans, knearest, svm, Randomforest,
gaussiannb) under normalized names; ``load_reference_model`` is the TPU-era
replacement for the pickle-loading if-chain at traffic_classifier.py:229-243
(including fixing the knearest/kneighbors dispatch bug noted in SURVEY.md §2).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

import jax.numpy as jnp

from ..io import sklearn_import
from . import forest, gnb, kmeans, knn, logreg, svc
from .base import ClassList

MODEL_MODULES = {
    "logreg": logreg,
    "gnb": gnb,
    "kmeans": kmeans,
    "knn": knn,
    "svc": svc,
    "forest": forest,
}

# reference CLI subcommand → normalized model name (traffic_classifier.py:189;
# both 'knearest' and 'kneighbors' accepted — the reference advertises the
# former but dispatches on the latter, a defect we fix rather than replicate).
SUBCOMMAND_ALIASES = {
    "logistic": "logreg",
    "kmeans": "kmeans",
    "knearest": "knn",
    "kneighbors": "knn",
    "svm": "svc",
    "Randomforest": "forest",
    "randomforest": "forest",
    "gaussiannb": "gnb",
}


@dataclass(frozen=True)
class LoadedModel:
    name: str
    params: Any
    classes: ClassList
    predict: Callable
    scores: Callable


def load_reference_model(
    name: str, checkpoint_path: str, dtype=jnp.float32
) -> LoadedModel:
    """Import a reference sklearn pickle and return params + predict fns."""
    name = SUBCOMMAND_ALIASES.get(name, name)
    mod = MODEL_MODULES[name]
    raw = sklearn_import.IMPORTERS[name](checkpoint_path)
    params = mod.from_numpy(raw, dtype=dtype)
    if name == "kmeans":
        classes = ClassList(kmeans.CLUSTER_LABELS_CHECKPOINT)
    else:
        classes = ClassList.from_array(raw["classes"])
    return LoadedModel(
        name=name,
        params=params,
        classes=classes,
        predict=mod.predict,
        scores=mod.scores,
    )
