"""Common protocol for the six classifier families.

Every model family is a module exposing:

  ``Params``                 a NamedTuple pytree of device arrays
  ``from_numpy(d, dtype)``   build Params from an importer dict (io/sklearn_import)
  ``scores(params, X)``      (N, C)-ish per-class score matrix (model-specific
                             semantics: logits, log-probs, votes, −distances)
  ``predict(params, X)``     (N,) int32 indices into the model's class list
  ``predict_scores(params, X)``  ``(labels, scores)`` from ONE score
                             computation — the open-set serving surface:
                             ``labels == argmax(scores)`` structurally
                             (the argmax shares the family's tie order),
                             so score-based rejection can never disagree
                             with the label it rejects. Parity with
                             ``predict`` is pinned per family in
                             tests/test_model_parity.py.

``predict`` and ``predict_scores`` are pure functions of (params, X) with
static shapes — safe to ``jax.jit``, ``vmap`` and ``shard_map`` as-is. The
native C++ evaluators expose the same score surfaces for the degrade
rungs (``NativeForest.predict_proba``, ``NativeKnn.votes``). Class
*labels* (strings) never enter device code; ``ClassList`` decodes indices
on the host.

This replaces the reference's per-flow ``model.predict(List[List[float]])``
call (reference: traffic_classifier.py:104-106) with batched device-resident
math.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass(frozen=True)
class ClassList:
    """Host-side label decode. The reference remaps int cluster ids through a
    hardcoded 6-entry dict (traffic_classifier.py:109-114); here every model
    carries its own checkpoint-era class list (4-class vs 6-class pickles are
    mutually inconsistent in the reference — SURVEY.md §2.2)."""

    names: tuple

    @classmethod
    def from_array(cls, arr) -> "ClassList":
        return cls(tuple(str(x) for x in np.asarray(arr).tolist()))

    def decode(self, indices) -> list:
        idx = np.asarray(indices).ravel()
        return [self.names[i] for i in idx]

    def __len__(self) -> int:
        return len(self.names)
