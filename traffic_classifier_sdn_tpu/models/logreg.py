"""Multinomial logistic-regression predict as one batched matmul.

Replaces sklearn's ``LogisticRegression.predict`` (reference checkpoint
``models/LogisticRegression``, fitted in notebook ``1_log_Kmeans.ipynb``;
loaded at traffic_classifier.py:230). sklearn's predict is argmax of the
decision function ``X @ coef.T + intercept`` — softmax is monotonic so the
argmax needs no normalization (SURVEY.md §2.2).

The reference calls this once per flow on a (1, 12) matrix inside a Python
loop; here it is a single (N, 12) @ (12, C) matmul on the MXU.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class Params(NamedTuple):
    coef: jax.Array  # (C, F)
    intercept: jax.Array  # (C,)


def from_numpy(d: dict, dtype=jnp.float32) -> Params:
    return Params(
        coef=jnp.asarray(d["coef"], dtype=dtype),
        intercept=jnp.asarray(d["intercept"], dtype=dtype),
    )


def scores(params: Params, X: jax.Array) -> jax.Array:
    """Decision function, (N, C).

    precision='highest' because this XLA build's DEFAULT matmul precision is
    bf16-like even on CPU (see models/svc.py numerical notes)."""
    return (
        jnp.matmul(X, params.coef.T, precision=jax.lax.Precision.HIGHEST)
        + params.intercept
    )


def predict(params: Params, X: jax.Array) -> jax.Array:
    return jnp.argmax(scores(params, X), axis=-1).astype(jnp.int32)


def predict_scores(params: Params, X: jax.Array) -> tuple[jax.Array, jax.Array]:
    """(labels, scores) from ONE score computation — the open-set
    serving surface (models/base.py protocol). ``argmax(scores) ==
    predict`` by construction; parity is pinned per family in
    tests/test_model_parity.py."""
    s = scores(params, X)
    return jnp.argmax(s, axis=-1).astype(jnp.int32), s
