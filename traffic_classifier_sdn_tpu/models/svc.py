"""RBF-kernel SVC predict as one kernel computation + one vote matmul.

Replaces libsvm's ``SVC.predict`` (reference checkpoint ``models/SVC``:
RBF, C=1, gamma=scale→5.5169e-9, 2281 support vectors, 6 classes, 15
one-vs-one pairs, fitted in ``2_SVM.ipynb``; loaded at
traffic_classifier.py:233-234; SURVEY.md §2.2).

libsvm walks support vectors per class-pair in C++; here the ragged
per-pair/per-class coefficient structure is flattened at import time into a
dense (P, S) matrix, so the whole ovo decision is

    K = exp(−γ · ‖x − sv‖²)            (N, S)
    D = K @ pair_coef.T + intercept     (N, P)
    votes: D[p] > 0 → class i(p), else class j(p); argmax of vote counts

with libsvm's tie-break (lowest class index among vote-count maxima).

Numerical design (SURVEY.md §7 hard part b — measured, not guessed):
- Feature values reach ~8e8, so ‖x−sv‖² spans [0, ~1e16]. The dot-product
  expansion of d² catastrophically cancels in float32, and even casting the
  *query* to float32 perturbs d² enough to flip ovo votes (decision margins
  on this checkpoint go down to ~0.04). Remedy: a two-float (hi/lo) split of
  both support vectors and queries; the difference form
  ``(x_hi−s_hi)+(x_lo−s_lo)`` is then exact-to-f32-rounding, giving
  argmax parity with float64 at float32 speed.
- On this XLA build, DEFAULT matmul precision is bf16-like (max error ~0.2 on
  the vote matmul — larger than the decision margins), so every matmul here
  pins ``precision='highest'``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

_HI = jax.lax.Precision.HIGHEST


class Params(struct.PyTreeNode):
    sv_hi: jax.Array  # (S, F) support vectors, hi part
    sv_lo: jax.Array  # (S, F) residual (sv − f32(sv)); zeros in f64 mode
    pair_coef: jax.Array  # (P, S) dense ovo dual coefficients
    intercept: jax.Array  # (P,)
    vote_i: jax.Array  # (P,) int32 class voted when D > 0
    vote_j: jax.Array  # (P,) int32 class voted otherwise
    gamma: jax.Array  # () scalar
    n_classes: int = struct.field(pytree_node=False)  # static under jit
    # static "sv_lo is not identically zero" flag: lets the
    # dot-expansion path skip its lo-correction matmul at TRACE time in
    # f64 mode (where split_hilo leaves lo all-zero and the correction
    # is exactly 0). Default True = conservative (compute it) — old
    # checkpoints without the manifest key load unchanged.
    has_lo: bool = struct.field(pytree_node=False, default=True)


def _pairs(n_classes: int):
    return [(i, j) for i in range(n_classes) for j in range(i + 1, n_classes)]


def split_hilo(X, dtype=jnp.float32):
    """Two-float split of a float64 array: X ≈ hi + lo with hi = f32(X).

    Host-side helper for parity-exact float32 queries; in float64 mode lo
    is identically zero.
    """
    X = np.asarray(X, dtype=np.float64)
    if dtype == jnp.float64:
        return jnp.asarray(X), jnp.zeros_like(jnp.asarray(X))
    hi = X.astype(np.float32)
    lo = (X - hi).astype(np.float32)
    return jnp.asarray(hi, dtype=dtype), jnp.asarray(lo, dtype=dtype)


def from_numpy(d: dict, dtype=jnp.float32) -> Params:
    sv = np.asarray(d["support_vectors"], dtype=np.float64)
    dual = np.asarray(d["dual_coef"], dtype=np.float64)  # (C-1, S)
    n_support = np.asarray(d["n_support"], dtype=np.int64)
    n_classes = len(n_support)
    starts = np.concatenate([[0], np.cumsum(n_support)])
    pairs = _pairs(n_classes)

    # Dense (P, S) ovo coefficients: for pair (i, j), class-i SVs contribute
    # dual[j-1] and class-j SVs contribute dual[i] (libsvm sv_coef layout).
    pair_coef = np.zeros((len(pairs), sv.shape[0]), dtype=np.float64)
    for p, (i, j) in enumerate(pairs):
        si, ei = starts[i], starts[i + 1]
        sj, ej = starts[j], starts[j + 1]
        pair_coef[p, si:ei] = dual[j - 1, si:ei]
        pair_coef[p, sj:ej] = dual[i, sj:ej]

    sv_hi, sv_lo = split_hilo(sv, dtype=dtype)
    return Params(
        sv_hi=sv_hi,
        sv_lo=sv_lo,
        pair_coef=jnp.asarray(pair_coef, dtype=dtype),
        intercept=jnp.asarray(d["intercept"], dtype=dtype),
        vote_i=jnp.asarray([i for i, _ in pairs], dtype=jnp.int32),
        vote_j=jnp.asarray([j for _, j in pairs], dtype=jnp.int32),
        gamma=jnp.asarray(d["gamma"], dtype=dtype),
        n_classes=n_classes,
        has_lo=bool(np.any(np.asarray(sv_lo))),
    )


def rbf_kernel(params: Params, X: jax.Array, X_lo=None) -> jax.Array:
    """exp(−γ‖x−sv‖²), (N, S), difference form with optional lo correction.

    Pass ``X_lo`` (from ``split_hilo``) for float64-equivalent accuracy when
    the raw features exceed float32's 24-bit integer range.
    """
    diff = X[:, None, :] - params.sv_hi[None, :, :]
    if X_lo is not None:
        diff = diff + (X_lo[:, None, :] - params.sv_lo[None, :, :])
    else:
        diff = diff - params.sv_lo[None, :, :]
    d2 = jnp.sum(diff * diff, axis=-1)
    return jnp.exp(-params.gamma * d2)


def _decision_from_kernel(params: Params, K: jax.Array) -> jax.Array:
    return (
        jnp.matmul(K, params.pair_coef.T, precision=_HI)
        + params.intercept[None, :]
    )


def _votes_from_decision(params: Params, D: jax.Array) -> jax.Array:
    """ovo vote counts, (N, C) — ONE home for the libsvm vote semantics
    so the canonical and dot-expansion paths cannot drift."""
    pos = D > 0
    votes_i = jax.nn.one_hot(params.vote_i, params.n_classes, dtype=D.dtype)
    votes_j = jax.nn.one_hot(params.vote_j, params.n_classes, dtype=D.dtype)
    return jnp.where(pos[:, :, None], votes_i, votes_j).sum(axis=1)


def decision_ovo(params: Params, X: jax.Array, X_lo=None) -> jax.Array:
    """Per-pair ovo decision values, (N, P)."""
    return _decision_from_kernel(params, rbf_kernel(params, X, X_lo))


def scores(params: Params, X: jax.Array, X_lo=None) -> jax.Array:
    """Vote counts per class, (N, C)."""
    return _votes_from_decision(params, decision_ovo(params, X, X_lo))


def predict(params: Params, X: jax.Array, X_lo=None) -> jax.Array:
    return jnp.argmax(scores(params, X, X_lo), axis=-1).astype(jnp.int32)


def predict_scores(
    params: Params, X: jax.Array, X_lo=None
) -> tuple[jax.Array, jax.Array]:
    """(labels, ovo vote-count scores) from ONE kernel computation —
    the open-set serving surface (models/base.py protocol);
    ``argmax(scores) == predict`` by construction (same votes, same
    libsvm lowest-index tie order)."""
    votes = scores(params, X, X_lo)
    return jnp.argmax(votes, axis=-1).astype(jnp.int32), votes


def predict_chunked(
    params: Params, X: jax.Array, X_lo=None, row_chunk: int = 65536
) -> jax.Array:
    """``predict`` for batches whose (N, S) kernel matrix would blow HBM
    (2²⁰ × 2281 f32 ≈ 9.5 GB): rows stream through the shared
    ``ops.chunking.chunked_predict`` dispatch (see its docstring for the
    lo-less fast path)."""
    from ..ops.chunking import chunked_predict

    return chunked_predict(
        lambda xc, xlo=None: predict(params, xc, xlo), row_chunk, X, X_lo
    )


def rbf_kernel_dot(params: Params, X: jax.Array, X_lo=None) -> jax.Array:
    """(N, S) RBF kernel via the dot expansion ``d² = ‖x‖² + ‖s‖² − 2x·s``
    (clamped at 0 — cancellation can push it negative): no (N, S, F)
    difference tensor, so the hot loop is one matmul. On the CPU host
    the difference form materializes ~1.8 GB per 16k batch and runs
    3.6× slower (measured; bench races the two and parity-gates).

    hi/lo compensation (structural, mirroring ``rbf_kernel``): with
    ``x = x_hi + x_lo`` and ``s = s_hi + s_lo``,

        d² = ‖Δh‖² + 2·Δh·Δl + ‖Δl‖²,   Δh = x_hi − s_hi, Δl = x_lo − s_lo

    The base expansion above is ‖Δh‖² alone; earlier revisions DROPPED
    the lo parts entirely, so the split-checkpoint residuals the
    difference path compensates for never reached this path and parity
    held only empirically (same-run gate in bench.py — VERDICT r5 weak
    #3). The cross terms expand into two extra matmuls (one when
    ``X_lo`` is None) plus per-row/per-SV scalars, so the correction is
    O(matmul) like the base, and dropping ``sv_lo``/``X_lo`` now fails
    a structural regression test (tests/test_model_parity.py) instead
    of a gate.

    Residual numerics — still read before enabling in serving: the
    compensation makes the lo parts structural, but the HI expansion
    itself still cancels in f32. Features reach ~8e8, so ‖x‖²/‖s‖² ~
    1e18 in f32 and the subtraction cancels to an absolute d² error up
    to ~1e11 — γ·1e11 ≈ 5.5e2 in the exponent, i.e. kernel values near
    a support vector can be wrong by orders of magnitude for
    large-magnitude rows, NOT by ulps. That part of the safety story
    still rests on EMPIRICAL label parity: 100% on the full reference
    corpus (the gate bench.py applies before promotion, and the
    contract tests/test_model_parity.py pins). The difference form
    (``rbf_kernel``) remains the canonical/exact path and the serving
    default; ``TCSDN_SVC_KERNEL=dot`` is a deliberate opt-in for hosts
    where the 3.6× matters more than worst-case boundary exactness."""
    sv_sq = jnp.sum(params.sv_hi * params.sv_hi, axis=1)
    x_sq = jnp.sum(X * X, axis=1)
    d2 = (
        x_sq[:, None]
        + sv_sq[None, :]
        - 2.0 * jnp.matmul(X, params.sv_hi.T, precision=_HI)
    )
    # 2·Δh·Δl + ‖Δl‖², expanded so every (N, S) term is a matmul or a
    # broadcast of per-row/per-SV reductions. Both lo sources are
    # STATICALLY gated (params.has_lo is a trace-time constant, X_lo
    # None is a Python branch): the f64 mode, whose lo parts are
    # identically zero, compiles the bare hi expansion with no
    # correction matmul at all.
    corr = None
    if params.has_lo:
        sv_hilo = jnp.sum(params.sv_hi * params.sv_lo, axis=1)  # (S,)
        sv_lo_sq = jnp.sum(params.sv_lo * params.sv_lo, axis=1)  # (S,)
        corr = (
            (2.0 * sv_hilo + sv_lo_sq)[None, :]
            - 2.0 * jnp.matmul(X, params.sv_lo.T, precision=_HI)
        )
    if X_lo is not None:
        x_hilo = jnp.sum(X * X_lo, axis=1)  # (N,)
        x_lo_sq = jnp.sum(X_lo * X_lo, axis=1)  # (N,)
        x_corr = (
            (2.0 * x_hilo + x_lo_sq)[:, None]
            - 2.0 * jnp.matmul(X_lo, params.sv_hi.T, precision=_HI)
        )
        if params.has_lo:
            x_corr = x_corr - 2.0 * jnp.matmul(
                X_lo, params.sv_lo.T, precision=_HI
            )
        corr = x_corr if corr is None else corr + x_corr
    if corr is not None:
        d2 = d2 + corr
    return jnp.exp(-params.gamma * jnp.maximum(d2, 0.0))


def predict_dot(params: Params, X: jax.Array, X_lo=None) -> jax.Array:
    """``predict`` through ``rbf_kernel_dot`` (see its numerics note) —
    the vote/argmax tail is the canonical path's, shared."""
    votes = _votes_from_decision(
        params,
        _decision_from_kernel(params, rbf_kernel_dot(params, X, X_lo)),
    )
    return jnp.argmax(votes, axis=-1).astype(jnp.int32)


def predict_dot_chunked(
    params: Params, X: jax.Array, X_lo=None, row_chunk: int = 65536
) -> jax.Array:
    """``predict_dot`` with rows streamed in ``row_chunk`` slices; the
    optional ``X_lo`` rides the same chunking as the difference path."""
    from ..ops.chunking import chunked_predict

    return chunked_predict(
        lambda xc, xlo=None: predict_dot(params, xc, xlo),
        row_chunk, X, X_lo,
    )
