"""KMeans nearest-centroid assignment as a batched matmul + argmin.

Replaces sklearn's ``KMeans.predict`` (reference checkpoint
``models/KMeans_Clustering`` — 4 clusters, from the 4-class data era; loaded
at traffic_classifier.py:231-232). Assignment is argmin of squared L2 to the
centers; ``‖x‖²`` is constant across centers so the argmin only needs
``−2 x·μᵀ + ‖μ‖²`` — one MXU matmul plus a broadcast add (SURVEY.md §2.2).

Cluster→label remapping is a host-side concern: the reference's online remap
(traffic_classifier.py:109-114) assumes 6 clusters and disagrees with the
notebook-derived 4-cluster map (0=dns, 1=ping, 2=telnet, 3=voice from
``1_log_Kmeans.ipynb`` cell 116) — a known reference defect we do not
replicate (SURVEY.md §2, defects list). Both maps are provided.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..core.features import CLASSES_6


class Params(NamedTuple):
    centers: jax.Array  # (K, F)


# Checkpoint-era (correct) map, derived in 1_log_Kmeans.ipynb cell 116 by
# matching cluster modes on the 4-class training data.
CLUSTER_LABELS_CHECKPOINT = ("dns", "ping", "telnet", "voice")
# The reference's (buggy) online remap at traffic_classifier.py:109-114,
# kept only for behavioral documentation.
CLUSTER_LABELS_REFERENCE_ONLINE = CLASSES_6


def from_numpy(d: dict, dtype=jnp.float32) -> Params:
    return Params(centers=jnp.asarray(d["cluster_centers"], dtype=dtype))


def scores(params: Params, X: jax.Array) -> jax.Array:
    """Negated squared distance, (N, K): higher = closer.

    Difference form, not the dot-product expansion: features reach ~8e8 so
    ‖x‖²-scale terms catastrophically cancel in float32 (measured for the
    RBF kernel — see models/svc.py numerical notes). K is 4, so the (N, K, F)
    intermediate is trivially small and XLA fuses the whole thing."""
    diff = X[:, None, :] - params.centers[None, :, :]
    return -jnp.sum(diff * diff, axis=-1)


def predict(params: Params, X: jax.Array) -> jax.Array:
    return jnp.argmax(scores(params, X), axis=-1).astype(jnp.int32)


def predict_scores(params: Params, X: jax.Array) -> tuple[jax.Array, jax.Array]:
    """(cluster ids, negated-inertia scores) from ONE score
    computation — the open-set serving surface (models/base.py
    protocol); ``argmax(scores) == predict`` by construction."""
    s = scores(params, X)
    return jnp.argmax(s, axis=-1).astype(jnp.int32), s
