"""IVF (inverted-file) approximate KNN — the cluster-probed serving tier.

The classic IVF design (Jégou-style inverted lists) applied to the KNN
serving hot path: the training corpus is partitioned by a coarse KMeans
quantizer — fit by the SAME already-device-resident Lloyd kernel the
kmeans family trains with (train/kmeans.py) — and a query runs the
exact top-k only within its ``nprobe`` nearest centroid lists instead
of all S corpus rows. This is an APPROXIMATE tier: a true neighbor
whose list is not probed is missed, so it serves strictly behind the
explicit ``--knn-topk ivf`` / ``TCSDN_KNN_TOPK=ivf`` opt-in with a
measured recall artifact (docs/artifacts/knn_ivf_recall_cpu.json,
tools/bench_knn.py) — never silently substituted for an exact path.

Anchors:

- ``nprobe >= n_lists`` is the EXACT search bit-for-bit: the probed
  lists then cover the whole corpus (the lists partition it), the
  candidate set is sorted into ascending corpus order before the final
  ``lax.top_k``, and ties therefore resolve to the lowest corpus index
  — the full-row ``lax.top_k`` rule (pinned in tests/test_knn_ivf.py).
- The ranking values are the SAME f32 dot-expansion similarities the
  exact XLA paths rank by (``models/knn._dot_expansion_sim``), so at
  nprobe == n_lists the label stream is bitwise-identical to
  ``top_k_impl='sort'``.
- The native C++ evaluator mirrors the tier (``NativeKnn.build_ivf`` /
  ``predict_ivf`` over the same quantizer) — and IS what the serving
  opt-in resolves to on hosts where it builds: on CPU the XLA tier's
  per-row candidate gathers cost more than the full sort network it
  avoids (measured in knn_ivf_recall_cpu.json's xla_flows_per_sec
  column), while the native tier probes at 4-6× the full scan. The XLA
  path remains the device-side implementation (TPU evidence is armed
  in tools/tpu_day.sh) and the recall harness's reference.

The probe stage ranks centroids by the difference-form distance
(``models/kmeans.scores`` semantics — the dot-expansion cancels
catastrophically at this data's ~8e8 feature scale, see
models/kmeans.py); probe selection only decides WHICH lists are
searched, so its numerics affect recall, never exactness of the
within-list ranking.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from flax import struct
from jax import lax

from ..models import knn

# Shipped default probe count: the smallest nprobe that clears the
# >= 0.99 recall@1 gate on the reference-scale recall sweep
# (docs/artifacts/knn_ivf_recall_cpu.json regenerates the evidence:
# recall@1 0.998 at nprobe=2 with the native tier probing at ~4.6x the
# unpruned full scan; nprobe=4 reaches recall 1.0 at ~2.6x — pass
# --knn-topk ivf4 to trade speed for the wider probe).
DEFAULT_NPROBE = 2


def default_n_clusters(n_rows: int) -> int:
    """K ≈ √S — the standard IVF balance between probe cost (∝ K) and
    list-scan cost (∝ S/K per probed list)."""
    return max(1, int(round(float(n_rows) ** 0.5)))


class IvfParams(struct.PyTreeNode):
    """The serving bundle: exact corpus params + the coarse index.

    ``list_idx`` rows are ascending corpus indices padded with S (the
    one-past-the-end sentinel — its similarity column is -inf and its
    label is dropped by the one-hot, so padding never votes)."""

    base: knn.Params
    centers: jax.Array     # (K, F) f32 coarse quantizer
    list_idx: jax.Array    # (K, L) int32 member corpus indices, pad = S
    nprobe: int = struct.field(pytree_node=False)

    @property
    def n_lists(self) -> int:
        return int(self.centers.shape[0])


def assignments(fit_X: np.ndarray, centers: np.ndarray) -> np.ndarray:
    """(S,) int32 nearest-centroid ids — f64 difference form on host,
    lowest-index ties (np.argmin), shared by the XLA list build and the
    native mirror so both tiers hold the SAME partition. Row-chunked:
    the (S, K, F) broadcast would be ~28 MB f64 at reference scale."""
    fx = np.asarray(fit_X, np.float64)
    ce = np.asarray(centers, np.float64)
    out = np.empty(fx.shape[0], np.int32)
    for lo in range(0, fx.shape[0], 1024):
        d2 = ((fx[lo:lo + 1024, None, :] - ce[None, :, :]) ** 2).sum(-1)
        out[lo:lo + 1024] = np.argmin(d2, axis=1)
    return out


def assignments_of(ivf: IvfParams) -> np.ndarray:
    """(S,) int32 assignments recovered from the built lists — O(S)
    inversion, no distance recompute (the serving resolution hands the
    native mirror the SAME partition without paying the assignment
    pass twice)."""
    list_idx = np.asarray(ivf.list_idx)
    S = int(ivf.base.fit_X.shape[0])
    out = np.empty(S, np.int32)
    for c in range(list_idx.shape[0]):
        members = list_idx[c][list_idx[c] < S]
        out[members] = c
    return out


def build(params: knn.Params, *, n_clusters: int | None = None,
          nprobe: int = DEFAULT_NPROBE, seed: int = 0,
          n_init: int = 4, n_iter: int = 25) -> IvfParams:
    """Fit the coarse quantizer on the corpus (train/kmeans Lloyd kernel,
    deterministic seed) and assemble the serving bundle. Runs at
    params-build time — the serving path resolution (models/__init__)
    calls this once per loaded model."""
    from ..train import kmeans as tkmeans

    fit_X = np.asarray(params.fit_X, np.float32)
    S = fit_X.shape[0]
    K = n_clusters if n_clusters is not None else default_n_clusters(S)
    K = max(1, min(int(K), S))
    kparams, _ = tkmeans.fit(
        fit_X, k=K, n_init=n_init, n_iter=n_iter, seed=seed
    )
    centers = np.asarray(kparams.centers, np.float32)
    assign = assignments(fit_X, centers)
    lists: list[list[int]] = [[] for _ in range(K)]
    for s, c in enumerate(assign):  # ascending s → ascending per list
        lists[int(c)].append(s)
    L = max(1, max(len(li) for li in lists))
    list_idx = np.full((K, L), S, np.int32)  # pad = S sentinel
    for c, li in enumerate(lists):
        list_idx[c, : len(li)] = li
    if nprobe < 1:
        raise ValueError(f"nprobe={nprobe} must be >= 1")
    return IvfParams(
        base=params,
        centers=jnp.asarray(centers),
        list_idx=jnp.asarray(list_idx),
        nprobe=int(min(nprobe, K)),
    )


def _probe_lists(ivf: IvfParams, X: jax.Array, nprobe: int) -> jax.Array:
    """(N, nprobe) probed list ids — nearest centroids by the
    difference-form distance, ties to the lowest centroid index
    (``lax.top_k`` over the negated distances)."""
    diff = X[:, None, :] - ivf.centers[None, :, :]
    csim = -jnp.sum(diff * diff, axis=-1)  # (N, K)
    _, psel = lax.top_k(csim, nprobe)
    return psel


def neighbor_votes_ivf(ivf: IvfParams, X: jax.Array,
                       nprobe: int | None = None) -> jax.Array:
    """(N, C) neighbor votes over the probed lists only.

    The candidate set (union of the probed lists, padded entries =
    corpus-size sentinel) is SORTED into ascending corpus order before
    the final ``lax.top_k``, so equal similarities resolve to the
    lowest corpus index — the full-row tie rule; at nprobe == n_lists
    the candidate set is exactly 0..S-1 and the result is
    bitwise-identical to the exact sort path. The sentinel's similarity
    column is -inf (it loses every comparison) and its label row is
    out-of-range for the one-hot (a zero row), so a probe set holding
    fewer than k real candidates votes over the real ones only — the
    same guarantee the native mirror makes."""
    p = ivf.base
    np_eff = ivf.nprobe if nprobe is None else int(nprobe)
    np_eff = max(1, min(np_eff, ivf.n_lists))
    n = X.shape[0]
    S = p.fit_X.shape[0]
    sim = knn._dot_expansion_sim(X, p.fit_X, p.half_sq_norms)  # (N, S)
    psel = _probe_lists(ivf, X, np_eff)  # (N, nprobe)
    cand = ivf.list_idx[psel]  # (N, nprobe, L)
    cand = jnp.sort(cand.reshape(n, -1), axis=1)  # ascending; pad last
    simp = jnp.concatenate(
        [sim, jnp.full((n, 1), -jnp.inf, sim.dtype)], axis=1
    )
    vals = jnp.take_along_axis(simp, cand, axis=1)
    _, sel = lax.top_k(vals, p.n_neighbors)
    nbr = jnp.take_along_axis(cand, sel, axis=1)  # (N, k), may hold S
    fit_y_ext = jnp.concatenate(
        [p.fit_y, jnp.full((1,), -1, p.fit_y.dtype)]
    )
    return knn.count_votes(fit_y_ext, p.n_classes, nbr)


def predict(ivf: IvfParams, X: jax.Array,
            nprobe: int | None = None) -> jax.Array:
    """(N,) labels through the IVF tier — the ``(params, X)`` serving
    signature (``IvfParams`` is the params pytree)."""
    return jnp.argmax(
        neighbor_votes_ivf(ivf, X, nprobe), axis=-1
    ).astype(jnp.int32)


def predict_scores(
    ivf: IvfParams, X: jax.Array, nprobe: int | None = None,
) -> tuple[jax.Array, jax.Array]:
    """(labels, neighbor-vote scores) from ONE vote computation — the
    open-set serving surface; ``argmax(scores) == predict`` by
    construction (same votes, same first-max tie order)."""
    votes = neighbor_votes_ivf(ivf, X, nprobe)
    return jnp.argmax(votes, axis=-1).astype(jnp.int32), votes


def predict_chunked(
    ivf: IvfParams, X: jax.Array, X_lo=None, row_chunk: int = 16384,
) -> jax.Array:
    """``predict`` for serving-scale batches: rows stream through the
    shared ``ops.chunking.chunked_predict`` dispatch (the (N, S)
    similarity plus the (N, nprobe·L) candidate gather bound the
    per-chunk footprint — 16k rows keeps both under the KNN row-chunk
    budget). ``X_lo`` is accepted for serving-signature compatibility
    and ignored: the IVF tier ranks by the f32 fast-path similarity by
    definition."""
    del X_lo  # the approximate tier has no two-float exact form
    from .chunking import chunked_predict

    return chunked_predict(
        lambda xc, xlo=None: predict(ivf, xc), row_chunk, X,
    )


def exact_top1(params: knn.Params, X: jax.Array) -> jax.Array:
    """(N,) the exact nearest-neighbor corpus index (sort-path ranking)
    — the recall@1 reference."""
    sim = knn._dot_expansion_sim(X, params.fit_X, params.half_sq_norms)
    return jnp.argmax(sim, axis=1).astype(jnp.int32)


def ivf_top1(ivf: IvfParams, X: jax.Array,
             nprobe: int | None = None) -> jax.Array:
    """(N,) the IVF tier's nearest-neighbor corpus index."""
    p = ivf.base
    np_eff = ivf.nprobe if nprobe is None else int(nprobe)
    np_eff = max(1, min(np_eff, ivf.n_lists))
    n = X.shape[0]
    sim = knn._dot_expansion_sim(X, p.fit_X, p.half_sq_norms)
    psel = _probe_lists(ivf, X, np_eff)
    cand = jnp.sort(ivf.list_idx[psel].reshape(n, -1), axis=1)
    simp = jnp.concatenate(
        [sim, jnp.full((n, 1), -jnp.inf, sim.dtype)], axis=1
    )
    vals = jnp.take_along_axis(simp, cand, axis=1)
    best = jnp.argmax(vals, axis=1)
    return jnp.take_along_axis(cand, best[:, None], axis=1)[:, 0]


def recall_at_1(ivf: IvfParams, X: jax.Array,
                nprobe: int | None = None) -> float:
    """Fraction of queries whose IVF top-1 neighbor IS the exact top-1
    — the artifact's recall column (tools/bench_knn.py sweeps it over
    nprobe; the unit anchor is recall == 1.0 at nprobe == n_lists)."""
    a = np.asarray(ivf_top1(ivf, X, nprobe))
    b = np.asarray(exact_top1(ivf.base, X))
    return float((a == b).mean())
