"""Tensorized decision-tree ensemble evaluation.

Replaces the 100 sequential Cython ``sklearn.tree._tree.Tree`` traversals
inside ``RandomForestClassifier.predict`` (reference checkpoint
``models/RandomForestClassifier``; SURVEY.md §2.3) with a lockstep gather
traversal: all (sample, tree) pairs walk their tree in ``max_depth`` rounds
of vectorized gathers over dense (T, M) node stacks.

This is the CPU-friendly strategy (and the semantic reference the others
are tested against). On TPU, per-element gathers serialize badly; the
production paths are the GEMM formulation (ops/tree_gemm.py) and the fused
Pallas kernel (ops/pallas_forest.py).

Leaves are encoded sklearn-style: ``left == right == -1``; padded slots are
leaves with zero value rows. A walker that reaches a leaf self-loops, so
running the full ``max_depth`` rounds is harmless and keeps control flow
static for XLA.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def traverse_gather(
    left: jax.Array,  # (T, M) int32
    right: jax.Array,  # (T, M) int32
    feature: jax.Array,  # (T, M) int32 (leaves/padding: 0)
    threshold: jax.Array,  # (T, M)
    X: jax.Array,  # (N, F)
    max_depth: int,
) -> jax.Array:
    """Return final leaf index per (sample, tree): (N, T) int32."""
    n_trees = left.shape[0]
    tree_ar = jnp.arange(n_trees)[None, :]  # (1, T)
    idx0 = jnp.zeros((X.shape[0], n_trees), dtype=jnp.int32)

    def step(_, idx):
        f = feature[tree_ar, idx]  # (N, T)
        thr = threshold[tree_ar, idx]  # (N, T)
        xv = jnp.take_along_axis(X, f, axis=1)  # (N, T)
        l = left[tree_ar, idx]
        r = right[tree_ar, idx]
        nxt = jnp.where(xv <= thr, l, r)
        return jnp.where(l < 0, idx, nxt)  # leaf: stay put

    return lax.fori_loop(0, max_depth, step, idx0)


def forest_proba(
    left, right, feature, threshold, values, X, max_depth: int,
    tree_chunk: int = 16,
) -> jax.Array:
    """Mean of per-tree normalized leaf class distributions, (N, C) — the
    exact quantity sklearn's ``RandomForestClassifier.predict_proba``
    averages before argmax.

    Trees are accumulated in chunks of ``tree_chunk`` so the live
    intermediate is (N, chunk, C), not (N, T, C) — a million-flow batch
    against 100 trees would otherwise materialize ~25 GB in HBM."""
    leaf = traverse_gather(left, right, feature, threshold, X, max_depth)
    n_trees = left.shape[0]
    n_classes = values.shape[-1]
    # Normalize leaf count rows into distributions once (tiny: T·M·C).
    norm = jnp.sum(values, axis=-1, keepdims=True)
    values_n = values / jnp.maximum(norm, 1e-30)

    chunk = min(tree_chunk, n_trees)
    n_chunks, rem = divmod(n_trees, chunk)

    def add_chunk(t0, probs):
        idx = lax.dynamic_slice_in_dim(leaf, t0, chunk, axis=1)  # (N, c)
        vals = lax.dynamic_slice_in_dim(values_n, t0, chunk, axis=0)  # (c,M,C)
        picked = vals[jnp.arange(chunk)[None, :], idx]  # (N, c, C)
        return probs + jnp.sum(picked, axis=1)

    probs = jnp.zeros((X.shape[0], n_classes), values_n.dtype)
    probs = lax.fori_loop(
        0, n_chunks, lambda i, p: add_chunk(i * chunk, p), probs
    )
    if rem:
        idx = leaf[:, n_trees - rem:]
        vals = values_n[n_trees - rem:]
        probs = probs + jnp.sum(
            vals[jnp.arange(rem)[None, :], idx], axis=1
        )
    return probs / n_trees


def tree_votes(left, right, feature, threshold, values, X, max_depth: int):
    """Per-tree normalized distributions, (N, T, C) — the psum-able quantity
    for tree-sharded ensembles (parallel/forest_sharded.py)."""
    leaf = traverse_gather(left, right, feature, threshold, X, max_depth)
    tree_ar = jnp.arange(left.shape[0])[None, :]
    leaf_vals = values[tree_ar, leaf]
    norm = jnp.sum(leaf_vals, axis=-1, keepdims=True)
    return leaf_vals / jnp.maximum(norm, 1e-30)
