"""Tensorized decision-tree ensemble evaluation.

Replaces the 100 sequential Cython ``sklearn.tree._tree.Tree`` traversals
inside ``RandomForestClassifier.predict`` (reference checkpoint
``models/RandomForestClassifier``; SURVEY.md §2.3). Two strategies:

1. ``traverse_gather`` — all (sample, tree) pairs walk their tree in
   lockstep: ``max_depth`` rounds of vectorized gathers. Work is
   O(N·T·depth) with tiny constants; the node arrays live in VMEM-friendly
   dense (T, M) stacks padded to the max node count.
2. ``traverse_onehot`` — Hummingbird-style GEMM formulation (kept for
   benchmarking; gather wins at these tree sizes).

Leaves are encoded sklearn-style: ``left == right == -1``; padded slots are
leaves with zero value rows. A walker that reaches a leaf self-loops, so
running the full ``max_depth`` rounds is harmless and keeps control flow
static for XLA.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def traverse_gather(
    left: jax.Array,  # (T, M) int32
    right: jax.Array,  # (T, M) int32
    feature: jax.Array,  # (T, M) int32 (leaves/padding: 0)
    threshold: jax.Array,  # (T, M)
    X: jax.Array,  # (N, F)
    max_depth: int,
) -> jax.Array:
    """Return final leaf index per (sample, tree): (N, T) int32."""
    n_trees = left.shape[0]
    tree_ar = jnp.arange(n_trees)[None, :]  # (1, T)
    idx0 = jnp.zeros((X.shape[0], n_trees), dtype=jnp.int32)

    def step(_, idx):
        f = feature[tree_ar, idx]  # (N, T)
        thr = threshold[tree_ar, idx]  # (N, T)
        xv = jnp.take_along_axis(X, f, axis=1)  # (N, T)
        l = left[tree_ar, idx]
        r = right[tree_ar, idx]
        nxt = jnp.where(xv <= thr, l, r)
        return jnp.where(l < 0, idx, nxt)  # leaf: stay put

    return lax.fori_loop(0, max_depth, step, idx0)


def forest_proba(
    left, right, feature, threshold, values, X, max_depth: int
) -> jax.Array:
    """Mean of per-tree normalized leaf class distributions, (N, C) — the
    exact quantity sklearn's ``RandomForestClassifier.predict_proba``
    averages before argmax."""
    leaf = traverse_gather(left, right, feature, threshold, X, max_depth)
    n_trees = left.shape[0]
    tree_ar = jnp.arange(n_trees)[None, :]
    leaf_vals = values[tree_ar, leaf]  # (N, T, C) class counts
    norm = jnp.sum(leaf_vals, axis=-1, keepdims=True)
    probs = leaf_vals / jnp.maximum(norm, 1e-30)
    return jnp.mean(probs, axis=1)


def tree_votes(left, right, feature, threshold, values, X, max_depth: int):
    """Per-tree normalized distributions, (N, T, C) — the psum-able quantity
    for tree-sharded ensembles (parallel/forest_sharded.py)."""
    leaf = traverse_gather(left, right, feature, threshold, X, max_depth)
    tree_ar = jnp.arange(left.shape[0])[None, :]
    leaf_vals = values[tree_ar, leaf]
    norm = jnp.sum(leaf_vals, axis=-1, keepdims=True)
    return leaf_vals / jnp.maximum(norm, 1e-30)
