"""Fused Pallas TPU kernel for RBF-SVC decision evaluation.

The XLA path (models/svc.py) materializes the (N, S) kernel matrix in HBM
before the vote matmul — ~9 GB of traffic for a million-flow batch against
the reference's 2281 support vectors (SURVEY.md §7 hard part b). This
kernel fuses distance, exponential, and vote-projection per grid step so
the kernel matrix never leaves VMEM:

    d²   = Σ_f ((x_f − s_f) + (xlo_f − slo_f))²   (VPU, two-float exact)
    K    = exp(−γ·d²)                              (VPU)
    acc += K @ coef_chunk                          (MXU, f32)

per (row-tile × SV-chunk) grid step; the (TILE, P) output block stays
resident and accumulates over SV chunks. The two-float difference form is
the same parity trick as models/svc.py: raw features reach ~8e8, where the
dot-product expansion of d² cancels catastrophically in f32.

HBM traffic collapses to: read X once, stream the (F, S) support vectors +
(S, P) coefficients per row tile (~150 KB for the reference checkpoint),
write (N, P) decisions once.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..models import svc


class SvcPallas(struct.PyTreeNode):
    sv_t_hi: jax.Array  # (F, Sp) support vectors, transposed, hi part
    sv_t_lo: jax.Array  # (F, Sp) two-float residual
    coef_t: jax.Array  # (Sp, P) dense ovo coefficients, transposed
    intercept: jax.Array  # (P,)
    vote_i: jax.Array  # (P,) int32
    vote_j: jax.Array  # (P,) int32
    gamma: jax.Array  # (1, 1) f32 (SMEM scalar)
    n_classes: int = struct.field(pytree_node=False)
    row_tile: int = struct.field(pytree_node=False)
    sv_chunk: int = struct.field(pytree_node=False)


def sv_layout(params: svc.Params, padded_rows: int):
    """The kernel's pre-laid operands for ``padded_rows`` total SV slots:
    ``((F, padded) sv_t_hi, (F, padded) sv_t_lo, (padded, P) coef_t)``,
    transposed so per-feature rows broadcast along lanes, padding slots
    carrying ZERO dual coefficients (their K contribution is killed by
    the zero coefficient, so no ±inf bookkeeping is needed). The ONE
    home of that invariant — ``compile_svc`` and the SV-sharded layout
    (parallel/svc_sharded.fused_predict) both build through it."""
    sv_hi = np.asarray(params.sv_hi, np.float32)
    sv_lo = np.asarray(params.sv_lo, np.float32)
    coef = np.asarray(params.pair_coef, np.float32)  # (P, S)
    pad = padded_rows - sv_hi.shape[0]
    if pad:
        sv_hi = np.concatenate([sv_hi, np.zeros((pad, sv_hi.shape[1]), np.float32)])
        sv_lo = np.concatenate([sv_lo, np.zeros((pad, sv_lo.shape[1]), np.float32)])
        coef = np.concatenate([coef, np.zeros((coef.shape[0], pad), np.float32)], axis=1)
    return jnp.asarray(sv_hi.T), jnp.asarray(sv_lo.T), jnp.asarray(coef.T)


def compile_svc(
    params: svc.Params, row_tile: int = 512, sv_chunk: int = 1024
) -> SvcPallas:
    """Re-lay a models/svc.Params for the fused kernel: S padded to the
    chunk size (zero-coefficient padding — see ``sv_layout``)."""
    S = np.asarray(params.sv_hi).shape[0]
    sv_t_hi, sv_t_lo, coef_t = sv_layout(params, S + (-S) % sv_chunk)
    return SvcPallas(
        sv_t_hi=sv_t_hi,
        sv_t_lo=sv_t_lo,
        coef_t=coef_t,
        intercept=params.intercept,
        vote_i=params.vote_i,
        vote_j=params.vote_j,
        gamma=jnp.reshape(params.gamma.astype(jnp.float32), (1, 1)),
        n_classes=params.n_classes,
        row_tile=row_tile,
        sv_chunk=sv_chunk,
    )


def _kernel(gamma_ref, x_ref, xlo_ref, svt_ref, svtlo_ref, coef_ref, out_ref,
            *, n_features: int):
    s = pl.program_id(1)
    g = gamma_ref[0, 0]
    d2 = jnp.zeros((x_ref.shape[0], svt_ref.shape[1]), jnp.float32)
    for f in range(n_features):  # static unroll: F outer-product adds
        diff = (x_ref[:, f : f + 1] - svt_ref[f : f + 1, :]) + (
            xlo_ref[:, f : f + 1] - svtlo_ref[f : f + 1, :]
        )
        d2 = d2 + diff * diff
    K = jnp.exp(-g * d2)  # (TILE, SC)
    # precision=HIGHEST: the MXU's default f32 matmul is bf16-like, and
    # ovo margins go down to ~0.04 (models/svc.py numerical notes)
    acc = jnp.dot(
        K,
        coef_ref[:],
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )

    @pl.when(s == 0)
    def _():
        out_ref[:] = acc

    @pl.when(s > 0)
    def _():
        out_ref[:] = out_ref[:] + acc


def partial_decision(
    X: jax.Array, X_lo: jax.Array, gamma: jax.Array,
    sv_t_hi: jax.Array, sv_t_lo: jax.Array, coef_t: jax.Array,
    row_tile: int = 512, sv_chunk: int = 1024, interpret: bool = False,
) -> jax.Array:
    """(N, P) K @ coef for the GIVEN pre-laid support-vector block —
    NO intercept. Traceable building block: operands are the arrays of a
    ``SvcPallas`` (or one state-axis shard of them —
    parallel/svc_sharded.py calls this per device inside ``shard_map``
    and psums the partials before adding the intercept once).
    ``sv_t_*`` columns must be a multiple of ``sv_chunk``; padding
    columns must carry zero coefficients (their contribution is exactly
    zero — compile_svc's layout guarantees this)."""
    N, F = X.shape
    Sp = sv_t_hi.shape[1]
    P = coef_t.shape[1]
    if Sp % sv_chunk:
        raise ValueError(
            f"support columns {Sp} not a multiple of chunk {sv_chunk}"
        )
    gamma = jnp.reshape(gamma.astype(jnp.float32), (1, 1))

    padded = (-N) % row_tile
    if padded:
        X = jnp.concatenate([X, jnp.zeros((padded, F), X.dtype)])
        X_lo = jnp.concatenate([X_lo, jnp.zeros((padded, F), X_lo.dtype)])
    n_tiles = X.shape[0] // row_tile
    n_chunks = Sp // sv_chunk

    kernel = functools.partial(_kernel, n_features=F)
    out = pl.pallas_call(
        kernel,
        grid=(n_tiles, n_chunks),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # gamma (1,1)
            pl.BlockSpec((row_tile, F), lambda i, s: (i, 0)),
            pl.BlockSpec((row_tile, F), lambda i, s: (i, 0)),
            pl.BlockSpec((F, sv_chunk), lambda i, s: (0, s)),
            pl.BlockSpec((F, sv_chunk), lambda i, s: (0, s)),
            pl.BlockSpec((sv_chunk, P), lambda i, s: (s, 0)),
        ],
        out_specs=pl.BlockSpec((row_tile, P), lambda i, s: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((X.shape[0], P), jnp.float32),
        interpret=interpret,
    )(gamma, X, X_lo, sv_t_hi, sv_t_lo, coef_t)
    return out[:N]


@functools.partial(jax.jit, static_argnames=("interpret",))
def decision_ovo_pallas(
    g: SvcPallas, X: jax.Array, X_lo=None, interpret: bool = False
) -> jax.Array:
    """Per-pair ovo decision values, (N, P) — fused kernel version of
    models/svc.decision_ovo."""
    if X_lo is None:
        X_lo = jnp.zeros_like(X)
    out = partial_decision(
        X, X_lo, g.gamma, g.sv_t_hi, g.sv_t_lo, g.coef_t,
        row_tile=g.row_tile, sv_chunk=g.sv_chunk, interpret=interpret,
    )
    return out + g.intercept[None, :]


def scores(g: SvcPallas, X, X_lo=None, interpret: bool = False) -> jax.Array:
    """Vote counts per class, (N, C) — same ovo aggregation as models/svc."""
    D = decision_ovo_pallas(g, X, X_lo, interpret=interpret)
    pos = D > 0
    votes_i = jax.nn.one_hot(g.vote_i, g.n_classes, dtype=D.dtype)
    votes_j = jax.nn.one_hot(g.vote_j, g.n_classes, dtype=D.dtype)
    return jnp.where(pos[:, :, None], votes_i, votes_j).sum(axis=1)


def predict(g: SvcPallas, X, X_lo=None, interpret: bool = False) -> jax.Array:
    return jnp.argmax(scores(g, X, X_lo, interpret=interpret), axis=-1).astype(
        jnp.int32
    )
