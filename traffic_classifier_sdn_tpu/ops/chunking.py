"""Row-chunked mapping over big batches — shared by every predictor whose
intermediate would not fit HBM in one shot (tree_gemm's (N, T·D)
comparison matrix, svc's (N, S) kernel matrix).

``lax.map`` keeps the loop on device with ONE compiled body per chunk
shape; the remainder rows run as a second, smaller program rather than
padding (the two shapes are stable across calls, so XLA compiles each
once).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def chunked_predict(predict_fn, row_chunk: int, X, X_lo=None):
    """Row-chunked wrapper for the ``predict(params-bound, X, X_lo=None)``
    family (SVC, KNN): dispatches the lo-less mode over X alone — a zeros
    X_lo would be semantically identical but costs an extra broadcast
    pass over the dominant distance stage, and XLA cannot fold a traced
    map operand."""
    if X_lo is None:
        return map_row_chunks(lambda xc: predict_fn(xc), row_chunk, X)
    return map_row_chunks(
        lambda xc, xlo: predict_fn(xc, xlo), row_chunk, X, X_lo
    )


def map_row_chunks(fn, chunk: int, X, *rest):
    """Apply ``fn(X_slice, *rest_slices)`` over ``chunk``-row slices and
    concatenate along axis 0. ``rest`` arrays must share X's leading
    dimension. Calls ``fn`` directly when the batch fits one chunk."""
    N = X.shape[0]
    chunk = min(chunk, N)
    if N <= chunk:
        return fn(X, *rest)
    arrays = (X, *rest)
    n_chunks, rem = divmod(N, chunk)
    main = tuple(
        a[: n_chunks * chunk].reshape(n_chunks, chunk, *a.shape[1:])
        for a in arrays
    )
    out = lax.map(lambda t: fn(*t), main)
    out = out.reshape(n_chunks * chunk, *out.shape[2:])
    if rem:
        tail = fn(*(a[n_chunks * chunk:] for a in arrays))
        out = jnp.concatenate([out, tail])
    return out
