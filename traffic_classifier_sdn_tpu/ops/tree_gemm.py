"""GEMM-form decision-forest evaluation — the MXU-native tree kernel.

The lockstep gather traversal (ops/tree_eval.py) is fine on CPU but
pathological on TPU: per-(sample, tree) index chasing compiles to serialized
gathers (measured ~7.8 s for a 131k-row batch — ~1000× slower than the
matmuls below). This module re-expresses the entire ensemble as three
matrix products, after Hummingbird's GEMM strategy (PAPERS.md), with exact
semantics:

  1. node comparisons:  cmp = (X @ A ≤ B)           A: one-hot feature
     selector (F, T·D) — column selection via matmul is exact; cmp ∈ {0,1}
  2. path aggregation:  S = pm @ P  where pm = 2·cmp−1 ∈ {−1,+1} and
     P (T·D, L) holds +1/−1/0 for left/right/absent ancestor edges.
     A leaf l is reached iff S[l] == depth[l] (every ancestor agreed).
     All values are small integers, exact in bf16 → full MXU speed.
  3. distribution select: probs = match @ V, match ∈ {0,1}, V (T·L, C) the
     per-leaf normalized class distributions — one row selected per tree.

Row-chunking bounds the (N, T·D) intermediates; everything else is
shape-static for XLA. Padded node/leaf slots use a depth sentinel (127) so
they can never match.

Argmax parity with the gather traversal (and hence sklearn) is tested on
the reference checkpoint + datasets.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct
from jax import lax

_HI = lax.Precision.HIGHEST


def _reachable_nodes(left, right, t: int) -> list[int]:
    """Nodes reachable from tree t's root (skips the importer's padding,
    which has ``left == -1`` and is unreachable): BFS from node 0."""
    reach = [0]
    seen = {0}
    for n in reach:
        if left[t, n] != -1:
            for ch in (int(left[t, n]), int(right[t, n])):
                if ch not in seen:
                    seen.add(ch)
                    reach.append(ch)
    return reach


class ForestGemm(struct.PyTreeNode):
    feat_onehot: jax.Array  # (F, T*D) f32 one-hot feature selector
    thresholds: jax.Array  # (T*D,) f32 (+inf at padded node slots)
    path: jax.Array  # (T, D, L) bf16 per-tree ±1/0 ancestor-edge matrices
    leaf_depth: jax.Array  # (T, L) f32 (127 at padded leaf slots)
    leaf_values: jax.Array  # (T, L, C) f32 normalized distributions / T
    n_classes: int = struct.field(pytree_node=False)
    row_chunk: int = struct.field(pytree_node=False)


def build_gemm_operands(d: dict, n_features: int | None = None,
                        n_trees_total: int | None = None) -> dict:
    """Extract per-tree GEMM operands (numpy) from importer node arrays
    (io/sklearn_import.import_forest format). Shared by the XLA GEMM path
    below and the fused Pallas kernel (ops/pallas_forest.py).

    ``n_features`` must match the width of the X the forest will see; it
    defaults to the importer dict's value, else the widest feature id used
    by any split. ``n_trees_total`` sets the ensemble-mean divisor when
    ``d`` holds only a subset of the forest (size-bucketed compilation):
    per-leaf values are divided by the FULL tree count so group
    contributions sum to the ensemble mean."""
    left, right = d["left"], d["right"]
    feature, threshold, values = d["feature"], d["threshold"], d["values"]
    n_trees, M = left.shape
    n_classes = values.shape[2]
    if n_features is None:
        n_features = int(d.get("n_features", int(np.max(feature)) + 1))

    per_tree = []
    D_max = L_max = 0
    for t in range(n_trees):
        # node_count = nodes before padding (padding has left == -1 and zero
        # values; real leaves also have left == -1 but nonzero values)
        internal = []
        leaves = []
        # reconstruct parents to walk ancestor paths
        parent = {}
        for n in range(M):
            if left[t, n] != -1:
                parent[int(left[t, n])] = (n, +1)
                parent[int(right[t, n])] = (n, -1)
        reach = _reachable_nodes(left, right, t)
        node_slot = {}
        for n in reach:
            if left[t, n] != -1:
                node_slot[n] = len(internal)
                internal.append(n)
            else:
                leaves.append(n)
        # ancestor paths per leaf
        paths = []
        for leaf in leaves:
            edges = []
            n = leaf
            while n in parent:
                p, sign = parent[n]
                edges.append((node_slot[p], sign))
                n = p
            paths.append(edges)
        per_tree.append((internal, leaves, paths))
        D_max = max(D_max, max(len(internal), 1))
        L_max = max(L_max, len(leaves))

    TD = n_trees * D_max
    feat_onehot = np.zeros((n_features, TD), np.float32)
    thresholds = np.full(TD, np.inf, np.float64)
    path = np.zeros((n_trees, D_max, L_max), np.float32)
    leaf_depth = np.full((n_trees, L_max), 127.0, np.float32)
    leaf_values = np.zeros((n_trees, L_max, n_classes), np.float32)

    from ..io.sklearn_import import f32_safe_thresholds

    divisor = n_trees_total if n_trees_total is not None else n_trees
    for t, (internal, leaves, paths) in enumerate(per_tree):
        for s, n in enumerate(internal):
            col = t * D_max + s
            feat_onehot[feature[t, n], col] = 1.0
            thresholds[col] = threshold[t, n]
        for s, (leaf, edges) in enumerate(zip(leaves, paths)):
            leaf_depth[t, s] = len(edges)
            v = values[t, leaf]
            tot = v.sum()
            if tot > 0:
                leaf_values[t, s] = v / tot / divisor
            for node_s, sign in edges:
                path[t, node_s, s] = sign

    # f32 round-down keeps every decision identical to sklearn's
    # f32-feature vs f64-threshold comparison (io/sklearn_import).
    finite = np.isfinite(thresholds)
    thr32 = np.full(TD, np.inf, np.float32)
    thr32[finite] = f32_safe_thresholds(thresholds[finite])
    thresholds = thr32

    return {
        "feat_onehot": feat_onehot,  # (F, T*D)
        "thresholds": thresholds,  # (T*D,)
        "path": path,  # (T, D, L)
        "leaf_depth": leaf_depth,  # (T, L)
        "leaf_values": leaf_values,  # (T, L, C), pre-divided by T
        "n_trees": n_trees,
        "n_internal": D_max,
        "n_leaves": L_max,
        "n_classes": n_classes,
        "n_features": n_features,
    }


class ForestGemmGroups(struct.PyTreeNode):
    """Size-bucketed ensemble: trees sorted by D·L and split into groups,
    each padded only to ITS max (D, L). The reference checkpoint's trees
    range 12–50 internal nodes, so uniform padding wastes 3.4× of the
    stage-2 FLOPs and 1.9× of the (N, T·D) HBM intermediate; four buckets
    recover most of both. Group leaf values are pre-divided by the FULL
    tree count, so summing group probabilities yields the ensemble mean."""

    groups: tuple  # of ForestGemm
    n_classes: int = struct.field(pytree_node=False)


def dtyped_operands(ops: dict) -> dict:
    """Device arrays with the canonical GEMM dtypes — the ONE dtype
    policy (path bf16: ±1 ancestor-edge sums of ints ≤ depth are exact;
    everything else f32). ``_single_group`` and the tree-sharded layout
    (parallel/forest_sharded.gemm_sharded_predict) both build through
    it, so the exactness argument cannot drift between paths."""
    return {
        "feat_onehot": jnp.asarray(ops["feat_onehot"]),
        "thresholds": jnp.asarray(ops["thresholds"]),
        "path": jnp.asarray(ops["path"], jnp.bfloat16),
        "leaf_depth": jnp.asarray(ops["leaf_depth"]),
        "leaf_values": jnp.asarray(ops["leaf_values"]),
    }


def _single_group(ops: dict, row_chunk: int) -> ForestGemm:
    return ForestGemm(
        **dtyped_operands(ops),
        n_classes=ops["n_classes"],
        row_chunk=row_chunk,
    )


def _tree_sizes(d: dict) -> np.ndarray:
    """Per-tree (internal·leaf) size product — the stage-2 FLOP weight."""
    left, right = d["left"], d["right"]
    sizes = []
    for t in range(left.shape[0]):
        reach = _reachable_nodes(left, right, t)
        D = sum(1 for n in reach if left[t, n] != -1)
        sizes.append(D * (len(reach) - D))
    return np.asarray(sizes)


def split_tree_buckets(
    d: dict, n_buckets: int, n_features: int | None = None
) -> list[tuple[dict, int, int]]:
    """Partition an importer forest dict into size buckets for independent
    compilation (shared by the XLA GEMM path and the fused Pallas kernel):
    trees sorted by their D·L stage-2 FLOP weight, split into
    ``n_buckets`` equal-count groups. Returns
    ``[(sub_dict, n_features, n_trees_total), ...]`` — feature width is
    resolved ONCE over the whole forest (a per-bucket fallback would infer
    mismatched feat_onehot widths from each subset's own max split
    feature), and the total tree count is the ensemble-mean divisor every
    bucket must share."""
    n_trees = d["left"].shape[0]
    n_buckets = max(1, min(n_buckets, n_trees))
    if n_features is None:
        n_features = int(
            d.get("n_features", int(np.max(d["feature"])) + 1)
        )
    if n_buckets == 1:
        return [(d, n_features, n_trees)]
    order = np.argsort(_tree_sizes(d), kind="stable")
    tree_keys = ("left", "right", "feature", "threshold", "values")
    out = []
    for part in np.array_split(order, n_buckets):
        if part.size == 0:
            continue
        sub = dict(d)
        for k in tree_keys:
            sub[k] = d[k][part]
        out.append((sub, n_features, n_trees))
    return out


def compile_forest(
    d: dict, row_chunk: int = 32768, n_features: int | None = None,
    n_buckets: int = 8,
) -> ForestGemm | ForestGemmGroups:
    """Build device GEMM operands from importer node arrays.

    ``n_buckets > 1`` splits the trees into size buckets (sorted by D·L,
    equal tree counts) compiled independently — the same ensemble mean up
    to f32 group-sum reassociation (argmax parity vs the golden traversal
    is test- and bench-gated), substantially less padding FLOPs/traffic on
    heterogeneous forests (3.4×/1.9× on the reference checkpoint).
    """
    buckets = split_tree_buckets(d, n_buckets, n_features)
    groups = [
        _single_group(
            build_gemm_operands(sub, n_features=nf, n_trees_total=nt),
            row_chunk,
        )
        for sub, nf, nt in buckets
    ]
    if len(groups) == 1:
        return groups[0]
    return ForestGemmGroups(
        groups=tuple(groups), n_classes=groups[0].n_classes
    )


def _proba_chunk(g: ForestGemm, X: jax.Array) -> jax.Array:
    T, D, L = g.path.shape
    # 1. all node comparisons at once (exact column selection by matmul)
    xf = jnp.matmul(X, g.feat_onehot, precision=_HI)  # (n, T*D)
    pm = jnp.where(xf <= g.thresholds[None, :], 1.0, -1.0).astype(jnp.bfloat16)
    pm = jnp.moveaxis(pm.reshape(-1, T, D), 1, 0)  # (T, n, D)
    # 2. per-tree path aggregation — ±1 sums of ints ≤ depth, exact in bf16;
    # batched per-tree matmuls avoid the 100× FLOP waste of one
    # block-diagonal GEMM
    S = lax.dot_general(
        pm, g.path,
        dimension_numbers=(((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )  # (T, n, L)
    match = (S == g.leaf_depth[:, None, :]).astype(jnp.float32)
    # 3. one selected leaf distribution per tree, summed across trees
    per_tree = lax.dot_general(
        match, g.leaf_values,
        dimension_numbers=(((2,), (1,)), ((0,), (0,))),
        precision=_HI,
    )  # (T, n, C)
    return jnp.sum(per_tree, axis=0)


def forest_proba_gemm(
    g: ForestGemm | ForestGemmGroups, X: jax.Array
) -> jax.Array:
    """(N, C) ensemble-mean class distributions, row-chunked."""
    from .chunking import map_row_chunks

    if isinstance(g, ForestGemmGroups):
        out = forest_proba_gemm(g.groups[0], X)
        for sub in g.groups[1:]:
            out = out + forest_proba_gemm(sub, X)
        return out
    return map_row_chunks(lambda xc: _proba_chunk(g, xc), g.row_chunk, X)


def predict(g: ForestGemm | ForestGemmGroups, X: jax.Array) -> jax.Array:
    return jnp.argmax(forest_proba_gemm(g, X), axis=-1).astype(jnp.int32)


# --------------------------------------------------------------------------
# v2: traffic-lean transposed formulation
#
# The v1 path above is HBM-bound, not FLOP-bound: per classified row it
# materializes ~40 kB of intermediates (xf f32, pm bf16 + its transpose,
# S f32, match f32) — ~5.3 GB for a 131k batch, which at ~1 TB/s accounts
# for essentially all of the measured 6 ms (VERDICT r3 weak item 5). v2
# attacks the traffic, keeping semantics bit-exact:
#
#   - everything runs in a transposed (..., n) layout so no large
#     intermediate is ever physically transposed: X.T is (12, n), tiny;
#     reshapes (T*D, n) -> (T, D, n) are free (contiguous split).
#   - stage 1 drops the f32 one-hot matmul (HIGHEST-precision f32 on MXU
#     is ~6 bf16 passes) for a static row-gather of X.T + compare, whose
#     epilogue writes pm as INT8 (T*D bytes/row instead of 4*T*D + the
#     bf16 copy). Identical decisions: same f32 X vs f32-safe thresholds.
#   - stage 2 is an int8 x int8 -> int32 MXU matmul: path entries are
#     -1/0/+1 and |S| <= D <= 50 < 127, so int8 operands are exact and
#     run at 2x the bf16 MXU rate.
#   - stage 3 selects the matched leaf's distribution either by matmul
#     ("dot": match {0,1} x leaf_values f32, exact one-row selection) or
#     by argmax-leaf + per-tree gather ("gather": S==depth never has two
#     true leaves, the table is ~150 kB and VMEM-resident). The two are
#     raced on chip; both are exact selections, differing only in HBM
#     traffic shape (12.6 kB/row of match f32 vs 0.4 kB of leaf ids).
#
# Reference semantics unchanged from v1 (traffic_classifier.py:103-106's
# per-flow sklearn predict); argmax parity is gated in tests and bench.
# --------------------------------------------------------------------------


class ForestGemmV2(struct.PyTreeNode):
    feat_ids: jax.Array  # (T*D,) int32 feature id per node slot (0 if pad)
    thresholds: jax.Array  # (T*D, 1) f32, +inf at padded node slots
    path_t: jax.Array  # (T, L, D) int8 ±1/0 ancestor-edge matrices
    leaf_depth: jax.Array  # (T, L, 1) int32 (127 at padded leaf slots)
    leaf_values: jax.Array  # (T, L, C) f32 distributions / T_total
    leaf_values_t: jax.Array  # (T, C, L) f32 (stage-3 "dot" operand)
    n_classes: int = struct.field(pytree_node=False)
    row_chunk: int = struct.field(pytree_node=False)
    stage3: str = struct.field(pytree_node=False)  # "dot" | "gather"


class ForestGemmV2Groups(struct.PyTreeNode):
    groups: tuple  # of ForestGemmV2
    n_classes: int = struct.field(pytree_node=False)


def _single_group_v2(ops: dict, row_chunk: int, stage3: str) -> ForestGemmV2:
    T, D, L = ops["path"].shape
    # feat_onehot is (F, T*D) with at most one 1 per column; padded node
    # slots have an all-zero column -> argmax 0, harmless under +inf thr
    feat_ids = np.argmax(ops["feat_onehot"], axis=0).astype(np.int32)
    lv = ops["leaf_values"]
    return ForestGemmV2(
        feat_ids=jnp.asarray(feat_ids),
        thresholds=jnp.asarray(ops["thresholds"])[:, None],
        path_t=jnp.asarray(
            np.moveaxis(ops["path"], 1, 2).astype(np.int8)
        ),
        leaf_depth=jnp.asarray(
            ops["leaf_depth"].astype(np.int32)
        )[:, :, None],
        leaf_values=jnp.asarray(lv),
        leaf_values_t=jnp.asarray(np.moveaxis(lv, 1, 2)),
        n_classes=ops["n_classes"],
        row_chunk=row_chunk,
        stage3=stage3,
    )


def compile_forest_v2(
    d: dict, row_chunk: int = 32768, n_features: int | None = None,
    n_buckets: int = 8, stage3: str = "dot",
) -> ForestGemmV2 | ForestGemmV2Groups:
    """v2 operands from importer node arrays; same size-bucketing as
    :func:`compile_forest` (group sums share the full-ensemble divisor)."""
    buckets = split_tree_buckets(d, n_buckets, n_features)
    groups = [
        _single_group_v2(
            build_gemm_operands(sub, n_features=nf, n_trees_total=nt),
            row_chunk, stage3,
        )
        for sub, nf, nt in buckets
    ]
    if len(groups) == 1:
        return groups[0]
    return ForestGemmV2Groups(
        groups=tuple(groups), n_classes=groups[0].n_classes
    )


def _proba_chunk_v2(g: ForestGemmV2, Xt: jax.Array) -> jax.Array:
    """(C, n) ensemble contribution for one transposed chunk (F, n)."""
    T, L, D = g.path_t.shape
    # 1. node comparisons: static row-gather of X.T (reads a 12-row
    # table, writes int8) — no matmul, no transpose of anything large
    xg = Xt[g.feat_ids]  # (T*D, n) f32
    pm = jnp.where(xg <= g.thresholds, jnp.int8(1), jnp.int8(-1))
    pm = pm.reshape(T, D, -1)  # contiguous split: free
    # 2. ±1 path aggregation on the MXU in int8 (exact: |S| <= D <= 50)
    S = lax.dot_general(
        g.path_t, pm,
        dimension_numbers=(((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.int32,
    )  # (T, L, n)
    match = S == g.leaf_depth  # (T, L, n) bool: exactly one true leaf/tree
    if g.stage3 == "gather":
        # 3a. leaf id per (tree, row) then per-tree distribution lookup —
        # (T, n) int32 + (T, n, C) f32 of traffic, no stage-3 FLOPs
        leaf = jnp.argmax(match, axis=1)  # (T, n)
        vals = jax.vmap(lambda lv, li: lv[li])(g.leaf_values, leaf)
        return jnp.sum(vals, axis=0).T  # (C, n)
    # 3b. exact one-row selection by matmul
    per_tree = lax.dot_general(
        g.leaf_values_t, match.astype(jnp.float32),
        dimension_numbers=(((2,), (1,)), ((0,), (0,))),
        precision=_HI,
    )  # (T, C, n)
    return jnp.sum(per_tree, axis=0)


def forest_proba_gemm_v2(
    g: ForestGemmV2 | ForestGemmV2Groups, X: jax.Array
) -> jax.Array:
    """(N, C) ensemble-mean class distributions via the v2 layout."""
    from .chunking import map_row_chunks

    groups = g.groups if isinstance(g, ForestGemmV2Groups) else (g,)

    def chunk(xc: jax.Array) -> jax.Array:
        Xt = xc.T  # (F, n): the only transpose, 48 B/row
        out = _proba_chunk_v2(groups[0], Xt)
        for sub in groups[1:]:
            out = out + _proba_chunk_v2(sub, Xt)
        return out.T  # (n, C)

    return map_row_chunks(chunk, groups[0].row_chunk, X)


def predict_v2(
    g: ForestGemmV2 | ForestGemmV2Groups, X: jax.Array
) -> jax.Array:
    return jnp.argmax(forest_proba_gemm_v2(g, X), axis=-1).astype(jnp.int32)
