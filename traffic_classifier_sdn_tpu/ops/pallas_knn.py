"""Fused Pallas TPU kernel for brute-force KNN: distances + running top-k
in one pass, the (N, S) similarity matrix never leaving VMEM.

Why: every XLA top-k variant in models/knn.py (sort network, k argmax
passes, hierarchical grouped selection) first materializes the (N, S)
similarity matrix in HBM and then reads it back at least once — ~2.2 GB of
round-trip traffic for a 64k batch against the reference's 4448-row corpus
(models/KNeighbors, k=5, loaded at traffic_classifier.py:234-236), and the
k-argmax variant reads it k times. This kernel computes each (row-tile ×
corpus-chunk) similarity tile on the MXU, extracts the tile's top-k with k
max+mask passes on the VPU, and merges it into a VMEM-resident running
top-k carry — HBM traffic collapses to: read X once, stream the (F, S)
corpus per row tile (~0.2 MB), write (N, k) neighbor indices once.

Exactness, including tie order (the property every KNN path in this repo
holds to): corpus chunks are CONTIGUOUS ascending index ranges walked in
ascending grid order, the in-tile extraction takes the FIRST maximum
(lowest lane index) per pass, and the carry/tile merge ranks candidates by
(value desc, global index asc) with carry — whose indices are all smaller —
winning value ties. That is the same total order ``lax.top_k`` produces
over the full row (same argument as models/knn.py::_topk_hier_idx and the
big-corpus scan), asserted bitwise in tests/test_pallas_knn.py.

Similarity is the dot-expansion form of models/knn.py::_dot_expansion_sim
(argmin ‖x−s‖² == argmax x·s − ½‖s‖², precision=HIGHEST), i.e. the same
numerics as the serving fast path; the two-float exact form stays on the
XLA paths.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..models import knn


class KnnPallas(struct.PyTreeNode):
    fit_t: jax.Array  # (F, Sp) corpus transposed, f32, zero-padded cols
    half_sq: jax.Array  # (1, Sp) ½‖s‖²; +inf on padded cols (they lose)
    fit_y: jax.Array  # (S,) int32 class indices (unpadded)
    n_rows: int = struct.field(pytree_node=False)  # real corpus rows S
    n_neighbors: int = struct.field(pytree_node=False)
    n_classes: int = struct.field(pytree_node=False)
    row_tile: int = struct.field(pytree_node=False)
    corpus_chunk: int = struct.field(pytree_node=False)


def corpus_layout(fit_X, half_sq_norms, padded_rows: int):
    """The kernel's pre-laid operands for ``padded_rows`` total corpus
    slots: ``((F, padded) fit_t, (1, padded) half_sq)``, transposed so
    the per-chunk similarity is one (TILE, F)·(F, CHUNK) MXU dot,
    padding slots carrying +inf half-norms (their similarity is −inf,
    losing every comparison; S ≥ k real rows always exist, so no padded
    index can survive the final merge). The ONE home of that invariant —
    ``compile_knn`` and the state-sharded layout
    (parallel/knn_sharded.fused_predict) both build through it."""
    fit = np.asarray(fit_X, np.float32)
    half = np.asarray(half_sq_norms, np.float32)
    pad = padded_rows - fit.shape[0]
    if pad:
        fit = np.concatenate([fit, np.zeros((pad, fit.shape[1]), np.float32)])
        half = np.concatenate([half, np.full((pad,), np.inf, np.float32)])
    return jnp.asarray(fit.T), jnp.asarray(half[None, :])


def compile_knn(
    params: knn.Params, row_tile: int = 512, corpus_chunk: int = 512
) -> KnnPallas:
    """Re-lay a models/knn.Params for the fused kernel: S padded to a
    chunk multiple (+inf-half-norm padding — see ``corpus_layout``)."""
    if params.n_neighbors > corpus_chunk:
        # topk_sim_idx re-checks at call time; failing here gives the
        # error at layout time, before any padding work
        raise ValueError(
            f"corpus_chunk={corpus_chunk} must be >= "
            f"n_neighbors={params.n_neighbors}"
        )
    if params.n_neighbors > 128:
        raise ValueError(
            f"n_neighbors={params.n_neighbors} exceeds the kernel's "
            f"128-lane top-k carry"
        )
    S = np.asarray(params.fit_X).shape[0]
    if S < params.n_neighbors:
        # The no-padded-index-survives invariant (corpus_layout) requires
        # >= k real rows; with fewer, padded +inf-half-norm slots reach
        # the final top-k and fit_y[idx] silently clamps to wrong labels
        # where the XLA path's lax.top_k fails loudly. Enforce, don't
        # assume.
        raise ValueError(
            f"corpus has {S} rows < n_neighbors={params.n_neighbors}"
        )
    fit_t, half_sq = corpus_layout(
        params.fit_X, params.half_sq_norms, S + (-S) % corpus_chunk
    )
    return KnnPallas(
        fit_t=fit_t,
        half_sq=half_sq,
        fit_y=params.fit_y,
        n_rows=S,
        n_neighbors=int(params.n_neighbors),
        n_classes=int(params.n_classes),
        row_tile=row_tile,
        corpus_chunk=corpus_chunk,
    )


def _kernel(x_ref, fitt_ref, half_ref, out_ref, outv_ref, vs_ref, is_ref,
            *, k: int, chunk: int, n_chunks: int):
    s = pl.program_id(1)

    @pl.when(s == 0)
    def _():  # new row tile: reset the running top-k carry
        vs_ref[:] = jnp.full_like(vs_ref, -jnp.inf)
        is_ref[:] = jnp.zeros_like(is_ref)

    # similarity tile: one MXU dot (argmax order == ascending distance);
    # precision matches models/knn._dot_expansion_sim
    sim = (
        jnp.dot(
            x_ref[:],
            fitt_ref[:],
            preferred_element_type=jnp.float32,
            precision=lax.Precision.HIGHEST,
        )
        - half_ref[:]
    )  # (TILE, CHUNK)
    lane = lax.broadcasted_iota(jnp.int32, sim.shape, 1)

    # in-tile top-k: k max+mask passes; FIRST maximum (lowest lane) per
    # pass — lax.top_k's tie order within the chunk
    tile_v, tile_i = [], []
    for _ in range(k):
        m = jnp.max(sim, axis=1, keepdims=True)  # (TILE, 1)
        idx = jnp.min(
            jnp.where(sim == m, lane, chunk), axis=1, keepdims=True
        )
        tile_v.append(m)
        tile_i.append(idx + s * chunk)  # global corpus index
        sim = jnp.where(lane == idx, -jnp.inf, sim)

    carry_v = [vs_ref[:, j : j + 1] for j in range(k)]
    carry_i = [is_ref[:, j : j + 1] for j in range(k)]

    # merge two descending k-lists into one: rank by (value desc, global
    # index asc). Carry indices are all < tile indices (earlier chunks),
    # so carry wins value ties — strict '>' one way, '>=' the other.
    one = jnp.ones_like(tile_v[0], jnp.int32)
    zero = jnp.zeros_like(one)
    rank_c = []  # final rank of carry_v[i]
    for i in range(k):
        r = zero + i
        for j in range(k):
            r = r + jnp.where(tile_v[j] > carry_v[i], one, zero)
        rank_c.append(r)
    rank_t = []  # final rank of tile_v[j]
    for j in range(k):
        r = zero + j
        for i in range(k):
            r = r + jnp.where(carry_v[i] >= tile_v[j], one, zero)
        rank_t.append(r)

    new_v, new_i = [], []
    for r in range(k):
        acc_v = jnp.full_like(tile_v[0], -jnp.inf)
        acc_i = jnp.zeros_like(tile_i[0])
        for i in range(k):
            hit = rank_c[i] == r
            acc_v = jnp.where(hit, carry_v[i], acc_v)
            acc_i = jnp.where(hit, carry_i[i], acc_i)
        for j in range(k):
            hit = rank_t[j] == r
            acc_v = jnp.where(hit, tile_v[j], acc_v)
            acc_i = jnp.where(hit, tile_i[j], acc_i)
        new_v.append(acc_v)
        new_i.append(acc_i)

    for r in range(k):
        vs_ref[:, r : r + 1] = new_v[r]
        is_ref[:, r : r + 1] = new_i[r]

    @pl.when(s == n_chunks - 1)
    def _():
        out_ref[:] = jnp.concatenate(new_i, axis=1)  # (TILE, k)
        outv_ref[:] = jnp.concatenate(new_v, axis=1)  # (TILE, k)


def topk_sim_idx(
    X: jax.Array, fit_t: jax.Array, half_sq: jax.Array, k: int,
    row_tile: int = 512, corpus_chunk: int = 512, interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """((N, k) similarities, (N, k) indices) of the k most-similar corpus
    columns — descending, ties to the lowest index, bitwise what
    ``lax.top_k`` over the full similarity row returns. Traceable
    building block: operands are the PRE-LAID arrays of a ``KnnPallas``
    (or one shard of them — parallel/knn_sharded.py calls this per
    device inside ``shard_map``, where numpy re-layout is impossible).
    ``fit_t`` columns must be a multiple of ``corpus_chunk``."""
    N, F = X.shape
    Sp = fit_t.shape[1]
    if Sp % corpus_chunk:
        raise ValueError(
            f"corpus columns {Sp} not a multiple of chunk {corpus_chunk}"
        )
    if k > corpus_chunk:
        raise ValueError(
            f"corpus_chunk={corpus_chunk} must be >= k={k}"
        )
    if k > 128:
        # the kernel's carry scratch holds one lane per neighbor
        raise ValueError(f"k={k} exceeds the kernel's 128-lane carry")

    padded = (-N) % row_tile
    if padded:
        X = jnp.concatenate([X, jnp.zeros((padded, F), X.dtype)])
    n_tiles = X.shape[0] // row_tile
    n_chunks = Sp // corpus_chunk

    kernel = functools.partial(
        _kernel, k=k, chunk=corpus_chunk, n_chunks=n_chunks
    )
    idx, vals = pl.pallas_call(
        kernel,
        grid=(n_tiles, n_chunks),
        in_specs=[
            pl.BlockSpec((row_tile, F), lambda i, s: (i, 0)),
            pl.BlockSpec((F, corpus_chunk), lambda i, s: (0, s)),
            pl.BlockSpec((1, corpus_chunk), lambda i, s: (0, s)),
        ],
        out_specs=[
            pl.BlockSpec((row_tile, k), lambda i, s: (i, 0)),
            pl.BlockSpec((row_tile, k), lambda i, s: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((X.shape[0], k), jnp.int32),
            jax.ShapeDtypeStruct((X.shape[0], k), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((row_tile, 128), jnp.float32),  # carry values
            pltpu.VMEM((row_tile, 128), jnp.int32),  # carry global idx
        ],
        interpret=interpret,
    )(X.astype(jnp.float32), fit_t, half_sq)
    return vals[:N], idx[:N]


@functools.partial(jax.jit, static_argnames=("interpret",))
def neighbor_idx(
    g: KnnPallas, X: jax.Array, interpret: bool = False
) -> jax.Array:
    """(N, k) global indices of the k nearest corpus rows, descending
    similarity, ties to the lowest index — bitwise what ``lax.top_k``
    over the full similarity row returns."""
    _, idx = topk_sim_idx(
        X, g.fit_t, g.half_sq, g.n_neighbors,
        row_tile=g.row_tile, corpus_chunk=g.corpus_chunk,
        interpret=interpret,
    )
    return idx


def scores(g: KnnPallas, X, X_lo=None, interpret: bool = False) -> jax.Array:
    """(N, C) neighbor class counts — models/knn.neighbor_votes semantics.
    ``X_lo`` is accepted for serving-signature compatibility and must be
    None: the fused kernel computes the fast dot-expansion form only (the
    exact two-float path stays on XLA)."""
    if X_lo is not None:
        raise ValueError("pallas knn kernel has no two-float mode")
    idx = neighbor_idx(g, X, interpret=interpret)
    return knn.count_votes(g.fit_y, g.n_classes, idx)


def predict(g: KnnPallas, X, X_lo=None, interpret: bool = False) -> jax.Array:
    return jnp.argmax(
        scores(g, X, X_lo, interpret=interpret), axis=-1
    ).astype(jnp.int32)


def predict_chunked(
    g: KnnPallas, X, X_lo=None, row_chunk: int = 65536,
    interpret: bool = False,
) -> jax.Array:
    """Row-chunked predict for serving-size batches (same dispatch as the
    XLA families; the kernel's own tiling bounds VMEM, this bounds the
    (N, k) gather/vote intermediates)."""
    from .chunking import chunked_predict

    if X_lo is not None:
        raise ValueError("pallas knn kernel has no two-float mode")
    return chunked_predict(
        lambda xc: predict(g, xc, interpret=interpret), row_chunk, X
    )
