"""Fused Pallas TPU kernel for GEMM-form forest evaluation.

The XLA GEMM path (ops/tree_gemm.py) is memory-bound: the (N, T·D)
comparison matrix and (T, N, L) path-score tensor round-trip through HBM
(~100 GB of traffic per million-flow batch). This kernel fuses all three
stages in VMEM per row-tile × tree-chunk grid step:

    xf    = X_tile @ A_chunk            (MXU, exact column select)
    pm    = where(xf ≤ thr, +1, −1)     (VPU, bf16)
    S_k   = pm_k @ path_k               (MXU, small-int exact in bf16)
    match = (S_k == depth_k)            (VPU)
    acc  += match @ leaf_values_k       (MXU, f32 accumulate)

HBM traffic collapses to: read X once, write (N, C) probabilities once,
re-stream ~1 MB of tree operands per row tile. Grid iterates tree-chunks
fastest, so the output block stays resident and accumulates across chunks.

Semantics match tree_gemm (and hence sklearn predict_proba) exactly.
Coverage: tests/test_tree_kernels.py runs this kernel in interpreter mode
on CPU; compiled-on-TPU execution, argmax parity, and timing vs the XLA
GEMM path are exercised by ``bench.py`` (``pallas_forest_*`` fields in the
bench JSON) and by ``tools/tpu_proof.py``, which records the result in
``docs/artifacts/``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct
from jax.experimental import pallas as pl
from . import tree_gemm


class ForestPallas(struct.PyTreeNode):
    """Operands grouped tpd = 128//D trees at a time: ``path`` holds one
    BLOCK-DIAGONAL (gD, gL) = (tpd·D, tpd·L) score operand per group, so
    each score dot contracts a full 128-wide MXU tile; depth/values are
    the matching concatenations. G below is the group count T//tpd."""

    feat_onehot: jax.Array  # (F, G*gD) f32
    thresholds: jax.Array  # (1, G*gD) f32 (+inf padding)
    path: jax.Array  # (G, gD, gL) bf16, block-diagonal per group
    leaf_depth: jax.Array  # (G, gL) f32
    leaf_values: jax.Array  # (G, gL, C) f32 (pre-divided by total T)
    n_classes: int = struct.field(pytree_node=False)
    n_internal: int = struct.field(pytree_node=False)  # gD
    n_leaves: int = struct.field(pytree_node=False)  # gL
    row_tile: int = struct.field(pytree_node=False)
    tree_chunk: int = struct.field(pytree_node=False)  # chunk_g groups/step
    # one wide (TILE, chunk_g*gL) leaf GEMM per step when that buffer fits
    # VMEM comfortably; per-group accumulation otherwise
    fuse_leaf_gemm: bool = struct.field(pytree_node=False, default=True)
    # round-4 compute-shaping variant (chip-raced by bench.py):
    #   - stage 1 as THREE bf16 dots over the exact bf16x3 split of X
    #     (the one-hot operand is exactly bf16; every f32 splits exactly
    #     into three bf16 components, and each partial product lands in a
    #     disjoint bit range of the f32 accumulator) instead of one
    #     full-f32 dot (~6 MXU passes). PRECONDITION: features must be
    #     FINITE NORMAL f32 (no ±inf — bf16(±inf) makes the residual
    #     NaN; no finite values above bf16 max ~3.39e38; no subnormals
    #     below ~2^-126, which split to 0). The 12 flow features satisfy
    #     this by construction: counters are float32(u64) ≤ ~1.8e19 and
    #     rates are ratios of ints over whole seconds, so the fast path
    #     is exact on every input the serving spine can produce — but a
    #     caller feeding arbitrary floats must use the baseline variant;
    #   - stage 2 as int8 x int8 with int32 accumulation (path entries
    #     are -1/0/+1, pm is +-1: exact integer sums, 2x the bf16 MXU
    #     rate).
    fast_stages: bool = struct.field(pytree_node=False, default=False)


class ForestPallasGroups(struct.PyTreeNode):
    """Size-bucketed variant, mirroring tree_gemm.ForestGemmGroups: trees
    sorted by D·L and compiled per-bucket so each bucket's VMEM operands
    are padded only to its own (D, L) — smaller tree-chunk blocks for the
    small trees, less streamed traffic per row tile. Group leaf values are
    pre-divided by the FULL tree count; summing group probabilities gives
    the ensemble mean."""

    groups: tuple  # of ForestPallas
    n_classes: int = struct.field(pytree_node=False)


def compile_forest(
    d: dict, row_tile: int = 512, tree_chunk: int = 16, n_buckets: int = 1,
    fuse: bool | None = None, fast_stages: bool = False,
    n_features: int | None = None,
) -> ForestPallas | ForestPallasGroups:
    """``fuse`` overrides the VMEM-based choice of the wide leaf GEMM
    (None = automatic): forcing False is the safe fallback if a target's
    Mosaic build rejects the in-kernel concat/reshape the fused path
    uses. ``fast_stages`` enables the bf16x3 stage-1 / int8 stage-2
    variant (see ForestPallas) — semantically exact, raced on chip.
    ``n_features`` pins the selector width (required when the X the
    kernel will see is wider than the forest's max split feature, e.g.
    the fixed 12-column serving matrix)."""
    buckets = tree_gemm.split_tree_buckets(d, n_buckets, n_features)
    groups = [
        _compile_single(
            sub, row_tile, tree_chunk,
            n_features=nf, n_trees_total=nt, fuse=fuse,
            fast_stages=fast_stages,
        )
        for sub, nf, nt in buckets
    ]
    if len(groups) == 1:
        return groups[0]
    return ForestPallasGroups(
        groups=tuple(groups), n_classes=groups[0].n_classes
    )


def _compile_single(
    d: dict, row_tile: int, tree_chunk: int,
    n_features: int | None = None, n_trees_total: int | None = None,
    fuse: bool | None = None, fast_stages: bool = False,
) -> ForestPallas:
    ops = tree_gemm.build_gemm_operands(
        d, n_features=n_features, n_trees_total=n_trees_total
    )
    T, D, L = ops["n_trees"], ops["n_internal"], ops["n_leaves"]
    C = ops["n_classes"]
    F = ops["n_features"]
    # MXU shaping: a lone tree's score dot is (TILE, D) @ (D, L) with
    # D ≈ 64, L ≈ 56 — a quarter-occupied 128×128 MXU tile. Pack
    # tpd = 128//D trees into one BLOCK-DIAGONAL operand so every score
    # dot runs K = tpd·D = 128 (one full tile of contraction), and fuse
    # the per-tree (match @ leaf_values) dots into one wide K = TC·tpd·L
    # GEMM per grid step. D first pads to a power of two ≤ 128 (inert
    # columns: +inf threshold → pm=+1, zero path row → no score
    # contribution), which also satisfies the Mosaic block rule (last two
    # block dims divisible by (8, 128) or equal to the full dim).
    # power-of-two padding only pays below 65 internal nodes, where it
    # buys tpd >= 2 packing; above that tpd is 1 regardless, so a
    # 16-multiple (the Mosaic minimum once chunk_g is a multiple of 8)
    # wastes far fewer inert columns
    if D <= 64:
        Dp = max(8, 1 << (D - 1).bit_length())
    else:
        Dp = ((D + 15) // 16) * 16
    dpad = Dp - D
    if dpad:
        ops["feat_onehot"] = np.concatenate(
            [
                ops["feat_onehot"].reshape(F, T, D),
                np.zeros((F, T, dpad), np.float32),
            ],
            axis=2,
        ).reshape(F, T * Dp)
        ops["thresholds"] = np.concatenate(
            [
                ops["thresholds"].reshape(T, D),
                np.full((T, dpad), np.inf, np.float32),
            ],
            axis=1,
        ).reshape(-1)
        ops["path"] = np.concatenate(
            [ops["path"], np.zeros((T, dpad, L), np.float32)], axis=1
        )
        D = Dp
    tpd = max(1, 128 // D)  # trees per block-diagonal dot group
    # Grid chunking in GROUPS, honoring ``tree_chunk`` as the requested
    # trees per grid step. The (chunk_g, gL) depth block needs
    # chunk_g % 8 == 0 — unless chunk_g equals the whole group axis, so a
    # small or 8-indivisible group count runs as one grid step instead of
    # padding up to 7 inert groups (up to 7·tpd = 112 inert trees for
    # shallow-tree buckets).
    G_min = -(-T // tpd)
    if G_min < 8 or (G_min <= 32 and G_min % 8 != 0):
        chunk_g = G_min
    else:
        pref = max(8, ((max(1, -(-tree_chunk // tpd)) + 7) // 8) * 8)
        # honor the requested trees/step, but never at the cost of more
        # inert-group padding than the minimal 8-group chunking needs
        chunk_g = pref if (-G_min) % pref <= (-G_min) % 8 else 8
    # pad tree count so the group axis divides evenly (inert trees: zero
    # leaf_values contribute nothing; depth 127 never matches)
    pad = -(-G_min // chunk_g) * chunk_g * tpd - T
    if pad:
        ops["feat_onehot"] = np.concatenate(
            [
                ops["feat_onehot"].reshape(F, T, D),
                np.zeros((F, pad, D), np.float32),
            ],
            axis=1,
        ).reshape(F, (T + pad) * D)
        ops["thresholds"] = np.concatenate(
            [
                ops["thresholds"].reshape(T, D),
                np.full((pad, D), np.inf, np.float32),
            ]
        ).reshape(-1)
        ops["path"] = np.concatenate(
            [ops["path"], np.zeros((pad, D, L), np.float32)]
        )
        ops["leaf_depth"] = np.concatenate(
            [ops["leaf_depth"], np.full((pad, L), 127.0, np.float32)]
        )
        ops["leaf_values"] = np.concatenate(
            [ops["leaf_values"], np.zeros((pad, L, C), np.float32)]
        )
        T += pad
    G, gD, gL = T // tpd, tpd * D, tpd * L
    path_blk = np.zeros((G, gD, gL), np.float32)
    for g in range(G):
        for j in range(tpd):
            path_blk[g, j * D:(j + 1) * D, j * L:(j + 1) * L] = (
                ops["path"][g * tpd + j]
            )
    assert (chunk_g * gD) % 128 == 0 or chunk_g == G
    depth = ops["leaf_depth"].reshape(G, gL)
    return ForestPallas(
        feat_onehot=jnp.asarray(
            ops["feat_onehot"],
            jnp.bfloat16 if fast_stages else jnp.float32,
        ),
        thresholds=jnp.asarray(ops["thresholds"][None, :]),
        path=jnp.asarray(
            path_blk, jnp.int8 if fast_stages else jnp.bfloat16
        ),
        leaf_depth=jnp.asarray(
            depth, jnp.int32 if fast_stages else jnp.float32
        ),
        leaf_values=jnp.asarray(ops["leaf_values"].reshape(G, gL, C)),
        n_classes=C,
        n_internal=gD,
        n_leaves=gL,
        row_tile=row_tile,
        tree_chunk=chunk_g,
        fuse_leaf_gemm=(
            fuse if fuse is not None else (chunk_g * gL) <= 2048
        ),
        fast_stages=fast_stages,
    )


def _kernel(
    x_ref, a_ref, thr_ref, path_ref, depth_ref, vals_ref, out_ref,
    *, tree_chunk: int, n_internal: int, fuse_leaf_gemm: bool,
    fast_stages: bool,
):
    t = pl.program_id(1)
    if fast_stages:
        # exact bf16x3 column select: X splits exactly into three bf16
        # components (8+8+8 significand bits cover f32's 24); the one-hot
        # operand is exactly bf16, and each partial product occupies a
        # disjoint bit range of the f32 accumulator, so the sum
        # reconstructs X[n, f] bit-exactly — in 3 bf16 MXU passes
        # instead of a full-f32 dot.
        x = x_ref[:]
        x1 = x.astype(jnp.bfloat16)
        r1 = x - x1.astype(jnp.float32)
        x2 = r1.astype(jnp.bfloat16)
        x3 = (r1 - x2.astype(jnp.float32)).astype(jnp.bfloat16)
        a = a_ref[:]
        xf = (
            jnp.dot(x3, a, preferred_element_type=jnp.float32)
            + jnp.dot(x2, a, preferred_element_type=jnp.float32)
            + jnp.dot(x1, a, preferred_element_type=jnp.float32)
        )  # (TILE, chunk_g*gD)
        pm = jnp.where(
            xf <= thr_ref[:], jnp.int8(1), jnp.int8(-1)
        )
    else:
        xf = jnp.dot(
            x_ref[:], a_ref[:], preferred_element_type=jnp.float32
        )  # (TILE, chunk_g*gD)
        pm = jnp.where(xf <= thr_ref[:], 1.0, -1.0).astype(jnp.bfloat16)
    # per-group score dots: (TILE, gD=128) @ block-diag (gD, gL) — each
    # contracts a full MXU tile (tpd trees per pass instead of one)
    matches = []
    for k in range(tree_chunk):
        pm_k = pm[:, k * n_internal:(k + 1) * n_internal]
        if fast_stages:
            S = jnp.dot(
                pm_k, path_ref[k], preferred_element_type=jnp.int32
            )  # (TILE, gL) exact integer path sums
        else:
            S = jnp.dot(
                pm_k, path_ref[k], preferred_element_type=jnp.float32
            )  # (TILE, gL)
        matches.append(S == depth_ref[k][None, :])
    if fuse_leaf_gemm:
        # ONE wide leaf-value GEMM per grid step: (TILE, chunk_g*gL) @
        # (.., C) replaces chunk_g skinny K=gL dots
        match = jnp.concatenate(matches, axis=1).astype(jnp.float32)
        acc = jnp.dot(
            match,
            vals_ref[:].reshape(-1, out_ref.shape[1]),
            preferred_element_type=jnp.float32,
        )
    else:
        # deep-tree buckets: the concatenated match buffer would not fit
        # VMEM — accumulate group by group instead
        acc = jnp.zeros((x_ref.shape[0], out_ref.shape[1]), jnp.float32)
        for k, m in enumerate(matches):
            acc = acc + jnp.dot(
                m.astype(jnp.float32), vals_ref[k],
                preferred_element_type=jnp.float32,
            )

    @pl.when(t == 0)
    def _():
        out_ref[:] = acc

    @pl.when(t > 0)
    def _():
        out_ref[:] = out_ref[:] + acc


@functools.partial(jax.jit, static_argnames=("interpret",))
def forest_proba_pallas(
    g: ForestPallas | ForestPallasGroups, X: jax.Array,
    interpret: bool = False,
) -> jax.Array:
    """(N, C) ensemble-mean class distributions via the fused kernel."""
    if isinstance(g, ForestPallasGroups):
        out = forest_proba_pallas(g.groups[0], X, interpret=interpret)
        for sub in g.groups[1:]:
            out = out + forest_proba_pallas(sub, X, interpret=interpret)
        return out
    N, F = X.shape
    TILE, TC = g.row_tile, g.tree_chunk
    D, L, C = g.n_internal, g.n_leaves, g.n_classes
    T = g.path.shape[0]
    n_chunks = T // TC

    padded = (-N) % TILE
    if padded:
        X = jnp.concatenate([X, jnp.zeros((padded, F), X.dtype)])
    n_tiles = X.shape[0] // TILE

    kernel = functools.partial(
        _kernel, tree_chunk=TC, n_internal=D,
        fuse_leaf_gemm=g.fuse_leaf_gemm, fast_stages=g.fast_stages,
    )
    out = pl.pallas_call(
        kernel,
        grid=(n_tiles, n_chunks),
        in_specs=[
            pl.BlockSpec((TILE, F), lambda i, t: (i, 0)),
            pl.BlockSpec((F, TC * D), lambda i, t: (0, t)),
            pl.BlockSpec((1, TC * D), lambda i, t: (0, t)),
            pl.BlockSpec((TC, D, L), lambda i, t: (t, 0, 0)),
            pl.BlockSpec((TC, L), lambda i, t: (t, 0)),
            pl.BlockSpec((TC, L, C), lambda i, t: (t, 0, 0)),
        ],
        out_specs=pl.BlockSpec((TILE, C), lambda i, t: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((X.shape[0], C), jnp.float32),
        interpret=interpret,
    )(X, g.feat_onehot, g.thresholds, g.path, g.leaf_depth, g.leaf_values)
    return out[:N]


def predict(
    g: ForestPallas | ForestPallasGroups, X: jax.Array,
    interpret: bool = False,
) -> jax.Array:
    return jnp.argmax(
        forest_proba_pallas(g, X, interpret=interpret), axis=-1
    ).astype(jnp.int32)
