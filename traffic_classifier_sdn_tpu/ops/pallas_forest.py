"""Fused Pallas TPU kernel for GEMM-form forest evaluation.

The XLA GEMM path (ops/tree_gemm.py) is memory-bound: the (N, T·D)
comparison matrix and (T, N, L) path-score tensor round-trip through HBM
(~100 GB of traffic per million-flow batch). This kernel fuses all three
stages in VMEM per row-tile × tree-chunk grid step:

    xf    = X_tile @ A_chunk            (MXU, exact column select)
    pm    = where(xf ≤ thr, +1, −1)     (VPU, bf16)
    S_k   = pm_k @ path_k               (MXU, small-int exact in bf16)
    match = (S_k == depth_k)            (VPU)
    acc  += match @ leaf_values_k       (MXU, f32 accumulate)

HBM traffic collapses to: read X once, write (N, C) probabilities once,
re-stream ~1 MB of tree operands per row tile. Grid iterates tree-chunks
fastest, so the output block stays resident and accumulates across chunks.

Semantics match tree_gemm (and hence sklearn predict_proba) exactly.
Coverage: tests/test_tree_kernels.py runs this kernel in interpreter mode
on CPU; compiled-on-TPU execution, argmax parity, and timing vs the XLA
GEMM path are exercised by ``bench.py`` (``pallas_forest_*`` fields in the
bench JSON) and by ``tools/tpu_proof.py``, which records the result in
``docs/artifacts/``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import tree_gemm


class ForestPallas(struct.PyTreeNode):
    feat_onehot: jax.Array  # (F, T*D) f32
    thresholds: jax.Array  # (1, T*D) f32 (+inf padding)
    path: jax.Array  # (T, D, L) bf16
    leaf_depth: jax.Array  # (T, L) f32
    leaf_values: jax.Array  # (T, L, C) f32 (pre-divided by T)
    n_classes: int = struct.field(pytree_node=False)
    n_internal: int = struct.field(pytree_node=False)  # D
    n_leaves: int = struct.field(pytree_node=False)  # L
    row_tile: int = struct.field(pytree_node=False)
    tree_chunk: int = struct.field(pytree_node=False)


class ForestPallasGroups(struct.PyTreeNode):
    """Size-bucketed variant, mirroring tree_gemm.ForestGemmGroups: trees
    sorted by D·L and compiled per-bucket so each bucket's VMEM operands
    are padded only to its own (D, L) — smaller tree-chunk blocks for the
    small trees, less streamed traffic per row tile. Group leaf values are
    pre-divided by the FULL tree count; summing group probabilities gives
    the ensemble mean."""

    groups: tuple  # of ForestPallas
    n_classes: int = struct.field(pytree_node=False)


def compile_forest(
    d: dict, row_tile: int = 512, tree_chunk: int = 16, n_buckets: int = 1
) -> ForestPallas | ForestPallasGroups:
    buckets = tree_gemm.split_tree_buckets(d, n_buckets)
    groups = [
        _compile_single(
            sub, row_tile, tree_chunk,
            n_features=nf, n_trees_total=nt,
        )
        for sub, nf, nt in buckets
    ]
    if len(groups) == 1:
        return groups[0]
    return ForestPallasGroups(
        groups=tuple(groups), n_classes=groups[0].n_classes
    )


def _compile_single(
    d: dict, row_tile: int, tree_chunk: int,
    n_features: int | None = None, n_trees_total: int | None = None,
) -> ForestPallas:
    ops = tree_gemm.build_gemm_operands(
        d, n_features=n_features, n_trees_total=n_trees_total
    )
    T, D, L = ops["n_trees"], ops["n_internal"], ops["n_leaves"]
    # Mosaic block-shape rule: the last two dims of every block must be
    # divisible by (8, 128) or equal the full array dim. Pad D to a
    # multiple of 8 with inert columns (+inf threshold -> pm=+1, zero
    # path row -> no score contribution) and force the tree chunk to a
    # multiple of 16, so the (F, TC*D) / (1, TC*D) blocks end on a
    # 128-multiple and the (TC, L) depth block starts on an 8-multiple.
    dpad = (-D) % 8
    if dpad:
        ops["feat_onehot"] = np.concatenate(
            [
                ops["feat_onehot"].reshape(ops["n_features"], T, D),
                np.zeros((ops["n_features"], T, dpad), np.float32),
            ],
            axis=2,
        ).reshape(ops["n_features"], T * (D + dpad))
        ops["thresholds"] = np.concatenate(
            [
                ops["thresholds"].reshape(T, D),
                np.full((T, dpad), np.inf, np.float32),
            ],
            axis=1,
        ).reshape(-1)
        ops["path"] = np.concatenate(
            [ops["path"], np.zeros((T, dpad, L), np.float32)], axis=1
        )
        D += dpad
    tree_chunk = max(16, ((tree_chunk + 15) // 16) * 16)
    assert (tree_chunk * D) % 128 == 0 and tree_chunk % 8 == 0
    # pad tree count to a multiple of tree_chunk with inert trees
    # (zero leaf_values rows contribute nothing; depth 127 never matches)
    pad = (-T) % tree_chunk
    if pad:
        ops["feat_onehot"] = np.concatenate(
            [
                ops["feat_onehot"].reshape(-1, T, D),
                np.zeros((ops["n_features"], pad, D), np.float32),
            ],
            axis=1,
        ).reshape(ops["n_features"], (T + pad) * D)
        ops["thresholds"] = np.concatenate(
            [
                ops["thresholds"].reshape(T, D),
                np.full((pad, D), np.inf, np.float32),
            ]
        ).reshape(-1)
        ops["path"] = np.concatenate(
            [ops["path"], np.zeros((pad, D, L), np.float32)]
        )
        ops["leaf_depth"] = np.concatenate(
            [ops["leaf_depth"], np.full((pad, L), 127.0, np.float32)]
        )
        ops["leaf_values"] = np.concatenate(
            [
                ops["leaf_values"],
                np.zeros((pad, L, ops["n_classes"]), np.float32),
            ]
        )
    return ForestPallas(
        feat_onehot=jnp.asarray(ops["feat_onehot"]),
        thresholds=jnp.asarray(ops["thresholds"][None, :]),
        path=jnp.asarray(ops["path"], jnp.bfloat16),
        leaf_depth=jnp.asarray(ops["leaf_depth"]),
        leaf_values=jnp.asarray(ops["leaf_values"]),
        n_classes=ops["n_classes"],
        n_internal=D,
        n_leaves=L,
        row_tile=row_tile,
        tree_chunk=tree_chunk,
    )


def _kernel(
    x_ref, a_ref, thr_ref, path_ref, depth_ref, vals_ref, out_ref,
    *, tree_chunk: int, n_internal: int,
):
    t = pl.program_id(1)
    xf = jnp.dot(
        x_ref[:], a_ref[:], preferred_element_type=jnp.float32
    )  # (TILE, TC*D)
    pm = jnp.where(xf <= thr_ref[:], 1.0, -1.0).astype(jnp.bfloat16)
    acc = jnp.zeros((x_ref.shape[0], out_ref.shape[1]), jnp.float32)
    for k in range(tree_chunk):
        pm_k = pm[:, k * n_internal:(k + 1) * n_internal]
        S = jnp.dot(
            pm_k, path_ref[k], preferred_element_type=jnp.float32
        )  # (TILE, L)
        match = (S == depth_ref[k][None, :]).astype(jnp.float32)
        acc = acc + jnp.dot(
            match, vals_ref[k], preferred_element_type=jnp.float32
        )

    @pl.when(t == 0)
    def _():
        out_ref[:] = acc

    @pl.when(t > 0)
    def _():
        out_ref[:] = out_ref[:] + acc


@functools.partial(jax.jit, static_argnames=("interpret",))
def forest_proba_pallas(
    g: ForestPallas | ForestPallasGroups, X: jax.Array,
    interpret: bool = False,
) -> jax.Array:
    """(N, C) ensemble-mean class distributions via the fused kernel."""
    if isinstance(g, ForestPallasGroups):
        out = forest_proba_pallas(g.groups[0], X, interpret=interpret)
        for sub in g.groups[1:]:
            out = out + forest_proba_pallas(sub, X, interpret=interpret)
        return out
    N, F = X.shape
    TILE, TC = g.row_tile, g.tree_chunk
    D, L, C = g.n_internal, g.n_leaves, g.n_classes
    T = g.path.shape[0]
    n_chunks = T // TC

    padded = (-N) % TILE
    if padded:
        X = jnp.concatenate([X, jnp.zeros((padded, F), X.dtype)])
    n_tiles = X.shape[0] // TILE

    kernel = functools.partial(_kernel, tree_chunk=TC, n_internal=D)
    out = pl.pallas_call(
        kernel,
        grid=(n_tiles, n_chunks),
        in_specs=[
            pl.BlockSpec((TILE, F), lambda i, t: (i, 0)),
            pl.BlockSpec((F, TC * D), lambda i, t: (0, t)),
            pl.BlockSpec((1, TC * D), lambda i, t: (0, t)),
            pl.BlockSpec((TC, D, L), lambda i, t: (t, 0, 0)),
            pl.BlockSpec((TC, L), lambda i, t: (t, 0)),
            pl.BlockSpec((TC, L, C), lambda i, t: (t, 0, 0)),
        ],
        out_specs=pl.BlockSpec((TILE, C), lambda i, t: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((X.shape[0], C), jnp.float32),
        interpret=interpret,
    )(X, g.feat_onehot, g.thresholds, g.path, g.leaf_depth, g.leaf_values)
    return out[:N]


def predict(
    g: ForestPallas | ForestPallasGroups, X: jax.Array,
    interpret: bool = False,
) -> jax.Array:
    return jnp.argmax(
        forest_proba_pallas(g, X, interpret=interpret), axis=-1
    ).astype(jnp.int32)
