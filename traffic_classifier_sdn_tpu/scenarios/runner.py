"""The scenario campaign runner: drive a declarative timeline through
the REAL serve composition and score it against its SLO gates.

This is deliberately NOT a simulation harness: each scenario runs the
same objects the ``serve`` command composes — a raw-mode
:class:`~traffic_classifier_sdn_tpu.ingest.fanin.FanInIngest` tier
(native-ingest byte pumps, lockstep-paced), a
:class:`~traffic_classifier_sdn_tpu.ingest.batcher.FlowStateEngine`
(C++ spine when built, Python fallback otherwise),
the degrade ladder / open-set gate / incremental label cache exactly
as ``cli.py`` stacks them, the latency-provenance waterfall
(obs/latency.py), and the flight recorder + metrics planes the gates
read. The tick drive order mirrors the CLI serial loop byte for byte:
``mark_tick → ingest_bytes per (sid, batch) → mark_parse → step →
mark_scatter → evict dead namespaces → idle evict → labels → seal →
mark_device → render_sample → render_visible``.

Determinism: the fan-in tier (and the degrade ladder, when armed) run
on a VIRTUAL clock the runner advances ``clock_step_s`` per tick —
quarantine deadlines, flap windows and probe schedules are measured in
ticks, so the tier-1 scenario tests sleep for nothing. Real wall time
still drives the cadence and e2e gates (those SLOs are real-time
phenomena by definition).

Gate failures record a ``scenario.gate_breach`` ring event per failed
gate and, when ``obs_dir`` is set, write an atomic post-mortem bundle
named by scenario id: the flight-recorder JSONL dump + a metrics
snapshot (the PR 3 / PR 11 dump paths, obs/flight_recorder.py) + a
manifest carrying the timeline position the run ended at.
"""

from __future__ import annotations

import importlib
import io
import json
import os
import random
import sys
import time
from dataclasses import dataclass, field

import numpy as np

from ..ingest.batcher import FlowStateEngine
from ..ingest.fanin import FanInIngest
from ..obs.device import DeviceTelemetry
from ..obs.flight_recorder import FlightRecorder, dump_metrics_snapshot
from ..obs.latency import LatencyProvenance
from ..obs.perf_recorder import PerfRecorder
from ..utils import faults
from ..utils.atomicio import atomic_write_bytes
from ..utils.metrics import Metrics
from .timeline import Scenario

# the flight-recorder kinds the scorecard's transition trace keeps —
# the state machines the gates watch, not the whole ring
_TRACE_KINDS = (
    "scenario.phase",
    "fanin.source_dead",
    "fanin.source_restart",
    "fanin.flap_escalated",
    "fanin.restart_refused",
    "fanin.drop",
    "degrade.transition",
    "drift.transition",
    "openset.reject",
    "latency.slo_breach",
    "fault.fire",
    "actuation.install",
    "actuation.retract",
    "actuation.refused",
    "actuation.flap_suppressed",
    "actuation.degrade",
    "actuation.probe",
    "actuation.reconcile",
    "actuation.demote",
    "actuation.repromote",
    "actuation.quarantine",
)


@dataclass
class RunContext:
    """Everything a gate or a scheduled action can reach: the live
    serve objects plus the run's collected observations (``obs``)."""

    scenario: Scenario
    tier: FanInIngest
    engine: FlowStateEngine
    metrics: Metrics
    recorder: FlightRecorder
    lat: LatencyProvenance
    inc: object = None
    openset: object = None
    degrade: object = None
    actuation: object = None
    n_classes: int = 4
    tick: int = 0
    phase: int = 0
    vclock: dict = field(default_factory=lambda: {"t": 0.0})
    obs: dict = field(default_factory=dict)

    # -- scheduled-action ops (the library's timeline verbs) ---------------
    def kill(self, sid: int) -> None:
        """Unclean-kill one source and register the death NOW (at this
        tick's virtual time): kill, join the pump, run one supervision
        pass — the flap clock starts at a deterministic tick instead
        of whenever the serve thread next polls."""
        self.tier.kill_source(sid)
        with self.tier._roster_lock:
            w = self.tier._workers[sid]
        w.join(timeout=5.0)
        self.tier._supervise()

    def restart(self, sid: int, force: bool = False) -> bool:
        ok = self.tier.restart_source(sid, force=force)
        if not ok:
            self.obs["restarts_refused"] = (
                self.obs.get("restarts_refused", 0) + 1
            )
        return ok


def _build_model(n_classes: int):
    """The serve composition's model: a synthetic GNB (the cheapest
    full-table family — scenario gates exercise the serve machinery,
    not model accuracy; the open-set tier is feature-space and does
    not consult the model at all)."""
    from ..models import gnb, jit_serving_fn

    rng = np.random.RandomState(0)
    params = gnb.from_numpy(
        {
            "theta": rng.gamma(2.0, 100.0, (n_classes, 12)),
            "var": rng.gamma(2.0, 50.0, (n_classes, 12)) + 1.0,
            "class_prior": np.full(n_classes, 1.0 / n_classes, dtype=np.float64),
        }
    )
    return jit_serving_fn(gnb.predict), params


def _compose_serve(sc: Scenario, m: Metrics, recorder: FlightRecorder,
                   engine: FlowStateEngine, vclock) -> tuple:
    """Stack the serving ladders exactly as cli.py does: degrade
    innermost (wrapping the device predict), open-set outermost, the
    incremental label cache around the whole composition."""
    predict, params = _build_model(sc.n_classes)
    degrade = None
    if sc.degrade is not None:
        from ..models import resolve_fallback
        from ..serving.degrade import DegradeLadder

        degrade = DegradeLadder(
            predict, resolve_fallback("gnb", params),
            deadline=float(sc.degrade.get("deadline", 2.0)),
            probe_every=float(sc.degrade.get("probe_every", 2.0)),
            probe_successes=int(sc.degrade.get("probe_successes", 2)),
            metrics=m, recorder=recorder,
            clock=(lambda: vclock["t"]),
            rng=random.Random(sc.fault_seed),
        )
        predict = degrade
    openset = None
    if sc.openset is not None:
        from ..serving.openset import OpenSetGate

        openset = OpenSetGate(
            predict, n_classes=sc.n_classes,
            margin=float(sc.openset.get("margin", 3.0)),
            calibration_rows=int(
                sc.openset.get("calibration_rows", 256)
            ),
            metrics=m, recorder=recorder,
        )
        predict = openset
    from ..serving.incremental import IncrementalLabels

    inc = IncrementalLabels(
        engine, predict, params, degrade=degrade,
        metrics=m, recorder=recorder,
    )
    return inc, openset, degrade


def _accounting_switch_cls():
    """tools/fake_switch.AccountingSwitch — the dev harness lives
    outside the package on purpose (it is a test double, not a serve
    component), so the push-mode scenario resolves it off the repo's
    tools/ directory when it is not already importable."""
    try:
        return importlib.import_module("fake_switch").AccountingSwitch
    except ImportError:
        tools = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))),
            "tools",
        )
        if tools not in sys.path:
            sys.path.insert(0, tools)
        return importlib.import_module("fake_switch").AccountingSwitch


def _arm_actuation(sc: Scenario, m: Metrics, recorder: FlightRecorder,
                   clock) -> tuple:
    """Build the scenario's actuation plane exactly as cli.py would:
    policy parsed against the scenario's class names (plus ``unknown``
    when the open-set tier is armed), dry-run by default, push mode
    against an in-process AccountingSwitch the runner owns. Returns
    ``(plane, switch, names)`` — all None/() when the scenario does
    not arm actuation."""
    if sc.actuation is None:
        return None, None, ()
    from ..controller.policy import parse_policy
    from ..serving.actuation import ActuationPlane, SwitchLink

    names = tuple(f"class{i}" for i in range(sc.n_classes))
    if sc.openset is not None:
        names = names + ("unknown",)
    policy = parse_policy(sc.actuation["policy"], names)
    mode = sc.actuation.get("mode", "dry-run")
    switch = None
    link_factory = None
    if mode == "push":
        switch = _accounting_switch_cls()()
        switch.start()

        def link_factory():
            return SwitchLink(switch.host, switch.port)

    plane = ActuationPlane(
        policy, mode=mode,
        k_install=int(sc.actuation.get("k_install", 3)),
        k_retract=int(sc.actuation.get("k_retract", 3)),
        clock=clock, link_factory=link_factory,
        backoff_base_s=float(sc.actuation.get("backoff_base_s", 1.0)),
        metrics=m, recorder=recorder,
        # the dry-run intended-mods table is operator UX; the
        # scorecard reads the ledger and the ring instead
        out=io.StringIO(),
    )
    return plane, switch, names


def run_scenario(sc: Scenario, *, native: str = "auto",
                 obs_dir: str | None = None) -> dict:
    """Run one scenario timeline through the real serve loop; returns
    its scorecard dict (``passed``, per-gate results, latency status,
    transition trace). See the module docstring for the drive order
    and the post-mortem contract."""
    import jax

    from ..native import engine as native_engine

    use_native = (
        native == "on"
        or (native == "auto" and native_engine.available())
    )
    m = Metrics()
    recorder = FlightRecorder(capacity=8192)
    # Per-scenario device plane: compile/retrace accounting scoped to
    # this timeline, so a gate breach's post-mortem can say whether
    # XLA recompiled mid-scenario. The black-box perf ring only exists
    # when a bundle directory does — it is post-mortem evidence.
    dev = DeviceTelemetry(metrics=m, recorder=recorder)
    dev.attach()
    perf = None
    if obs_dir:
        perf = PerfRecorder(
            os.path.join(obs_dir, "perf", sc.id),
            ticks_per_segment=32, keep_segments=8, metrics=m,
        )
    vclock = {"t": 0.0}
    clock = time.monotonic if sc.real_clock else (lambda: vclock["t"])
    tier = FanInIngest(
        sc.sources, queue_records=sc.queue_records,
        quarantine_s=sc.quarantine_s, metrics=m, recorder=recorder,
        clock=clock, stamp=True, raw=True,
        max_flaps=sc.max_flaps, flap_window_s=sc.flap_window_s,
    )
    engine = FlowStateEngine(
        sc.capacity, native=use_native, track_dirty=True,
    )
    lat = LatencyProvenance(m, recorder, slo_s=sc.e2e_slo_s)
    inc, openset, degrade = _compose_serve(
        sc, m, recorder, engine, vclock,
    )
    actuation, switch, act_names = _arm_actuation(sc, m, recorder, clock)
    ctx = RunContext(
        scenario=sc, tier=tier, engine=engine, metrics=m,
        recorder=recorder, lat=lat, inc=inc, openset=openset,
        degrade=degrade, actuation=actuation, n_classes=sc.n_classes,
        vclock=vclock,
    )
    ctx.obs["tick_wall_s"] = []
    ctx.obs["evicted_slots"] = 0
    ctx.obs["evicted_sids"] = set()
    plan = faults.FaultPlan(
        [faults.FaultRule(**r) for r in sc.fault_rules],
        seed=sc.fault_seed,
    )
    labels = None
    # Warm the jit cache OUTSIDE the timeline: the composed predict
    # compiles for (capacity, 12) on first use, and the incremental
    # dirty-update path compiles separately on its first non-full
    # sweep — without this, tick 0's cadence/e2e samples would measure
    # XLA, not the scenario. Runs before faults install, so it
    # consumes no fault-rule `after` budget. The traffic half drives a
    # throwaway namespace (sid 63) through ingest → step → labels
    # twice (full path, then dirty path) and evicts it; it is SKIPPED
    # when the scenario arms the open-set tier, whose calibration
    # would otherwise consume the throwaway rows (openset scenarios
    # do not gate e2e, so the one-off compile there is harmless).
    jax.block_until_ready(inc.labels())
    if sc.openset is None:
        from ..ingest.replay import SyntheticFlows

        warm_gen = SyntheticFlows(4, seed=99, mac_base=1 << 40)
        for _ in range(2):
            engine.mark_tick()
            engine.ingest_bytes(warm_gen.tick_bytes(), 63)
            engine.step()
            jax.block_until_ready(inc.labels())
        engine.evict_source(63)
        inc.invalidate("scenario-warmup")
        jax.block_until_ready(inc.labels())
    # Any compile past this point happened inside the timeline — a
    # retrace the scorecard's device block will carry. Openset
    # scenarios skip the traffic warm above, so their calibration
    # compile registers honestly here (they do not gate on e2e).
    dev.mark_warmup_complete()
    tier.start()
    gen = tier.ticks(tick_timeout=sc.tick_timeout, poll_s=0.005)
    try:
        with faults.installed(plan), recorder.observing_faults():
            for tick in range(sc.total_ticks):
                ctx.tick = tick
                phase_idx, phase = sc.phase_at(tick)
                if phase_idx != ctx.phase or tick == 0:
                    ctx.phase = phase_idx
                    m.set("scenario_phase", phase_idx)
                    recorder.record(
                        "scenario.phase", scenario=sc.id, tick=tick,
                        phase=phase.name, index=phase_idx,
                    )
                for action in sc.actions.get(tick, ()):
                    action(ctx)
                t0 = time.perf_counter()
                batch = next(gen, None)
                if batch is None:
                    break  # every source ended and the queue drained
                lat.begin_tick(tier.pop_provenance())
                engine.mark_tick()
                n_rec = sum(
                    engine.ingest_bytes(data, sid)
                    for sid, data in batch
                )
                m.inc("records", n_rec)
                lat.mark_parse()
                engine.step()
                lat.mark_scatter()
                for sid in tier.take_evictions():
                    ctx.obs["evicted_sids"].add(sid)
                    n = engine.evict_source(sid)
                    ctx.obs["evicted_slots"] += n
                    m.inc("evicted", n)
                    lat.drop_source(sid)
                    if inc is not None and n:
                        inc.invalidate(f"evict-source-{sid}")
                if sc.idle_evict_s is not None and engine.last_time:
                    n = engine.evict_idle(
                        engine.last_time, sc.idle_evict_s,
                    )
                    ctx.obs["evicted_slots"] += n
                    m.inc("evicted", n)
                    if inc is not None and n:
                        inc.invalidate("idle-evict")
                seal = lat.seal()
                dev.mark_dispatch()
                labels = inc.labels()
                jax.block_until_ready(labels)
                lat.mark_device(seal)
                rendered = engine.render_sample(labels, sc.table_rows)
                lat.render_visible(seal)
                if actuation is not None:
                    # the plane sees what the serve renders: the same
                    # (slot, src, dst, label-name) rows cli.py feeds it
                    meta = engine.slot_metadata(
                        slots=[r[0] for r in rendered],
                    )
                    actuation.observe([
                        (slot, *meta[slot],
                         act_names[c] if c < len(act_names) else "?")
                        for slot, c, _fa, _ra in rendered
                        if slot in meta
                    ])
                wall = time.perf_counter() - t0
                ctx.obs["tick_wall_s"].append(wall)
                devs = dev.sample()
                if perf is not None:
                    sample = {
                        "tick": tick,
                        "phase": phase.name,
                        "tick_wall_s": round(wall, 6),
                        "jit_compiles": devs["jit_compiles"],
                        "retraces_after_warmup": devs[
                            "retraces_after_warmup"
                        ],
                    }
                    if devs["hbm_bytes"] is not None:
                        sample["hbm_bytes"] = devs["hbm_bytes"]
                    perf.record(sample)
                vclock["t"] += sc.clock_step_s
    finally:
        gen.close()
        tier.stop()
        if degrade is not None:
            degrade.close()
        if actuation is not None:
            actuation.close()
        if switch is not None:
            switch.stop()
        if perf is not None:
            perf.flush()
        dev.detach()
    # final-state observations the ground-truth gates read: per-MAC
    # labels from the last tick's full label vector (capacities here
    # are scenario-sized — the full fetch the 2²⁰ serve avoids is
    # fine). One slot per conversation: both endpoints carry its label.
    mac_labels: dict = {}
    if labels is not None:
        lab = np.asarray(labels)
        for slot, (src, dst) in engine.slot_metadata().items():
            if slot < lab.shape[0]:
                mac_labels[src] = int(lab[slot])
                mac_labels[dst] = int(lab[slot])
    ctx.obs["mac_labels"] = mac_labels
    results = [g.evaluate(ctx) for g in sc.gates]
    passed = all(r.passed for r in results)
    card = {
        "scenario": sc.id,
        "title": sc.title,
        "passed": passed,
        "ticks_run": len(ctx.obs["tick_wall_s"]),
        "phases": [
            {"name": p.name, "ticks": p.ticks} for p in sc.phases
        ],
        "gates": [r.as_dict() for r in results],
        "latency": lat.status(),
        "flows": engine.num_flows(),
        "records": int(m.counters.get("records", 0)),
        "parse_errors": engine.parse_errors(),
        "evicted_slots": int(ctx.obs["evicted_slots"]),
        "transitions": _transition_trace(recorder),
        "engine": "native" if use_native else "python",
        "device": dev.status(),
    }
    if actuation is not None:
        card["actuation"] = actuation.status()
        if switch is not None:
            card["switch"] = {
                "installs": switch.installs(),
                "deletes": switch.deletes(),
                "refusals": switch.refusals(),
                "live_rules": len(switch.live_cookies()),
                "barriers": switch.barriers,
            }
    if not passed:
        for r in results:
            if not r.passed:
                recorder.record(
                    "scenario.gate_breach", scenario=sc.id,
                    gate=r.id, value=r.value, bound=r.bound,
                    detail=r.detail,
                )
        if obs_dir:
            card["post_mortem"] = _dump_post_mortem(
                sc, ctx, m, recorder, results, obs_dir,
                dev=dev, perf=perf,
            )
    return card


def _transition_trace(recorder: FlightRecorder) -> list[dict]:
    """The scorecard's compact state-machine trace: only the watched
    kinds, only the fields that tell the story."""
    out = []
    for e in recorder.tail(4096):
        if e.get("kind") not in _TRACE_KINDS:
            continue
        row = {
            k: v for k, v in e.items()
            if k not in ("ts",)
        }
        out.append(row)
    return out


def _dump_post_mortem(sc: Scenario, ctx: RunContext, m: Metrics,
                      recorder: FlightRecorder, results,
                      obs_dir: str, dev=None, perf=None) -> dict:
    """The satellite-2 contract: a gate failure leaves an atomic
    bundle named by scenario id — flight-recorder JSONL + metrics
    snapshot (the PR 3/PR 11 dump paths) + a manifest carrying the
    timeline position. Forensics must never become a second failure:
    each piece is attempted independently and the manifest records
    what landed."""
    reason = f"scenario-{sc.id}"
    bundle: dict = {"scenario": sc.id}
    try:
        bundle["flight"] = recorder.dump(obs_dir, reason)
    except OSError as e:
        bundle["flight_error"] = str(e)
    try:
        bundle["metrics"] = dump_metrics_snapshot(m, obs_dir, reason)
    except OSError as e:
        bundle["metrics_error"] = str(e)
    phase_idx, phase = sc.phase_at(max(0, ctx.tick))
    manifest = {
        "scenario": sc.id,
        "title": sc.title,
        "timeline_position": {
            "tick": ctx.tick,
            "total_ticks": sc.total_ticks,
            "phase": phase.name,
            "phase_index": phase_idx,
        },
        "failed_gates": [
            r.as_dict() for r in results if not r.passed
        ],
        "flight": bundle.get("flight"),
        "metrics": bundle.get("metrics"),
    }
    # Device-plane evidence: what the chip was doing when the gate
    # broke. Attempted independently — a wedged device must not cost
    # us the manifest.
    if dev is not None:
        try:
            manifest["device"] = dev.status()
        except Exception as e:
            manifest["device_error"] = str(e)
    if perf is not None:
        try:
            perf.flush()
            manifest["perf_tail"] = perf.tail(32)
        except Exception as e:
            manifest["perf_tail_error"] = str(e)
    path = os.path.join(obs_dir, f"scenario-{sc.id}-postmortem.json")
    try:
        os.makedirs(obs_dir, exist_ok=True)
        atomic_write_bytes(
            path, json.dumps(manifest, indent=2).encode(),
        )
        bundle["manifest"] = path
    except OSError as e:
        bundle["manifest_error"] = str(e)
    return bundle


def run_campaign(scenarios, *, native: str = "auto",
                 obs_dir: str | None = None,
                 platform: str = "cpu") -> dict:
    """Run a scenario list and fold the scorecards into the campaign
    matrix (the ``scenario_matrix_<platform>.json`` artifact shape).
    ``passed`` is the conjunction — the matrix is a gate, not a
    report (tools/bench_scenarios.py exits nonzero on it)."""
    cards = [
        run_scenario(sc, native=native, obs_dir=obs_dir)
        for sc in scenarios
    ]
    return {
        "platform": platform,
        "scenarios": cards,
        "passed": all(c["passed"] for c in cards),
        "gate_failures": [
            {"scenario": c["scenario"], "gate": g["id"]}
            for c in cards
            for g in c["gates"] if not g["passed"]
        ],
    }
