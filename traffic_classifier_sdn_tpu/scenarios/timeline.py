"""Scenario timelines and SLO gates — the declarative half of the
adversarial-scenario campaign (F13, docs/ROBUSTNESS.md).

A :class:`Scenario` is a declarative timeline: named phases (each a
tick count), a set of fan-in sources (usually ``feed``-kind
SourceSpecs whose scripts compose the existing generators —
``ingest/replay.SyntheticFlows``, ``ingest/workload.ClassWorkload`` /
``OpenWorldWorkload`` / ``perturb_pools``), scheduled actions at
specific ticks (kill/restart a source, arm nothing new — fault
schedules ride the existing ``utils/faults.SITES`` seams via
``fault_rules``), and a list of SLO :class:`Gate`\\ s evaluated against
the REAL serve loop's observability planes after the run.

Gates are factory-built closures: each returns a :class:`GateResult`
with the measured value beside its bound, so the campaign scorecard
(tools/bench_scenarios.py → docs/artifacts/scenario_matrix_cpu.json)
carries evidence, not just verdicts. The shared gate vocabulary:

- ``cadence_p50``      — scenario tick wall time p50 within bound (the
  1 s cadence SLO, scaled for test profiles)
- ``accounting_exact`` — per-source ``emitted == accepted + (drops −
  purged)``: NO silent drops, ever, in any scenario
- ``drops``            — put-time drops exactly zero (default) or
  expected-and-accounted (the queue-saturation flood)
- ``e2e_p99``          — latency-provenance e2e p99 within bound
  (PR 11's waterfall)
- ``events``           — required flight-recorder kinds observed (and
  forbidden kinds absent): the degrade/drift/fan-in transition gates
- ``final_state``      — the LAST event of a kind carries an expected
  field value (recovery checks: the ladder must end HEALTHY)
- plus scenario-shaped gates over the engine (flow population bounds,
  post-reset feature sanity, eviction counts) and over open-set ground
  truth (novel flows rejected, boundary-hugging evasion NOT rejected).

Everything here is pure data + closures — the drive loop lives in
``scenarios/runner.py``.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np


@dataclass(frozen=True)
class Phase:
    """One named span of the scenario timeline, ``ticks`` serve ticks
    long. The runner publishes the active phase index as the
    ``scenario_phase`` gauge and records ``scenario.phase`` to the
    flight recorder at each boundary."""

    name: str
    ticks: int


@dataclass
class GateResult:
    """One gate's verdict with its evidence: the measured ``value``
    beside the ``bound`` it was held to."""

    id: str
    passed: bool
    value: object = None
    bound: object = None
    detail: str = ""

    def as_dict(self) -> dict:
        return {
            "id": self.id,
            "passed": bool(self.passed),
            "value": self.value,
            "bound": self.bound,
            "detail": self.detail,
        }


@dataclass
class Gate:
    """A named SLO check: ``fn(ctx) -> GateResult`` evaluated by the
    runner after the timeline completes (``ctx`` is the runner's
    RunContext — tier, engine, metrics, recorder, latency plane, and
    the run's collected observations)."""

    id: str
    fn: object

    def evaluate(self, ctx) -> GateResult:
        try:
            return self.fn(ctx)
        except Exception as e:  # noqa: BLE001 — a broken gate is a failed gate
            return GateResult(
                self.id, False,
                detail=f"gate crashed: {type(e).__name__}: {e}",
            )


@dataclass
class Scenario:
    """One declarative adversarial scenario (see module docstring).

    ``sources`` are ``ingest.fanin.SourceSpec`` rows (normally
    ``feed``-kind, lockstep). ``actions`` maps a global tick index to
    callables run at that tick's START, before the tier assembles the
    tick (``fn(ctx)`` — the library builds them from the runner's ops
    helpers). ``fault_rules`` are ``utils.faults.FaultRule`` kwargs
    dicts (fresh rule objects are built per run — rules carry fired
    state). Clocks: the tier runs on a VIRTUAL clock the runner
    advances ``clock_step_s`` per tick, so quarantine windows, flap
    windows and degrade probe schedules are measured in ticks —
    deterministic, no sleeps."""

    id: str
    title: str
    phases: tuple
    sources: tuple
    gates: tuple
    actions: dict = field(default_factory=dict)
    fault_rules: tuple = ()
    fault_seed: int = 0
    capacity: int = 256
    queue_records: int = 4096
    quarantine_s: float = 3.0
    max_flaps: int = 5
    flap_window_s: float = 60.0
    clock_step_s: float = 1.0
    tick_timeout: float = 2.0
    table_rows: int = 8
    n_classes: int = 4
    openset: dict | None = None  # {"margin":…, "calibration_rows":…}
    degrade: dict | None = None  # {"deadline":…, "probe_every":…, …}
    # arm the actuation plane (serving/actuation.py): {"policy": SPEC,
    # "mode": "dry-run"|"push", "k_install":…, "k_retract":…,
    # "backoff_base_s":…}. Push mode runs against an in-process
    # AccountingSwitch (tools/fake_switch.py) the runner owns.
    actuation: dict | None = None
    idle_evict_s: float | None = None
    e2e_slo_s: float = 0.0
    # run the tier on REAL time instead of the virtual clock: required
    # when a live lockstep source can fail to deliver a granted tick
    # (the queue-saturation flood drops its batch at the bound) — the
    # assembly deadline must then expire on real time or the tick
    # never completes. Only valid for scenarios with no quarantine /
    # flap / probe timing, which would otherwise lose determinism.
    real_clock: bool = False
    notes: str = ""

    @property
    def total_ticks(self) -> int:
        return sum(p.ticks for p in self.phases)

    def phase_at(self, tick: int) -> tuple[int, Phase]:
        """(phase index, Phase) covering global ``tick``."""
        acc = 0
        for i, p in enumerate(self.phases):
            acc += p.ticks
            if tick < acc:
                return i, p
        return len(self.phases) - 1, self.phases[-1]


# -- gate factories ----------------------------------------------------------

def gate_cadence(p50_bound_s: float = 1.0) -> Gate:
    """Serve cadence held: p50 of full scenario tick wall time (tick
    assembly + ingest + predict + render) within the bound."""

    def fn(ctx) -> GateResult:
        ticks = ctx.obs.get("tick_wall_s", [])
        if not ticks:
            return GateResult("cadence_p50", False, detail="no ticks ran")
        p50 = float(np.percentile(np.asarray(ticks), 50))
        return GateResult(
            "cadence_p50", p50 <= p50_bound_s, round(p50, 6),
            p50_bound_s, f"{len(ticks)} ticks",
        )

    return Gate("cadence_p50", fn)


def gate_accounting() -> Gate:
    """Zero SILENT drops: every record a pump emitted is accounted as
    accepted or dropped, exactly, per source — ``emitted == accepted +
    (drops − purged)`` (purged batches were accepted first, then
    re-classified at eviction; see FanInQueue.purged)."""

    def fn(ctx) -> GateResult:
        accepted = ctx.tier.queue.accepted()
        drops = ctx.tier.queue.drops()
        purged = ctx.tier.queue.purged()
        bad = []
        total = 0
        for row in ctx.tier.roster():
            sid = row["id"]
            emitted = row["emitted"]
            total += emitted
            accounted = (
                accepted.get(sid, 0)
                + drops.get(sid, 0) - purged.get(sid, 0)
            )
            if emitted != accounted:
                bad.append(f"sid {sid}: emitted {emitted} != "
                           f"accounted {accounted}")
        return GateResult(
            "accounting_exact", not bad, total, None,
            "; ".join(bad) if bad else f"{total} records exact",
        )

    return Gate("accounting_exact", fn)


def gate_drops(expect: bool = False) -> Gate:
    """Put-time drop policy: by default ZERO records dropped at the
    queue bound; the flood scenario flips ``expect`` — drops must then
    be nonzero AND (via gate_accounting) exactly attributed."""

    def fn(ctx) -> GateResult:
        drops = ctx.tier.queue.drops()
        purged = ctx.tier.queue.purged()
        put_drops = sum(drops.values()) - sum(purged.values())
        if expect:
            return GateResult(
                "drops_expected", put_drops > 0, put_drops, ">0",
                "queue bound exercised" if put_drops else
                "flood never hit the queue bound",
            )
        return GateResult(
            "drops_zero", put_drops == 0, put_drops, 0,
            "" if put_drops == 0 else f"{put_drops} records dropped",
        )

    return Gate("drops_expected" if expect else "drops_zero", fn)


def gate_e2e_p99(bound_s: float) -> Gate:
    """Bounded end-to-end latency via the provenance waterfall: emit →
    render p99 within ``bound_s`` (obs/latency.py)."""

    def fn(ctx) -> GateResult:
        st = ctx.lat.status()
        if not st.get("observed"):
            return GateResult(
                "e2e_p99", False, detail="no stamped batches folded",
            )
        p99 = st["e2e_p99_s"]
        return GateResult(
            "e2e_p99", p99 <= bound_s, p99, bound_s,
            f"dominant stage: {st.get('dominant_stage')}",
        )

    return Gate("e2e_p99", fn)


def gate_events(required=(), forbid=()) -> Gate:
    """Required flight-recorder event kinds observed at least once;
    forbidden kinds never."""

    def fn(ctx) -> GateResult:
        kinds = {e.get("kind") for e in ctx.recorder.tail(4096)}
        missing = [k for k in required if k not in kinds]
        present = [k for k in forbid if k in kinds]
        ok = not missing and not present
        bits = []
        if missing:
            bits.append(f"missing: {', '.join(missing)}")
        if present:
            bits.append(f"forbidden present: {', '.join(present)}")
        return GateResult(
            "events", ok, sorted(kinds & set(required)), list(required),
            "; ".join(bits) if bits else "all transitions observed",
        )

    return Gate("events", fn)


def gate_final_state(kind: str, fld: str, expect) -> Gate:
    """The LAST flight-recorder event of ``kind`` carries
    ``fld == expect`` — the recovery gate shape (e.g. the degrade
    ladder's final transition must land back on HEALTHY)."""

    def fn(ctx) -> GateResult:
        last = None
        for e in ctx.recorder.tail(4096):
            if e.get("kind") == kind:
                last = e
        gid = f"final:{kind}.{fld}"
        if last is None:
            return GateResult(gid, False, None, expect,
                              f"no {kind} event recorded")
        val = last.get(fld)
        return GateResult(gid, val == expect, val, expect)

    return Gate(f"final:{kind}.{fld}", fn)


def gate_flows(min_flows: int | None = None,
               max_flows: int | None = None) -> Gate:
    """Final flow-table population inside the expected band (flash
    crowd grows it, mass eviction shrinks it, a reset storm must leave
    it untouched)."""

    def fn(ctx) -> GateResult:
        n = ctx.engine.num_flows()
        ok = ((min_flows is None or n >= min_flows)
              and (max_flows is None or n <= max_flows))
        return GateResult(
            "flow_population", ok, n, [min_flows, max_flows],
        )

    return Gate("flow_population", fn)


def gate_feature_sanity(max_abs: float = 1e9) -> Gate:
    """No mod-2³² wrap artifacts: after a cumulative-counter reset
    storm every feature must stay physically plausible — a botched
    wrap delta shows up as ~4.29e9 × bytes-per-packet, orders of
    magnitude past this bound."""

    def fn(ctx) -> GateResult:
        X = np.asarray(ctx.engine.features())
        worst = float(np.max(np.abs(X))) if X.size else 0.0
        return GateResult(
            "feature_sanity", worst <= max_abs, worst, max_abs,
        )

    return Gate("feature_sanity", fn)


def gate_evicted(min_slots: int) -> Gate:
    """At least ``min_slots`` flow slots were reclaimed during the run
    (idle eviction + namespace eviction, counted by the runner)."""

    def fn(ctx) -> GateResult:
        n = int(ctx.obs.get("evicted_slots", 0))
        return GateResult("evicted_slots", n >= min_slots, n, min_slots)

    return Gate("evicted_slots", fn)


def gate_unknown_recall(novel_macs, min_recall: float = 0.9) -> Gate:
    """Where the scenario injects novelty: the open-set tier must
    label (at least) ``min_recall`` of the novel population's flows
    ``unknown`` at the final render. Ground truth is the injected
    population's MAC set (OpenWorldWorkload.novel_macs)."""
    novel = frozenset(novel_macs)

    def fn(ctx) -> GateResult:
        mac_labels = ctx.obs.get("mac_labels", {})
        unknown = ctx.n_classes
        seen = [m for m in novel if m in mac_labels]
        if not seen:
            return GateResult(
                "unknown_recall", False, 0.0, min_recall,
                "no novel flow reached the table",
            )
        hit = sum(1 for m in seen if mac_labels[m] == unknown)
        recall = hit / len(seen)
        return GateResult(
            "unknown_recall", recall >= min_recall, round(recall, 4),
            min_recall, f"{hit}/{len(seen)} novel flows rejected",
        )

    return Gate("unknown_recall", fn)


def gate_known_accept(known_macs, max_reject: float = 0.05) -> Gate:
    """The evasion side of the novelty gate: boundary-hugging
    perturbed-but-KNOWN flows (workload.perturb_pools) must NOT be
    rejected — the calibrated threshold covers the known envelope by
    construction."""
    known = frozenset(known_macs)

    def fn(ctx) -> GateResult:
        mac_labels = ctx.obs.get("mac_labels", {})
        unknown = ctx.n_classes
        seen = [m for m in known if m in mac_labels]
        if not seen:
            return GateResult(
                "known_accept", False, None, max_reject,
                "no known flow reached the table",
            )
        rejected = sum(1 for m in seen if mac_labels[m] == unknown)
        frac = rejected / len(seen)
        return GateResult(
            "known_accept", frac <= max_reject, round(frac, 4),
            max_reject,
            f"{rejected}/{len(seen)} known/evasion flows rejected",
        )

    return Gate("known_accept", fn)


def gate_rule_accounting() -> Gate:
    """The actuation ledger is EXACT: every rule the plane ever
    intended is accounted as installed, refused, or retracted —
    ``intended == installed + retracted + refused`` — including the
    rules pushed before a mid-run degrade and the retractions after a
    quarantine."""

    def fn(ctx) -> GateResult:
        st = ctx.actuation.status()
        led = st["ledger"]
        return GateResult(
            "rule_accounting_exact", bool(led["exact"]), led, None,
            f"plane ended {st['state']}",
        )

    return Gate("rule_accounting_exact", fn)


def gate_zero_rule_flaps(min_suppressed: int = 1) -> Gate:
    """The hysteresis contract under oscillating labels: ZERO rule
    flaps (a re-install of a pair whose rule was label-retracted) —
    while ``flaps_suppressed`` proves the storm actually reached the
    plane (at least ``min_suppressed`` broken streaks / ended
    deviation episodes; a quiet run must not pass vacuously)."""

    def fn(ctx) -> GateResult:
        st = ctx.actuation.status()
        flaps = int(st["rule_flaps"])
        suppressed = int(st["flaps_suppressed"])
        ok = flaps == 0 and suppressed >= min_suppressed
        return GateResult(
            "rule_flaps_zero", ok, flaps, 0,
            f"{suppressed} flaps suppressed"
            + ("" if suppressed >= min_suppressed else
               f" (< {min_suppressed}: storm never reached the plane)"),
        )

    return Gate("rule_flaps_zero", fn)


def gate_rules_installed(min_rules: int = 1) -> Gate:
    """The plane actually programmed the switch: at least ``min_rules``
    installs landed over the run (zero-flap gates must not pass by
    never installing anything)."""

    def fn(ctx) -> GateResult:
        n = int(ctx.actuation.status()["ledger"]["installed"])
        return GateResult("rules_installed", n >= min_rules, n, min_rules)

    return Gate("rules_installed", fn)


def gate_namespace_evicted(sid: int) -> Gate:
    """A quarantined namespace was actually evicted: the engine holds
    zero slots for ``sid`` at the end (the flap-storm escalation must
    END in an eviction, not a livelock)."""

    def fn(ctx) -> GateResult:
        evicted = ctx.obs.get("evicted_sids", set())
        return GateResult(
            f"namespace_evicted:{sid}", sid in evicted,
            sorted(evicted), sid,
        )

    return Gate(f"namespace_evicted:{sid}", fn)


def gate_restart_refused(min_refusals: int = 1) -> Gate:
    """The flap-escalation contract: at least ``min_refusals`` restart
    attempts were refused after escalation (the runner's restart ops
    record each refusal)."""

    def fn(ctx) -> GateResult:
        n = int(ctx.obs.get("restarts_refused", 0))
        return GateResult(
            "restart_refused", n >= min_refusals, n, min_refusals,
        )

    return Gate("restart_refused", fn)
