"""The scenario library: the adversarial timelines the campaign runs
(ISSUE 16 / F13, docs/ROBUSTNESS.md).

Each builder composes a :class:`~.timeline.Scenario` from the existing
generators — ``ingest/replay.SyntheticFlows`` for rate-shaped
populations, ``ingest/workload.ClassWorkload`` + ``perturb_pools`` /
``novel_delta_pool`` for class-conditional and open-world traffic —
scripted through ``feed``-kind SourceSpecs (one callable per source,
returning each poll tick's wire bytes). Feeds are STATEFUL closures
that ignore the pump's tick index for content decisions driven by
global time would break under restart; instead they carry their own
cumulative-counter state forward, which is exactly the
monitor-restart story the tier is built around (a restarted feed
resumes its counters → one large first delta).

Two profiles per scenario:

- ``t1``  — scaled down for the tier-1 suite: small populations, short
  phases, everything timed on the virtual clock (no sleeps);
- ``cpu`` — the committed-artifact shape (tools/bench_scenarios.py →
  docs/artifacts/scenario_matrix_cpu.json): larger populations, longer
  phases, same gates.

``SCENARIOS`` maps scenario id → builder; ``build(name, profile)``
instantiates one (builders return FRESH generator state per call —
scenarios are single-use, like fault plans).
"""

from __future__ import annotations

from ..ingest.fanin import SourceSpec
from ..ingest.protocol import format_line
from ..ingest.replay import SyntheticFlows
from ..ingest.workload import (
    ClassWorkload,
    novel_delta_pool,
    perturb_pools,
    synthetic_delta_pools,
)
from .timeline import (
    Gate,
    GateResult,
    Phase,
    Scenario,
    gate_accounting,
    gate_cadence,
    gate_drops,
    gate_e2e_p99,
    gate_events,
    gate_evicted,
    gate_feature_sanity,
    gate_final_state,
    gate_flows,
    gate_known_accept,
    gate_namespace_evicted,
    gate_restart_refused,
    gate_rule_accounting,
    gate_rules_installed,
    gate_unknown_recall,
    gate_zero_rule_flaps,
)

_PROFILES = ("t1", "cpu")


def _check_profile(profile: str) -> bool:
    if profile not in _PROFILES:
        raise ValueError(
            f"unknown scenario profile {profile!r} (expected one of "
            f"{_PROFILES})"
        )
    return profile == "t1"


def _feed_spec(sid: int, feed, name: str = "") -> SourceSpec:
    return SourceSpec(
        kind="feed", sid=sid, feed=feed, lockstep=True, name=name,
    )


def _records_feed(workloads, start_tick: int = 0):
    """A feed emitting ``format_line`` wire bytes for each workload's
    ``tick()`` records — silent (noise line) before ``start_tick``.
    Stateful: counters advance only on emitting ticks."""
    n = {"i": 0}

    def feed(_i: int) -> bytes:
        i = n["i"]
        n["i"] = i + 1
        if i < start_tick:
            return b""
        return b"".join(
            format_line(r) for w in workloads for r in w.tick()
        )

    return feed


# -- 1 · flash crowd ---------------------------------------------------------

def flash_crowd(profile: str = "t1") -> Scenario:
    """10× source ramp mid-serve: one source carries the baseline,
    then nine more populations light up on the SAME serve loop in one
    tick. The serve must absorb a 10× record-rate and flow-population
    step without dropping a record or losing its cadence."""
    t1 = _check_profile(profile)
    n_sources = 10
    flows = 8 if t1 else 32
    baseline = 3 if t1 else 5
    surge = 5 if t1 else 15

    def make_feed(sid: int):
        gen = SyntheticFlows(flows, seed=sid, mac_base=sid * flows)
        start = 0 if sid == 0 else baseline

        def feed(_i: int, n={"i": 0}) -> bytes:
            i = n["i"]
            n["i"] = i + 1
            return gen.tick_bytes() if i >= start else b""

        return feed

    sources = tuple(
        _feed_spec(sid, make_feed(sid), f"crowd-{sid}")
        for sid in range(n_sources)
    )
    total_flows = n_sources * flows  # one flow slot per conversation
    return Scenario(
        id="flash_crowd",
        title="flash crowd: 10x source ramp mid-serve",
        phases=(Phase("baseline", baseline), Phase("surge", surge)),
        sources=sources,
        capacity=max(256, 2 * total_flows),
        gates=(
            gate_cadence(1.0),
            gate_accounting(),
            gate_drops(expect=False),
            gate_e2e_p99(1.0),
            gate_flows(total_flows, total_flows),
        ),
        notes=f"{n_sources} sources x {flows} conversations",
    )


# -- 2 · source flap storm ---------------------------------------------------

def source_flap_storm(profile: str = "t1") -> Scenario:
    """Repeated unclean deaths + restarts racing the quarantine timer
    — the livelock satellite 1 fixed: each restart used to cancel the
    pending quarantine forever. The tier must ESCALATE after the flap
    cap, refuse further restarts, and let the quarantine finally evict
    the namespace while the other sources keep serving."""
    t1 = _check_profile(profile)
    flows = 8 if t1 else 16
    victim = 2

    def make_feed(sid: int):
        gen = SyntheticFlows(flows, seed=sid, mac_base=sid * flows)
        return lambda _i: gen.tick_bytes()

    sources = tuple(
        _feed_spec(sid, make_feed(sid), f"flap-{sid}")
        for sid in range(3)
    )
    # virtual-time script (clock_step_s=1.0 → vt == tick index):
    # kill@2 (quarantine deadline 5) → restart@3 cancels it;
    # kill@4 (deadline 7) → restart@5 cancels; kill@6 is the 3rd flap
    # inside the window → ESCALATED, deadline 9 stands; restart@7 is
    # REFUSED; take_evictions at vt=9 evicts the namespace.
    actions = {
        2: (lambda ctx: ctx.kill(victim),),
        3: (lambda ctx: ctx.restart(victim),),
        4: (lambda ctx: ctx.kill(victim),),
        5: (lambda ctx: ctx.restart(victim),),
        6: (lambda ctx: ctx.kill(victim),),
        7: (lambda ctx: ctx.restart(victim),),
    }
    return Scenario(
        id="source_flap_storm",
        title="source flap storm: restarts racing the quarantine",
        phases=(
            Phase("steady", 2),
            Phase("flapping", 6),
            Phase("escalated", 6),
        ),
        sources=sources,
        actions=actions,
        capacity=max(256, 3 * flows * 4),
        quarantine_s=3.0,
        max_flaps=3,
        flap_window_s=60.0,
        gates=(
            gate_events(required=(
                "fanin.source_dead",
                "fanin.source_restart",
                "fanin.flap_escalated",
                "fanin.restart_refused",
            )),
            gate_restart_refused(1),
            gate_namespace_evicted(victim),
            gate_flows(2 * flows, 2 * flows),
            gate_cadence(1.0),
            gate_accounting(),
            gate_drops(expect=False),
        ),
        notes="victim sid 2 flaps 3x; survivors keep serving",
    )


# -- 3 · cumulative-counter reset storm --------------------------------------

def counter_reset_storm(profile: str = "t1") -> Scenario:
    """Mod-2^32 deltas across MANY flows in ONE tick: the whole
    population's cumulative counters reset simultaneously (a switch
    reboot, not a single flow re-add — PR 13 pinned the single-flow
    shape). Every feature must stay physically plausible and the flow
    population must not change."""
    t1 = _check_profile(profile)
    flows = 32 if t1 else 256
    pre = 3 if t1 else 5
    post = 4 if t1 else 6
    state = {"gen": SyntheticFlows(flows, seed=3)}

    def feed(_i: int, n={"i": 0}) -> bytes:
        i = n["i"]
        n["i"] = i + 1
        if i == pre:
            # the storm: a fresh generator, same flow keys (same seed/
            # mac_base), counters restarted from zero — every flow's
            # next cumulative value goes BACKWARD in the same tick
            state["gen"] = SyntheticFlows(
                flows, seed=3, start_time=state["gen"].t,
            )
        return state["gen"].tick_bytes()

    return Scenario(
        id="counter_reset_storm",
        title="cumulative-counter reset storm across the population",
        phases=(Phase("cruise", pre), Phase("reset_storm", post)),
        sources=(_feed_spec(0, feed, "reset-storm"),),
        capacity=max(256, flows * 4),
        gates=(
            gate_feature_sanity(1e9),
            gate_flows(flows, flows),
            gate_cadence(1.0),
            gate_accounting(),
            gate_drops(expect=False),
        ),
        notes=f"{flows} conversations reset in one tick",
    )


# -- 4 · novel-class wave + boundary-hugging evasion -------------------------

def novel_wave_evasion(profile: str = "t1") -> Scenario:
    """Open-world under adversarial pressure: the stream carries a
    closed-world base AND boundary-hugging perturbed flows
    (workload.perturb_pools — hardest known rows) from tick 0; a NOVEL
    class joins mid-run. The calibrated open-set tier must reject the
    novel wave as ``unknown`` while NOT rejecting the evasion flows it
    calibrated over."""
    t1 = _check_profile(profile)
    fpc = 2 if t1 else 6
    calibrate = 5 if t1 else 8
    wave = 5 if t1 else 8
    pools = synthetic_delta_pools(4)
    base = ClassWorkload(pools, flows_per_class=fpc, seed=0)
    evasion = ClassWorkload(
        perturb_pools(pools, epsilon=0.2), flows_per_class=fpc,
        seed=1, mac_base=2 * len(base.labels),
    )
    novel = ClassWorkload(
        {"novel": novel_delta_pool(pools)},
        flows_per_class=max(2, fpc), seed=2,
        mac_base=2 * len(base.labels) + 2 * len(evasion.labels),
    )
    known_macs = {
        mac
        for w in (base, evasion)
        for i in range(len(w.labels))
        for mac in w.flow_macs(i)
    }
    novel_macs = {
        mac
        for i in range(len(novel.labels))
        for mac in novel.flow_macs(i)
    }
    known_feed = _records_feed([base, evasion])
    wave_feed = _records_feed([novel], start_tick=calibrate)

    def feed(i: int) -> bytes:
        return known_feed(i) + wave_feed(i)

    n_known_rows = 2 * (len(base.labels) + len(evasion.labels))
    return Scenario(
        id="novel_wave_evasion",
        title="novel-class wave + boundary-hugging evasion",
        phases=(Phase("calibrate", calibrate), Phase("wave", wave)),
        sources=(_feed_spec(0, feed, "open-world"),),
        capacity=max(128, 4 * n_known_rows),
        n_classes=4,
        openset={
            "margin": 3.0,
            # arm inside the calibrate phase: ~n_known_rows active
            # rows fold in per tick
            "calibration_rows": 2 * n_known_rows,
        },
        gates=(
            gate_unknown_recall(novel_macs, min_recall=0.9),
            gate_known_accept(known_macs, max_reject=0.05),
            gate_events(required=("openset.reject",)),
            gate_cadence(1.0),
            gate_accounting(),
            gate_drops(expect=False),
        ),
        notes="evasion flows inside the calibration envelope",
    )


# -- 5 · mass-eviction churn spike -------------------------------------------

def mass_eviction_churn(profile: str = "t1") -> Scenario:
    """A churn spike: most of the flow population goes silent at once
    and must be idle-evicted in bulk while a live population keeps
    serving — the table shrinks by thousands of slots (profile-scaled)
    without a cadence wobble or an accounting gap."""
    t1 = _check_profile(profile)
    doomed_flows = 24 if t1 else 512
    live_flows = 8 if t1 else 64
    mixed = 4 if t1 else 6
    churn = 8 if t1 else 10
    idle_s = 3
    doomed = SyntheticFlows(doomed_flows, seed=4)
    live = SyntheticFlows(
        live_flows, seed=5, mac_base=doomed_flows + 8,
    )

    def feed(_i: int, n={"i": 0}) -> bytes:
        i = n["i"]
        n["i"] = i + 1
        if i < mixed:
            return doomed.tick_bytes() + live.tick_bytes()
        return live.tick_bytes()

    return Scenario(
        id="mass_eviction_churn",
        title="mass-eviction churn spike",
        phases=(Phase("mixed", mixed), Phase("churn", churn)),
        sources=(_feed_spec(0, feed, "churn"),),
        capacity=max(256, (doomed_flows + live_flows) * 4),
        idle_evict_s=float(idle_s),
        gates=(
            gate_evicted(doomed_flows),
            gate_flows(live_flows, live_flows),
            gate_cadence(1.0),
            gate_accounting(),
            gate_drops(expect=False),
        ),
        notes=f"{doomed_flows} conversations go silent at tick {mixed}",
    )


# -- 6 · queue-saturation flood ----------------------------------------------

def queue_saturation_flood(profile: str = "t1") -> Scenario:
    """Aggregate rate past the FanInQueue bound: two sources whose
    combined per-tick record count overflows the queue. The contract
    under saturation is NOT zero drops — it is zero SILENT drops:
    every dropped batch is counted against its source, accounting
    stays exact, and the loop keeps its cadence. Runs on the REAL
    clock: the starved lockstep slot can never deliver its dropped
    batch, so the tick-assembly deadline must actually expire."""
    t1 = _check_profile(profile)
    modest = 8 if t1 else 32
    flood = 40 if t1 else 160
    ticks = 5 if t1 else 8

    def make_feed(sid: int, flows: int):
        gen = SyntheticFlows(flows, seed=sid, mac_base=sid * flood)
        return lambda _i: gen.tick_bytes()

    return Scenario(
        id="queue_saturation_flood",
        title="queue-saturation flood past the fan-in bound",
        phases=(Phase("flood", ticks),),
        sources=(
            _feed_spec(0, make_feed(0, modest), "flood-modest"),
            _feed_spec(1, make_feed(1, flood), "flood-heavy"),
        ),
        capacity=max(256, flood * 2 * 4),
        # deterministic saturation, no drain race: the modest source's
        # 2*modest-record batch always fits the bound, the heavy
        # source's 2*flood-record batch NEVER does (even into an empty
        # queue) — every one of its ticks drops whole and attributed
        queue_records=4 * modest,
        real_clock=True,
        tick_timeout=0.25,
        gates=(
            gate_drops(expect=True),
            gate_accounting(),
            gate_events(required=("fanin.drop",)),
            gate_flows(modest, modest),
            gate_cadence(1.0),
        ),
        notes="heavy source's batch alone exceeds the queue bound",
    )


# -- 7 · device wedge + degrade recovery -------------------------------------

def device_wedge_degrade(profile: str = "t1") -> Scenario:
    """A device dispatch stall mid-serve (fault site
    ``degrade.dispatch_stall``): the ladder must demote to the host
    fallback without missing a tick, probe on the virtual clock, and
    END the run recovered (final transition back to HEALTHY)."""
    t1 = _check_profile(profile)
    flows = 16 if t1 else 64
    gen = SyntheticFlows(flows, seed=6)
    return Scenario(
        id="device_wedge_degrade",
        title="device wedge: degrade demotion + probed recovery",
        phases=(
            Phase("healthy", 3),
            Phase("wedged", 4),
            Phase("recovery", 8),
        ),
        sources=(
            _feed_spec(0, lambda _i: gen.tick_bytes(), "wedge"),
        ),
        capacity=max(256, flows * 4),
        degrade={
            "deadline": 2.0,
            "probe_every": 1.5,
            "probe_successes": 2,
        },
        # 3rd in-plan device call wedges (ticks 0,1 pass → tick 2)
        fault_rules=(
            {"site": "degrade.dispatch_stall", "after": 2, "times": 1},
        ),
        gates=(
            gate_events(required=("degrade.transition", "fault.fire")),
            gate_final_state("degrade.transition", "to", "HEALTHY"),
            gate_cadence(1.0),
            gate_accounting(),
            gate_drops(expect=False),
            gate_e2e_p99(2.5),
        ),
        notes="dispatch stall at tick 2; probes on the virtual clock",
    )


# -- 8 · label flap storm vs the actuation hysteresis ------------------------

def label_flap_storm(profile: str = "t1") -> Scenario:
    """Class-boundary oscillation vs the actuation plane (F14): a
    stable population earns its flow-rules, an oscillating population
    flips label every tick (its per-tick deltas alternate between the
    lightest and heaviest class pools — the classifier cannot hold a
    verdict), and a novel wave joins mid-run to blip the open-set
    ``unknown`` through the rendered table. Push mode against the
    AccountingSwitch with an ``actuation.send`` fault mid-storm: the
    plane must degrade to dry-run, re-probe on the virtual clock,
    reconcile, and re-earn its installs — with ZERO rule flaps, the
    rule ledger exact, and the serve cadence untouched throughout."""
    t1 = _check_profile(profile)
    fpc = 2 if t1 else 4               # stable flows per class (4 classes)
    osc_flows = 4 if t1 else 8
    novel_flows = 2 if t1 else 4
    calibrate = 4 if t1 else 6
    storm = 6 if t1 else 10
    wave = 6 if t1 else 8
    pools = synthetic_delta_pools(4)
    stable = ClassWorkload(pools, flows_per_class=fpc, seed=0)
    # the oscillator: same conversations every tick, but the pool its
    # deltas draw from alternates between the lightest and heaviest
    # class shape — cumulative counters stay monotonic (no wrap
    # artifacts), the per-tick features swing ~64x, and the label
    # cannot complete an install streak
    keys = sorted(pools)
    osc_pools = {"osc": pools[keys[0]]}
    osc = ClassWorkload(
        osc_pools, flows_per_class=osc_flows, seed=7,
        mac_base=4 * len(stable.labels),
    )
    novel = ClassWorkload(
        {"novel": novel_delta_pool(pools)},
        flows_per_class=novel_flows, seed=2,
        mac_base=4 * len(stable.labels) + 4 * len(osc.labels),
    )
    stable_feed = _records_feed([stable])
    wave_feed = _records_feed([novel], start_tick=calibrate + storm)

    def osc_feed(_i: int, n={"i": 0}) -> bytes:
        i = n["i"]
        n["i"] = i + 1
        osc_pools["osc"] = pools[keys[0]] if i % 2 else pools[keys[-1]]
        return b"".join(format_line(r) for r in osc.tick())

    def feed(i: int) -> bytes:
        return stable_feed(i) + osc_feed(i) + wave_feed(i)

    n_flows = len(stable.labels) + len(osc.labels)
    return Scenario(
        id="label_flap_storm",
        title="label flap storm vs the actuation hysteresis",
        phases=(
            Phase("calibrate", calibrate),
            Phase("storm", storm),
            Phase("wave", wave),
        ),
        sources=(_feed_spec(0, feed, "flap-storm"),),
        capacity=max(256, 8 * (n_flows + novel_flows)),
        table_rows=2 * (n_flows + novel_flows),
        n_classes=4,
        openset={
            "margin": 3.0,
            "calibration_rows": 2 * n_flows,
        },
        actuation={
            # every class carries a clause: any stable verdict earns a
            # rule, so the hysteresis is exercised on the whole table
            "policy": ("class0=queue:1,class1=queue:2,"
                       "class2=meter:5,class3=drop"),
            "mode": "push",
            "k_install": 3,
            "k_retract": 3,
            "backoff_base_s": 1.0,
        },
        # mid-storm wire fault: the 3rd pushed mod dies — the first
        # install burst must degrade to dry-run, not break accounting
        fault_rules=(
            {"site": "actuation.send", "after": 2, "times": 1},
        ),
        gates=(
            gate_zero_rule_flaps(min_suppressed=1),
            gate_rule_accounting(),
            gate_rules_installed(len(stable.labels)),
            gate_events(required=(
                "actuation.install",
                "actuation.flap_suppressed",
                "actuation.degrade",
                "actuation.probe",
                "actuation.reconcile",
                "fault.fire",
                "openset.reject",
            )),
            gate_cadence(1.0),
            gate_accounting(),
            gate_drops(expect=False),
        ),
        notes=(f"{len(stable.labels)} stable + {len(osc.labels)} "
               f"oscillating conversations; novel wave at tick "
               f"{calibrate + storm}"),
    )


SCENARIOS = {
    "flash_crowd": flash_crowd,
    "source_flap_storm": source_flap_storm,
    "counter_reset_storm": counter_reset_storm,
    "novel_wave_evasion": novel_wave_evasion,
    "mass_eviction_churn": mass_eviction_churn,
    "queue_saturation_flood": queue_saturation_flood,
    "device_wedge_degrade": device_wedge_degrade,
    "label_flap_storm": label_flap_storm,
}


def build(name: str, profile: str = "t1") -> Scenario:
    """Instantiate one scenario by id (fresh generator state)."""
    try:
        builder = SCENARIOS[name]
    except KeyError:
        raise ValueError(
            f"unknown scenario {name!r} (known: {sorted(SCENARIOS)})"
        ) from None
    return builder(profile)


__all__ = [
    "SCENARIOS",
    "build",
    "Gate",
    "GateResult",
    "Phase",
    "Scenario",
]
