"""Adversarial scenario campaign (F13): composable traffic-scenario
timelines with per-scenario SLO scorecards, driven through the REAL
serve loop (fan-in tier × native ingest × incremental serving, with
the degrade/open-set ladders live where a scenario arms them).

- ``timeline``  — the declarative half: Scenario/Phase/Gate + the gate
  factory vocabulary (cadence, exact drop accounting, e2e p99,
  transition events, open-world ground truth, …);
- ``library``   — the scenarios themselves (flash crowd, flap storm,
  reset storm, novel wave + evasion, mass eviction, queue flood,
  device wedge) in ``t1`` and ``cpu`` profiles;
- ``runner``    — the campaign runner: drives a timeline through the
  serve composition on a virtual clock, evaluates the gates, and
  dumps an atomic post-mortem bundle on gate failure.

The campaign artifact lives at docs/artifacts/scenario_matrix_cpu.json
(tools/bench_scenarios.py regenerates it and exits nonzero on any gate
failure).
"""

from .library import SCENARIOS, build
from .runner import RunContext, run_campaign, run_scenario
from .timeline import Gate, GateResult, Phase, Scenario

__all__ = [
    "SCENARIOS",
    "build",
    "run_campaign",
    "run_scenario",
    "RunContext",
    "Gate",
    "GateResult",
    "Phase",
    "Scenario",
]
