"""Framework configuration — the typed flag layer the reference lacks.

The reference hardcodes every knob: the monitor launch command
(traffic_classifier.py:22), the 15-minute collection timeout (:27), model
pickle paths (:230-240), the 1 Hz poll period (simple_monitor_13.py:36),
and the print-every-10-lines cadence (traffic_classifier.py:167); SURVEY.md
§5 calls for a real config layer for mesh shape, batch/padding policy,
model choice, and poll rates. One frozen dataclass, JSON round-trip,
overridable field-by-field from CLI flags or environment.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field


@dataclass(frozen=True)
class MeshConfig:
    """Device-mesh shape (parallel/mesh.py axes)."""

    n_data: int = 1  # batch-sharding axis size
    n_state: int = 1  # model-state-sharding axis size (KNN corpus, RF trees)


@dataclass(frozen=True)
class IngestConfig:
    """Host-shell ingest policy (ingest/batcher.py, ingest/collector.py)."""

    capacity: int = 65536  # flow-table rows
    # padded batch sizes (mirror ingest/batcher.DEFAULT_BUCKETS: the top
    # bucket covers a full 2²⁰-record tick in one flush)
    buckets: tuple = (256, 1024, 4096, 16384, 65536, 262144, 1048576)
    shards: int = 0  # >1: mesh-shard the flow table (--shards)
    idle_timeout_s: int = 60  # flow eviction horizon (0 = never)
    poll_period_s: float = 1.0  # monitor poll cadence (reference: 1 Hz)
    monitor_cmd: str | None = None  # None → reference's ryu command
    queue_size: int = 1 << 16


@dataclass(frozen=True)
class ModelConfig:
    """Model family + checkpoint selection."""

    name: str = "forest"  # MODEL_MODULES key
    # resolution: CLI --checkpoint-dir > this field > $TCSDN_MODELS_DIR >
    # ./models (the reference's own relative layout, traffic_classifier.py:230)
    checkpoint_dir: str | None = None
    native_checkpoint: str | None = None  # io/checkpoint.py dir (wins)
    dtype: str = "float32"


@dataclass(frozen=True)
class TrainConfig:
    """Offline retraining knobs (train/*)."""

    test_size: float = 0.5  # notebook 50/50 split
    seed: int = 101  # notebook random_state
    collect_duration_s: float = 15 * 60  # reference TIMEOUT (:27)
    checkpoint_every: int = 0  # steps between train-state saves (0 = off)
    train_state_dir: str | None = None  # where resumable state lands


@dataclass(frozen=True)
class Config:
    mesh: MeshConfig = field(default_factory=MeshConfig)
    ingest: IngestConfig = field(default_factory=IngestConfig)
    model: ModelConfig = field(default_factory=ModelConfig)
    train: TrainConfig = field(default_factory=TrainConfig)
    print_every: int = 10  # render cadence, poll ticks


def _to_dict(cfg) -> dict:
    d = dataclasses.asdict(cfg)

    def tuples_to_lists(v):
        if isinstance(v, dict):
            return {k: tuples_to_lists(x) for k, x in v.items()}
        if isinstance(v, tuple):
            return list(v)
        return v

    return tuples_to_lists(d)


def save(cfg: Config, path: str) -> None:
    with open(path, "w") as f:
        json.dump(_to_dict(cfg), f, indent=1)


def load(path: str) -> Config:
    with open(path) as f:
        return from_dict(json.load(f))


def from_dict(d: dict) -> Config:
    """Build a Config from a (possibly partial) nested dict — unknown keys
    are an error, missing keys take defaults."""

    def build(cls, sub: dict):
        names = {f.name for f in dataclasses.fields(cls)}
        unknown = set(sub) - names
        if unknown:
            raise ValueError(f"unknown {cls.__name__} keys: {sorted(unknown)}")
        kwargs = {}
        for f in dataclasses.fields(cls):
            if f.name not in sub:
                continue
            v = sub[f.name]
            kwargs[f.name] = tuple(v) if isinstance(v, list) else v
        return cls(**kwargs)

    return Config(
        mesh=build(MeshConfig, d.get("mesh", {})),
        ingest=build(IngestConfig, d.get("ingest", {})),
        model=build(ModelConfig, d.get("model", {})),
        train=build(TrainConfig, d.get("train", {})),
        **{k: v for k, v in d.items()
           if k not in ("mesh", "ingest", "model", "train")},
    )
