"""Analysis toolkit: on-device preprocessing (StandardScaler, PCA),
evaluation (accuracy, confusion matrix), and cluster→label mode matching
— the TPU-native equivalent of the reference's notebook analysis cells
(SURVEY.md §2 C13: 1_log_Kmeans.ipynb cells 70-129)."""

from .eval import accuracy, confusion_matrix, match_clusters  # noqa: F401
from .preprocess import PCA, StandardScaler  # noqa: F401
