"""C13 analysis figures — the visual half of the reference's analysis
notebook, rendered from the on-device analysis stack.

Reproduces the four figure families of ``models/notebooks.zip!notebooks/
1_log_Kmeans.ipynb`` cells 70-129 (the round-1 gap VERDICT item 7):

- cell 85: PCA-2 scatter of the scaled features, colored by traffic type;
- cell 98: logistic-regression decision boundaries in PCA-2 space
  (contourf over a meshgrid + the class scatter);
- cell 112: per-class cluster-center strips (each KMeans center as a
  1×12 heatmap);
- cell 126: side-by-side PCA-2 scatters of learned cluster ids vs true
  labels (the notebook's KMeans-on-raw-PCA comparison, cells 122-126).

All numerics run through the framework's own kernels (analysis.preprocess
scaler/PCA, train.logreg, train.kmeans) — matplotlib only draws.
"""

from __future__ import annotations

import os

import numpy as np

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402


def _scatter_by_class(ax, Z, y, classes):
    cmap = plt.get_cmap("tab10")
    for i, name in enumerate(classes):
        m = y == i
        ax.scatter(Z[m, 0], Z[m, 1], s=8, alpha=0.6,
                   color=cmap(i % 10), label=str(name))


def fig_pca_scatter(Z, y, classes, path: str) -> None:
    """Cell 85: PCA-2 embedding colored by true traffic type."""
    fig, ax = plt.subplots(figsize=(10, 6))
    _scatter_by_class(ax, Z, y, classes)
    ax.set_xlabel("First Principal Component", fontsize=15)
    ax.set_ylabel("Second Principal Component", fontsize=15)
    ax.legend(fontsize=12)
    fig.tight_layout()
    fig.savefig(path, dpi=110)
    plt.close(fig)


def fig_decision_boundary(Z, y, classes, predict_grid, path: str,
                          spacing: float = 0.05) -> None:
    """Cell 98: contourf of a classifier's prediction over a PCA-2
    meshgrid, overlaid with the class scatter. ``predict_grid`` maps an
    (M, 2) array of PCA coordinates to int class ids."""
    x_min, x_max = Z[:, 0].min() - 1, Z[:, 0].max() + 1
    y_min, y_max = Z[:, 1].min() - 1, Z[:, 1].max() + 1
    xx, yy = np.meshgrid(
        np.arange(x_min, x_max, spacing), np.arange(y_min, y_max, spacing)
    )
    grid = np.stack([xx.ravel(), yy.ravel()], axis=1).astype(np.float32)
    zz = np.asarray(predict_grid(grid)).reshape(xx.shape)
    fig, ax = plt.subplots(figsize=(10, 6))
    ax.contourf(xx, yy, zz, cmap=plt.cm.Spectral, alpha=0.8,
                levels=np.arange(len(classes) + 1) - 0.5)
    _scatter_by_class(ax, Z, y, classes)
    ax.set_title("Decision Boundaries", fontsize=15)
    ax.set_xlabel("First Principal Component", fontsize=15)
    ax.set_ylabel("Second Principal Component", fontsize=15)
    ax.set_xlim(x_min, x_max)
    ax.set_ylim(y_min, y_max)
    ax.legend(fontsize=12)
    fig.tight_layout()
    fig.savefig(path, dpi=110)
    plt.close(fig)


def fig_cluster_centers(centers, names, path: str) -> None:
    """Cell 112: each cluster center as a 1×F binary-cmap strip."""
    k = centers.shape[0]
    ncols = 2
    nrows = (k + ncols - 1) // ncols
    fig = plt.figure(figsize=(8, 1.6 * nrows))
    for i in range(k):
        ax = fig.add_subplot(nrows, ncols, 1 + i, xticks=[], yticks=[])
        ax.set_title(str(names[i]))
        ax.imshow(centers[i].reshape(1, -1), cmap=plt.cm.binary,
                  aspect="auto")
    fig.tight_layout()
    fig.savefig(path, dpi=110)
    plt.close(fig)


def fig_cluster_scatter(Z, clusters, y, path: str) -> None:
    """Cell 126: learned cluster ids vs true labels, side by side."""
    k = int(max(clusters.max(), y.max())) + 1
    kwargs = dict(cmap=plt.get_cmap("rainbow", k), edgecolor="none",
                  alpha=0.6, s=8)
    fig, ax = plt.subplots(1, 2, figsize=(9, 4))
    ax[0].scatter(Z[:, 0], Z[:, 1], c=clusters, **kwargs)
    ax[0].set_title("learned cluster labels")
    ax[1].scatter(Z[:, 0], Z[:, 1], c=y, **kwargs)
    ax[1].set_title("true labels")
    fig.tight_layout()
    fig.savefig(path, dpi=110)
    plt.close(fig)


def save_all(ds, out_dir: str, seed: int = 101) -> dict:
    """Render every C13 figure for a FlowDataset; returns
    {figure_name: path} plus the headline analysis numbers (PCA-2
    explained variance, PCA-space logreg accuracy, cluster accuracy)."""
    import jax.numpy as jnp

    from ..train import kmeans as kmeans_train
    from ..train import logreg as logreg_train
    from . import eval as ev
    from .preprocess import PCA, StandardScaler

    os.makedirs(out_dir, exist_ok=True)
    # dtype follows the x64 config: float64 under the test harness
    # (conftest enables x64 for sklearn-exact parity), float32 in the
    # production CLI — an explicit float64 request would silently
    # truncate there and warn on every run.
    X = jnp.asarray(ds.X)
    y = np.asarray(ds.y)
    k = len(ds.classes)

    # scaled PCA-2 embedding (cells 70-85)
    sp = StandardScaler.fit(X)
    Xs = StandardScaler.transform(sp, X)
    pp = PCA.fit(Xs, 2)
    Z = np.asarray(PCA.transform(pp, Xs))
    evr = float(np.sum(np.asarray(pp.explained_variance_ratio)))
    paths = {"pca_scatter": os.path.join(out_dir, "pca_scatter.png")}
    fig_pca_scatter(Z, y, ds.classes, paths["pca_scatter"])

    # logreg decision boundary in PCA space (cells 89-98); split on the
    # embedded coordinates directly (70/30, notebook cell 91)
    from ..models import logreg as logreg_model

    rng = np.random.RandomState(seed)
    perm = rng.permutation(len(Z))
    n_te = int(round(len(Z) * 0.3))
    te_idx, tr_idx = perm[:n_te], perm[n_te:]
    lp = logreg_train.fit(Z[tr_idx], y[tr_idx], k)
    pred = np.asarray(
        logreg_model.predict(lp, jnp.asarray(Z[te_idx], jnp.float32))
    )
    pca_logreg_acc = float(np.mean(pred == y[te_idx]))
    paths["decision_boundary"] = os.path.join(
        out_dir, "decision_boundary.png"
    )
    fig_decision_boundary(
        Z, y, ds.classes,
        lambda G: logreg_model.predict(lp, jnp.asarray(G)),
        paths["decision_boundary"],
    )

    # KMeans on scaled features: center strips (cells 104-112)
    kp, _ = kmeans_train.fit(np.asarray(Xs), k=k, seed=0)
    centers_scaled = np.asarray(kp.centers)
    paths["cluster_centers"] = os.path.join(out_dir, "cluster_centers.png")
    fig_cluster_centers(
        centers_scaled, [f"cluster {i}" for i in range(k)],
        paths["cluster_centers"],
    )

    # KMeans on raw-PCA coordinates: side-by-side scatter (cells 122-126)
    pr = PCA.fit(X, 2)
    Zr = np.asarray(PCA.transform(pr, X))
    kp2, _ = kmeans_train.fit(Zr, k=k, seed=0)
    from ..models import kmeans as kmeans_model

    clusters = np.asarray(
        kmeans_model.predict(kp2, jnp.asarray(Zr, jnp.float32))
    )
    cluster_acc = float(
        ev.clustering_accuracy(
            jnp.asarray(clusters), jnp.asarray(y), k, len(ds.classes)
        )
    )
    paths["cluster_scatter"] = os.path.join(out_dir, "cluster_scatter.png")
    fig_cluster_scatter(Zr, clusters, y, paths["cluster_scatter"])

    return {
        "paths": paths,
        "pca2_explained_variance": evr,
        "pca_logreg_accuracy": pca_logreg_acc,
        "cluster_accuracy": cluster_acc,
    }
