"""On-device preprocessing: StandardScaler and PCA as pure JAX.

TPU-native reimplementation of the reference's analysis-only pipeline
(1_log_Kmeans.ipynb cells 70-98: StandardScaler → PCA(2) with 81.11%
explained variance → PCA-space LogisticRegression at 83.03%). The
reference never ships these to the online path (no scaler is pickled —
SURVEY.md §3.5); we keep them importable for both analysis and as
optional feature-space transforms.

Both are parameter NamedTuples + pure functions, so they jit/vmap/pjit
like every other model in the framework. PCA is computed from the
covariance eigendecomposition (features are only 12-dimensional: the
12×12 eigh is trivial; no need for a randomized SVD) with sklearn's sign
convention (largest-|loading| component positive) so parity tests can
compare components directly.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class ScalerParams(NamedTuple):
    mean: jax.Array  # (d,)
    scale: jax.Array  # (d,) std with ddof=0; zeros replaced by 1


class StandardScaler:
    """fit/transform with sklearn semantics (ddof=0, zero-variance → 1)."""

    @staticmethod
    def fit(X: jax.Array) -> ScalerParams:
        mean = jnp.mean(X, axis=0)
        var = jnp.var(X, axis=0)
        scale = jnp.where(var == 0.0, 1.0, jnp.sqrt(var))
        return ScalerParams(mean=mean, scale=scale)

    @staticmethod
    def transform(p: ScalerParams, X: jax.Array) -> jax.Array:
        return (X - p.mean) / p.scale

    @staticmethod
    def inverse_transform(p: ScalerParams, Z: jax.Array) -> jax.Array:
        return Z * p.scale + p.mean


class PCAParams(NamedTuple):
    mean: jax.Array  # (d,)
    components: jax.Array  # (k, d) rows = principal axes
    explained_variance: jax.Array  # (k,)
    explained_variance_ratio: jax.Array  # (k,)


class PCA:
    """Principal components via covariance eigh (exact for small d)."""

    @staticmethod
    def fit(X: jax.Array, n_components: int) -> PCAParams:
        n = X.shape[0]
        mean = jnp.mean(X, axis=0)
        Xc = X - mean
        # sample covariance with ddof=1, matching sklearn's PCA
        cov = (Xc.T @ Xc) / (n - 1)
        eigvals, eigvecs = jnp.linalg.eigh(cov)  # ascending
        order = jnp.argsort(-eigvals)
        eigvals = eigvals[order][:n_components]
        comps = eigvecs[:, order][:, :n_components].T  # (k, d)
        # sklearn sign convention: largest-|loading| entry positive
        idx = jnp.argmax(jnp.abs(comps), axis=1)
        signs = jnp.sign(comps[jnp.arange(comps.shape[0]), idx])
        comps = comps * signs[:, None]
        total_var = jnp.sum(jnp.var(X, axis=0, ddof=1))
        return PCAParams(
            mean=mean,
            components=comps,
            explained_variance=eigvals,
            explained_variance_ratio=eigvals / total_var,
        )

    @staticmethod
    def transform(p: PCAParams, X: jax.Array) -> jax.Array:
        return (X - p.mean) @ p.components.T

    @staticmethod
    def inverse_transform(p: PCAParams, Z: jax.Array) -> jax.Array:
        return Z @ p.components + p.mean
