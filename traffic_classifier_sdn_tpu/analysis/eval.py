"""On-device evaluation: accuracy, confusion matrix, cluster→label mode
matching.

The reference's only quality control is notebook-side held-out accuracy
and seaborn confusion-matrix plots (SURVEY.md §4); the KMeans
cluster→label map is derived by taking the mode of the true labels inside
each cluster (1_log_Kmeans.ipynb cell 116). These are the same
computations as pure jit-able functions over device arrays, usable in
tests, retraining gates, and the CLI's retrain report.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def accuracy(y_true: jax.Array, y_pred: jax.Array) -> jax.Array:
    """Fraction of exact matches (scalar float32)."""
    return jnp.mean((y_true == y_pred).astype(jnp.float32))


def confusion_matrix(y_true: jax.Array, y_pred: jax.Array,
                     n_classes: int) -> jax.Array:
    """(n_classes, n_classes) int32; rows = true, cols = predicted —
    sklearn's orientation."""
    idx = y_true.astype(jnp.int32) * n_classes + y_pred.astype(jnp.int32)
    flat = jnp.zeros((n_classes * n_classes,), jnp.int32).at[idx].add(1)
    return flat.reshape(n_classes, n_classes)


def match_clusters(cluster_ids: jax.Array, y_true: jax.Array, k: int,
                   n_classes: int) -> jax.Array:
    """cluster → label map by majority vote (the notebook's mode
    matching): entry c is the most frequent true label among samples
    assigned to cluster c. Ties resolve to the smallest label, matching
    scipy.stats.mode. Empty clusters map to label 0."""
    counts = jnp.zeros((k, n_classes), jnp.int32).at[
        cluster_ids.astype(jnp.int32), y_true.astype(jnp.int32)
    ].add(1)
    return jnp.argmax(counts, axis=1).astype(jnp.int32)


def clustering_accuracy(cluster_ids: jax.Array, y_true: jax.Array, k: int,
                        n_classes: int) -> jax.Array:
    """Accuracy after mode matching — the notebook's KMeans score
    (1_log_Kmeans.ipynb cell 118: 46.38% on the 4-class data)."""
    remap = match_clusters(cluster_ids, y_true, k, n_classes)
    return accuracy(y_true, remap[cluster_ids.astype(jnp.int32)])
