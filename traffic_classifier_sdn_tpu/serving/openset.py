"""Open-set rejection: calibrated unknown-class detection on the
serving path.

The reference's contract is a closed 6-class world
(``dns, game, ping, quake, telnet, voice`` — PAPER.md), but production
traffic is dominated by classes the model has never seen, and a closed
argmax serves every unseen flow a confident wrong label. This module is
the serve path's "none of the above": an ``OpenSetGate`` wraps the
final predict composition (ladder- and drift-gate-wrapped) and relabels
rows whose features sit too far from EVERY known class as an explicit
``unknown`` — never a stale or fabricated known class.

Score and threshold
-------------------

The rejection score is feature-space, not model-space: for per-class
per-feature reference statistics (mean ``μ_cf``, std ``σ_cf`` — the
same shape of statistics the drift monitor keeps, serving/drift.py),

    d(x, c) = sqrt( mean_f ((x_f − μ_cf) / max(σ_cf, floor_f))² )
    score(x) = min_c d(x, c)

— a diagonal Mahalanobis distance to the nearest known class. Being
feature-space it works identically on EVERY serving rung (device
kernel, native C++ fallback, stale-label BROKEN rung) and for every
family; the per-family ``predict_scores`` surfaces (models/base.py)
remain the model-space view for eval and operators
(tools/bench_openset.py publishes both). ``floor_f`` guards
near-constant features: a within-class std below 5% of the feature's
global calibration std is floored there, so counter jitter cannot
manufacture rejections.

Calibration is from the live stream's first windows — the same
first-windows discipline the drift monitor uses: the gate stays
byte-transparent while it accumulates ``calibration_rows`` active
labeled rows, then freezes per-class stats and sets

    threshold = margin × max(calibration scores)

so traffic from the calibration distribution is, by construction, not
rejected (``--openset auto`` output is byte-identical to ``--openset
off`` on closed-world traffic — pinned serial + pipelined,
``--incremental auto/off``). On a drift promotion the controller
re-bases the gate onto the retrain window's KNOWN-labeled rows
(``rebase``) exactly like it re-bases the monitor's reference — and
because rejected rows never re-enter the retrain window or the class
stats, a promoted model still rejects what it was never taught.

Composition
-----------

The gate is the OUTERMOST predict wrapper (cli.py): promotions hot-swap
inside it, the incremental label cache wraps outside it and watches
``label_epoch`` (any calibration freeze or rebase bumps the gate's own
epoch, so wrong-but-cached closed-world labels never survive an arming
or a threshold move). The drift controller consumes the gate's capture
(``take_capture``) instead of the drift gate's, so the monitor sees the
``unknown`` labels as a (C+1)th class — an unknown-fraction surge IS
the class-mix drift signal, attributed as class ``unknown``.

Fault sites (utils/faults.SITES), both ABSORBED:

- ``openset.score`` — the per-tick scoring fails: that tick serves the
  inner (closed-world) labels fresh; never a fabricated ``unknown``.
- ``openset.calibrate`` — a calibration/rebase update fails: the
  sample is dropped (calibration just takes longer; a failed rebase
  keeps the previous stats), telemetry and labels are never touched.

Threading: predict calls arrive from one thread at a time (the serve
loop / device-stage worker, like DriftGate); ``status()`` may be read
concurrently from the exposition thread. Shared state is guarded by
``_lock``, never held across a predict or a device sync.

Compile discipline: the device relabel program is built once and
jit's shape-keyed cache handles re-traces (a new present-class count
after a rebase, a new dirty-bucket shape under incremental serving).
Each first-use-of-a-shape compiles on the HOST stage at the tick that
hits it — outside the DeviceWatchdog's dispatch (the gate wraps the
ladder, not the reverse), so a compile can never trip a spurious
degrade; it costs that one tick latency, the same
lazily-compiled-path behavior every un-warmed program in the repo
has. The stats' float32 device copies are cached per epoch — no
per-tick upload.
"""

from __future__ import annotations

import threading

import numpy as np

from ..utils import faults

CALIBRATING = "CALIBRATING"
ARMED = "ARMED"

# the openset_state gauge encoding (docs/OBSERVABILITY.md)
STATE_GAUGE = {CALIBRATING: 0, ARMED: 1}

_STD_FLOOR_FRAC = 0.05  # per-class std floor, as a fraction of global std
_EPS = 1e-9


def class_reference(X, y, n_classes: int, eps: float = _EPS) -> dict:
    """Per-class per-feature reference statistics from a labeled window:
    ``{"class_mean": (C, F), "class_std": (C, F), "class_count": (C,)}``
    (float64). Rows labeled outside ``[0, n_classes)`` — the ``unknown``
    index included — are EXCLUDED: an unknown row has no trustworthy
    class to teach. Classes with no rows get zero mean and ``eps`` std
    (inert: nothing is near them, so they never win the min)."""
    X = np.asarray(X, np.float64)
    y = np.asarray(y).astype(np.int64).ravel()[: X.shape[0]]
    mean = np.zeros((n_classes, X.shape[1]), np.float64)
    std = np.full((n_classes, X.shape[1]), eps, np.float64)
    count = np.zeros(n_classes, np.float64)
    for c in range(n_classes):
        rows = X[y == c]
        count[c] = rows.shape[0]
        if rows.shape[0]:
            mean[c] = rows.mean(axis=0)
            std[c] = rows.std(axis=0)
    return {"class_mean": mean, "class_std": std, "class_count": count}


def floored_std(class_std: np.ndarray, global_std: np.ndarray,
                eps: float = _EPS) -> np.ndarray:
    """The score denominator: per-class std floored at
    ``_STD_FLOOR_FRAC`` of the global per-feature std (and ``eps``
    absolutely) — near-constant features can't turn jitter into
    rejections, while a feature that is constant EVERYWHERE still
    rejects genuinely novel values."""
    return np.maximum(
        np.maximum(class_std, _STD_FLOOR_FRAC * global_std[None, :]),
        eps,
    )


def reference_matrices(
    ref: dict, global_std: np.ndarray,
) -> tuple[np.ndarray, np.ndarray] | None:
    """``(mean, inv_std)`` scoring matrices from a ``class_reference``
    dict: EMPTY classes are dropped, not floored — a class the
    calibration window never saw would otherwise become a phantom
    acceptance basin at the origin (mean 0, std floored to 5% of the
    global std), silently accepting exactly the low-rate novel traffic
    the gate exists to reject. None when NO class has rows (nothing to
    measure distance to — the caller must not arm)."""
    present = ref["class_count"] > 0
    if not present.any():
        return None
    mean = ref["class_mean"][present]
    inv_std = 1.0 / floored_std(ref["class_std"][present], global_std)
    return mean, inv_std


def openset_scores(X, mean, inv_std) -> np.ndarray:
    """(N,) min-over-classes diagonal Mahalanobis RMS distance — the
    ONE home of the score expression. The jitted device path in
    ``OpenSetGate`` mirrors it term for term in float32 (device
    dtype): labels can differ from this float64 host path only for a
    score within f32 epsilon of the threshold — ~7 orders of magnitude
    inside the default margin of 3×, so the paths agree on every row
    that isn't an exact threshold tie (tests pin equality on
    representative data)."""
    X = np.asarray(X, np.float64)
    best = None
    for c in range(mean.shape[0]):
        z = (X - mean[c][None, :]) * inv_std[c][None, :]
        d = np.mean(z * z, axis=-1)
        best = d if best is None else np.minimum(best, d)
    return np.sqrt(best)


class OpenSetGate:
    """The outermost predict wrapper: closed-world labels in, open-set
    labels out (``unknown_index == n_classes`` for rejected rows).

    Byte-transparent until calibration completes, and on every fault
    path after it — a scoring failure serves that tick's inner labels
    fresh. ``host_native`` mirrors the wrapped predict so the serve
    loop's routing is unchanged.
    """

    def __init__(self, predict, n_classes: int, *, margin: float = 3.0,
                 calibration_rows: int = 4096,
                 metrics=None, recorder=None, reference: dict | None = None):
        if n_classes < 1:
            raise ValueError("n_classes must be >= 1")
        if margin <= 0:
            raise ValueError("margin must be > 0")
        self.host_native = bool(getattr(predict, "host_native", False))
        self.n_classes = int(n_classes)
        self.unknown_index = int(n_classes)
        self.margin = float(margin)
        self.calibration_rows = max(1, int(calibration_rows))
        self._inner = predict
        self._metrics = metrics
        self._recorder = recorder
        self._lock = threading.Lock()
        self._state = CALIBRATING
        self._epoch = 0
        # calibration accumulators (host; dropped at freeze) + the
        # one-tick-deferred (X, labels) pair awaiting materialization
        self._cal_X: list[np.ndarray] = []
        self._cal_y: list[np.ndarray] = []
        self._cal_rows = 0
        self._pending_cal: tuple | None = None
        # armed stats (compacted — present classes only)
        self._mean: np.ndarray | None = None  # (P, F) f64
        self._inv_std: np.ndarray | None = None  # (P, F) f64
        self._threshold = float("inf")
        self._calibrated_at_rows = 0
        # device-path mirrors of the armed stats, cached per epoch so
        # the hot path never re-uploads them tick after tick
        # the epoch tag is held OUTSIDE the device tuple so the hot
        # path's cache-hit test compares two host ints — never a
        # device value (graftsync: implicit-sync would flag it)
        self._device_stats: tuple | None = None  # (mean32, inv32, thr32)
        self._device_stats_epoch: int | None = None
        # counters / capture (capture is OPT-IN: without a drift
        # controller draining it, holding the last tick's full feature
        # matrix by reference would pin device memory for nothing)
        self._rejections = 0
        self._last_rejected = 0
        self._score_faults = 0
        self._calibrate_faults = 0
        self._capture = None
        self._capture_enabled = False
        self._pending_count = None  # device-path lazy rejection count
        self._reject_jit = None  # built once, shape-keyed by jit
        if metrics is not None:
            metrics.set("openset_state", STATE_GAUGE[CALIBRATING])
        if reference is not None:
            # a persisted reference (serving-checkpoint round-trip):
            # the gate boots ARMED against the SAME stats + threshold
            # it served with — a serve restarted mid-novel-episode
            # must not re-calibrate on the novel traffic and unlearn
            # its rejection
            self._seed_reference(reference)

    def _seed_reference(self, reference: dict) -> None:
        mean = np.asarray(reference["openset_mean"], np.float64)
        inv_std = np.asarray(reference["openset_inv_std"], np.float64)
        threshold = float(np.asarray(reference["openset_threshold"]))
        rows = int(np.asarray(reference.get(
            "openset_calibrated_rows", 0
        )))
        if (mean.ndim != 2 or mean.shape != inv_std.shape
                or not mean.shape[0]):
            raise ValueError(
                f"openset reference shapes {mean.shape} / "
                f"{inv_std.shape} are not a (present_classes, "
                f"features) pair — the persisted reference belongs to "
                f"a different layout"
            )
        with self._lock:
            self._mean = mean
            self._inv_std = inv_std
            self._threshold = threshold
            self._calibrated_at_rows = rows
            self._state = ARMED
            self._epoch += 1
        if self._metrics is not None:
            self._metrics.set("openset_state", STATE_GAUGE[ARMED])

    def reference_arrays(self) -> dict | None:
        """The armed scoring reference as a flat name→array dict — the
        serving checkpoint's ``feature_reference`` block carries it
        beside the drift monitor's stats (io/serving_checkpoint.save),
        and a restored serve seeds it back via ``reference=``. None
        while calibrating."""
        with self._lock:
            if self._state != ARMED:
                return None
            return {
                "openset_mean": np.array(self._mean),
                "openset_inv_std": np.array(self._inv_std),
                "openset_threshold": np.float64(self._threshold),
                # provenance for /healthz: a restored gate reports the
                # window it was ORIGINALLY calibrated on, not 0
                "openset_calibrated_rows": np.float64(
                    self._calibrated_at_rows
                ),
            }

    # -- predict surface ---------------------------------------------------
    def __call__(self, params, X):
        labels = self._inner(params, X)
        self._drain_pending_count()
        with self._lock:
            armed = self._state == ARMED
            # previous tick's calibration pair: by now its device
            # labels have long since materialized, so folding it here
            # costs no fresh host↔device sync on the serve path (the
            # same one-tick-lazy discipline as _drain_pending_count);
            # arming drops any leftover pair (stats are frozen)
            pending, self._pending_cal = self._pending_cal, None
        if not armed:
            if pending is not None:
                self._calibrate_tick(*pending)
            with self._lock:
                # re-check: folding the pending pair may just have
                # armed the gate — then this tick's pair has nothing
                # left to teach
                if self._state != ARMED:
                    self._pending_cal = (X, labels)
            out = labels
        else:
            out = self._apply(X, labels)
        with self._lock:
            if self._capture_enabled:
                self._capture = (X, out)
        return out

    def enable_capture(self) -> None:
        """Opt in to per-tick ``(X, labels)`` capture — called by the
        drift controller's ``set_openset`` wiring. Without a consumer
        the gate records nothing: a by-reference capture would pin the
        last tick's full feature matrix for nobody."""
        with self._lock:
            self._capture_enabled = True

    def take_capture(self):
        """The newest ``(X, labels)`` pair — labels INCLUDING any
        ``unknown`` relabels — consumed (None when no predict ran since
        the last take). The drift controller observes through this so
        the monitor's class mix carries the unknown fraction."""
        with self._lock:
            cap = self._capture
            self._capture = None
            return cap

    @property
    def label_epoch(self) -> tuple:
        """Composed label-source epoch for the incremental cache
        (serving/incremental.py): the gate's own epoch (bumped at
        calibration freeze and every rebase — both change what a row's
        label MEANS) plus the inner composition's."""
        with self._lock:
            own = self._epoch
        return (own, getattr(self._inner, "label_epoch", 0))

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def threshold(self) -> float:
        with self._lock:
            return self._threshold

    def status(self) -> dict:
        """The /healthz self-report (obs.HealthState.set_openset)."""
        with self._lock:
            return {
                "state": self._state,
                "gauge": STATE_GAUGE[self._state],
                "threshold": (
                    None if self._threshold == float("inf")
                    else round(self._threshold, 6)
                ),
                "margin": self.margin,
                "rejections": self._rejections,
                "last_rejected": self._last_rejected,
                "calibration_rows": (
                    self._calibrated_at_rows or self._cal_rows
                ),
                "score_faults": self._score_faults,
                "calibrate_faults": self._calibrate_faults,
            }

    # -- calibration -------------------------------------------------------
    def _calibrate_tick(self, X, labels) -> None:
        """Fold one pre-arming tick's ACTIVE labeled rows into the
        calibration window; freeze stats + threshold once enough rows
        accumulated. Absorbing: a failure drops this tick's sample,
        never the labels (they were already produced)."""
        try:
            faults.fault_point("openset.calibrate")
            Xh = np.asarray(
                X, np.float64
            )  # graftlint: disable=implicit-sync -- deferred-drain: prior tick's pair, materialized
            yh = np.asarray(
                labels
            ).astype(np.int64).ravel()  # graftlint: disable=implicit-sync -- deferred-drain: prior tick
            yh = yh[: Xh.shape[0]]
            mask = Xh.any(axis=1)
            with self._lock:
                if int(mask.sum()):
                    self._cal_X.append(Xh[mask].astype(np.float32))
                    self._cal_y.append(yh[mask].astype(np.int32))
                    self._cal_rows += int(mask.sum())
                due = self._cal_rows >= self.calibration_rows
            if due:
                self._freeze()
        except Exception as e:  # noqa: BLE001 — calibration must not fail the serve
            self._absorb("openset.calibrate", e)

    def _freeze(self) -> None:
        with self._lock:
            cal_X, self._cal_X = self._cal_X, []
            cal_y, self._cal_y = self._cal_y, []
            # reset so a failed install re-accumulates a fresh window
            # instead of re-freezing empty buffers forever
            self._cal_rows = 0
        X = np.concatenate(cal_X, axis=0)
        y = np.concatenate(cal_y, axis=0)
        self._install_reference(X, y, reason="calibrated")

    def _install_reference(self, X, y, reason: str) -> None:
        """Compute per-class stats + the margin-calibrated threshold
        from a labeled window and arm (or re-arm) the gate. Shared by
        the first-windows freeze and the promotion-time ``rebase``.
        Classes the window never saw are DROPPED from the scoring
        matrices (reference_matrices) — never floored into a phantom
        acceptance basin."""
        X = np.asarray(X, np.float64)
        ref = class_reference(X, y, self.n_classes)
        matrices = reference_matrices(ref, X.std(axis=0))
        if matrices is None:
            raise ValueError(
                "calibration window has no class-labeled rows"
            )
        mean, inv_std = matrices
        scores = openset_scores(X, mean, inv_std)
        threshold = self.margin * float(scores.max()) if scores.size \
            else float("inf")
        with self._lock:
            self._mean = mean
            self._inv_std = inv_std
            self._threshold = threshold
            self._calibrated_at_rows = int(X.shape[0])
            self._state = ARMED
            self._epoch += 1
            # the jitted program survives: stats are runtime operands
            # (jit re-traces only on a shape change, e.g. a different
            # present-class count), but the cached device copies are
            # stale now — the next device tick re-uploads once
            self._device_stats = None
            self._device_stats_epoch = None
        if self._metrics is not None:
            self._metrics.set("openset_state", STATE_GAUGE[ARMED])
        if self._recorder is not None:
            self._recorder.record(
                "openset.calibrated", reason=reason,
                rows=int(X.shape[0]), threshold=threshold,
            )

    def rebase(self, X, y) -> bool:
        """Re-reference onto a promotion's retrain window (the drift
        controller calls this with the reservoir's KNOWN-labeled rows —
        rejected rows never teach the stats, which is what keeps a
        promoted model rejecting what it was never taught). Absorbing:
        a failure keeps the previous stats — never fails a promotion."""
        try:
            faults.fault_point("openset.calibrate")
            X = np.asarray(X, np.float64)
            y = np.asarray(y)
            known = y.astype(np.int64) < self.n_classes
            if not int(known.sum()):
                return False
            self._install_reference(X[known], y[known], reason="rebase")
            return True
        except Exception as e:  # noqa: BLE001 — a promotion must not die of its rebase
            self._absorb("openset.calibrate", e)
            return False

    # -- armed scoring -----------------------------------------------------
    def _apply(self, X, labels):
        """Relabel over-threshold active rows ``unknown``; absorbing —
        any scoring failure serves the inner labels fresh."""
        try:
            faults.fault_point("openset.score")
            if self.host_native or isinstance(labels, np.ndarray):
                return self._apply_host(X, labels)
            return self._apply_device(X, labels)
        except Exception as e:  # noqa: BLE001 — scoring must not fail the serve
            self._absorb("openset.score", e)
            return labels

    def _apply_host(self, X, labels):
        with self._lock:
            mean, inv_std, thr = self._mean, self._inv_std, self._threshold
        Xh = np.asarray(
            X, np.float64
        )  # graftlint: disable=implicit-sync -- host-native: host-mode gate, X is already host
        yh = np.asarray(labels)
        scores = openset_scores(Xh, mean, inv_std)
        active = Xh.any(axis=1)
        rej = active & (scores > thr)
        n = int(rej.sum())
        out = np.where(
            rej[: yh.shape[0]], np.int32(self.unknown_index), yh
        ).astype(yh.dtype, copy=False)
        self._note_rejections(n)
        return out

    def _apply_device(self, X, labels):
        """The device path: one jitted relabel program (built once;
        jit's cache keys re-traces on shape changes such as a new
        present-class count), all dispatch — the rejection count is a
        device scalar drained LAZILY at the next call, and the stats'
        device copies are cached per epoch, so the pipelined render
        gains neither a host sync nor a per-tick re-upload."""
        import jax
        import jax.numpy as jnp

        with self._lock:
            fn = self._reject_jit
            mean, inv_std = self._mean, self._inv_std
            thr = self._threshold
            epoch = self._epoch
            cached = self._device_stats
            cached_epoch = self._device_stats_epoch
        if fn is None:
            # mirror of openset_scores, device dtype; the unknown
            # index is a trace-time constant
            unknown = self.unknown_index

            def _reject(X, labels, mean, inv_std, thr):
                Xf = X.astype(jnp.float32)
                best = None
                for c in range(mean.shape[0]):
                    z = (Xf - mean[c][None, :]) * inv_std[c][None, :]
                    d = jnp.mean(z * z, axis=-1)
                    best = d if best is None else jnp.minimum(best, d)
                score = jnp.sqrt(best)
                active = jnp.any(X != 0, axis=-1)
                rej = active & (score > thr)
                out = jnp.where(
                    rej[: labels.shape[0]], jnp.int32(unknown), labels
                )
                return out, jnp.sum(rej, dtype=jnp.int32)

            fn = jax.jit(_reject)
            with self._lock:
                self._reject_jit = fn
        if cached is not None and cached_epoch == epoch:
            mean32, inv32, thr32 = cached
        else:
            # the PR 12 epoch-cached seam: one upload per calibration
            # epoch, never per tick (re-armed only when _recalibrate
            # bumps the epoch and clears the cache)
            mean32 = jnp.asarray(
                mean, jnp.float32
            )  # graftlint: disable=transfer-discipline -- epoch-cached: one upload per epoch
            inv32 = jnp.asarray(
                inv_std, jnp.float32
            )  # graftlint: disable=transfer-discipline -- epoch-cached: one upload per epoch
            thr32 = jnp.float32(thr)
            with self._lock:
                if self._epoch == epoch:
                    self._device_stats = (mean32, inv32, thr32)
                    self._device_stats_epoch = epoch
        out, count = fn(X, labels, mean32, inv32, thr32)
        with self._lock:
            self._pending_count = count
        return out

    def _drain_pending_count(self) -> None:
        """Fold the previous device tick's rejection count into the
        counters (it has long since materialized — no fresh sync)."""
        with self._lock:
            count, self._pending_count = self._pending_count, None
        if count is None:
            return
        try:
            n = int(count)  # graftlint: disable=implicit-sync -- deferred-drain: last tick's count
            self._note_rejections(n)
        except Exception:  # noqa: BLE001 — a deleted/donated scalar drops the sample
            pass

    def _note_rejections(self, n: int) -> None:
        with self._lock:
            self._last_rejected = n
            self._rejections += n
        if self._metrics is not None:
            self._metrics.set("openset_rejected_rows", n)
            if n:
                self._metrics.inc("openset_rejections", n)
        if n and self._recorder is not None:
            self._recorder.record("openset.reject", rows=n)

    # -- fault absorption --------------------------------------------------
    def _absorb(self, site: str, e: Exception) -> None:
        with self._lock:
            if site == "openset.score":
                self._score_faults += 1
            else:
                self._calibrate_faults += 1
        if self._metrics is not None:
            self._metrics.inc("openset_faults")
        if self._recorder is not None:
            self._recorder.record(
                "openset.fault_absorbed", site=site,
                error=type(e).__name__, detail=str(e),
            )
