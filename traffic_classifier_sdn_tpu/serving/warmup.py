"""AOT warmup: compile the serving fns at startup, not at tick one.

Without this, the first serve tick pays every jit compile in line —
multi-second for the 2²⁰-row forest GEMM — which shows up as a
first-tick ``tick``-span p99 orders of magnitude above steady state,
and again on every restart. ``warmup_serving`` AOT-lowers and primes
the exact jitted callables the serve loop uses (the batcher's donated
``apply_wire_jit`` per power-of-two bucket shape, the donated
feature-stage projection, the jitted predict, the ranked render
gather, the eviction kernels) against zero-filled inputs of the real
serving shapes, so the first tick runs hot.

``enable_compilation_cache`` wires ``--compilation-cache-dir`` to
JAX's persistent compilation cache: the warmup's compiles land on
disk, and a restarted serve — including a checkpoint-rollback restart
(PR 1) — replays them as cache hits instead of recompiling. AOT
``.lower(...).compile()`` alone does not prime jax's in-process
call-path cache on this jax version, so each warm also makes one
priming call (against scratch state for donated fns — donation
consumes the input, and the serve loop's live table must never be
warmup fodder).

Latency provenance (obs/latency.py) deliberately needs NOTHING warmed
here: emit stamps and boundary marks are host-side clock reads on
plain Python objects — zero traced ops, zero new jit programs — so
the warm set below is complete with the plane armed and the
first-tick compile discipline survives (tests/test_latency.py pins
the plane jax-free — no traced op can hide in a host-only module).
"""

from __future__ import annotations

import time

import numpy as np

import jax
import jax.numpy as jnp

from ..core import flow_table as ft
from .pipeline import _FEATURES_INTO


def enable_compilation_cache(path: str) -> None:
    """Point JAX's persistent compilation cache at ``path`` and drop
    the persistence gates: the default min-compile-time threshold
    would skip exactly the small-bucket programs a restart re-pays."""
    jax.config.update("jax_compilation_cache_dir", path)
    for knob, val in (
        ("jax_persistent_cache_min_compile_time_secs", 0.0),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
    ):
        try:
            jax.config.update(knob, val)
        except AttributeError:  # older jax without the knob
            pass
    try:
        from jax._src import compilation_cache as _cc

        # a process that already compiled anything has the cache module
        # initialized (possibly as disabled) — re-point it or the new
        # dir silently never sees a write
        _cc.reset_cache()
    except (ImportError, AttributeError):  # private API moved — degrade
        pass


def _warm_jitted(fn, *args) -> None:
    """AOT-lower + compile (feeds the persistent cache), then one
    priming call (feeds the in-process cache). ``fn`` must be pure or
    called on scratch state the caller owns."""
    fn.lower(*args).compile()
    jax.block_until_ready(fn(*args))


def warmup_serving(engine, predict, params, *, table_rows: int,
                   idle_timeout: int | None = None,
                   incremental: bool = False) -> dict:
    """Precompile the serve loop's device programs for ``engine``'s
    shapes. Returns ``{"warmed": [...], "seconds": float}``.

    Single-device engines get the full treatment. The mesh-sharded
    engine's read side is warmed through one inert
    ``tick_read_dispatch`` and its write side through
    ``warmup_scatter`` (one apply program per wire bucket — a serve
    whose batch sizes vary tick to tick would otherwise pay a compile
    at the first hit of each new bucket shape)."""
    t0 = time.perf_counter()
    warmed: list[str] = []
    host_native = getattr(predict, "host_native", False)

    if not hasattr(engine, "table"):  # sharded spine
        outs = engine.tick_read_dispatch(now=0)
        jax.block_until_ready(outs)
        warmed.append("sharded.tick_read")
        warmed.extend(engine.warmup_scatter())
        if getattr(engine, "native", False) and hasattr(
            engine.batcher, "warm_stage"
        ):
            engine.batcher.warm_stage()
            warmed.append("wire_stage")
        if incremental and getattr(engine, "incremental", False):
            # every dirty-bucket variant of the incremental read side
            # (one tick_read_dispatch only hit one bucket)
            warmed.extend(engine.warmup_incremental())
        return {"warmed": warmed, "seconds": time.perf_counter() - t0}

    from ..ingest import batcher as batcher_mod

    capacity = engine.table.capacity
    scratch = ft.make_table(capacity)

    # -- scatter: one compile per bucket shape (compact wire) -------------
    # Warm every bucket a tick at this capacity can plausibly fill
    # (≤ two records per tracked flow per tick); larger buckets — and
    # the rare (B, 6) full-width wire — still compile lazily.
    limit = batcher_mod.bucket_size(
        min(2 * capacity, engine.buckets[-1]), engine.buckets
    )
    track_dirty = incremental and getattr(engine, "dirty", None) is not None
    dirty_scratch = (
        jnp.ones(capacity + 1, bool) if track_dirty else None
    )
    # native ingest: fault in the pinned wire-staging pages (the C++
    # engine writes packed batches straight into them — their lazy
    # first-touch allocation must not land inside serving tick one)
    if getattr(engine, "native", False) and hasattr(
        engine.batcher, "warm_stage"
    ):
        engine.batcher.warm_stage()
        warmed.append("wire_stage")

    for b in engine.buckets:
        if b > limit:
            break
        wire = np.zeros((b, 4), np.uint32)
        wire[:, 0] = np.uint32(capacity)  # all-padding rows: a clean no-op
        if track_dirty:
            # the dirty-tracking serve scatters through the FUSED
            # apply+mark program — warming the plain one would leave
            # the first tick's compile stall in place
            batcher_mod.apply_wire_dirty_jit.lower(
                scratch, dirty_scratch, wire
            ).compile()
            scratch, dirty_scratch = batcher_mod.apply_wire_dirty_jit(
                scratch, dirty_scratch, wire
            )
            warmed.append(f"apply_wire_dirty[{b}]")
            continue
        batcher_mod.apply_wire_jit.lower(scratch, wire).compile()
        # the priming call donates its input table; chain the returned
        # scratch so one table's worth of HBM covers every bucket
        scratch = batcher_mod.apply_wire_jit(scratch, wire)
        warmed.append(f"apply_wire[{b}]")
    jax.block_until_ready(scratch)

    # -- features: the donated double-buffer projection (pipelined) and
    # the eager projection (serial / host-native / full-table paths,
    # which compile a dozen small kernels on first touch otherwise)
    buf = jnp.zeros((capacity, ft.NUM_FEATURES), jnp.float32)
    _FEATURES_INTO.lower(buf, scratch).compile()
    X = _FEATURES_INTO(buf, scratch)
    jax.block_until_ready(ft.features12(scratch))
    warmed.append("features_into")

    # -- predict -----------------------------------------------------------
    # (the serving-path resolution already built whatever index the
    # kernel needs — the pruned native KNN's cluster index at
    # NativeKnn(), the IVF tier's coarse quantizer at knn_ivf.build —
    # so warming the predict below also pins those structures' pages)
    if host_native:
        # nothing jitted to compile, but the call loads the C++ library
        # and faults its pages in — the native first-tick stall
        labels = jnp.asarray(predict(params, X))
        warmed.append("predict[native]")
    else:
        _warm_jitted(predict, params, X)
        labels = predict(params, X)
        warmed.append("predict")

    # -- degrade fallback rung --------------------------------------------
    # a ladder-wrapped predict exposes warm_fallback: prime the host
    # rung (eager-CPU jit compiles, native-evaluator page faults, the
    # votes/score surface) so the first DEMOTED tick pays none of it
    warm_fb = getattr(predict, "warm_fallback", None)
    if warm_fb is not None and warm_fb(
        np.zeros((8, ft.NUM_FEATURES), np.float32)
    ):
        warmed.append("fallback_rung")

    # -- incremental dirty path (serving/incremental.py) -------------------
    # One program per dirty-bucket shape: compaction, dirty-row feature
    # gather, subset predict, and cache scatter — the serve picks its
    # bucket from the same dirty_buckets list, so the first
    # nonzero-churn tick can never hit an un-warmed shape.
    if track_dirty:
        from . import incremental as inc_mod

        _warm_jitted(inc_mod.dirty_count_jit, dirty_scratch)
        cache = jnp.zeros(capacity, jnp.asarray(labels).dtype)
        for b in inc_mod.dirty_buckets(capacity):
            inc_mod.compact_dirty_jit.lower(
                dirty_scratch, bucket=b
            ).compile()
            idx = inc_mod.compact_dirty_jit(dirty_scratch, bucket=b)
            _warm_jitted(inc_mod.features12_at_jit, scratch, idx)
            Xd = inc_mod.features12_at_jit(scratch, idx)
            if host_native:
                sub = jnp.asarray(predict(params, Xd))
            else:
                _warm_jitted(predict, params, Xd)
                sub = predict(params, Xd)
                # cache scatter (cache donated): chain the returned
                # buffer so one cache's worth of HBM covers all buckets
                inc_mod.merge_labels_jit.lower(
                    cache, idx, sub
                ).compile()
                cache = inc_mod.merge_labels_jit(cache, idx, sub)
            # re-invalidation marks arrive bucket-shaped (donated)
            inc_mod.mark_dirty_slots_jit.lower(
                dirty_scratch, idx
            ).compile()
            dirty_scratch = inc_mod.mark_dirty_slots_jit(
                dirty_scratch, idx
            )
            warmed.append(f"dirty[{b}]")
        jax.block_until_ready((cache, dirty_scratch))

    # -- ranked render gather ---------------------------------------------
    floor = np.int32(0)
    if table_rows > 0:
        n = min(table_rows, capacity)
        if host_native:
            _warm_jitted(ft.top_active_flags, scratch, n, floor)
            warmed.append("top_active_flags")
        _warm_jitted(ft.top_active_render, scratch, labels, n, floor)
        warmed.append("top_active_render")

    # -- eviction ----------------------------------------------------------
    if idle_timeout:
        _warm_jitted(ft.stale_bits, scratch, np.int32(0),
                     np.int32(idle_timeout))
        smallest = engine.buckets[0]
        pad = np.full(smallest, capacity, np.int32)
        if track_dirty:
            # dirty-tracking eviction clears through the fused
            # clear+invalidate program (dirty donated: chain it)
            batcher_mod.clear_slots_dirty_jit.lower(
                scratch, dirty_scratch, pad
            ).compile()
            _, dirty_scratch = batcher_mod.clear_slots_dirty_jit(
                scratch, dirty_scratch, pad
            )
            jax.block_until_ready(dirty_scratch)
        else:
            _warm_jitted(ft.clear_slots, scratch, pad)
        warmed.append("evict")

    return {"warmed": warmed, "seconds": time.perf_counter() - t0}
