"""AOT warmup: compile the serving fns at startup, not at tick one.

Without this, the first serve tick pays every jit compile in line —
multi-second for the 2²⁰-row forest GEMM — which shows up as a
first-tick ``tick``-span p99 orders of magnitude above steady state,
and again on every restart. ``warmup_serving`` AOT-lowers and primes
the exact jitted callables the serve loop uses (the batcher's donated
``apply_wire_jit`` per power-of-two bucket shape, the donated
feature-stage projection, the jitted predict, the ranked render
gather, the eviction kernels) against zero-filled inputs of the real
serving shapes, so the first tick runs hot.

``enable_compilation_cache`` wires ``--compilation-cache-dir`` to
JAX's persistent compilation cache: the warmup's compiles land on
disk, and a restarted serve — including a checkpoint-rollback restart
(PR 1) — replays them as cache hits instead of recompiling. AOT
``.lower(...).compile()`` alone does not prime jax's in-process
call-path cache on this jax version, so each warm also makes one
priming call (against scratch state for donated fns — donation
consumes the input, and the serve loop's live table must never be
warmup fodder).
"""

from __future__ import annotations

import time

import numpy as np

import jax
import jax.numpy as jnp

from ..core import flow_table as ft
from .pipeline import _FEATURES_INTO


def enable_compilation_cache(path: str) -> None:
    """Point JAX's persistent compilation cache at ``path`` and drop
    the persistence gates: the default min-compile-time threshold
    would skip exactly the small-bucket programs a restart re-pays."""
    jax.config.update("jax_compilation_cache_dir", path)
    for knob, val in (
        ("jax_persistent_cache_min_compile_time_secs", 0.0),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
    ):
        try:
            jax.config.update(knob, val)
        except AttributeError:  # older jax without the knob
            pass
    try:
        from jax._src import compilation_cache as _cc

        # a process that already compiled anything has the cache module
        # initialized (possibly as disabled) — re-point it or the new
        # dir silently never sees a write
        _cc.reset_cache()
    except (ImportError, AttributeError):  # private API moved — degrade
        pass


def _warm_jitted(fn, *args) -> None:
    """AOT-lower + compile (feeds the persistent cache), then one
    priming call (feeds the in-process cache). ``fn`` must be pure or
    called on scratch state the caller owns."""
    fn.lower(*args).compile()
    jax.block_until_ready(fn(*args))


def warmup_serving(engine, predict, params, *, table_rows: int,
                   idle_timeout: int | None = None) -> dict:
    """Precompile the serve loop's device programs for ``engine``'s
    shapes. Returns ``{"warmed": [...], "seconds": float}``.

    Single-device engines get the full treatment. The mesh-sharded
    engine's read side is warmed through one inert
    ``tick_read_dispatch`` (its apply path compiles per bucket on
    first flush — those programs are per-shard-shaped and cheap next
    to the read side's full-shard predict)."""
    t0 = time.perf_counter()
    warmed: list[str] = []
    host_native = getattr(predict, "host_native", False)

    if not hasattr(engine, "table"):  # sharded spine
        outs = engine.tick_read_dispatch(now=0)
        jax.block_until_ready(outs)
        warmed.append("sharded.tick_read")
        return {"warmed": warmed, "seconds": time.perf_counter() - t0}

    from ..ingest import batcher as batcher_mod

    capacity = engine.table.capacity
    scratch = ft.make_table(capacity)

    # -- scatter: one compile per bucket shape (compact wire) -------------
    # Warm every bucket a tick at this capacity can plausibly fill
    # (≤ two records per tracked flow per tick); larger buckets — and
    # the rare (B, 6) full-width wire — still compile lazily.
    limit = batcher_mod.bucket_size(
        min(2 * capacity, engine.buckets[-1]), engine.buckets
    )
    for b in engine.buckets:
        if b > limit:
            break
        wire = np.zeros((b, 4), np.uint32)
        wire[:, 0] = np.uint32(capacity)  # all-padding rows: a clean no-op
        batcher_mod.apply_wire_jit.lower(scratch, wire).compile()
        # the priming call donates its input table; chain the returned
        # scratch so one table's worth of HBM covers every bucket
        scratch = batcher_mod.apply_wire_jit(scratch, wire)
        warmed.append(f"apply_wire[{b}]")
    jax.block_until_ready(scratch)

    # -- features: the donated double-buffer projection (pipelined) and
    # the eager projection (serial / host-native / full-table paths,
    # which compile a dozen small kernels on first touch otherwise)
    buf = jnp.zeros((capacity, ft.NUM_FEATURES), jnp.float32)
    _FEATURES_INTO.lower(buf, scratch).compile()
    X = _FEATURES_INTO(buf, scratch)
    jax.block_until_ready(ft.features12(scratch))
    warmed.append("features_into")

    # -- predict -----------------------------------------------------------
    if host_native:
        # nothing jitted to compile, but the call loads the C++ library
        # and faults its pages in — the native first-tick stall
        labels = jnp.asarray(predict(params, X))
        warmed.append("predict[native]")
    else:
        _warm_jitted(predict, params, X)
        labels = predict(params, X)
        warmed.append("predict")

    # -- ranked render gather ---------------------------------------------
    floor = np.int32(0)
    if table_rows > 0:
        n = min(table_rows, capacity)
        if host_native:
            _warm_jitted(ft.top_active_flags, scratch, n, floor)
            warmed.append("top_active_flags")
        _warm_jitted(ft.top_active_render, scratch, labels, n, floor)
        warmed.append("top_active_render")

    # -- eviction ----------------------------------------------------------
    if idle_timeout:
        _warm_jitted(ft.stale_bits, scratch, np.int32(0),
                     np.int32(idle_timeout))
        smallest = engine.buckets[0]
        pad = np.full(smallest, capacity, np.int32)
        _warm_jitted(ft.clear_slots, scratch, pad)
        warmed.append("evict")

    return {"warmed": warmed, "seconds": time.perf_counter() - t0}
