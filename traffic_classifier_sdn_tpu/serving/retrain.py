"""Background retraining + candidate checkpoint rotation for the drift
loop (serving/drift.py).

Two halves:

- **Fitting** (``fit_family``): a fresh checkpoint for any of the six
  model families from the drift monitor's recent labeled window. The
  families with distributed trainers (gnb, kmeans, forest, svc) route
  through ``train/distributed.py`` on a single-device ``(1, 1)`` mesh —
  the same code path that scales the fit across chips when the window
  outgrows one device — and logreg/knn use their canonical
  ``train/<family>.fit``. The ``retrain.fit`` fault site sits at the
  entry so the chaos suite can kill a refit mid-fit and prove the serve
  keeps the old model.

- **Candidate rotation**: fitted candidates are written through
  ``io/checkpoint.save_model`` — the staged-arrays + atomic-manifest
  commit path, so a crash mid-save can never publish a half-written
  candidate — into tick-ordered ``model-<seq>`` directories under the
  drift directory. ``resolve_latest`` returns the newest candidate that
  actually LOADS (mirroring ``io/serving_checkpoint.resolve_latest``'s
  rollback semantics): a bad promotion discards its candidate and
  reloads through here, so the old model keeps serving. The rotation is
  seeded with the boot model at drift-enable time, which is what makes
  "roll back" well-defined before any promotion has ever happened.

``BackgroundRetrainer`` runs one fit at a time on a daemon worker with
the ``DeviceWatchdog`` abandon discipline (serving/degrade.py): the
caller polls, and a fit that outlives its deadline is ABANDONED — the
generation counter bumps, the worker's late result is discarded when it
eventually lands, and the loop returns to watching the stream. The
deadline itself is enforced by the caller's injectable clock
(serving/drift.DriftController), so tests pin the exact abandon tick
without sleeping.
"""

from __future__ import annotations

import os
import re
import shutil
import threading

import numpy as np

from ..utils import faults

_MODEL_RE = re.compile(r"^model-(\d+)$")

# BackgroundRetrainer states
IDLE = "idle"
RUNNING = "running"
DONE = "done"
FAILED = "failed"


# ---------------------------------------------------------------------------
# fitting
# ---------------------------------------------------------------------------


def fit_family(family: str, X, y, n_classes: int, **kw):
    """Fit fresh ``family`` params from the labeled window ``(X, y)``.

    gnb/kmeans/forest/svc go through ``train/distributed.py`` on a
    single-device mesh; logreg/knn use their canonical trainers (no
    distributed variant exists). ``kw`` forwards family-specific knobs
    (e.g. ``n_trees`` for forest). Raises whatever the trainer raises —
    the caller (the background worker) owns failure semantics."""
    faults.fault_point("retrain.fit")
    import jax
    import jax.numpy as jnp

    X = np.asarray(X, np.float32)
    y = np.asarray(y, np.int32)
    if family in ("gnb", "kmeans", "forest", "svc"):
        from ..parallel import mesh as meshlib
        from ..train import distributed as dist

        mesh = meshlib.make_mesh(
            n_data=1, n_state=1, devices=jax.devices()[:1]
        )
        if family == "gnb":
            return dist.fit_gnb(mesh, X, y, n_classes, **kw)
        if family == "kmeans":
            params, _inertia = dist.fit_kmeans(
                mesh, X, k=n_classes, **kw
            )
            return params
        if family == "forest":
            return dist.fit_forest(mesh, X, y, n_classes, **kw)
        return dist.fit_svc(mesh, X, y, n_classes, **kw)
    if family == "logreg":
        from ..train import logreg as t

        return t.fit(jnp.asarray(X), jnp.asarray(y), n_classes, **kw)
    if family == "knn":
        from ..train import knn as t

        kw.setdefault("n_neighbors", 5)
        return t.fit(
            jnp.asarray(X), jnp.asarray(y), n_classes=n_classes, **kw
        )
    raise ValueError(f"unknown model family {family!r}")


# ---------------------------------------------------------------------------
# candidate rotation
# ---------------------------------------------------------------------------


def candidate_path(directory: str, seq: int) -> str:
    return os.path.join(directory, f"model-{seq:09d}")


def list_candidates(directory: str) -> list[tuple[int, str]]:
    """``(seq, path)`` for every rotation member, newest seq first."""
    out = []
    try:
        names = os.listdir(directory)
    except FileNotFoundError:
        return []
    for name in names:
        m = _MODEL_RE.match(name)
        if m and os.path.isdir(os.path.join(directory, name)):
            out.append((int(m.group(1)), os.path.join(directory, name)))
    out.sort(reverse=True)
    return out


def next_seq(directory: str) -> int:
    members = list_candidates(directory)
    return members[0][0] + 1 if members else 0


def save_candidate(directory: str, seq: int, family: str, params,
                   classes) -> str:
    """Write one candidate through the atomic staged-commit model
    checkpoint path (io/checkpoint.save_model). Returns its path."""
    from ..io import checkpoint as ck

    path = candidate_path(directory, seq)
    ck.save_model(path, family, params, classes=list(classes))
    return path


def load_candidate(path: str):
    """``io/checkpoint.load_model`` → models.LoadedModel (canonical
    params + classes); raises on a missing/garbage candidate."""
    from ..io import checkpoint as ck

    return ck.load_model(path)


def discard_candidate(path: str) -> None:
    """Remove a rejected/rolled-back candidate so ``resolve_latest``
    can never hand it back."""
    shutil.rmtree(path, ignore_errors=True)


def _resolve_and_load(directory: str):
    """Newest rotation member that LOADS, with its loaded content —
    the rollback read path decodes the winner exactly once. Members
    that fail to load are skipped on the way down (the
    serving-checkpoint rollback semantics, applied to model dirs)."""
    for _, path in list_candidates(directory):
        try:
            return path, load_candidate(path)
        except Exception:  # noqa: BLE001 — any unloadable member is skipped
            continue
    return None, None


def resolve_latest(directory: str) -> str | None:
    """The newest candidate checkpoint that actually loads — a corrupt
    or discarded newest member means rollback to its predecessor (the
    boot seed at minimum), never a crash. None when the rotation holds
    nothing loadable."""
    return _resolve_and_load(directory)[0]


def prune_candidates(directory: str, keep: int = 3) -> None:
    """Keep the newest ``keep`` members; pruning is advisory (a failed
    unlink must never fail a promotion)."""
    for _, old in list_candidates(directory)[max(keep, 1):]:
        shutil.rmtree(old, ignore_errors=True)


# ---------------------------------------------------------------------------
# the background worker
# ---------------------------------------------------------------------------


class BackgroundRetrainer:
    """One background fit at a time, abandonable.

    ``submit(fn)`` starts a daemon worker running ``fn(is_current)``,
    where ``is_current()`` reports whether this generation is still the
    live one — the job checks it before PUBLISHING side effects (the
    candidate checkpoint save), so an abandoned fit leaves no stray in
    the rotation. The caller polls for ``DONE``/``FAILED`` and consumes
    the terminal state with ``take``. ``abandon`` bumps the generation
    so a worker that outlived its deadline publishes into the void when
    it eventually returns — the same discard-late-results discipline as
    ``serving.degrade.DeviceWatchdog``, minus the blocking wait (the
    drift loop must keep serving while the fit runs)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._gen = 0
        self._state = IDLE
        self._result = None
        self._error: BaseException | None = None

    def submit(self, fn) -> None:
        with self._lock:
            if self._state == RUNNING:
                raise RuntimeError("a retrain is already running")
            self._gen += 1
            gen = self._gen
            self._state = RUNNING
            self._result = None
            self._error = None
        threading.Thread(
            target=self._run, args=(gen, fn), name="tcsdn-retrain",
            daemon=True,
        ).start()

    def _is_current(self, gen: int) -> bool:
        with self._lock:
            return gen == self._gen

    def _run(self, gen: int, fn) -> None:
        try:
            out = fn(lambda: self._is_current(gen))
        except BaseException as e:  # noqa: BLE001 — published to the poller
            with self._lock:
                if gen == self._gen and self._state == RUNNING:
                    self._state = FAILED
                    self._error = e
            return
        with self._lock:
            if gen == self._gen and self._state == RUNNING:
                # an abandoned generation publishes nothing here, and
                # the job's own is_current() check keeps it from
                # committing a candidate into the rotation either
                self._state = DONE
                self._result = out

    def poll(self) -> str:
        with self._lock:
            return self._state

    def take(self):
        """Consume a terminal state: ``(state, result, error)``, reset
        to IDLE. Call only after ``poll`` reports DONE/FAILED."""
        with self._lock:
            state, result, error = self._state, self._result, self._error
            self._state = IDLE
            self._result = None
            self._error = None
            return state, result, error

    def abandon(self) -> None:
        """Discard the in-flight fit (deadline expiry): its eventual
        result is dropped by the generation check."""
        with self._lock:
            self._gen += 1
            self._state = IDLE
            self._result = None
            self._error = None
