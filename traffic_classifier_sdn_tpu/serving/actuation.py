"""Flap-proof actuation: hysteresis-gated flow programming driven by
the classifier's labels.

This is the tier that closes the loop the reference never closed — the
table the serve renders becomes OpenFlow 1.3 flow-mods — and the whole
design is about when it is *forbidden* to do so:

* **Hysteresis** — a per-flow rule walks PENDING → ARMED → INSTALLED →
  RETRACTING. A rule arms only after ``k_install`` consecutive observed
  ticks of the same actionable label; an installed rule retracts only
  after ``k_retract`` consecutive deviating ticks. A single-tick label
  flip or an open-set ``unknown`` blip therefore never touches the
  switch — it resets the streak and counts ``flaps_suppressed``.
* **Freshness** — a stale render (degrade ladder on its BROKEN rung)
  or a drift rollback demotes actuation to hold-and-retract: installed
  rules are pulled, nothing new installs, and a rollback latches the
  plane in dry-run until the drift loop PROMOTES again. Labels that are
  stale or unpromoted never program a switch.
* **Blast radius** — a quarantined namespace's rules retract exactly
  with its slots (:meth:`ActuationPlane.retract_source`, hooked off the
  serve loop's ``take_evictions`` drain), and a fleet member given a
  source span only ever actuates slots owned by its span.
* **Absorption** — the fault sites ``actuation.send`` /
  ``actuation.barrier`` / ``actuation.retract`` are ABSORBED: a wedged
  socket, refused mod, or lost barrier reply degrades the plane to
  dry-run with exponential-backoff re-probing, in-flight operations
  resolve as refused, and the classify plane serves every tick
  byte-identically to ``--actuation off`` (stdout is never touched —
  dry-run renders to stderr and the flight ring).
* **Exact accounting** — every operation the plane decides to perform
  increments ``intended`` and terminally resolves as exactly one of
  ``installed`` / ``refused`` / ``retracted``; the invariant
  ``intended == installed + refused + retracted`` holds at every
  observe boundary and spans restarts (a rebuilt plane adopts the
  previous ledger via ``ledger=``).

The plane never raises into the serve loop and never blocks it beyond
the transport's short socket timeout; pushes happen inside
``observe()`` on whichever thread renders (serial main thread or the
pipeline's device stage), guarded by one leaf lock.
"""

from __future__ import annotations

import socket
import sys
import threading
import time
from dataclasses import dataclass
from typing import Callable, Iterable

from ..controller import openflow as of
from ..controller.policy import (
    PolicyAction,
    compile_install,
    compile_retract,
    compile_wipe,
)
from ..utils.faults import FaultInjected, fault_point

# actuation_state gauge values (obs idiom shared with degrade/drift)
STATE_GAUGE = {
    "off": 0,
    "dry-run": 1,
    "push": 2,
    "degraded": 3,
    "demoted": 4,
}

# rule lifecycle states
PENDING = "PENDING"        # streak building toward k_install
ARMED = "ARMED"            # streak earned — install op issued this flush
INSTALLED = "INSTALLED"    # resolved on the switch (or dry-run ledger)
RETRACTING = "RETRACTING"  # delete op issued this flush


@dataclass
class _Rule:
    slot: int
    src: str
    dst: str
    label: str                    # label the current streak is for
    streak: int = 0
    state: str = PENDING
    installed_label: str | None = None
    cookie: int = 0
    deviation: int = 0            # consecutive ticks off installed_label


@dataclass
class _Op:
    """One intended switch operation, resolved exactly once."""

    kind: str                     # "install" | "retract"
    rule: _Rule
    reason: str = ""
    xid: int = 0
    payload: bytes = b""
    resolution: str | None = None  # "installed" | "retracted" | "refused"


@dataclass
class Ledger:
    """The exact-accounting invariant: ``intended`` equals the sum of
    the three terminal resolutions at every observe boundary."""

    intended: int = 0
    installed: int = 0
    refused: int = 0
    retracted: int = 0

    def exact(self) -> bool:
        return self.intended == self.installed + self.refused + self.retracted

    def as_dict(self) -> dict:
        return {
            "intended": self.intended, "installed": self.installed,
            "refused": self.refused, "retracted": self.retracted,
            "exact": self.exact(),
        }


class SwitchLink:
    """Minimal OF1.3 controller-side link: hello exchange, flow-mod
    writes, barrier round-trips with refusal collection. Blocking reads
    are bounded by ``timeout`` so a wedged switch costs one timeout,
    never a hung serve."""

    def __init__(self, host: str, port: int, timeout: float = 0.25):
        self.host = host
        self.port = port
        self.timeout = timeout
        self._sock: socket.socket | None = None
        self._reader = of.MessageReader()
        self._xid = 0

    def next_xid(self) -> int:
        self._xid += 1
        return self._xid

    def open(self) -> None:
        sock = socket.create_connection(
            (self.host, self.port), timeout=self.timeout
        )
        sock.settimeout(self.timeout)
        self._sock = sock
        self._reader = of.MessageReader()
        self.send(of.hello(self.next_xid()))
        # the peer's HELLO is the liveness probe: a listener that
        # accepts but does not speak OpenFlow fails here, not mid-push
        deadline = time.monotonic() + max(self.timeout, 0.05) * 4
        while time.monotonic() < deadline:
            for mtype, _xid, _body in self._recv():
                if mtype == of.OFPT_HELLO:
                    return
        raise OSError("switch link: no HELLO from peer")

    def send(self, payload: bytes) -> None:
        if self._sock is None:
            raise OSError("switch link not open")
        self._sock.sendall(payload)

    def _recv(self) -> list[tuple[int, int, bytes]]:
        if self._sock is None:
            raise OSError("switch link not open")
        try:
            data = self._sock.recv(65536)
        except socket.timeout:
            return []
        if not data:
            raise OSError("switch link closed by peer")
        return self._reader.feed(data)

    def barrier(self, xid: int) -> set[int]:
        """Send a barrier and wait (bounded) for its reply; returns the
        xids the switch refused with OFPT_ERROR before the barrier.
        Raises ``OSError`` if the reply never arrives."""
        self.send(of.barrier_request(xid))
        refused: set[int] = set()
        deadline = time.monotonic() + max(self.timeout, 0.05) * 4
        while time.monotonic() < deadline:
            for mtype, rxid, body in self._recv():
                if mtype == of.OFPT_ERROR:
                    bad = of.parse_error(body)["offending_xid"]
                    if bad is not None:
                        refused.add(bad)
                elif mtype == of.OFPT_BARRIER_REPLY and rxid == xid:
                    return refused
        raise OSError(f"switch link: barrier {xid} reply lost")

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None


class ActuationPlane:
    """The policy tier's runtime: hysteresis FSM + transport + ledger.

    ``mode`` is the *configured* mode (``dry-run`` or ``push``); the
    *live* state additionally includes ``degraded`` (push demoted by an
    actuation fault, re-probing on backoff) and ``demoted`` (drift
    rollback or stale render latched the plane safe). ``--actuation
    off`` never constructs a plane at all.
    """

    def __init__(
        self,
        policy: dict[str, PolicyAction],
        *,
        mode: str = "dry-run",
        k_install: int = 3,
        k_retract: int = 3,
        clock: Callable[[], float] = time.monotonic,
        link_factory: Callable[[], SwitchLink] | None = None,
        span: frozenset[int] | None = None,
        slots_for_source: Callable[[int], Iterable[int]] | None = None,
        ledger: dict | None = None,
        backoff_base_s: float = 1.0,
        backoff_max_s: float = 60.0,
        metrics=None,
        recorder=None,
        out=None,
    ):
        if mode not in ("dry-run", "push"):
            raise ValueError(f"actuation mode {mode!r}: want dry-run|push")
        if mode == "push" and link_factory is None:
            raise ValueError("push mode needs a link_factory (switch addr)")
        if span is not None and slots_for_source is None:
            raise ValueError("a source span needs slots_for_source")
        self.policy = policy
        self.mode = mode
        self.k_install = max(1, int(k_install))
        self.k_retract = max(1, int(k_retract))
        self._clock = clock
        self._link_factory = link_factory
        self._link: SwitchLink | None = None
        self._span = span
        self._slots_for_source = slots_for_source
        self._m = metrics
        self._rec = recorder
        self._out = out if out is not None else sys.stderr
        self._lock = threading.Lock()
        self._rules: dict[int, _Rule] = {}
        self._cookie = 0
        self._xid = 0
        self.ledger = Ledger(**{
            k: int((ledger or {}).get(k, 0))
            for k in ("intended", "installed", "refused", "retracted")
        })
        self.flaps_suppressed = int((ledger or {}).get("flaps_suppressed", 0))
        self.rule_flaps = int((ledger or {}).get("rule_flaps", 0))
        # pairs whose rule was retracted because its label deviated: a
        # later re-install of such a pair IS a rule flap (the thing the
        # flap-storm scenario gates to zero)
        self._label_retracted: set[tuple[str, str]] = set()
        # pairs whose retract resolved WITHOUT reaching the wire
        # (dry/degraded/refused in push mode): the switch may still
        # hold their rule — reconcile wipes them even when no INSTALLED
        # rule covers the pair anymore
        self._orphans: set[tuple[str, str]] = set()
        self._degraded = False
        self._demoted = False          # latched by rollback/stale
        self._demote_reason = ""
        self._backoff_base = backoff_base_s
        self._backoff_max = backoff_max_s
        self._backoff = backoff_base_s
        self._next_probe = 0.0
        self._last_drift_state: str | None = None
        self._probes = 0
        self._degrades = 0
        self._set_state_gauge()

    # -- state surface ------------------------------------------------------

    @property
    def state(self) -> str:
        """Live plane state (gauge keys in :data:`STATE_GAUGE`)."""
        if self._demoted:
            return "demoted"
        if self._degraded:
            return "degraded"
        return self.mode

    def _set_state_gauge(self) -> None:
        if self._m is not None:
            self._m.set("actuation_state", STATE_GAUGE[self.state])

    def status(self) -> dict:
        """The /healthz actuation block (json-safe, lock-consistent)."""
        with self._lock:
            states: dict[str, int] = {}
            for r in self._rules.values():
                states[r.state] = states.get(r.state, 0) + 1
            return {
                "mode": self.mode,
                "state": self.state,
                "demote_reason": self._demote_reason or None,
                "rules": states,
                "installed_rules": states.get(INSTALLED, 0),
                "ledger": self.ledger.as_dict(),
                "flaps_suppressed": self.flaps_suppressed,
                "rule_flaps": self.rule_flaps,
                "orphan_pairs": len(self._orphans),
                "degrades": self._degrades,
                "probes": self._probes,
                "backoff_s": self._backoff if self._degraded else 0.0,
                "k_install": self.k_install,
                "k_retract": self.k_retract,
            }

    # -- the per-tick observation ------------------------------------------

    def observe(
        self,
        rows: Iterable[tuple[int, str, str, str]],
        *,
        stale: bool = False,
        drift_state: str | None = None,
    ) -> None:
        """Feed one rendered tick: ``rows`` are ``(slot, src_mac,
        dst_mac, label_name)`` as the render decoded them (the open-set
        tier's rejections arrive as the literal ``"unknown"``). Never
        raises; never touches stdout."""
        ops: list[_Op] = []
        with self._lock:
            self._note_drift_locked(drift_state, ops)
            if stale and not self._demoted:
                # hold-and-retract: a BROKEN-rung render serves stale
                # labels — pull every installed rule, install nothing
                self._demote_locked("stale_render", ops)
            if not stale and self._demoted and self._demote_reason == (
                "stale_render"
            ):
                # freshness returned on its own (ladder probed back):
                # un-latch; rules re-earn their installs via streaks
                self._demoted = False
                self._demote_reason = ""
                self._event("actuation.repromote", via="fresh_render")
            allowed = self._span_slots_locked()
            for slot, src, dst, label in rows:
                if allowed is not None and slot not in allowed:
                    continue
                self._observe_row_locked(slot, src, dst, label, ops)
            self._probe_locked()
            self._flush_locked(ops)
            self._set_state_gauge()

    def _span_slots_locked(self) -> set[int] | None:
        if self._span is None:
            return None
        allowed: set[int] = set()
        for sid in self._span:
            try:
                allowed.update(int(s) for s in self._slots_for_source(sid))
            except Exception:
                continue  # a just-evicted sid resolves to no slots
        return allowed

    def _observe_row_locked(self, slot: int, src: str, dst: str,
                            label: str, ops: list[_Op]) -> None:
        actionable = label in self.policy
        rule = self._rules.get(slot)
        if rule is None:
            if actionable:
                self._rules[slot] = _Rule(slot, src, dst, label, streak=1)
            return
        if (rule.src, rule.dst) != (src, dst):
            # slot reused for a different flow pair: the old rule's
            # match no longer describes this slot — retract if live,
            # then start over for the new pair
            if rule.state == INSTALLED:
                self._queue_retract_locked(rule, "slot_reused", ops)
            self._rules.pop(slot, None)
            if actionable:
                self._rules[slot] = _Rule(slot, src, dst, label, streak=1)
            return
        if rule.state == INSTALLED:
            if label == rule.installed_label:
                if rule.deviation > 0:
                    # the deviation episode ended before k_retract:
                    # hysteresis ate a would-be flap
                    rule.deviation = 0
                    self._suppress_locked(slot, label)
                return
            rule.deviation += 1
            if rule.deviation >= self.k_retract:
                self._label_retracted.add((src, dst))
                self._queue_retract_locked(rule, "label_changed", ops)
                self._rules.pop(slot, None)
                if actionable:
                    self._rules[slot] = _Rule(slot, src, dst, label, streak=1)
            return
        # PENDING: streak arithmetic toward k_install
        if label == rule.label and actionable:
            rule.streak += 1
            if rule.streak >= self.k_install and not self._demoted:
                self._queue_install_locked(rule, ops)
        else:
            if rule.streak > 0:
                # blip: unknown, an unactionable class, or a flip to
                # another class before the streak earned installation
                self._suppress_locked(slot, label)
            rule.label = label
            rule.streak = 1 if actionable else 0

    def _suppress_locked(self, slot: int, label: str) -> None:
        self.flaps_suppressed += 1
        if self._m is not None:
            self._m.inc("actuation_flaps_suppressed")
        self._event("actuation.flap_suppressed", slot=slot, label=label)

    # -- op lifecycle -------------------------------------------------------

    def _queue_install_locked(self, rule: _Rule, ops: list[_Op]) -> None:
        self._cookie += 1
        rule.cookie = self._cookie
        rule.state = ARMED
        self.ledger.intended += 1
        if (rule.src, rule.dst) in self._label_retracted:
            self.rule_flaps += 1
            if self._m is not None:
                self._m.inc("actuation_rule_flaps")
        ops.append(_Op("install", rule))

    def _queue_retract_locked(self, rule: _Rule, reason: str,
                              ops: list[_Op]) -> None:
        rule.state = RETRACTING
        self.ledger.intended += 1
        ops.append(_Op("retract", rule, reason=reason))

    def _retract_all_locked(self, reason: str, ops: list[_Op]) -> None:
        for slot in list(self._rules):
            rule = self._rules[slot]
            if rule.state == INSTALLED:
                self._queue_retract_locked(rule, reason, ops)
            self._rules.pop(slot, None)

    def _note_drift_locked(self, drift_state: str | None,
                           ops: list[_Op]) -> None:
        if drift_state is None or drift_state == self._last_drift_state:
            self._last_drift_state = drift_state or self._last_drift_state
            return
        self._last_drift_state = drift_state
        if drift_state == "ROLLED_BACK":
            # never actuate on unpromoted labels: the rollback latches
            # the plane demoted until the drift loop earns PROMOTED
            self._demote_locked("drift_rollback", ops)
        elif drift_state == "PROMOTED" and self._demoted and (
            self._demote_reason == "drift_rollback"
        ):
            self._demoted = False
            self._demote_reason = ""
            self._event("actuation.repromote", via="drift_promoted")

    def _demote_locked(self, reason: str, ops: list[_Op]) -> None:
        if self._demoted:
            return
        self._demoted = True
        self._demote_reason = reason
        self._retract_all_locked(reason, ops)
        self._event("actuation.demote", reason=reason)

    # -- transport + resolution --------------------------------------------

    def _flush_locked(self, ops: list[_Op]) -> None:
        if not ops:
            return
        # demotion forbids NEW installs (none are queued while demoted)
        # but its hold-and-retract deletes must still reach the wire —
        # only a degraded/dry-run transport resolves dry
        if self.mode == "push" and not self._degraded:
            self._flush_push_locked(ops)
        else:
            self._resolve_dry_locked(ops)
        # the invariant is checked HERE, every flush: a resolution bug
        # surfaces at the tick that caused it, not in a far-away gate
        assert self.ledger.exact(), self.ledger.as_dict()

    def _flush_push_locked(self, ops: list[_Op]) -> None:
        link = self._link
        try:
            if link is None:
                link = self._ensure_link_locked()
            for op in ops:
                op.xid = link.next_xid()
                op.payload = self._encode_locked(op)
                if op.kind == "retract":
                    fault_point("actuation.retract")
                else:
                    fault_point("actuation.send")
                link.send(op.payload)
            bxid = link.next_xid()
            fault_point("actuation.barrier")
            refused = link.barrier(bxid)
        except (FaultInjected, OSError) as e:
            self._degrade_locked(str(e) or type(e).__name__)
            for op in ops:
                if op.resolution is None:
                    self._resolve_locked(op, "refused", via="degrade")
            return
        any_refused = False
        for op in ops:
            if op.xid in refused:
                any_refused = True
                self._resolve_locked(op, "refused", via="switch_error")
            else:
                self._resolve_locked(
                    op,
                    "installed" if op.kind == "install" else "retracted",
                    via="push",
                )
        if any_refused:
            # a switch refusing our mods is as suspect as a dead one:
            # stop pushing, re-probe on backoff (ISSUE semantics)
            self._degrade_locked("switch refused flow-mod")

    def _resolve_dry_locked(self, ops: list[_Op]) -> None:
        lines = []
        for op in ops:
            self._resolve_locked(
                op,
                "installed" if op.kind == "install" else "retracted",
                via="dry-run",
            )
            rule = op.rule
            if op.kind == "install":
                action = self.policy[rule.label].describe()
                lines.append(
                    f"  + install cookie={rule.cookie} {rule.src}->"
                    f"{rule.dst} class={rule.label} [{action}]"
                )
            else:
                lines.append(
                    f"  - retract cookie={rule.cookie} {rule.src}->"
                    f"{rule.dst} reason={op.reason}"
                )
        # the intended-mods table: stderr only — stdout belongs to the
        # classify render and stays byte-identical to --actuation off
        print(f"actuation[{self.state}] intended mods:", file=self._out)
        for line in lines:
            print(line, file=self._out)

    def _encode_locked(self, op: _Op) -> bytes:
        rule = op.rule
        if op.kind == "install":
            return compile_install(
                op.xid, rule.src, rule.dst,
                self.policy[rule.label], rule.cookie,
            )
        return compile_retract(op.xid, rule.src, rule.dst, rule.cookie)

    def _resolve_locked(self, op: _Op, resolution: str, via: str) -> None:
        op.resolution = resolution
        rule = op.rule
        if resolution == "installed":
            self.ledger.installed += 1
            rule.state = INSTALLED
            rule.installed_label = rule.label
            rule.deviation = 0
            if via == "push":
                # OF1.3 ADD-replace: a landed install evicts any stale
                # rule under the same match — the pair is clean again
                self._orphans.discard((rule.src, rule.dst))
            if self._m is not None:
                self._m.inc("actuation_rules_installed")
            self._event(
                "actuation.install", slot=rule.slot, cookie=rule.cookie,
                src=rule.src, dst=rule.dst, label=rule.label, via=via,
            )
        elif resolution == "retracted":
            self.ledger.retracted += 1
            if via == "push":
                self._orphans.discard((rule.src, rule.dst))
            elif self.mode == "push":
                # the delete resolved dry while degraded: the switch
                # may still hold the rule — reconcile must wipe it
                self._orphans.add((rule.src, rule.dst))
            if self._m is not None:
                self._m.inc("actuation_rules_retracted")
            self._event(
                "actuation.retract", slot=rule.slot, cookie=rule.cookie,
                src=rule.src, dst=rule.dst, reason=op.reason, via=via,
            )
        else:
            self.ledger.refused += 1
            if op.kind == "install":
                # the install never landed: back to earning the streak
                rule.state = PENDING
                rule.streak = 0
            if self.mode == "push":
                # a refused op's wire state is UNKNOWN (a delete left
                # the rule live; an install may have landed before the
                # barrier died) — track the pair for a reconcile wipe
                self._orphans.add((rule.src, rule.dst))
            if self._m is not None:
                self._m.inc("actuation_rules_refused")
            self._event(
                "actuation.refused", slot=rule.slot, cookie=rule.cookie,
                op=op.kind, via=via,
            )

    # -- degrade / re-probe -------------------------------------------------

    def _degrade_locked(self, reason: str) -> None:
        if self._link is not None:
            self._link.close()
            self._link = None
        if not self._degraded:
            self._degraded = True
            self._degrades += 1
            self._backoff = self._backoff_base
            self._event(
                "actuation.degrade", reason=reason, backoff_s=self._backoff,
            )
        self._next_probe = self._clock() + self._backoff

    def _probe_locked(self) -> None:
        if not self._degraded or self.mode != "push":
            return
        if self._clock() < self._next_probe:
            return
        self._probes += 1
        try:
            self._ensure_link_locked()
        except (OSError, FaultInjected) as e:
            self._event("actuation.probe", ok=False, error=str(e))
            self._backoff = min(self._backoff * 2, self._backoff_max)
            self._next_probe = self._clock() + self._backoff
            return
        self._degraded = False
        self._backoff = self._backoff_base
        self._event("actuation.probe", ok=True)
        self._reconcile_locked()

    def _ensure_link_locked(self) -> SwitchLink:
        if self._link is None:
            link = self._link_factory()
            link.open()
            self._link = link
        return self._link

    def _reconcile_locked(self) -> None:
        """After a successful re-probe the switch's table may disagree
        with the FSM (rules dry-installed or dry-retracted while
        degraded): replay the FSM's INSTALLED view onto the wire.
        Reconcile ops are idempotent repairs, not new intent — they are
        counted separately and never touch the exact ledger."""
        link = self._link
        installed = [r for r in self._rules.values() if r.state == INSTALLED]
        pairs = {(r.src, r.dst) for r in installed}
        orphans = sorted(p for p in self._orphans if p not in pairs)
        try:
            for src, dst in orphans:
                # pairs whose retract/refusal left unknown wire state
                # and that carry no live rule anymore: wipe outright
                link.send(compile_wipe(link.next_xid(), src, dst))
            for rule in installed:
                # wipe stale copies (any cookie), then assert intent
                link.send(compile_wipe(link.next_xid(), rule.src, rule.dst))
                link.send(compile_install(
                    link.next_xid(), rule.src, rule.dst,
                    self.policy[rule.installed_label], rule.cookie,
                ))
            link.barrier(link.next_xid())
        except OSError as e:
            self._degrade_locked(f"reconcile failed: {e}")
            return
        self._orphans.clear()
        self._event(
            "actuation.reconcile", rules=len(installed),
            orphans_wiped=len(orphans),
        )

    # -- blast radius -------------------------------------------------------

    def retract_source(self, sid: int, slots: Iterable[int]) -> None:
        """Quarantine hook: called with a namespace's slot set captured
        *before* ``engine.evict_source`` releases them. Retracts exactly
        the dead namespace's installed rules and forgets its tracks —
        no other source's rules move."""
        ops: list[_Op] = []
        with self._lock:
            pulled = 0
            for slot in slots:
                rule = self._rules.pop(int(slot), None)
                if rule is None:
                    continue
                if rule.state == INSTALLED:
                    self._queue_retract_locked(rule, f"quarantine sid={sid}",
                                               ops)
                    pulled += 1
            self._event("actuation.quarantine", sid=sid, rules=pulled)
            self._flush_locked(ops)
            self._set_state_gauge()

    # -- plumbing -----------------------------------------------------------

    def _event(self, kind: str, **fields) -> None:
        if self._rec is not None:
            self._rec.record(kind, **fields)

    def close(self) -> None:
        with self._lock:
            if self._link is not None:
                self._link.close()
                self._link = None
