"""Fleet serving: take the composed region serve horizontal.

One region-scale serve (fan-in × sharded × incremental × native ingest)
owns a PARTITION of the telemetry sources; a fleet is N such processes
sharing one model-checkpoint rotation directory (``--drift-dir``). The
sharing is what makes the fleet one system instead of N serves:

- **Promotion propagation.** Every member runs the drift loop; one
  member's trip retrains and stages a candidate into the SHARED
  rotation (serving/retrain's seq-numbered members). Every other member
  runs with ``follow_rotation`` (CLI ``--drift-follow``): its
  controller scans the rotation each poll, adopts a newer member as its
  own candidate, and promotes it only through its OWN parity-gated
  probes against its OWN live labels — fleet-wide propagation that
  never bypasses the wrong-but-fresh gate, and never lets one member's
  bad fit install anywhere it cannot reproduce the live labels.
- **Blast radius.** Followers never discard a rejected adopted member
  (it is the peer's, possibly the peer's promoted model); they remember
  its seq and move on.

This module holds the process-independent pieces: the source
partitioner and the ``/healthz`` roster-of-rosters aggregator — one
scrape target that folds every member's health report (each already a
roster of its fan-in sources) into a fleet view. ``tools/fleet_serve.py``
is the launcher that wires both to real serve processes.

Stdlib only (urllib + http.server), matching obs/exposition.py: the
container image is fixed.
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


def partition_sources(n_sources: int, n_members: int) -> list[tuple[int, int]]:
    """Contiguous balanced ``(first_source, count)`` spans, one per
    member — member i serves sources [first, first+count). Remainder
    sources go to the earliest members, so no member ever carries more
    than one extra source."""
    if n_members <= 0:
        raise ValueError(f"n_members must be positive, got {n_members}")
    if n_sources < 0:
        raise ValueError(f"n_sources must be >= 0, got {n_sources}")
    base, extra = divmod(n_sources, n_members)
    out = []
    start = 0
    for i in range(n_members):
        count = base + (1 if i < extra else 0)
        out.append((start, count))
        start += count
    return out


def fetch_member_health(url: str, timeout: float = 2.0) -> dict:
    """One member's ``/healthz`` as a roster entry: ``reachable``,
    ``healthy``, HTTP ``status``, and the member's full ``report``.
    A 503 is REACHABLE-but-unhealthy and still carries the report (the
    exposition server answers 503 with the same JSON body); only a
    transport failure is unreachable. Never raises."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            status = resp.status
            body = resp.read()
    except urllib.error.HTTPError as e:
        status = e.code
        try:
            body = e.read()
        except Exception:  # noqa: BLE001 — a half-dead member must not kill the scrape
            body = b""
    except Exception as e:  # noqa: BLE001 — unreachable is a report, not a crash
        return {
            "url": url, "reachable": False, "healthy": False,
            "error": f"{type(e).__name__}: {e}",
        }
    try:
        report = json.loads(body.decode())
    except Exception as e:  # noqa: BLE001 — a torn payload is a report, not a crash
        return {
            "url": url, "reachable": True, "healthy": False,
            "status": status, "error": f"bad payload: {e}",
        }
    return {
        "url": url, "reachable": True,
        "healthy": bool(report.get("healthy", status == 200)),
        "status": status, "report": report,
    }


def aggregate(member_urls, timeout: float = 2.0,
              fetch=fetch_member_health) -> dict:
    """The roster-of-rosters: every member's health report folded into
    one fleet view. ``healthy`` is the conjunction over members (an
    unreachable member is unhealthy — a fleet with a silent member must
    probe-fail); ``sources`` concatenates each member's fan-in roster
    with a ``member`` index, so one scrape shows every source in the
    region; ``drift_states``/``promoted`` surface whether a promotion
    has propagated fleet-wide."""
    members = [fetch(u, timeout=timeout) for u in member_urls]
    sources = []
    drift_states = []
    swapped = []
    promotions_total = 0
    for i, m in enumerate(members):
        report = m.get("report") or {}
        for src in report.get("sources") or []:
            sources.append({**src, "member": i})
        drift = report.get("drift") or {}
        drift_states.append(drift.get("state"))
        swapped.append(bool(drift.get("swapped")))
        promotions_total += int(drift.get("promotions") or 0)
    return {
        "healthy": bool(members) and all(m["healthy"] for m in members),
        "fleet_size": len(members),
        "members_reachable": sum(
            1 for m in members if m["reachable"]
        ),
        "members_healthy": sum(1 for m in members if m["healthy"]),
        "members": members,
        "sources": sources,
        "drift_states": drift_states,
        "swapped": swapped,
        "promotions_total": promotions_total,
    }


class _AggregatorHandler(BaseHTTPRequestHandler):
    server_version = "tcsdn-fleet/1"

    def do_GET(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
        owner: FleetAggregator = self.server.owner  # type: ignore[attr-defined]
        if self.path.split("?", 1)[0] != "/healthz":
            body = b'{"error": "not found"}'
            self.send_response(404)
        else:
            healthy, report = owner.check()
            body = json.dumps(report, sort_keys=True).encode()
            self.send_response(200 if healthy else 503)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args) -> None:  # noqa: D102
        pass  # probes every few seconds must not spam stderr


class FleetAggregator:
    """The fleet's one scrape target: ``/healthz`` answering the
    roster-of-rosters (``aggregate``), 200 while every member is
    healthy, 503 otherwise. Members are polled ON DEMAND per request —
    no background thread, so the answer's freshness is the scrape's
    freshness and an idle aggregator costs nothing. ``port=0`` binds
    ephemeral (tests); ``self.port`` is the bound port after
    ``start()``. Loopback bind by default, same rationale as
    obs/exposition.ExpositionServer."""

    def __init__(self, member_urls, port: int = 0,
                 host: str = "127.0.0.1", timeout: float = 2.0,
                 fetch=fetch_member_health):
        self.member_urls = list(member_urls)
        self.host = host
        self.port = port
        self.timeout = float(timeout)
        self._fetch = fetch
        self._server: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    def check(self) -> tuple[bool, dict]:
        """(healthy, roster-of-rosters) — the /healthz payload; also
        the embedding API for callers that skip HTTP."""
        report = aggregate(
            self.member_urls, timeout=self.timeout, fetch=self._fetch
        )
        return report["healthy"], report

    def start(self) -> None:
        if self._server is not None:
            raise RuntimeError("fleet aggregator already started")
        server = ThreadingHTTPServer(
            (self.host, self.port), _AggregatorHandler
        )
        server.daemon_threads = True
        server.owner = self  # type: ignore[attr-defined]
        self._server = server
        self.port = server.server_address[1]
        self._thread = threading.Thread(
            target=server.serve_forever, name="tcsdn-fleet-aggregator",
            daemon=True,
        )
        self._thread.start()

    def stop(self) -> None:
        server, self._server = self._server, None
        thread, self._thread = self._thread, None
        if server is not None:
            server.shutdown()
            server.server_close()
        if thread is not None:
            thread.join(timeout=5.0)

    def __enter__(self) -> FleetAggregator:
        self.start()
        return self

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False
