"""Incremental active-set serving: dirty-row prediction with a
persistent device-resident label cache.

The flagship serving cost was never the traffic — it was the TABLE: at
2²⁰ capacity the serve tick re-predicted every row every tick (1.52 s
of a 1.79 s tick through the native forest,
docs/artifacts/serve_2m_cpu_native_forest.json) even when almost
nothing changed. The reference's own INACTIVE rule
(traffic_classifier.py:75-78) freezes a flow's 12 features whenever its
byte/packet deltas are zero — so a row with no telemetry this tick
projects the SAME feature vector it projected last tick, and a
row-independent classifier must give it the same label. Prediction cost
should scale with per-tick churn, not capacity. This module makes it
so:

- the ingest scatter already knows which slots it touched: with dirty
  tracking on, ``FlowStateEngine.step`` routes each packed wire batch
  through ``flow_table.apply_wire_dirty`` — the same scatter, fused
  with a per-slot dirty-bit update (one transfer, one dispatch) — and
  eviction invalidates through ``clear_slots_dirty``;
- each render tick, ``IncrementalLabels`` fetches ONE scalar (the
  dirty count), picks the smallest warmed bucket that admits it
  (``dirty_buckets`` — static shapes, so the retrace discipline matches
  the ingest scatter's and ``--warmup`` can AOT-compile every variant),
  compacts the dirty row indices on device (``compact_dirty``), gathers
  exactly those rows' features (``features12_at`` — elementwise
  identical to ``features12(table)[idx]``), predicts the subset, and
  scatters the fresh labels into a persistent donated label cache
  (``merge_labels``) that ``top_active_render``/the ranked read paths
  consume in place of a full-table predict;
- byte-identity with the full re-predict holds because the cache
  invariant is "``cache[i]`` equals what a full-table predict would
  label row ``i`` today": rows change features only through the scatter
  (marked dirty) or eviction (marked dirty), and the serving families
  are row-independent, so unchanged features ⇒ unchanged label.

Composition rules (every serve-loop consumer routes through here when
``--incremental`` is on):

- **promotion hot-swaps** (serving/drift.DriftGate) and **degrade rung
  changes** (serving/degrade.DegradeLadder) change what the predict
  callable MEANS — the wrapped callable exposes a ``label_epoch`` and
  any change invalidates the whole cache (wrong-but-cached must never
  survive a promotion; a DEGRADED serve must label the whole table on
  the fallback rung, exactly like the full re-predict path);
- while the ladder is off its HEALTHY rung the tick runs full-table
  (through the ladder — its fallback/probe machinery must keep
  running), and a tick whose predict came back STALE (the BROKEN rung's
  last-known-good path) NEVER commits: the label cache itself is the
  true last-known-good full vector, so it is served as-is and the
  attempted rows are re-marked dirty for the recovery tick — the
  stale-label path cannot alias the fresh-label cache;
- fault sites ``serve.dirty_mask`` and ``serve.label_cache``
  (utils/faults.SITES) are ABSORBED: a fire degrades that tick to a
  full-table re-predict served directly (cache and dirty mask left
  untouched), never a stale label served as fresh.

Threading: the host stage owns the dirty mask and the decide/dispatch
half; in the pipelined host-native composition the device-stage worker
runs the predict and commits the host-side cache. The small shared
state (host cache handle, re-dirty queue, invalidation flag) is guarded
by ``_lock``; it is never held across a predict.
"""

from __future__ import annotations

import contextlib
import threading

import numpy as np

import jax
import jax.numpy as jnp

from ..core import flow_table as ft
from ..utils import faults

# One compiled program per (shape-family): count, compaction (per
# bucket), dirty-row feature gather (per bucket), cache scatter (per
# bucket, cache donated so the persistent buffer updates in place), and
# re-invalidation marks. Shared across instances like the batcher's
# apply_wire_jit so --warmup primes the caches serving actually hits.
dirty_count_jit = jax.jit(ft.dirty_count)
compact_dirty_jit = jax.jit(ft.compact_dirty, static_argnames=("bucket",))
features12_at_jit = jax.jit(ft.features12_at)
merge_labels_jit = jax.jit(ft.merge_labels, donate_argnums=0)
mark_dirty_slots_jit = jax.jit(ft.mark_dirty_slots, donate_argnums=0)


def dirty_buckets(capacity: int) -> tuple[int, ...]:
    """The static compaction shapes for a table of ``capacity`` rows:
    powers of four from 16 up to (exclusive) capacity. Geometric with
    factor 4 keeps the compile count small (8 buckets at 2²⁰) while
    bounding padding waste at 4×; a dirty count above the largest
    bucket falls back to the full-table re-predict, which at that
    churn is the cheaper program anyway. Shared with warmup so serving
    can never pick an un-warmed shape."""
    out = []
    b = 16
    while b < capacity:
        out.append(b)
        b *= 4
    return tuple(out)


class _Pending:
    """One dispatched-but-uncommitted incremental update (the pipelined
    host-native split): the host stage fixed the device handles against
    tick N's table; ``IncrementalLabels.finish`` (device-stage worker)
    runs the host predict and commits."""

    __slots__ = ("kind", "idx", "X", "n_dirty", "labels")

    def __init__(self, kind: str, idx=None, X=None, n_dirty: int = 0,
                 labels=None):
        self.kind = kind  # "none" | "subset" | "full" | "full-nocommit"
        self.idx = idx  # (bucket,) device indices, padded with capacity
        self.X = X  # dirty-row (or full) feature matrix, device
        self.n_dirty = n_dirty
        self.labels = labels  # device mode: already-final label vector


class IncrementalLabels:
    """The serve loop's label source when ``--incremental`` is on: a
    persistent (capacity,) label vector maintained by dirty-set
    prediction.

    ``labels()`` is the serial entry point (and the pipelined DEVICE
    path's dispatch — everything it launches is async). The pipelined
    host-native path splits it: ``dispatch()`` on the host stage fixes
    the tick-N read side, ``finish()`` on the device-stage worker runs
    the (GIL-dropping) host predict and commits the cache.
    """

    def __init__(self, engine, predict, params, *, degrade=None,
                 metrics=None, recorder=None, tracer=None):
        if engine.dirty is None:
            engine.enable_dirty_tracking()
        self._engine = engine
        self._predict = predict
        self._params = params
        self._degrade = degrade
        self._metrics = metrics
        self._recorder = recorder
        self._tracer = tracer
        self.capacity = engine.table.capacity
        self.buckets = dirty_buckets(self.capacity)
        self.host_native = bool(getattr(predict, "host_native", False))
        # shared between the host stage and the device-stage worker
        # (pipelined host-native composition); never held across a
        # predict call
        self._lock = threading.Lock()
        self._cache = None  # device mode: jax.Array (capacity,)
        self._host_cache: np.ndarray | None = None
        self._pending_redirty: list[np.ndarray] = []
        self._invalidate = False
        self._epoch = self._current_epoch()
        self._last_dirty = 0
        self._invalidations = 0
        self._full_predicts = 0
        self._subset_predicts = 0

    # -- public surface ----------------------------------------------------
    def invalidate(self, reason: str = "explicit") -> None:
        """Mark the whole cache stale: the next render tick re-predicts
        the full table. Called internally on label-epoch changes
        (promotion hot-swap, degrade rung change) and by anything else
        that swaps label semantics out from under the cache."""
        with self._lock:
            self._invalidate = True
            self._invalidations += 1
        if self._metrics is not None:
            self._metrics.inc("label_cache_invalidations")
        if self._recorder is not None:
            self._recorder.record("label_cache.invalidate", reason=reason)

    def status(self) -> dict:
        """The /healthz self-report (obs.HealthState.set_label_cache)."""
        with self._lock:
            dirty = self._last_dirty
            inv = self._invalidations
            full = self._full_predicts
            subset = self._subset_predicts
        return {
            "mode": "host" if self.host_native else "device",
            "coverage": round(1.0 - dirty / max(1, self.capacity), 6),
            "dirty_rows": dirty,
            "invalidations": inv,
            "full_predicts": full,
            "subset_predicts": subset,
        }

    def labels(self):
        """This tick's full-table label vector (device array in device
        mode, host ndarray in host-native mode), refreshed by dirty-set
        prediction. Serial path and pipelined-device dispatch."""
        return self.finish(self.dispatch())

    # -- host-stage half ---------------------------------------------------
    def dispatch(self) -> _Pending:
        """Fix this render tick's read side against the CURRENT table
        (host stage; device work is dispatched, never synced — except
        the one dirty-count scalar). Returns the pending update for
        ``finish``."""
        span = (
            self._tracer.span("compact") if self._tracer is not None
            else contextlib.nullcontext()
        )
        with span:
            plan = self._plan()
        if plan.kind in ("full", "full-nocommit"):
            plan.X = ft.features12(self._engine.table)
            with self._lock:
                self._full_predicts += 1
        if self.host_native or plan.kind == "none":
            return plan
        # device mode: predict + commit now — all async dispatch
        return self._device_run(plan)

    def _plan(self) -> _Pending:
        """Decide none/subset/full for this tick and dispatch the
        compaction. Host stage only. Committing plans ("subset",
        "full") clear the dirty mask HERE: the next tick's scatter
        re-marks what it touches, and a later discarded commit (stale
        predict) re-marks through the redirty queue / invalidation."""
        eng = self._engine
        # label-source changes (promotion hot-swap, degrade rung move)
        # invalidate everything: wrong-but-cached must not survive them
        epoch = self._current_epoch()
        if epoch != self._epoch:
            self._epoch = epoch
            self.invalidate("label-epoch")
        with self._lock:
            invalidate = self._invalidate
            self._invalidate = False
            redirty, self._pending_redirty = self._pending_redirty, []
            primed = (
                self._cache is not None or self._host_cache is not None
            )
        try:
            faults.fault_point("serve.dirty_mask")
        except faults.FaultInjected:
            # ABSORBED: the dirty bookkeeping is suspect — serve this
            # tick from a direct full-table re-predict (no cache or
            # mask mutation on the fault path) and rebuild both from
            # scratch next tick; never a stale label served as fresh
            self._record_fault("serve.dirty_mask")
            self.invalidate("fault:serve.dirty_mask")
            with self._lock:
                self._pending_redirty = redirty + self._pending_redirty
            self._note(self.capacity)
            return _Pending("full-nocommit", n_dirty=self.capacity)
        for slots in redirty:
            eng.dirty = mark_dirty_slots_jit(eng.dirty, slots)
        if invalidate or not primed:
            eng.dirty = jnp.zeros_like(eng.dirty)
            self._note(self.capacity)
            return _Pending("full", n_dirty=self.capacity)
        if self._ladder_rung() not in (None, "HEALTHY"):
            # off the healthy rung the whole table must carry the
            # fallback's labels (what the full re-predict path serves);
            # routing the full matrix through the ladder also keeps its
            # per-tick retry/probe machinery live on idle streams
            eng.dirty = jnp.zeros_like(eng.dirty)
            self._note(self.capacity)
            return _Pending("full", n_dirty=self.capacity)
        n = int(
            dirty_count_jit(eng.dirty)
        )  # graftlint: disable=implicit-sync -- tick-plan: O(1) scalar that sizes this tick's dispatch
        self._note(n)
        if n == 0:
            if self._metrics is not None:
                self._metrics.inc("predict_rows_saved", self.capacity)
            return _Pending("none", n_dirty=0)
        bucket = next((b for b in self.buckets if n <= b), None)
        if bucket is None:
            # churn above the largest compaction bucket: the full
            # program is the cheaper one — predict everything, commit
            # (the gauge reports the full-table re-predict)
            eng.dirty = jnp.zeros_like(eng.dirty)
            self._note(self.capacity)
            return _Pending("full", n_dirty=n)
        try:
            faults.fault_point("serve.label_cache")
        except faults.FaultInjected:
            # ABSORBED: the cache merge seam is suspect — this tick is
            # served from a direct full re-predict, the cache and dirty
            # mask are left untouched (the dirty rows re-predict next
            # tick), and no stale label is ever served as fresh. The
            # gauge reports what the tick actually re-predicts: all of it
            self._record_fault("serve.label_cache")
            self._note(self.capacity)
            return _Pending("full-nocommit", n_dirty=n)
        idx = compact_dirty_jit(eng.dirty, bucket=bucket)
        Xd = features12_at_jit(eng.table, idx)
        eng.dirty = jnp.zeros_like(eng.dirty)
        if self._metrics is not None:
            self._metrics.inc("predict_rows_saved", self.capacity - n)
        with self._lock:
            self._subset_predicts += 1
        return _Pending("subset", idx=idx, X=Xd, n_dirty=n)

    def _note(self, n: int) -> None:
        """Record this tick's predicted-row count (gauge + /healthz)."""
        self._set_last_dirty(n)
        if self._metrics is not None:
            self._metrics.set("dirty_rows", n)

    def _device_run(self, plan: _Pending) -> _Pending:
        """Device-mode predict+commit (async; host stage)."""
        labels = self._predict(self._params, plan.X)
        if plan.kind == "subset":
            with self._lock:
                cache = self._cache
            cache = merge_labels_jit(cache, plan.idx, labels)
        elif plan.kind == "full-nocommit":
            # serve the fresh labels; leave cache+dirty for next tick
            plan.labels = labels
            return plan
        else:
            cache = labels
        with self._lock:
            self._cache = cache
        plan.labels = cache
        return plan

    # -- device-stage half -------------------------------------------------
    def finish(self, plan: _Pending):
        """Commit the pending update and return the full label vector.
        In the pipelined host-native composition this runs on the
        device-stage worker (the predict drops the GIL there); jobs are
        consumed serially, so commits land in dispatch order."""
        if not self.host_native:
            if plan.labels is not None:
                return plan.labels
            with self._lock:
                return self._cache
        if plan.kind == "none":
            with self._lock:
                return self._host_cache
        labels = np.asarray(
            self._predict(self._params, plan.X)
        )  # graftlint: disable=implicit-sync -- host-native: C++ predict, already host-resident
        if self._stale_now():
            # the ladder served last-known-good (BROKEN) — possibly
            # zero-padded to this batch's shape. NEVER commit: the
            # cache is the true last-known-good vector; re-mark the
            # attempted rows so recovery re-predicts them
            if plan.kind == "subset":
                # materialize the index vector BEFORE taking the lock:
                # a sync on a busy device while holding _lock would
                # wedge every thread that takes it (sync-under-lock)
                idx_host = np.asarray(
                    plan.idx
                )  # graftlint: disable=implicit-sync -- cold-path: BROKEN-rung recovery re-mark only
                with self._lock:
                    self._pending_redirty.append(idx_host)
            else:
                self.invalidate("stale-predict")
            with self._lock:
                cached = self._host_cache
            if cached is not None:
                return cached
            # broken from boot: nothing cached — the ladder's own
            # zero-label stale vector is exactly what the full path
            # serves here
            return np.zeros(self.capacity, labels.dtype)
        if self._current_epoch() != self._epoch:
            # the label source changed UNDER this predict (mid-call
            # trip/promotion): the returned labels are fresh on the NEW
            # source, so committing them is sound, but the rest of the
            # cache predates the change — rebuild next tick
            self.invalidate("epoch-mid-flight")
        if plan.kind == "full-nocommit":
            return labels
        if plan.kind == "subset":
            idx = np.asarray(
                plan.idx
            )  # graftlint: disable=implicit-sync -- host-native: host-cache commit needs host idx
            valid = idx < self.capacity
            with self._lock:
                cache = self._host_cache
                if cache is not None and cache.dtype == labels.dtype:
                    cache[idx[valid]] = labels[valid]
                    return cache
            # cache lost under an in-flight subset (invalidated by a
            # stale full predict ahead of us): serve zeros-consistent
            # behavior by re-marking and falling back to the ladder's
            # stale semantics
            with self._lock:
                self._pending_redirty.append(idx)
                cached = self._host_cache
            return (
                cached if cached is not None
                else np.zeros(self.capacity, labels.dtype)
            )
        cache = np.array(labels)  # own it: the cache outlives the tick
        with self._lock:
            self._host_cache = cache
        return cache

    # -- helpers -----------------------------------------------------------
    def _current_epoch(self):
        return getattr(self._predict, "label_epoch", None)

    def _ladder_rung(self) -> str | None:
        if self._degrade is None:
            return None
        try:
            return self._degrade.status().get("rung")
        except Exception:  # noqa: BLE001 — health probes must not serve
            return None

    def _stale_now(self) -> bool:
        return (
            self._degrade is not None
            and bool(getattr(self._degrade, "render_stale", False))
        )

    def _set_last_dirty(self, n: int) -> None:
        with self._lock:
            self._last_dirty = n

    def _record_fault(self, site: str) -> None:
        if self._recorder is not None:
            self._recorder.record(
                "label_cache.fault_absorbed", site=site
            )


# ---------------------------------------------------------------------------
# Pipelined read-side objects (serving/pipeline.dispatch_read builds these
# when the serve is incremental; same contract as RankedRead/FullRead)
# ---------------------------------------------------------------------------


class IncRankedRead:
    """Tick-N ranked read side through the label cache: the host stage
    dispatched the incremental update (``pending``) and the ranked
    flags against tick N's table; ``rows()`` (device-stage worker)
    commits the cache and joins labels by slot. Used for the
    host-native composition — the device-kernel path reads the cache
    through the ordinary ``RankedRead`` (labels gathered device-side,
    O(rows) crossing)."""

    __slots__ = ("_inc", "_pending", "_flags", "n_flows")

    def __init__(self, inc: IncrementalLabels, pending: _Pending,
                 flags, n_flows: int):
        self._inc = inc
        self._pending = pending
        self._flags = flags
        self.n_flows = n_flows

    def rows(self) -> list[tuple]:
        labels = np.asarray(
            self._inc.finish(self._pending)
        )  # graftlint: disable=implicit-sync -- host-native: finish() ran the C++ predict on host
        # one batched fetch for the device flags (see RankedRead.rows)
        idx, valid, fa, ra = jax.device_get(
            self._flags
        )  # graftlint: disable=implicit-sync -- render-sync: the tick's one batched fetch
        return [
            (int(s), int(labels[int(s)]), bool(f), bool(r))
            for s, v, f, r in zip(idx, valid, fa, ra)
            if v
        ]


class IncFullRead:
    """Unbounded (``--table-rows 0``) read side through the label
    cache: the full render is O(N) by definition, so the worker syncs
    the whole cached label vector (device or host mode) and joins the
    dispatch-time metadata snapshot — the ``FullRead`` contract."""

    __slots__ = ("_inc", "_pending", "_fa", "_ra", "_meta", "n_flows")

    def __init__(self, inc: IncrementalLabels, pending: _Pending,
                 fa, ra, meta, n_flows: int):
        self._inc = inc
        self._pending = pending
        self._fa = fa
        self._ra = ra
        self._meta = meta
        self.n_flows = n_flows

    def rows(self) -> list[tuple]:
        # device_get passes a host-mode label cache through untouched
        # and batches the device leaves into one blocking fetch
        labels, fa, ra = jax.device_get(
            (self._inc.finish(self._pending), self._fa, self._ra)
        )  # graftlint: disable=implicit-sync -- render-sync: the tick's one batched fetch
        labels = np.asarray(labels)
        return [
            (slot, src, dst, int(labels[slot]), bool(fa[slot]),
             bool(ra[slot]))
            for slot, (src, dst) in sorted(self._meta.items())
        ]
