"""Close the loop: online drift detection, background retrain, and
parity-gated hot checkpoint promotion.

The reference freezes its model at pickle time
(traffic_classifier.py:229-243 loads one fitted estimator and serves it
forever), so traffic whose distribution shifts under the server silently
degrades accuracy with no signal and no recourse. This module is the
first place train and serve meet in one process: a drift monitor over
the live feature stream, a background retrainer, and hot promotion of
the fresh checkpoint through the same parity-gated probing discipline
the degradation ladder (PR 5) uses for device recovery — wrong-but-fresh
never promotes, and a bad promotion rolls back via
``serving/retrain.resolve_latest`` with the old model still serving
every tick.

::

    STEADY ──window over threshold──► DRIFTING ──K consecutive──► RETRAINING
       ▲                                 │(score recovers)            │
       │◄────────────────────────────────┘      (fit done, staged)    │
       │                                                              ▼
       │◄──resume── ROLLED_BACK ◄──swap failed── CANDIDATE ◄──────────┘
       │                                   │(N consecutive clean
       │◄──resume── PROMOTED ◄──hot swap───┘  parity probes)

- **STEADY / DRIFTING** — ``DriftMonitor`` maintains streaming
  per-feature and per-class population statistics over the live feature
  matrix: each render tick's active rows fold into the current window's
  sums, windows fold into an EWMA of per-feature means, and a bounded
  reservoir keeps the most recent rows with the labels the live model
  assigned (the "recent labeled window" the retrainer consumes). Every
  ``window`` observations the window closes and scores against a
  **reference distribution** — calibrated from the first windows of the
  serve, persisted into the serving checkpoint (``feature_reference``
  block, io/serving_checkpoint.py FORMAT_VERSION 3) so a restored serve
  resumes against the same reference instead of re-calibrating on
  already-drifted traffic, and re-based onto the retrain window on every
  promotion. The score is the max of the per-feature EWMA z-shift
  (|mean − ref_mean| / ref_std) and the class-mix shift; a window over
  ``threshold`` enters DRIFTING, and ``trips`` CONSECUTIVE over-threshold
  windows trip the retrain (one noisy window never does).
- **RETRAINING** — the trip snapshots the reservoir and submits a fit to
  a ``retrain.BackgroundRetrainer`` worker: ``retrain.fit_family`` (the
  distributed trainers on a single-device mesh) then a candidate
  checkpoint written through the atomic staged-commit path
  (io/checkpoint.save_model) into the drift directory's ``model-<seq>``
  rotation. The serve keeps ticking on the old model throughout; a fit
  that outlives ``retrain_deadline`` (injectable clock) is ABANDONED —
  the watchdog discipline, minus the blocking wait.
- **CANDIDATE** — the staged candidate serves shadow batches off the hot
  path: each window boundary, its labels on the latest observed rows are
  compared against the labels the LIVE model assigned those rows (exact
  parity by default, ``parity_min``). ``probe_successes`` CONSECUTIVE
  clean probes promote; any miss resets the chain, and a candidate that
  keeps failing is rejected outright — wrong-but-fresh never promotes.
- **PROMOTED / ROLLED_BACK** — promotion hot-swaps the candidate's
  serving pair into the ``DriftGate`` (the predict wrapper both serve
  loops already route through) and re-bases the monitor's reference onto
  the retrain window. A failed swap rolls back: the candidate is
  discarded from the rotation and the newest checkpoint that still LOADS
  (``retrain.resolve_latest`` — the boot seed at minimum, saved at
  drift-enable time) is re-installed; if even the rollback reload fails,
  the gate simply keeps the pair it already holds. Either way the old
  model serves every tick. Both are momentary states: the next window
  resumes STEADY.

**No-fault guarantee**: with ``--drift auto`` and no drift, serve output
is byte-identical to ``--drift off`` (serial and pipelined —
tests/test_drift.py pins it). The gate forwards the caller's params
untouched until the first promotion and returns the inner predict's
labels unmodified; all monitor work happens AFTER the tick's labels are
produced, on the device-stage worker in pipelined mode (its idle time
between renders) or the serve thread in serial mode, and only touches
host copies.

Chaos: ``drift.window`` (window observation fails → dropped, counted),
``retrain.fit`` (refit dies → old model keeps serving, a still-drifting
stream re-trips), ``promote.swap`` (hot swap fails → rollback via
``resolve_latest``) and ``promote.rollback`` (the rollback reload itself
fails → the gate keeps its current pair) are registered fault sites —
ALL absorbed: the serve never crashes and never misses a tick
(tests/test_chaos.py). Every transition lands in the flight recorder
(``drift.transition``), /metrics (``drift_state``/``drift_score``
gauges; ``retrain_runs``/``promotions``/``rollbacks`` counters) and
/healthz (``drift`` block + ``model_age_s``).
"""

from __future__ import annotations

import collections
import os
import sys
import threading
import time

import numpy as np

from ..utils import faults
from . import retrain

STEADY = "STEADY"
DRIFTING = "DRIFTING"
RETRAINING = "RETRAINING"
CANDIDATE = "CANDIDATE"
PROMOTED = "PROMOTED"
ROLLED_BACK = "ROLLED_BACK"

# the drift_state gauge encoding (docs/OBSERVABILITY.md)
STATE_GAUGE = {
    STEADY: 0, DRIFTING: 1, RETRAINING: 2, CANDIDATE: 3, PROMOTED: 4,
    ROLLED_BACK: 5,
}


class DriftMonitor:
    """Streaming per-feature/per-class population statistics with a
    windowed trip rule and a bounded labeled reservoir.

    Single-threaded by contract: ``observe`` is called from exactly one
    thread at a time (the serve loop's render path — the device-stage
    worker when pipelined). The controller mirrors the fields other
    threads need under its own lock.

    ``reference`` seeds a previously persisted reference (the serving
    checkpoint's ``feature_reference`` block: ``mean``, ``std``,
    ``class_freq``, ``count`` arrays, and — since the open-set tier —
    optional ``class_mean``/``class_std``/``class_count`` per-class
    per-feature statistics); without one, the first
    ``calibration_windows`` non-empty windows calibrate it from the
    live stream.

    Open-world labels: observed labels may carry the ``unknown`` index
    ``n_classes`` (serving/openset.OpenSetGate rejections). The class
    mix tracks ``n_classes + 1`` slots — a surge in the unknown
    fraction IS a class-mix drift signal, attributed as the ``unknown``
    class — while the per-class feature statistics and the reference
    freeze EXCLUDE unknown rows (a rejected row has no trustworthy
    class to teach).

    Attribution: every scored window's report carries an
    ``attribution`` block — the top-k per-feature z-shift contributors
    and the top per-class frequency deltas, plus the score
    decomposition — so a trip names WHAT moved, not just that
    something did.
    """

    ATTRIBUTION_TOP_K = 3

    def __init__(self, n_features: int = 12, n_classes: int = 2, *,
                 window: int = 8, threshold: float = 4.0, trips: int = 3,
                 calibration_windows: int = 2, ewma_alpha: float = 0.5,
                 class_tolerance: float = 0.2,
                 reservoir_rows: int = 4096,
                 reference: dict | None = None, eps: float = 1e-9):
        if window < 1:
            raise ValueError("window must be >= 1")
        self.n_features = int(n_features)
        self.n_classes = int(n_classes)
        self.window = int(window)
        self.threshold = float(threshold)
        self.trips = max(1, int(trips))
        self.calibration_windows = max(1, int(calibration_windows))
        self.ewma_alpha = float(ewma_alpha)
        self.class_tolerance = float(class_tolerance)
        self.reservoir_rows = int(reservoir_rows)
        self.eps = float(eps)
        self.windows = 0  # completed windows (the test-visible index)
        self.score = 0.0
        self.over_streak = 0
        self._obs = 0
        # class-mix slots: n_classes known classes + one ``unknown``
        # slot (index n_classes) for open-set rejections
        self._n_mix = self.n_classes + 1
        self._wsum = np.zeros(self.n_features, np.float64)
        self._wsumsq = np.zeros(self.n_features, np.float64)
        self._wclass = np.zeros(self._n_mix, np.float64)
        self._wrows = 0
        self._ewma: np.ndarray | None = None
        self._cal_sum = np.zeros(self.n_features, np.float64)
        self._cal_sumsq = np.zeros(self.n_features, np.float64)
        self._cal_class = np.zeros(self._n_mix, np.float64)
        # per-class per-feature calibration moments (unknown excluded)
        self._cal_class_sum = np.zeros(
            (self.n_classes, self.n_features), np.float64
        )
        self._cal_class_sumsq = np.zeros(
            (self.n_classes, self.n_features), np.float64
        )
        self._cal_class_rows = np.zeros(self.n_classes, np.float64)
        self._cal_rows = 0
        self._cal_windows = 0
        self._res: collections.deque = collections.deque()
        self._res_rows = 0
        self._ref = self._validate_reference(reference)

    def _validate_reference(self, reference) -> dict | None:
        if not reference:
            return None
        ref = {
            k: np.asarray(reference[k], np.float64)
            for k in ("mean", "std", "class_freq")
        }
        ref["count"] = np.asarray(
            reference.get("count", 0.0), np.float64
        )
        # pre-open-set references carry n_classes mix slots; pad the
        # unknown slot with 0 (no rejections were possible then)
        if ref["class_freq"].shape == (self.n_classes,):
            ref["class_freq"] = np.concatenate(
                [ref["class_freq"], np.zeros(1, np.float64)]
            )
        # every shape checked HERE, at construction: a reference
        # persisted by a serve with a different feature/class layout
        # must fail loudly at startup, never as a broadcast error in
        # the middle of a window close
        for key, want in (("mean", (self.n_features,)),
                          ("std", (self.n_features,)),
                          ("class_freq", (self._n_mix,))):
            if ref[key].shape != want:
                raise ValueError(
                    f"feature_reference {key} shape {ref[key].shape} "
                    f"!= {want} — the persisted reference belongs to a "
                    f"different model layout"
                )
        # optional per-class per-feature stats (the open-set tier's
        # reference; absent in older checkpoints)
        for key, want in (
            ("class_mean", (self.n_classes, self.n_features)),
            ("class_std", (self.n_classes, self.n_features)),
            ("class_count", (self.n_classes,)),
        ):
            if key in reference:
                arr = np.asarray(reference[key], np.float64)
                if arr.shape != want:
                    raise ValueError(
                        f"feature_reference {key} shape {arr.shape} "
                        f"!= {want} — the persisted reference belongs "
                        f"to a different model layout"
                    )
                ref[key] = arr
        return ref

    @property
    def calibrated(self) -> bool:
        return self._ref is not None

    def reference_arrays(self) -> dict | None:
        """The reference as a flat name→array dict — the serving
        checkpoint's ``feature_reference`` block. None before
        calibration completes."""
        ref = self._ref
        if ref is None:
            return None
        return {k: np.array(v) for k, v in ref.items()}

    def observe(self, X, y) -> dict | None:
        """Fold one batch of ACTIVE rows (and the labels the live model
        assigned them) into the current window. Returns None mid-window
        and a window report dict at each window boundary."""
        X = np.asarray(X, np.float64)
        y = np.asarray(y)
        if X.shape[0]:
            self._wsum += X.sum(axis=0)
            self._wsumsq += np.square(X).sum(axis=0)
            # labels may carry the unknown index n_classes (open-set
            # rejections) — it gets its own mix slot
            labels = np.clip(
                y.astype(np.int64), 0, self.n_classes
            )
            self._wclass += np.bincount(
                labels, minlength=self._n_mix
            )[: self._n_mix]
            if self._ref is None:
                # per-class calibration moments — KNOWN rows only (a
                # rejected row has no trustworthy class to teach)
                known = labels < self.n_classes
                if known.any():
                    np.add.at(
                        self._cal_class_sum, labels[known], X[known]
                    )
                    np.add.at(
                        self._cal_class_sumsq, labels[known],
                        np.square(X[known]),
                    )
                    np.add.at(
                        self._cal_class_rows, labels[known], 1.0
                    )
            self._wrows += int(X.shape[0])
            self._res.append(
                (X.astype(np.float32), y.astype(np.int32))
            )
            self._res_rows += int(X.shape[0])
            while self._res_rows > self.reservoir_rows and len(
                self._res
            ) > 1:
                old_X, _old_y = self._res.popleft()
                self._res_rows -= int(old_X.shape[0])
        self._obs += 1
        if self._obs < self.window:
            return None
        return self._close_window()

    def _close_window(self) -> dict:
        rows = self._wrows
        mean = freq = sumsq = None
        if rows:
            mean = self._wsum / rows
            freq = self._wclass / rows
            sumsq = self._wsumsq.copy()
        self._wsum[:] = 0.0
        self._wsumsq[:] = 0.0
        self._wclass[:] = 0.0
        self._wrows = 0
        self._obs = 0
        self.windows += 1
        report = {
            "window": self.windows, "rows": rows, "score": self.score,
            "over": False, "tripped": False, "calibrating": False,
            "empty": rows == 0,
        }
        if rows == 0:
            return report  # nothing observed: the streak is untouched
        if self._ref is None:
            self._cal_sum += mean * rows
            self._cal_sumsq += sumsq
            self._cal_class += freq * rows
            self._cal_rows += rows
            self._cal_windows += 1
            report["calibrating"] = True
            if self._cal_windows >= self.calibration_windows:
                self._freeze_reference()
            return report
        a = self.ewma_alpha
        self._ewma = (
            mean if self._ewma is None
            else a * self._ewma + (1.0 - a) * mean
        )
        ref_std = np.maximum(self._ref["std"], self.eps)
        zs = np.abs(self._ewma - self._ref["mean"]) / ref_std
        z = float(np.max(zs))
        # class-mix shift scaled so it CAN trip the default threshold:
        # the max frequency delta is 1.0, so the score ceiling is
        # 1/class_tolerance — the default 0.2 puts a full label-mix
        # inversion at 5.0, above the default threshold 4.0 (a
        # tolerance of threshold⁻¹ or larger would make this signal
        # mathematically inert)
        class_deltas = freq - self._ref["class_freq"]
        c = float(np.max(np.abs(class_deltas))) / self.class_tolerance
        self.score = max(z, c)
        report["score"] = self.score
        # attribution: WHAT moved, not just that something did — the
        # top-k per-feature z contributors and per-class frequency
        # deltas, plus the score decomposition. Index n_classes in the
        # class list is the open-set ``unknown`` slot.
        k = self.ATTRIBUTION_TOP_K
        feat_order = np.argsort(zs)[::-1][:k]
        class_order = np.argsort(np.abs(class_deltas))[::-1][:k]
        report["attribution"] = {
            "z_score": z,
            "class_score": c,
            "dominant": "feature" if z >= c else "class",
            "features": [
                (int(i), float(zs[i])) for i in feat_order
            ],
            "classes": [
                (int(i), float(class_deltas[i])) for i in class_order
            ],
            # the FULL per-slot vector: gauge publication must refresh
            # every class every window — a class that left the top-k
            # must not keep its stale high gauge forever
            "all_class_deltas": [float(d) for d in class_deltas],
        }
        if self.score > self.threshold:
            self.over_streak += 1
            report["over"] = True
            if self.over_streak >= self.trips:
                report["tripped"] = True
        else:
            self.over_streak = 0
        return report

    def _freeze_reference(self) -> None:
        rows = self._cal_rows
        mean = self._cal_sum / rows
        var = np.maximum(self._cal_sumsq / rows - mean * mean, 0.0)
        # per-class stats from the same calibration windows (unknown
        # rows excluded at accumulation); empty classes are inert —
        # zero mean, eps std
        crows = np.maximum(self._cal_class_rows, 1.0)[:, None]
        cmean = self._cal_class_sum / crows
        cvar = np.maximum(
            self._cal_class_sumsq / crows - cmean * cmean, 0.0
        )
        self._ref = {
            "mean": mean,
            "std": np.sqrt(var),
            "class_freq": self._cal_class / rows,
            "count": np.float64(rows),
            "class_mean": cmean,
            "class_std": np.sqrt(cvar),
            "class_count": self._cal_class_rows.copy(),
        }

    def reset_streak(self) -> None:
        self.over_streak = 0

    def reservoir_window(self) -> tuple[np.ndarray, np.ndarray] | None:
        """The recent labeled window as ``(X, y)`` — the retrainer's
        training set (labels may include the unknown index; the
        controller filters before fitting). None when nothing has been
        observed."""
        if not self._res:
            return None
        X = np.concatenate([x for x, _ in self._res], axis=0)
        y = np.concatenate([y_ for _, y_ in self._res], axis=0)
        return X, y

    def known_reservoir_window(
        self,
    ) -> tuple[np.ndarray, np.ndarray] | None:
        """``reservoir_window`` restricted to rows with a KNOWN class
        label — what the retrainer fits on and what the per-class
        reference/open-set rebase learns from. A rejected (unknown)
        row has no trustworthy label: teaching it to any class would
        fold the novel traffic into the known world, which is exactly
        how a promoted model would FORGET to reject it."""
        window = self.reservoir_window()
        if window is None:
            return None
        X, y = window
        known = y.astype(np.int64) < self.n_classes
        if not int(known.sum()):
            return None
        return X[known], y[known]

    def rebase_from_reservoir(self) -> bool:
        """Re-reference onto the retrain window's own statistics after a
        promotion: the new model's 'training-time' distribution IS that
        window, so drift detection continues relative to it. Global
        feature stats and the class mix fold in EVERY reservoir row
        (the unknown fraction becomes the new baseline — sustained
        novel traffic stops re-tripping); the per-class stats fold in
        KNOWN rows only, so rejection survives the rebase. Resets the
        EWMA, streak, and score."""
        window = self.reservoir_window()
        if window is None:
            return False
        X, y = window
        Xf = np.asarray(X, np.float64)
        mean = Xf.mean(axis=0)
        labels = np.clip(y.astype(np.int64), 0, self.n_classes)
        freq = (
            np.bincount(labels, minlength=self._n_mix)[
                : self._n_mix
            ].astype(np.float64) / max(1, Xf.shape[0])
        )
        self._ref = {
            "mean": mean,
            "std": Xf.std(axis=0),
            "class_freq": freq,
            "count": np.float64(Xf.shape[0]),
        }
        # per-class moments through the ONE batch-window home
        # (serving/openset.class_reference — it excludes unknown rows
        # by the same rule); the streaming accumulators in observe/
        # _freeze_reference genuinely need their own incremental code,
        # this full-window path does not
        from .openset import class_reference

        cref = class_reference(Xf, labels, self.n_classes)
        self._ref["class_mean"] = cref["class_mean"]
        self._ref["class_std"] = cref["class_std"]
        self._ref["class_count"] = cref["class_count"]
        self._ewma = None
        self.over_streak = 0
        self.score = 0.0
        return True


class DriftGate:
    """The predict wrapper both serve loops route through: a transparent
    passthrough until the first promotion, an atomic hot-swap point
    after it.

    Pre-swap the caller's ``params`` are forwarded untouched and the
    inner predict's return value (device array or host array) comes back
    unmodified — which is what keeps ``--drift auto`` byte-identical to
    ``--drift off`` on the no-promotion path. ``install`` swaps in a
    ``(predict_fn, params)`` pair; from then on the gate's own pair
    serves and the caller's stale params operand is ignored.

    Each call also captures ``(X, labels)`` BY REFERENCE (host
    microseconds): the controller's ``poll`` materializes them off the
    hot path. ``host_native`` mirrors the wrapped predict so the serve
    loop's routing (pipelined read-side branch, warmup) is unchanged.
    """

    def __init__(self, predict):
        self.host_native = bool(getattr(predict, "host_native", False))
        self._lock = threading.Lock()
        self._fn = predict
        self._params = None
        self._swapped = False
        self._swap_count = 0
        self._capture = None

    def __call__(self, params, X):
        with self._lock:
            fn = self._fn
            p = self._params if self._swapped else params
        labels = fn(p, X)
        with self._lock:
            self._capture = (X, labels)
        return labels

    def take_capture(self):
        """The newest ``(X, labels)`` pair, consumed (None when no
        predict ran since the last take)."""
        with self._lock:
            cap = self._capture
            self._capture = None
            return cap

    def install(self, fn, params):
        """Atomically swap the serving pair (promotion / rollback);
        returns the REPLACED predict callable so the caller can retire
        it (a ladder-wrapped predict owns a watchdog thread)."""
        with self._lock:
            prev = self._fn
            self._fn = fn
            self._params = params
            self._swapped = True
            self._swap_count += 1
            return prev

    @property
    def inner(self):
        """The currently installed predict callable — consumers that
        must follow promotions (GateLadderView) read through this."""
        with self._lock:
            return self._fn

    @property
    def swapped(self) -> bool:
        with self._lock:
            return self._swapped

    @property
    def label_epoch(self) -> tuple:
        """Label-source epoch for the incremental predict path
        (serving/incremental.py): any promotion or rollback
        (``install``) bumps the swap count, and a wrapped ladder's own
        rung epoch rides along — comparing the pair detects BOTH swap
        kinds, so a model hot-swap always invalidates the whole label
        cache (wrong-but-cached must never survive a promotion)."""
        with self._lock:
            fn = self._fn
            count = self._swap_count
        return (count, getattr(fn, "label_epoch", 0))


class GateLadderView:
    """Degradation-ladder adapter for serves running BOTH ``--degrade``
    and ``--drift``: a promotion rebuilds the ladder around the promoted
    kernel (the CLI's ``build_serving``), so consumers of the ladder's
    ``render_stale``/``status`` surface — the render paths' STALE column
    and /healthz — must follow the gate's CURRENT inner callable, not
    the boot ladder object the serve started with."""

    def __init__(self, gate: DriftGate, boot_ladder):
        self._gate = gate
        self._boot = boot_ladder

    def _live(self):
        inner = self._gate.inner
        return inner if hasattr(inner, "render_stale") else self._boot

    @property
    def render_stale(self) -> bool:
        return bool(self._live().render_stale)

    def status(self) -> dict:
        live = self._live()
        status = getattr(live, "status", None)
        return status() if status is not None else self._boot.status()

    def close(self) -> None:
        """Retire BOTH the live ladder and the boot one (idempotent —
        a promoted serve's boot ladder was already closed at swap)."""
        for obj in (self._gate.inner, self._boot):
            close = getattr(obj, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:  # noqa: BLE001 — teardown is best-effort
                    pass


class ShardedDriftGate:
    """DriftGate-shaped adapter for the SHARDED spine. The single-device
    gate wraps the predict callable the serve loop invokes; the sharded
    engine instead compiles its predict INTO the per-shard read programs
    (parallel/table_sharded.make_tick_outputs*), so there is no call
    site to wrap. This adapter hands the DriftController the same
    surface — ``take_capture``/``install``/``swapped``/``inner`` — with
    ``install`` routed through ``ShardedFlowEngine.install_predict``
    (rebuilds the read programs and resets the per-shard label caches
    all-dirty: the sharded label-epoch invalidation) and captures FED by
    the serve loop, which samples the rendered rows' features
    (``engine.feature_sample``) and labels after each render and hands
    the pair to ``feed_capture``."""

    host_native = False

    def __init__(self, engine):
        self._engine = engine
        self._lock = threading.Lock()
        self._capture = None
        self._swapped = False

    def feed_capture(self, X, labels) -> None:
        """Per-render observation hand-off — the sharded stand-in for
        ``DriftGate.__call__``'s by-reference capture."""
        with self._lock:
            self._capture = (X, labels)

    def take_capture(self):
        with self._lock:
            cap, self._capture = self._capture, None
            return cap

    def install(self, fn, params):
        prev_fn, _prev_params = self._engine.install_predict(fn, params)
        with self._lock:
            self._swapped = True
        return prev_fn

    @property
    def inner(self):
        return self._engine._predict_fn

    @property
    def swapped(self) -> bool:
        with self._lock:
            return self._swapped


def default_build_serving(family: str, classes):
    """``params -> (jitted predict_fn, serve_params)`` through the same
    resolution the CLI boot path uses (models.serving_path +
    jit_serving_fn), so a promoted checkpoint serves on exactly the
    kernel family the boot model did."""
    from ..models import jit_serving_fn, make_loaded_model
    from ..models.base import ClassList

    def build(params):
        loaded = make_loaded_model(
            family, params, ClassList(tuple(classes))
        )
        fn, p = loaded.serving_path()
        return jit_serving_fn(fn), p

    return build


class DriftController:
    """The drift→retrain→promote state machine (module docstring).

    ``poll()`` is called once per render tick after the tick's labels
    are produced, from ONE thread at a time (the pipelined device-stage
    worker or the serial serve thread); ``status()``/
    ``reference_arrays()`` may be called concurrently from the
    exposition/snapshot threads and read only mirrored state under the
    controller lock. ``clock`` (monotonic seconds) is injectable so the
    retrain deadline and status ages are exact in tests.
    """

    def __init__(self, gate: DriftGate, *, family: str, classes,
                 directory: str, n_features: int = 12, window: int = 8,
                 threshold: float = 4.0, trips: int = 3,
                 calibration_windows: int = 2, ewma_alpha: float = 0.5,
                 class_tolerance: float = 0.2,
                 probe_successes: int = 3, parity_min: float = 1.0,
                 parity_mode: str = "exact",
                 candidate_max_failures: int = 6,
                 retrain_deadline: float = 300.0,
                 min_retrain_rows: int = 32,
                 reservoir_rows: int = 4096, keep: int = 3,
                 reference: dict | None = None, build_serving=None,
                 fit_kwargs: dict | None = None, metrics=None,
                 recorder=None, health=None, clock=time.monotonic,
                 boot_params=None, feature_names=None,
                 follow_rotation: bool = False):
        self._gate = gate
        self._family = family
        self._classes = tuple(classes)
        self._directory = directory
        # open-set composition — wired POST-construction via
        # set_openset (the OpenSetGate wraps the DriftGate, so it
        # cannot exist before the controller): the gate to re-base at
        # each promotion, and its capture as the observation source so
        # the monitor sees the ``unknown`` relabels as the (C+1)th
        # mix slot. One wiring point keeps the pair consistent.
        self._openset = None
        self._capture_source = None
        # display names for attribution: known classes + the open-set
        # unknown slot; feature names fall back to column indices
        self._mix_names = self._classes + ("unknown",)
        if feature_names is None and int(n_features) == 12:
            from ..core.features import FEATURE_COLUMNS_12

            feature_names = FEATURE_COLUMNS_12
        self._feature_names = (
            tuple(feature_names) if feature_names is not None
            else tuple(str(i) for i in range(int(n_features)))
        )
        self._attribution: dict | None = None
        self.probe_successes = max(1, int(probe_successes))
        self.parity_min = float(parity_min)
        if parity_mode not in ("exact", "mode-matched"):
            raise ValueError(
                f"parity_mode {parity_mode!r} not in "
                f"('exact', 'mode-matched')"
            )
        self.parity_mode = parity_mode
        self.candidate_max_failures = max(
            1, int(candidate_max_failures)
        )
        self.retrain_deadline = float(retrain_deadline)
        self.min_retrain_rows = int(min_retrain_rows)
        self.keep = int(keep)
        self._fit_kwargs = dict(fit_kwargs or {})
        self._metrics = metrics
        self._recorder = recorder
        self._health = health
        self._clock = clock
        self._build = (
            build_serving if build_serving is not None
            else default_build_serving(family, self._classes)
        )
        self._monitor = DriftMonitor(
            n_features=n_features, n_classes=len(self._classes),
            window=window, threshold=threshold, trips=trips,
            calibration_windows=calibration_windows,
            ewma_alpha=ewma_alpha, class_tolerance=class_tolerance,
            reservoir_rows=reservoir_rows,
            reference=reference,
        )
        self._retrainer = retrain.BackgroundRetrainer()
        self._lock = threading.Lock()
        self._state = STEADY
        self._candidate = None  # (fn, params, path, seq)
        # fleet follower mode: scan the shared rotation for members a
        # PEER serve staged and adopt them as candidates — promotion
        # then rides the same parity-gated probe ladder, so fleet-wide
        # propagation never bypasses the wrong-but-fresh gate
        self.follow_rotation = bool(follow_rotation)
        self._candidate_adopted = False
        # highest ADOPTED seq already judged (either way): a rejected
        # adoption must not be re-adopted every poll — but the member
        # stays in the rotation (it is the PEER's, maybe its promoted
        # model; a follower never discards shared members)
        self._follow_seen = 0
        # the latest FULL-shape capture (X f32, y, active mask) — probes
        # run the exact serving shape so the candidate compiles the one
        # program it will serve with, never a fresh shadow shape (the
        # same lesson serving/degrade.py's probe_rows=0 default encodes)
        self._last_shadow: tuple | None = None
        self._probe_ok = 0
        self._probe_failures = 0
        self._retrain_started_at = 0.0
        # the highest seq known to be a legitimate restore target;
        # rollback discards every rotation member ABOVE it — an
        # abandoned fit's late-committed candidate must never be what
        # resolve_latest hands back. Initialized below from the
        # rotation itself: a RESTARTED serve must treat prior runs'
        # promoted checkpoints as legitimate, not as strays
        self._promoted_seq = 0
        self._counts = {
            "windows": 0, "window_errors": 0, "retrain_runs": 0,
            "retrain_failures": 0, "promotions": 0, "rollbacks": 0,
            "probe_failures": 0,
        }
        self._score = 0.0
        os.makedirs(directory, exist_ok=True)
        # Seed the rotation with the BOOT model (staged-commit save) so
        # "roll back via resolve_latest" is well-defined before any
        # promotion has ever happened. Idempotent across restarts: an
        # existing loadable member is kept. A follow_rotation member
        # NEVER seeds: the shared rotation belongs to the fleet and two
        # members racing to write seq 0 would collide on one member
        # path — the leader owns the boot seed, followers adopt.
        latest = retrain.resolve_latest(directory)
        if (boot_params is not None and latest is None
                and not self.follow_rotation):
            latest = retrain.save_candidate(
                directory, 0, family, boot_params, self._classes
            )
        # never-reused candidate sequence numbers: an abandoned fit may
        # still be writing model-<seq> when the next trip launches — a
        # fresh seq per launch means the two can never collide on one
        # checkpoint directory
        self._next_candidate_seq = retrain.next_seq(directory)
        if latest is not None:
            for member_seq, member_path in retrain.list_candidates(
                directory
            ):
                if member_path == latest:
                    self._promoted_seq = member_seq
                    break
        if metrics is not None:
            metrics.set("drift_state", STATE_GAUGE[STEADY])
            metrics.set("drift_score", 0.0)

    # -- public surface ----------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def set_health(self, health) -> None:
        with self._lock:
            self._health = health

    def set_openset(self, gate) -> None:
        """Wire the outermost OpenSetGate (cli.py constructs it AFTER
        the controller — the gate wraps the DriftGate, so it cannot
        exist first): promotions re-base the gate's reference onto the
        retrain window, and observation consumes the gate's capture so
        the monitor sees the ``unknown`` relabels. The ONE wiring
        point — rebase target, capture source, and the gate's capture
        opt-in always move together."""
        gate.enable_capture()
        with self._lock:
            self._openset = gate
            self._capture_source = gate.take_capture

    def status(self) -> dict:
        """The /healthz self-report (obs.HealthState.set_drift)."""
        with self._lock:
            return {
                "state": self._state,
                "gauge": STATE_GAUGE[self._state],
                "score": self._score,
                # why the score is what it is: top z-shift features,
                # top class-mix deltas (unknown slot included), and
                # the score decomposition — an operator reads WHY the
                # monitor tripped without tailing the ring
                "attribution": self._attribution,
                "windows": self._counts["windows"],
                "window_errors": self._counts["window_errors"],
                "retrain_runs": self._counts["retrain_runs"],
                "retrain_failures": self._counts["retrain_failures"],
                "promotions": self._counts["promotions"],
                "rollbacks": self._counts["rollbacks"],
                "probe_successes": self._probe_ok,
                "calibrated": self._monitor.calibrated,
                "swapped": self._gate.swapped,
            }

    def reference_arrays(self) -> dict | None:
        """The monitor's reference for serving-checkpoint persistence
        (io/serving_checkpoint.save ``feature_reference=``)."""
        return self._monitor.reference_arrays()

    def close(self) -> None:
        self._retrainer.abandon()
        with self._lock:
            candidate, self._candidate = self._candidate, None
            self._last_shadow = None
        if candidate is not None:
            # a still-staged candidate owns its own predict (a rebuilt
            # ladder's watchdog thread included) — retire it with the
            # controller
            self._retire(candidate[0])

    # -- the per-render-tick poll ------------------------------------------
    def poll(self) -> None:
        """Advance the loop one step. Called after the tick's labels are
        produced — off the hot path. NEVER raises: every failure mode is
        absorbed and counted (the serve loop must not die of its own
        self-updating machinery)."""
        gate_cap = self._gate.take_capture()
        if self._capture_source is not None:
            # the openset gate is the outermost wrapper: observe ITS
            # labels (unknown relabels included); the drift gate's own
            # capture is drained above so it never pins a stale tick
            cap = self._capture_source()
        else:
            cap = gate_cap
        report = self._observe(cap) if cap is not None else None
        if self.state == RETRAINING:
            self._check_retrain()
        if self.follow_rotation and self.state in (STEADY, DRIFTING):
            # fleet follower: a peer's freshly staged rotation member
            # becomes a candidate HERE too — probed below like any
            # locally retrained one (the scan is one listdir; a member
            # already judged or predating the promoted seq is skipped)
            self._check_rotation()
        if report is None:
            return
        state = self.state
        if state in (PROMOTED, ROLLED_BACK):
            self._transition(STEADY, "resume")
            state = STEADY
        if state == CANDIDATE:
            self._probe_candidate()
            return
        if state not in (STEADY, DRIFTING):
            return
        if report["calibrating"] or report["empty"]:
            return
        if report["tripped"]:
            self._start_retrain(report)
        elif report["over"]:
            if state == STEADY:
                self._transition(
                    DRIFTING, f"score={report['score']:.3g}"
                )
        elif state == DRIFTING:
            self._transition(STEADY, "score-recovered")

    # -- observation -------------------------------------------------------
    def _observe(self, cap) -> dict | None:
        X, labels = cap
        try:
            faults.fault_point("drift.window")
            Xh = np.asarray(X, np.float64)
            yh = np.asarray(labels)
            yh = yh[: Xh.shape[0]]
            mask = Xh.any(axis=1)
            # the stats update sits INSIDE the absorbing try: poll()'s
            # never-raises contract covers the monitor math too — an
            # exotic batch must drop the sample, never the serve
            report = self._monitor.observe(Xh[mask], yh[mask])
        except Exception as e:  # noqa: BLE001 — observation must not kill the serve
            # absorbed: a failed observation — the injected
            # drift.window fire, a donated feature buffer superseded
            # under backpressure (jax reports it as a deleted-array
            # RuntimeError), or a stats-update failure — drops the
            # sample, never the serve
            self._count("window_errors", metric="drift_window_errors")
            if self._recorder is not None:
                self._recorder.record(
                    "drift.window_error", error=type(e).__name__,
                    detail=str(e),
                )
            return None
        with self._lock:
            # full serving-shape shadow, kept only while a candidate is
            # (about to be) probing — O(capacity) host memory is paid
            # exactly when the parity gate needs it
            if self._state in (RETRAINING, CANDIDATE) and int(
                mask.sum()
            ):
                self._last_shadow = (
                    Xh.astype(np.float32), yh, mask
                )
            if report is not None:
                self._counts["windows"] += 1
                self._score = report["score"]
                if report.get("attribution") is not None:
                    self._attribution = self._name_attribution(
                        report["attribution"]
                    )
        if report is not None:
            if self._metrics is not None:
                self._metrics.set("drift_score", report["score"])
                self._metrics.inc("drift_windows")
                attribution = report.get("attribution")
                if attribution is not None:
                    # per-class attribution gauges: the live |Δfreq|
                    # per mix slot (unknown included), scaled like the
                    # class score so the gauge is threshold-comparable.
                    # EVERY slot refreshes every scored window — a
                    # class that recovered must read ~0, not its last
                    # top-k value
                    for ci, delta in enumerate(
                        attribution["all_class_deltas"]
                    ):
                        name = self._mix_names[ci] if ci < len(
                            self._mix_names
                        ) else str(ci)
                        self._metrics.set(
                            f"drift_attribution_{name}",
                            abs(delta) / self._monitor.class_tolerance,
                        )
            if report["over"] and self._recorder is not None:
                self._recorder.record(
                    "drift.window", window=report["window"],
                    score=report["score"],
                    streak=self._monitor.over_streak,
                    attribution=self._name_attribution(
                        report.get("attribution")
                    ),
                )
        return report

    def _name_attribution(self, attribution) -> dict | None:
        """The monitor's index-based attribution with class/feature
        names resolved — what /healthz, the ring, and the transition
        log carry (an operator reads ``voice``/``Delta Forward
        Bytes``, not slot numbers)."""
        if attribution is None:
            return None
        def fname(i: int) -> str:
            return (
                self._feature_names[i]
                if i < len(self._feature_names) else str(i)
            )
        def cname(i: int) -> str:
            return (
                self._mix_names[i] if i < len(self._mix_names)
                else str(i)
            )
        return {
            "z_score": round(attribution["z_score"], 6),
            "class_score": round(attribution["class_score"], 6),
            "dominant": attribution["dominant"],
            "top_class": cname(attribution["classes"][0][0])
            if attribution["classes"] else None,
            "top_feature": fname(attribution["features"][0][0])
            if attribution["features"] else None,
            "features": [
                {"feature": fname(i), "z": round(z, 6)}
                for i, z in attribution["features"]
            ],
            "classes": [
                {"class": cname(i), "delta": round(d, 6)}
                for i, d in attribution["classes"]
            ],
        }

    # -- retrain -----------------------------------------------------------
    def _start_retrain(self, report: dict) -> None:
        # KNOWN-labeled rows only: an open-set rejection must never
        # become training signal (teaching the novel class to a known
        # label is exactly how the promoted model would stop rejecting
        # it)
        window = self._monitor.known_reservoir_window()
        n_classes = len(self._classes)
        if window is None or window[0].shape[0] < self.min_retrain_rows \
                or np.unique(window[1]).size < min(2, n_classes):
            # not enough labeled signal to refit: stay DRIFTING — the
            # streak persists, so a still-drifting stream retries at
            # the next window with a fuller reservoir
            if self._recorder is not None:
                self._recorder.record(
                    "drift.retrain_skipped", reason="window-insufficient"
                )
            if self.state == STEADY:
                self._transition(
                    DRIFTING, f"score={report['score']:.3g}"
                )
            return
        X, y = window
        self._monitor.reset_streak()
        family, classes = self._family, self._classes
        directory, fit_kwargs = self._directory, self._fit_kwargs
        with self._lock:
            seq = self._next_candidate_seq
            self._next_candidate_seq += 1
            self._retrain_started_at = self._clock()
            self._last_shadow = None  # probes must postdate the trip

        def job(is_current):
            params = retrain.fit_family(
                family, X, y, n_classes, **fit_kwargs
            )
            if not is_current():
                # abandoned at the deadline while fitting: publish
                # NOTHING into the rotation — a never-probed stray must
                # not become resolve_latest's rollback target
                return None
            path = retrain.save_candidate(
                directory, seq, family, params, classes
            )
            return params, path, seq

        self._count("retrain_runs", metric="retrain_runs")
        self._retrainer.submit(job)
        self._transition(
            RETRAINING, f"tripped(score={report['score']:.3g})"
        )

    def _check_retrain(self) -> None:
        state = self._retrainer.poll()
        if state == retrain.RUNNING:
            with self._lock:
                started = self._retrain_started_at
            if self._clock() - started > self.retrain_deadline:
                # the watchdog abandon discipline: the worker's late
                # result is discarded; the loop resumes watching
                self._retrainer.abandon()
                self._count(
                    "retrain_failures", metric="retrain_failures"
                )
                with self._lock:
                    self._last_shadow = None
                self._transition(STEADY, "retrain-deadline")
            return
        if state == retrain.IDLE:
            return
        _state, result, error = self._retrainer.take()
        if _state == retrain.FAILED or result is None:
            self._count("retrain_failures", metric="retrain_failures")
            with self._lock:
                self._last_shadow = None  # episode over: release it
            self._transition(
                STEADY,
                "retrain-failed:" + (
                    type(error).__name__ if error is not None
                    else "abandoned"
                ),
            )
            return
        params, path, seq = result
        try:
            fn, p = self._build(params)
        except Exception as e:  # noqa: BLE001 — a garbage fit must not kill the serve
            retrain.discard_candidate(path)
            self._count("retrain_failures", metric="retrain_failures")
            self._transition(
                STEADY, f"candidate-build-failed:{type(e).__name__}"
            )
            return
        with self._lock:
            self._candidate = (fn, p, path, seq)
            self._candidate_adopted = False
            self._probe_ok = 0
            self._probe_failures = 0
        self._transition(
            CANDIDATE, f"staged:{os.path.basename(path)}"
        )

    def _check_rotation(self) -> None:
        """Adopt a NEWER rotation member staged by a peer serve sharing
        this checkpoint directory (fleet mode): load it, build the
        serving pair, and stage it as this serve's candidate — the
        parity probes then judge it against THIS serve's own live
        labels before it can install. NEVER raises (poll's contract):
        a peer's torn write or a garbage member is counted and skipped,
        and its seq is remembered so it is not re-tried every tick."""
        try:
            members = retrain.list_candidates(self._directory)
        except Exception:  # noqa: BLE001 — a scan failure must not kill the serve
            return
        if not members:
            return
        seq, path = members[0]
        with self._lock:
            if seq <= max(self._promoted_seq, self._follow_seen):
                return
            self._follow_seen = seq
        try:
            loaded = retrain.load_candidate(path)
            fn, p = self._build(loaded.params)
        except Exception as e:  # noqa: BLE001 — a peer's torn member must not kill this serve
            self._count("retrain_failures", metric="retrain_failures")
            if self._recorder is not None:
                self._recorder.record(
                    "drift.follow_error", member=path,
                    error=type(e).__name__, detail=str(e),
                )
            return
        with self._lock:
            self._candidate = (fn, p, path, seq)
            self._candidate_adopted = True
            self._probe_ok = 0
            self._probe_failures = 0
        self._transition(
            CANDIDATE, f"adopted:{os.path.basename(path)}"
        )

    # -- probing / promotion -----------------------------------------------
    def _probe_candidate(self) -> None:
        with self._lock:
            candidate = self._candidate
            # CONSUME the shadow: each probe must judge a FRESH
            # observation — N consecutive clean probes means N
            # independent batches, never one stale batch re-counted
            # across empty windows
            shadow, self._last_shadow = self._last_shadow, None
        if candidate is None:
            self._transition(STEADY, "candidate-lost")
            return
        fn, params, path, seq = candidate
        if shadow is None:
            return  # no fresh observation to probe against this window
        Xs, ys, mask = shadow
        if not int(mask.sum()):
            return
        try:
            # the FULL captured matrix — the exact serving shape, so
            # the probe compiles the one program the promoted model
            # will serve with (no per-row-count shadow compiles, and
            # the first post-swap tick is already warm)
            got = np.asarray(fn(params, Xs))
        except Exception as e:  # noqa: BLE001 — a crashing candidate is a failed probe
            ok, agree, detail = False, 0.0, f"error:{type(e).__name__}"
        else:
            if got.shape[:1] != ys.shape[:1]:
                ok, agree, detail = False, 0.0, "shape-mismatch"
            else:
                ysm = np.asarray(ys)[mask]
                gotm = got[mask]
                # open-world shadows: rows the openset gate rejected
                # carry the unknown index — a closed-world candidate
                # can never reproduce it, so parity judges KNOWN rows
                # only (an all-unknown shadow judges nothing)
                known = ysm.astype(np.int64) < len(self._classes)
                if not int(known.sum()):
                    return
                agree = self._agreement(gotm[known], ysm[known])
                ok = agree >= self.parity_min
                detail = f"agree={agree:.4f}"
        if self._recorder is not None:
            self._recorder.record(
                "drift.probe", ok=ok, detail=detail,
                successes=self._probe_ok + (1 if ok else 0),
            )
        if ok:
            with self._lock:
                self._probe_ok += 1
                promote = self._probe_ok >= self.probe_successes
            if promote:
                self._promote(candidate)
            return
        self._count("probe_failures", metric="drift_probe_failures")
        with self._lock:
            self._probe_ok = 0
            self._probe_failures += 1
            rejected = (
                self._probe_failures >= self.candidate_max_failures
            )
            adopted = self._candidate_adopted
            if rejected:
                self._candidate = None
                self._candidate_adopted = False
        if rejected:
            # wrong-but-fresh: the candidate disagrees with the live
            # model on the very window it was trained against — it
            # never promotes, and the rotation forgets it; its predict
            # (a rebuilt ladder's watchdog included) is retired too.
            # An ADOPTED member stays: it belongs to the peer that
            # staged it (possibly that peer's promoted model) — the
            # remembered _follow_seen keeps it from being re-adopted
            if not adopted:
                retrain.discard_candidate(path)
            self._retire(fn)
            self._transition(STEADY, f"candidate-rejected:{detail}")

    def _agreement(self, got: np.ndarray, want: np.ndarray) -> float:
        """Probe agreement between candidate and live labels.

        ``exact`` is elementwise equality. ``mode-matched`` (the kmeans
        family's mode: a refit clustering orders its centroids
        arbitrarily, so raw cluster ids are a PERMUTATION of the live
        model's) maps each candidate label to the live majority label
        of its rows first — the same mode-matching discipline
        ``analysis.eval.clustering_accuracy`` uses — so a perfectly
        consistent relabeling scores 1.0 and an inconsistent one is
        still rejected."""
        if not got.shape[0]:
            return 0.0
        if self.parity_mode == "exact":
            return float(np.mean(got == want))
        matched = 0
        for label in np.unique(got):
            sel = got == label
            _vals, counts = np.unique(want[sel], return_counts=True)
            matched += int(counts.max())
        return matched / got.shape[0]

    def _promote(self, candidate) -> None:
        fn, params, path, seq = candidate
        installed = False
        try:
            faults.fault_point("promote.swap")
            prev = self._gate.install(fn, params)
            installed = True
        except Exception as e:  # noqa: BLE001 — a failed swap must roll back, not crash
            self._rollback(
                path, fn, f"swap-failed:{type(e).__name__}",
                installed=installed,
            )
            return
        with self._lock:
            self._candidate = None
            self._candidate_adopted = False
            self._probe_ok = 0
            self._promoted_seq = seq
            self._last_shadow = None  # O(capacity) host memory: only
            # held while the parity gate needs it
            health = self._health
        self._retire(prev)
        self._count("promotions", metric="promotions")
        if health is not None:
            health.model_promoted()
        # the rebase pair: the monitor re-references onto the retrain
        # window, and the open-set gate re-bases its per-class stats +
        # threshold onto the SAME window's known-labeled rows — the
        # promoted model keeps rejecting what it was never taught
        # (rejected rows are in neither the fit nor the stats). Both
        # are absorbing: a promotion that landed never un-lands.
        self._monitor.rebase_from_reservoir()
        if self._openset is not None:
            known = self._monitor.known_reservoir_window()
            if known is not None:
                self._openset.rebase(known[0], known[1])
        retrain.prune_candidates(self._directory, keep=self.keep)
        self._transition(
            PROMOTED, f"promoted:{os.path.basename(path)}"
        )

    def _retire(self, prev) -> None:
        """Close a replaced predict (a ladder-wrapped one owns a
        watchdog thread). Best-effort: retiring must never fail a
        promotion that already landed."""
        close = getattr(prev, "close", None)
        if close is None:
            return
        try:
            close()
        except Exception:  # noqa: BLE001 — teardown is best-effort
            pass

    def _rollback(self, bad_path: str, bad_fn, why: str,
                  installed: bool = False) -> None:
        """A bad promotion: discard the candidate (and any never-probed
        stray an abandoned fit left above the last promoted seq), then
        resolve the newest checkpoint that still loads
        (``retrain.resolve_latest`` — the boot seed at minimum). The
        resolved checkpoint is re-installed only when the failed swap
        actually LANDED in the gate (``installed`` — with the gate's
        atomic install this cannot happen today, so the branch is
        defensive); otherwise the gate already holds the old model's
        warm pair and keeps it — no cold reload, no compile spike. If
        even the rollback path fails, the gate keeps the pair it
        already holds; every branch ends with the old model serving
        every tick."""
        with self._lock:
            self._candidate = None
            self._probe_ok = 0
            self._last_shadow = None
            promoted_seq = self._promoted_seq
        self._retire(bad_fn)  # the never-installed candidate's threads
        self._count("rollbacks", metric="rollbacks")
        try:
            faults.fault_point("promote.rollback")
            retrain.discard_candidate(bad_path)
            for seq, stray in retrain.list_candidates(self._directory):
                if seq > promoted_seq:
                    retrain.discard_candidate(stray)
            good, loaded = retrain._resolve_and_load(self._directory)
            if good is None:
                detail = f"{why};no-restorable-checkpoint"
            elif installed:
                fn, p = self._build(loaded.params)
                prev = self._gate.install(fn, p)
                self._retire(prev)
                detail = f"{why};restored:{os.path.basename(good)}"
            else:
                # the swap never landed: the live pair IS the old
                # model, already warm — resolve_latest names the
                # restore target for the audit trail only
                detail = (
                    f"{why};kept-live-pair"
                    f"(latest:{os.path.basename(good)})"
                )
        except Exception as e:  # noqa: BLE001 — rollback failure keeps the live pair
            detail = f"{why};rollback-failed:{type(e).__name__}"
            if self._recorder is not None:
                self._recorder.record(
                    "drift.rollback_error", error=type(e).__name__,
                    detail=str(e),
                )
        self._transition(ROLLED_BACK, detail)

    # -- bookkeeping -------------------------------------------------------
    def _count(self, key: str, metric: str | None = None) -> None:
        with self._lock:
            if key in self._counts:
                self._counts[key] += 1
        if metric is not None and self._metrics is not None:
            self._metrics.inc(metric)

    def _transition(self, to: str, reason: str) -> None:
        with self._lock:
            frm = self._state
            if frm == to:
                return
            self._state = to
            # divergence transitions carry WHY: the responsible
            # class/feature rides the event, so a ring tail (or the
            # post-mortem dump) names the mover without correlation
            attribution = (
                self._attribution if to in (DRIFTING, RETRAINING)
                else None
            )
        if self._metrics is not None:
            self._metrics.inc("drift_transitions")
            self._metrics.set("drift_state", STATE_GAUGE[to])
        if self._recorder is not None:
            if attribution is not None:
                self._recorder.record(
                    "drift.transition", frm=frm, to=to, reason=reason,
                    attribution=attribution,
                )
            else:
                self._recorder.record(
                    "drift.transition", frm=frm, to=to, reason=reason
                )
        print(
            f"DRIFT: {frm} -> {to} ({reason})", file=sys.stderr,
            flush=True,
        )
