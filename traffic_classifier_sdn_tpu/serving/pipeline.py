"""Bounded two-deep software pipeline for the serve loop.

Two stages over one bounded handoff:

- the **host stage** (the caller's thread — the main thread in
  ``cli.py``) polls telemetry, parses it, scatters the update batch
  into the device flow table, and *dispatches* the tick's read side
  (features → predict → ranked render gather). JAX dispatch is
  asynchronous, so dispatching costs host microseconds; the host never
  waits for device results.
- the **device stage** (one worker thread) blocks on the dispatched
  arrays, converts the O(rows) results to host tuples, and renders.
  For host-native kernels (``TCSDN_FOREST_KERNEL=native``,
  ``TCSDN_KNN_TOPK=native``) there is nothing async to wait on, so the
  worker runs the C++ predict itself — the entry points drop the GIL
  and are mutex-guarded (native/flow_engine.cpp, PR 2), so host/compute
  overlap is real there too.

Backpressure is explicit and bounded: the handoff holds at most
``depth`` (1–2) staged ticks. When the device stage falls behind, a new
tick *coalesces* into the newest staged one (the stale render is
superseded — its telemetry is already in the flow table, only its
un-printed frame is dropped) rather than queueing unboundedly; the
``ticks_coalesced`` counter and ``queue_depth`` gauge make the overload
visible, and ``stage_overlap_s`` (observed per device-stage job) proves
the overlap on the same ``stage_*_p50/p99`` histograms the span tracer
already feeds.

Output equivalence: with the device stage keeping up (no coalescing),
the pipelined loop renders byte-identical PrettyTable rows to the
serial loop — the read side of tick N is dispatched *at* tick N (so it
sees exactly tick N's table), ``n_flows`` is captured at dispatch, and
idle eviction is deferred to pipeline-idle moments so a ranked slot's
host metadata cannot be released between dispatch and render
(tests/test_pipeline.py pins this for the device-kernel, host-native,
full-table, and sharded paths).

Fault sites ``pipeline.handoff`` and ``pipeline.coalesce`` thread the
chaos matrix through the new concurrency seams (utils/faults.SITES).

Latency provenance (obs/latency.py) rides the same dispatch/visibility
boundary this module defines: the serve loop SEALS the pending batch
entries at read-side dispatch on the host stage (the set of scatters
this render will make visible is fixed exactly there), the device-stage
job marks device completion after ``rows()`` syncs, and the fold runs
after the frame prints. Coalescing composes for free — a superseded
render's sealed generation folds at the render that actually printed,
which is when its telemetry truly became operator-visible.

Failure propagation at the device stage (serving/degrade.py): a raw
device kernel that wedges mid-dispatch would block the device-stage
worker forever — ``ServePipeline`` propagates device-stage EXCEPTIONS
back to the host stage, but a wedge raises nothing to propagate. The
degradation ladder closes that hole from inside the job: it is marked
``host_native``, so ``dispatch_read`` routes it through the host-call
read objects below, and the ladder's ``DeviceWatchdog`` bounds the
device sync with a wall-clock deadline ON the worker. A deadline trip
becomes a rung demotion (the job completes with fallback labels), not
a dead worker; genuine ladder-external failures still take the
existing ``raise_if_failed`` exception path.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from collections.abc import Callable

import numpy as np

import jax
import jax.numpy as jnp

from ..core import flow_table as ft
from ..utils import faults


class Handoff:
    """The bounded staging handoff between the two stages: a rotating
    set of at most ``depth`` slots guarded by one condition variable.

    ``put`` never blocks and never grows the queue past ``depth`` —
    when full, the new item coalesces into the newest staged slot
    (``merge(staged, new)``, default: replace) and the coalesce counter
    advances. ``get``/``done`` are the consumer half; ``join`` waits
    for empty-and-idle (the drain barrier a clean shutdown needs)."""

    def __init__(self, depth: int = 2,
                 merge: Callable | None = None):
        if depth < 1:
            raise ValueError("handoff depth must be >= 1")
        self.depth = depth
        self._merge = merge
        self._lock = threading.Condition()
        self._slots: deque = deque()
        self._inflight = 0
        self._coalesced = 0
        self._closed = False

    def put(self, item) -> bool:
        """Stage one item; True if queued, False if it coalesced into
        the newest staged slot (backpressure)."""
        with self._lock:
            faults.fault_point("pipeline.handoff")
            if self._closed:
                raise RuntimeError("handoff is closed")
            if len(self._slots) < self.depth:
                self._slots.append(item)
                self._lock.notify_all()
                return True
            faults.fault_point("pipeline.coalesce")
            staged = self._slots[-1]
            self._slots[-1] = (
                self._merge(staged, item) if self._merge is not None
                else item
            )
            self._coalesced += 1
            return False

    def get(self, timeout: float | None = None):
        """Next staged item (oldest first), blocking up to ``timeout``;
        None on timeout or when closed with nothing staged."""
        with self._lock:
            while not self._slots and not self._closed:
                if not self._lock.wait(timeout):
                    return None
            if not self._slots:
                return None  # closed and drained
            item = self._slots.popleft()
            self._inflight += 1
            self._lock.notify_all()
            return item

    def done(self) -> None:
        """Consumer: the last ``get`` item is fully processed."""
        with self._lock:
            if self._inflight > 0:
                self._inflight -= 1
            self._lock.notify_all()

    def join(self, timeout: float | None = None) -> bool:
        """Wait until nothing is staged or in flight; False on timeout."""
        deadline = (
            None if timeout is None else time.monotonic() + timeout
        )
        with self._lock:
            while self._slots or self._inflight:
                if deadline is None:
                    self._lock.wait()
                    continue
                left = deadline - time.monotonic()
                if left <= 0 or not self._lock.wait(left):
                    return False
            return True

    def close(self) -> None:
        """No further puts; staged items still drain through ``get``."""
        with self._lock:
            self._closed = True
            self._lock.notify_all()

    def abort(self) -> None:
        """Drop everything and close — the device stage died, or the
        host is bailing out on an exception; ``join`` must not hang on
        work that will never be consumed."""
        with self._lock:
            self._slots.clear()
            self._inflight = 0
            self._closed = True
            self._lock.notify_all()

    @property
    def queued(self) -> int:
        with self._lock:
            return len(self._slots)

    @property
    def coalesced(self) -> int:
        with self._lock:
            return self._coalesced

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed

    @property
    def idle(self) -> bool:
        with self._lock:
            return not self._slots and not self._inflight


class _HostBusy:
    """Context manager marking one host-stage busy interval — the
    overlap accounting's producer half (see ServePipeline)."""

    __slots__ = ("_pipe", "_t0")

    def __init__(self, pipe: "ServePipeline"):
        self._pipe = pipe

    def __enter__(self):
        self._t0 = self._pipe._clock()
        with self._pipe._lock:
            self._pipe._host_open = self._t0
        return self

    def __exit__(self, *exc) -> bool:
        t1 = self._pipe._clock()
        with self._pipe._lock:
            self._pipe._host_iv.append((self._t0, t1))
            self._pipe._host_busy_s += t1 - self._t0
            self._pipe._host_open = None
        return False


class ServePipeline:
    """The two-stage pipeline: a ``Handoff`` plus one device-stage
    worker thread running ``consume(item)`` per staged item, with
    exception propagation back to the host stage and exact
    host/device overlap accounting.

    The host stage wraps its per-tick work in ``host_stage()`` and
    stages render jobs with ``submit``; a device-stage failure is
    re-raised in the host thread at the next ``submit``/``drain``/
    ``raise_if_failed`` so the serve loop's crash forensics (the obs
    post-mortem dump) see the original exception."""

    def __init__(self, consume: Callable, *, depth: int = 2,
                 metrics=None, merge: Callable | None = None,
                 clock: Callable[[], float] = time.perf_counter):
        self._consume = consume
        self._metrics = metrics
        self._clock = clock
        self._handoff = Handoff(depth=depth, merge=merge)
        self._lock = threading.Lock()
        self._exc: BaseException | None = None
        # recent host busy intervals (bounded): the device stage
        # intersects its own busy window with these to observe
        # stage_overlap_s exactly — device jobs are serial, so each
        # host interval is counted against at most one device window
        self._host_iv: deque = deque(maxlen=256)
        self._host_open: float | None = None
        self._host_busy_s = 0.0
        self._device_busy_s = 0.0
        self._overlap_s = 0.0
        self._thread = threading.Thread(
            target=self._run, name="tcsdn-device-stage", daemon=True
        )

    # -- host stage --------------------------------------------------------
    def start(self) -> "ServePipeline":
        self._thread.start()
        return self

    def host_stage(self) -> _HostBusy:
        return _HostBusy(self)

    def submit(self, item) -> bool:
        """Stage one device-stage job; True if queued, False if it
        coalesced. Raises the device stage's exception if it died."""
        self.raise_if_failed()
        try:
            queued = self._handoff.put(item)
        except RuntimeError:
            # closed under us — the device stage died between checks
            self.raise_if_failed()
            raise
        if self._metrics is not None:
            self._metrics.set("queue_depth", self._handoff.queued)
            if not queued:
                self._metrics.inc("ticks_coalesced")
        return queued

    def raise_if_failed(self) -> None:
        with self._lock:
            exc = self._exc
        if exc is not None:
            raise exc

    def failed(self) -> bool:
        with self._lock:
            return self._exc is not None

    def idle(self) -> bool:
        """Nothing staged and nothing in flight — the host may run work
        (idle eviction) whose host-side bookkeeping a concurrent render
        would observe."""
        return self._handoff.idle

    def drain(self, timeout: float | None = None) -> bool:
        """Wait for every staged job to finish; re-raise a device-stage
        failure. False on timeout."""
        ok = self._handoff.join(timeout)
        self.raise_if_failed()
        return ok

    def shutdown(self, drain: bool = True,
                 timeout: float = 10.0) -> None:
        """Stop the device stage. ``drain=True`` lets staged jobs
        finish first (clean end of stream); ``drain=False`` drops them
        (error paths). Never raises — call ``raise_if_failed`` after a
        drain when failures must surface."""
        if drain and not self.failed():
            self._handoff.join(timeout)
            self._handoff.close()
        else:
            self._handoff.abort()
        if self._thread.is_alive():
            self._thread.join(timeout)

    def stats(self) -> dict:
        with self._lock:
            host_busy = self._host_busy_s
            device_busy = self._device_busy_s
            overlap = self._overlap_s
        return {
            "host_busy_s": host_busy,
            "device_busy_s": device_busy,
            "overlap_s": overlap,
            "ticks_coalesced": self._handoff.coalesced,
        }

    # -- device stage ------------------------------------------------------
    def _run(self) -> None:
        while True:
            item = self._handoff.get(timeout=0.2)
            if item is None:
                if self._handoff.closed:
                    return
                continue
            t0 = self._clock()
            try:
                self._consume(item)
            except BaseException as e:  # noqa: BLE001 — repropagated to host
                with self._lock:
                    self._exc = e
                self._handoff.done()
                self._handoff.abort()
                return
            self._handoff.done()
            self._account(t0, self._clock())

    def _account(self, t0: float, t1: float) -> None:
        overlap = 0.0
        with self._lock:
            self._device_busy_s += t1 - t0
            for a, b in self._host_iv:
                lo = a if a > t0 else t0
                hi = b if b < t1 else t1
                if hi > lo:
                    overlap += hi - lo
            if self._host_open is not None and t1 > self._host_open:
                # the host stage is busy RIGHT NOW — its open interval
                # won't be recorded until it exits, but the device job
                # overlapping it must still count
                lo = self._host_open if self._host_open > t0 else t0
                if t1 > lo:
                    overlap += t1 - lo
            self._overlap_s += overlap
        if self._metrics is not None:
            self._metrics.observe("stage_overlap_s", overlap)
            self._metrics.set("queue_depth", self._handoff.queued)


# ---------------------------------------------------------------------------
# Donated double-buffers for the feature matrix
# ---------------------------------------------------------------------------

# The feature projection with its output pinned to a donated buffer:
# (capacity, 12) f32 in → (capacity, 12) f32 out lets XLA alias the
# donated input for the result, so the per-render-tick feature matrix
# stops allocating fresh HBM (50 MB/tick at capacity 2²⁰) and instead
# rotates through two persistent buffers.
_FEATURES_INTO = jax.jit(
    lambda buf, table: ft.features12(table), donate_argnums=0
)


class FeatureStage:
    """Two rotating donated device buffers pinning the serving feature
    matrix. ``features(table)`` computes this tick's (capacity, 12)
    matrix *into* the older buffer (donated — XLA reuses its storage)
    while the newer one may still feed the previous tick's in-flight
    predict; JAX's dependency tracking orders the aliasing write after
    every dispatched reader."""

    def __init__(self, capacity: int, telemetry=None):
        self._bufs = [
            jnp.zeros((capacity, ft.NUM_FEATURES), jnp.float32)
            for _ in range(2)
        ]
        self._turn = 0
        # obs/device.DeviceTelemetry, when the device plane is armed:
        # each rotation reports whether XLA actually reused the donated
        # buffer's storage (donation-effectiveness reconciliation)
        self._telemetry = telemetry

    def features(self, table: ft.FlowTable) -> jax.Array:
        i = self._turn
        self._turn = 1 - i
        donated = self._bufs[i]
        tel = self._telemetry
        ptr = None
        if tel is not None:
            try:
                # read BEFORE the donating dispatch deletes the input
                ptr = donated.unsafe_buffer_pointer()
            except Exception:  # noqa: BLE001 — telemetry must not inject
                tel = None
        out = _FEATURES_INTO(donated, table)
        self._bufs[i] = out
        if tel is not None:
            try:
                tel.note_donation(
                    "feature", out.unsafe_buffer_pointer() == ptr
                )
            except Exception:  # noqa: BLE001 — telemetry must not inject
                pass
        return out


# ---------------------------------------------------------------------------
# Dispatched read-side objects (host stage dispatches, device stage syncs)
# ---------------------------------------------------------------------------


class RankedRead:
    """Tick-N ranked read side, dispatched but not yet synced: the
    device arrays of ``flow_table.top_active_render`` plus the
    dispatch-time flow count. ``rows()`` (device stage) blocks and
    builds the ``(slot, label, fwd_active, rev_active)`` list — exactly
    ``FlowStateEngine.render_sample``'s output."""

    __slots__ = ("_outs", "n_flows")

    def __init__(self, outs, n_flows: int):
        self._outs = outs
        self.n_flows = n_flows

    def rows(self) -> list[tuple]:
        # ONE batched device→host fetch: device_get starts every
        # leaf's copy asynchronously and blocks once, where a
        # per-array np.asarray loop pays five serial round trips
        idx, valid, lab, fa, ra = jax.device_get(
            self._outs
        )  # graftlint: disable=implicit-sync -- render-sync: the tick's one batched fetch
        return [
            (int(s), int(c), bool(f), bool(r))
            for s, v, c, f, r in zip(idx, valid, lab, fa, ra)
            if v
        ]


class NativeRankedRead:
    """Host-native variant: the worker thread runs the C++ predict
    itself (the GIL-dropping, mutex-guarded entry points make the
    overlap real), then joins the full-table labels with the
    tick-N ranked flags dispatched by the host stage."""

    __slots__ = ("_X", "_flags", "_predict", "_params", "n_flows")

    def __init__(self, X, flags, predict, params, n_flows: int):
        self._X = X
        self._flags = flags
        self._predict = predict
        self._params = params
        self.n_flows = n_flows

    def rows(self) -> list[tuple]:
        labels = np.asarray(
            self._predict(self._params, self._X)
        )  # graftlint: disable=implicit-sync -- host-native: C++ predict, already host-resident
        idx, valid, fa, ra = jax.device_get(
            self._flags
        )  # graftlint: disable=implicit-sync -- render-sync: the tick's one batched fetch
        return [
            (int(s), int(labels[int(s)]), bool(f), bool(r))
            for s, v, f, r in zip(idx, valid, fa, ra)
            if v
        ]


class FullRead:
    """Unbounded (``--table-rows 0``) read side: the whole label vector
    plus per-direction active flags and a dispatch-time snapshot of the
    slot→(src, dst) metadata (the full render is O(N) by definition, so
    the snapshot does not change its complexity). The active slices are
    fresh derived arrays, so the donated table update of a later tick
    cannot invalidate them."""

    __slots__ = ("_X", "_labels", "_fa", "_ra", "_meta", "_predict",
                 "_params", "n_flows")

    def __init__(self, X, labels, fa, ra, meta, predict, params,
                 n_flows: int):
        self._X = X
        self._labels = labels
        self._fa = fa
        self._ra = ra
        self._meta = meta
        self._predict = predict
        self._params = params
        self.n_flows = n_flows

    def rows(self) -> list[tuple]:
        if self._labels is None:
            labels_out = self._predict(self._params, self._X)
        else:
            labels_out = self._labels
        # device_get passes host-native labels through untouched and
        # batches the device leaves into one blocking fetch
        labels, fa, ra = jax.device_get(
            (labels_out, self._fa, self._ra)
        )  # graftlint: disable=implicit-sync -- render-sync: the tick's one batched fetch
        labels = np.asarray(labels)
        return [
            (slot, src, dst, int(labels[slot]), bool(fa[slot]),
             bool(ra[slot]))
            for slot, (src, dst) in sorted(self._meta.items())
        ]


def dispatch_read(engine, predict, params, table_rows: int,
                  feature_stage: FeatureStage | None = None,
                  inc=None):
    """Dispatch one render tick's whole read side against the engine's
    CURRENT (tick-N) table and return the un-synced read object —
    the host-stage half of the pipeline's render path, shared by
    ``cli.py`` and ``tools/bench_serve.py``.

    Everything the device stage will touch is either a dispatched
    device computation (fixed at dispatch: later scatters update new
    buffers) or a host value captured here (``n_flows``); slot
    metadata for ranked rows is resolved by the device stage per slot
    — safe because ranked slots are in-use at tick N and the serve
    loop defers eviction while renders are in flight.

    ``inc`` (serving/incremental.IncrementalLabels) swaps the
    full-table predict for the dirty-set/label-cache path: the
    device-kernel ranked read still flows through ``RankedRead`` (the
    cache is a device label vector — ``top_active_render`` gathers it
    device-side), the host-native and full-table reads route through
    the incremental read objects so the (GIL-dropping) predict still
    lands on the device-stage worker."""
    host_native = getattr(predict, "host_native", False)
    floor = np.int32(engine.tick_floor)
    n_flows = engine.num_flows()
    if table_rows > 0:
        n = min(table_rows, engine.table.capacity)
        if inc is not None:
            if inc.host_native:
                from .incremental import IncRankedRead

                pending = inc.dispatch()
                flags = ft.top_active_flags(engine.table, n, floor)
                return IncRankedRead(inc, pending, flags, n_flows)
            labels = inc.labels()  # dispatched; cache stays on device
            outs = ft.top_active_render(engine.table, labels, n, floor)
            return RankedRead(outs, n_flows)
        if host_native:
            X = engine.features()
            flags = ft.top_active_flags(engine.table, n, floor)
            return NativeRankedRead(X, flags, predict, params, n_flows)
        X = (
            feature_stage.features(engine.table)
            if feature_stage is not None else engine.features()
        )
        labels = predict(params, X)
        outs = ft.top_active_render(engine.table, labels, n, floor)
        return RankedRead(outs, n_flows)
    # [:-1] slices are fresh derived arrays — donation-safe snapshots
    fa = engine.table.fwd.active[:-1]
    ra = engine.table.rev.active[:-1]
    meta = dict(engine.slot_metadata())
    if inc is not None:
        from .incremental import IncFullRead

        pending = inc.dispatch()
        return IncFullRead(inc, pending, fa, ra, meta, n_flows)
    X = engine.features()
    labels = None if host_native else predict(params, X)
    return FullRead(X, labels, fa, ra, meta, predict, params, n_flows)
