"""Serve through device failure: watchdog, degradation ladder, and
self-healing kernel promotion.

The failure this subsystem exists for is the r04 chip-day wedge
(docs/artifacts/tpu_outage_r04.log): a device dispatch hung mid-kernel
and the serve process sat dead for 11 hours, because the predict path
had exactly one rung — the device kernel — and a wedged XLA dispatch
blocks its calling thread forever. ``tools/tpu_day.sh`` mitigates that
only *outside* the process; this module is the in-process answer. A
tick is ALWAYS produced within budget, on the best rung currently
working:

    HEALTHY ──deadline/error──► DEGRADED ──fallback error──► BROKEN
       ▲                            │                           │
       │                            └────────── probe due ──────┘
       └──── N consecutive clean probes ──── PROBING ◄──────────┘
                                            (probe failed: back to the
                                             prior rung, backoff grows)

- **HEALTHY** — the device-kernel predict, dispatched through a
  ``DeviceWatchdog``: a guarded worker thread with a wall-clock
  deadline (CLI ``--device-deadline``, measured on the same
  ``time.perf_counter``-family monotonic clock the ``stage.device``
  span uses). A dispatch that exceeds the deadline is ABANDONED — the
  wedged thread keeps blocking, its eventual result is discarded, and
  the ladder demotes — so a wedged chip costs one deadline, not the
  process. The first device call gets a grace deadline
  (``first_deadline``, default max(60 s, 10×deadline)) because it
  legitimately carries jit compile time.
- **DEGRADED** — the per-family host fallback resolved by
  ``models.resolve_fallback``: the host-native C++ evaluators for
  forest/KNN (``native/forest_eval.cpp`` / ``native/knn_eval.cpp``,
  the same ``host_native`` contract the serving kernels use), an
  eager-CPU jax predict pinned to the CPU backend for everything else
  (GNB, logreg, SVC, k-means — and forest/KNN on hosts without g++).
- **BROKEN** — the fallback itself failed (or none resolves): the
  last-known-good label vector is served, and the rendered table
  carries an explicit ``Label State = STALE`` column so nobody
  mistakes a frozen classification for a live one. The fallback is
  re-tried every tick, so a transient fallback failure self-heals to
  DEGRADED.
- **PROBING** — recovery: once a probe is due, the device path is
  re-run on a shadow batch AFTER the tick's fallback labels are
  computed, and its labels are compared against the active fallback's
  for parity (from BROKEN there is no live reference, so a clean
  in-deadline probe counts on its own). The probe runs synchronously
  on the predict thread, so a probing tick against a still-wedged
  device costs at most fallback + one deadline — i.e. the tick stays
  within the documented 2×-deadline budget, and probes are
  backoff-gated so the cost cannot recur every tick. The shadow batch
  defaults to the FULL feature matrix (``probe_rows=0``): probing the
  exact serving shape reuses the already-compiled device program, so a
  recovered device can never trip its first probe on a fresh
  shadow-shape compile. Re-promotion to HEALTHY needs
  ``probe_successes`` CONSECUTIVE clean probes; any failed probe
  resets the chain and re-enters exponential backoff with full jitter
  (``uniform(0, min(cap, probe_every · 2^level))`` — the
  SupervisedCollector ladder's shape, with jitter because a fleet of
  serving processes must not re-probe a recovering chip in lockstep).

The ladder object IS the serving predict callable: it is marked
``host_native`` (a plain host call — callers must never jit or
shard_map it; see models.jit_serving_fn), so both the serial and the
pipelined serve loops route it through their existing host-call
branches and the watchdog/fallback work lands on the pipeline's
device-stage worker, overlapped with host ingest. On the no-fault path
it returns exactly ``np.asarray(device_predict(params, X))`` — the
same values the un-wrapped kernel produces, which is what keeps
``--degrade auto`` byte-identical to ``--degrade off``
(tests/test_degrade.py pins it).

Chaos: ``degrade.dispatch_stall`` (simulated wedge → deadline trip),
``degrade.dispatch_error`` (simulated XLA error → error trip) and
``degrade.probe`` (failed recovery probe) are registered fault sites —
unlike the durability sites, the first two are ABSORBED by the ladder
(that is the guarantee under test), never propagated. Every transition
is recorded in the flight recorder (``degrade.transition`` /
``degrade.probe`` events), gauged in ``/metrics`` (``degrade_state``,
``degrade_transitions``, ``probe_failures``) and reported by
``/healthz`` as 200-but-degraded with the current rung.
"""

from __future__ import annotations

import random
import sys
import threading
import time

import numpy as np

from ..utils import faults

HEALTHY = "HEALTHY"
DEGRADED = "DEGRADED"
BROKEN = "BROKEN"
PROBING = "PROBING"

# the degrade_state gauge encoding (docs/OBSERVABILITY.md)
STATE_GAUGE = {HEALTHY: 0, DEGRADED: 1, BROKEN: 2, PROBING: 3}


class DeadlineExceeded(RuntimeError):
    """A device-stage dispatch ran past its watchdog deadline."""


class DeviceWatchdog:
    """Deadline-guarded executor for device-stage dispatches.

    One worker thread runs submitted calls; ``call(fn, deadline)``
    waits at most ``deadline`` seconds for the result. On expiry the
    call — and the worker, which may be wedged inside an XLA dispatch
    that will never return — is ABANDONED: the next ``call`` spawns a
    fresh worker, and the abandoned thread discards its late result
    (if any ever comes) and exits. Abandoned threads are bounded by
    trip count, and trips are backoff-gated by the ladder, so a
    permanently wedged device leaks a handful of parked threads, not
    an unbounded pile.

    Single-consumer contract: ``call`` is invoked from one thread at a
    time (the serve loop's predict path — the pipeline's device-stage
    worker or the serial loop's main thread).
    """

    def __init__(self, name: str = "tcsdn-device-watchdog"):
        self._name = name
        self._lock = threading.Condition()
        self._worker: threading.Thread | None = None
        self._job: tuple[int, object] | None = None
        self._results: dict[int, tuple[str, object]] = {}
        self._seq = 0
        self._abandoned = 0
        self._closed = False

    def call(self, fn, deadline: float | None = None):
        """Run ``fn()`` on the worker; raise ``DeadlineExceeded`` if no
        result lands within ``deadline`` seconds (None = wait forever).
        ``fn``'s own exception re-raises here unchanged."""
        with self._lock:
            if self._closed:
                raise RuntimeError("watchdog is closed")
            if self._worker is None or not self._worker.is_alive():
                self._worker = threading.Thread(
                    target=self._run, name=self._name, daemon=True
                )
                self._worker.start()
            self._seq += 1
            seq = self._seq
            self._job = (seq, fn)
            self._lock.notify_all()
            t_end = (
                None if deadline is None
                else time.monotonic() + deadline
            )
            while seq not in self._results:
                left = (
                    None if t_end is None
                    else t_end - time.monotonic()
                )
                if left is not None and left <= 0:
                    break
                self._lock.wait(left)
            if seq not in self._results:
                # expired: abandon the (possibly wedged) worker — a new
                # one is spawned on the next call; if the job was never
                # even picked up, retract it
                self._abandoned += 1
                self._worker = None
                if self._job is not None and self._job[0] == seq:
                    self._job = None
                self._lock.notify_all()
                raise DeadlineExceeded(
                    f"device dispatch exceeded its {deadline:.3f}s "
                    f"watchdog deadline"
                )
            kind, value = self._results.pop(seq)
        if kind == "err":
            raise value  # type: ignore[misc]
        return value

    @property
    def abandoned(self) -> int:
        """Dispatches abandoned at their deadline (lifetime)."""
        with self._lock:
            return self._abandoned

    def close(self, timeout: float = 2.0) -> None:
        """Stop the current worker (abandoned ones die on their own)."""
        with self._lock:
            self._closed = True
            worker = self._worker
            self._worker = None
            self._lock.notify_all()
        if worker is not None and worker.is_alive():
            worker.join(timeout)

    def _run(self) -> None:
        me = threading.current_thread()
        while True:
            with self._lock:
                while (
                    self._worker is me and self._job is None
                    and not self._closed
                ):
                    self._lock.wait()
                if self._worker is not me or self._closed:
                    return
                seq, fn = self._job
                self._job = None
            try:
                result = ("ok", fn())  # type: ignore[operator]
            except BaseException as e:  # noqa: BLE001 — re-raised in call()
                result = ("err", e)
            with self._lock:
                if self._worker is not me:
                    return  # abandoned mid-call: discard the late result
                self._results[seq] = result
                self._lock.notify_all()


class DegradeLadder:
    """The health-state machine wrapped around the serving predict path.

    Callable with the serving ``(params, X) -> labels`` signature and
    marked ``host_native`` so the existing serve-loop branches route it
    as a plain host call (see module docstring for the ladder itself).

    ``clock`` (monotonic seconds) and ``rng`` (a ``random.Random``) are
    injectable so tests pin the exact jittered backoff schedule without
    sleeping; the watchdog deadline itself is real wall-clock (a wedge
    is a real-time phenomenon).
    """

    host_native = True  # contract: never jit/shard_map this callable

    def __init__(self, device_predict, fallback=None, *,
                 deadline: float = 2.0,
                 first_deadline: float | None = None,
                 probe_every: float = 5.0,
                 probe_successes: int = 3,
                 probe_rows: int = 0,
                 backoff_cap: float = 300.0,
                 metrics=None, recorder=None,
                 clock=time.monotonic,
                 rng: random.Random | None = None,
                 watchdog: DeviceWatchdog | None = None):
        self._device_predict = device_predict
        self._fallback = fallback
        self.deadline = float(deadline)
        if first_deadline is None:
            # the first device call legitimately carries jit compile
            # time (seconds at 2²⁰ rows) — tripping on it would demote
            # every cold start
            first_deadline = (
                max(60.0, 10.0 * self.deadline)
                if self.deadline > 0 else 0.0
            )
        self.first_deadline = float(first_deadline)
        self.probe_every = float(probe_every)
        self.probe_successes = int(probe_successes)
        self.probe_rows = int(probe_rows)
        self.backoff_cap = float(backoff_cap)
        self._metrics = metrics
        self._recorder = recorder
        self._clock = clock
        self._rng = rng if rng is not None else random.Random()
        self._watchdog = (
            watchdog if watchdog is not None else DeviceWatchdog()
        )
        self._lock = threading.Lock()
        self._rung = HEALTHY
        self._label_epoch = 0
        self._probing = False
        self._device_tried = False  # first-ATTEMPT grace consumed
        self._fetch_wedged = False  # last host feature fetch timed out
        self._probe_ok = 0  # consecutive clean probes
        self._backoff_level = 0
        self._next_probe_at = 0.0
        self._last_labels: np.ndarray | None = None
        self._last_stale = False
        if metrics is not None:
            metrics.set("degrade_state", STATE_GAUGE[HEALTHY])

    # -- public surface ----------------------------------------------------
    @property
    def state(self) -> str:
        """PROBING means a promotion CHAIN is in progress (first probe
        ran clean, more are scheduled) — not that a probe is executing
        this instant; between chain probes the serve runs on the prior
        rung. A failed probe drops back to that rung (recorded), so
        watch ``degrade.probe`` events — emitted per probe with
        ``ok``/``successes`` — for the fine-grained trajectory."""
        with self._lock:
            return PROBING if self._probing else self._rung

    @property
    def render_stale(self) -> bool:
        """True when the labels most recently served are last-known-good
        (the BROKEN rung) — the render adds the STALE column."""
        with self._lock:
            return self._last_stale

    @property
    def label_epoch(self) -> int:
        """Monotonic counter of RUNG changes — the label-source epoch
        the incremental predict path (serving/incremental.py) watches:
        a rung move means subsequently served labels come from a
        different evaluator, so every cached label is suspect and the
        whole label cache must be invalidated."""
        with self._lock:
            return self._label_epoch

    def status(self) -> dict:
        """The /healthz self-report (obs.HealthState.set_degrade)."""
        with self._lock:
            state = PROBING if self._probing else self._rung
            return {
                "state": state,
                "rung": self._rung,
                "gauge": STATE_GAUGE[state],
                "probe_successes": self._probe_ok,
                "backoff_level": self._backoff_level,
                "fallback": (
                    self._fallback.kind
                    if self._fallback is not None else None
                ),
                "watchdog_abandoned": self._watchdog.abandoned,
            }

    def warm_fallback(self, X) -> bool:
        """Prime the fallback rung's evaluator OFF the hot path
        (serving/warmup.py): the first DEMOTED tick must not pay the
        rung's lazy costs — eager-CPU jit compiles, native evaluator
        page faults, the pruned-KNN score surface — on top of whatever
        just broke the device. Returns True when a rung was primed."""
        fb = self._fallback
        if fb is None:
            return False
        fb.predict(X)
        if fb.scores is not None:
            fb.scores(X)
        return True

    def close(self) -> None:
        self._watchdog.close()

    def __call__(self, params, X):
        if self.state == HEALTHY:
            try:
                labels = self._device_call(params, X)
            except DeadlineExceeded:
                self._trip("deadline")
            except Exception as e:  # noqa: BLE001 — XLA runtime / injected
                self._trip(f"error:{type(e).__name__}")
            else:
                self._remember(labels, stale=False)
                return labels
        # Degraded rungs work on HOST features — but materializing X is
        # itself a device sync that can queue behind the wedged kernel,
        # so the fetch runs under the watchdog too. A wedged fetch goes
        # BROKEN (stale labels need no X) and is retried on the probe
        # schedule, not every tick, so a fully wedged device costs one
        # deadline per backoff window, not per tick.
        now = self._clock()
        with self._lock:
            skip_fetch = self._fetch_wedged and now < self._next_probe_at
        X_host = None if skip_fetch else self._fetch_host(X)
        if X_host is None:
            if not skip_fetch:
                with self._lock:
                    self._fetch_wedged = True
                    if self._rung != BROKEN:
                        self._set_locked(
                            rung=BROKEN, reason="feature-fetch-failed"
                        )
                    else:
                        self._probe_failed_locked("feature-fetch-failed")
            return self._stale_labels(int(X.shape[0]))
        with self._lock:
            self._fetch_wedged = False
        labels, stale = self._fallback_labels(X_host)
        self._maybe_probe(params, X_host, None if stale else labels)
        return labels

    def _fetch_host(self, X) -> np.ndarray | None:
        """X as a host array, deadline-guarded; None on wedge/error."""
        if isinstance(X, np.ndarray):
            return X
        if self.deadline > 0:
            try:
                return self._watchdog.call(
                    lambda: np.asarray(X), self.deadline
                )  # graftlint: disable=implicit-sync -- watchdog-guarded: deadline bounds the fetch
            except DeadlineExceeded:
                return None
            except Exception:  # noqa: BLE001 — a sick device throws wide
                return None
        try:
            # --degrade-deadline 0 is the operator's explicit opt-out
            # of the bound; the sync itself is the same ladder seam
            return np.asarray(
                X
            )  # graftlint: disable=implicit-sync -- watchdog-guarded: deadline-0 opt-out branch
        except Exception:  # noqa: BLE001
            return None

    # -- device path -------------------------------------------------------
    def _device_call(self, params, X) -> np.ndarray:
        try:
            faults.fault_point("degrade.dispatch_stall")
        except faults.FaultInjected as e:
            # chaos cannot deterministically wedge a thread, so the
            # site converts into exactly what the watchdog reports at
            # the deadline — the stall edge, minus the wall-clock wait
            raise DeadlineExceeded(
                "injected dispatch stall (degrade.dispatch_stall)"
            ) from e

        def run():
            faults.fault_point("degrade.dispatch_error")
            return np.asarray(
                self._device_predict(params, X)
            )  # graftlint: disable=implicit-sync -- watchdog-guarded: deadline bounds the fetch

        # the grace deadline covers the first ATTEMPT only (that is
        # where the jit compile lives); a device wedged from boot must
        # not re-pay 60 s on every probe — once any dispatch has been
        # tried, compile time is either paid or moot, and probes cost
        # one ordinary deadline
        with self._lock:
            first = not self._device_tried
            self._device_tried = True
        deadline = self.first_deadline if first else self.deadline
        if deadline > 0:
            out = self._watchdog.call(run, deadline)
        else:
            out = run()  # deadline 0: error-only detection, no watchdog
        return out

    def _trip(self, reason: str) -> None:
        with self._lock:
            self._set_locked(rung=DEGRADED, reason=reason)

    # -- fallback / stale rungs --------------------------------------------
    def _fallback_labels(self, X) -> tuple[np.ndarray, bool]:
        """(labels, stale): the fallback's labels, or last-known-good.
        The fallback is re-tried even from BROKEN, so a transient
        fallback failure self-heals back to DEGRADED."""
        fb = self._fallback
        if fb is not None:
            try:
                labels = np.asarray(
                    fb.predict(X)
                )  # graftlint: disable=implicit-sync -- watchdog-guarded: deadline-bounded fetch
            except Exception as e:  # noqa: BLE001 — any rung may break
                with self._lock:
                    if self._rung != BROKEN:
                        self._set_locked(
                            rung=BROKEN,
                            reason=f"fallback-error:{type(e).__name__}",
                        )
            else:
                with self._lock:
                    if self._rung == BROKEN:
                        self._set_locked(
                            rung=DEGRADED, reason="fallback-recovered"
                        )
                self._remember(labels, stale=False)
                return labels, False
        else:
            with self._lock:
                if self._rung != BROKEN:
                    self._set_locked(rung=BROKEN, reason="no-fallback")
        return self._stale_labels(int(X.shape[0])), True

    def _stale_labels(self, n: int) -> np.ndarray:
        """Last-known-good labels sized to ``n`` rows (zeros before the
        first good predict); marks the render STALE."""
        with self._lock:
            cached = self._last_labels
            self._last_stale = True
        if cached is None:
            return np.zeros(n, np.int32)
        if cached.shape[0] >= n:
            return cached[:n]
        out = np.zeros(n, np.int32)
        out[: cached.shape[0]] = cached
        return out

    def _remember(self, labels, stale: bool) -> None:
        arr = np.asarray(labels)
        with self._lock:
            self._last_labels = arr
            self._last_stale = stale

    # -- probing / promotion -----------------------------------------------
    def _maybe_probe(self, params, X, parity_labels) -> None:
        now = self._clock()
        with self._lock:
            if self._rung == HEALTHY:
                return
            if now < self._next_probe_at:
                return
            self._set_locked(probing=True, reason="probe-due")
        ok, detail = self._run_probe(params, X, parity_labels)
        with self._lock:
            now = self._clock()
            if ok:
                self._probe_ok += 1
                if self._probe_ok >= self.probe_successes:
                    self._backoff_level = 0
                    self._probe_ok = 0
                    self._set_locked(
                        rung=HEALTHY, probing=False,
                        reason="promoted",
                    )
                    delay = None
                else:
                    # clean but chain incomplete: keep probing at the
                    # base cadence (no jitter — nothing failed)
                    delay = self.probe_every
                    self._next_probe_at = now + delay
            else:
                delay = self._probe_failed_locked(detail)
                self._set_locked(
                    probing=False, reason=f"probe-failed:{detail}"
                )
            successes = self._probe_ok
        if self._recorder is not None:
            self._recorder.record(
                "degrade.probe", ok=ok, detail=detail,
                successes=successes, next_delay_s=delay,
            )

    def _probe_failed_locked(self, detail: str) -> float:
        """Failed-probe bookkeeping (callers hold ``self._lock``):
        reset the success chain, count the failure, and return the
        full-jitter exponential delay applied to ``_next_probe_at``."""
        if self._metrics is not None:
            self._metrics.inc("probe_failures")
        self._probe_ok = 0
        self._backoff_level += 1
        window = min(
            self.backoff_cap,
            self.probe_every * (2.0 ** self._backoff_level),
        )
        # full jitter: uniform over the whole window so a fleet of
        # recovering serves cannot re-probe in lockstep
        delay = self._rng.uniform(0.0, window)
        self._next_probe_at = self._clock() + delay
        return delay

    def _run_probe(self, params, X, parity_labels) -> tuple[bool, str]:
        """One shadow-batch device probe; (clean, detail).

        ``probe_rows <= 0`` (the default) probes the FULL feature
        matrix: the exact serving shape, so the probe reuses the
        already-compiled device program and a recovered device cannot
        trip its first probe on a fresh shadow-shape compile."""
        try:
            faults.fault_point("degrade.probe")
            if self.probe_rows > 0:
                n = min(self.probe_rows, int(X.shape[0]))
                got = self._device_call(params, X[:n])
            else:
                got = self._device_call(params, X)
        except faults.FaultInjected:
            return False, "injected"
        except DeadlineExceeded:
            return False, "deadline"
        except Exception as e:  # noqa: BLE001 — a sick device throws wide
            return False, f"error:{type(e).__name__}"
        if parity_labels is not None:
            want = np.asarray(parity_labels)[: got.shape[0]]
            if got.shape[0] != want.shape[0] or not np.array_equal(
                got, want
            ):
                # the device answers in time but DISAGREES with the
                # live fallback — promoting would swap correct labels
                # for wrong ones; count it as a failed probe
                return False, "parity-mismatch"
        return True, "clean"

    # -- bookkeeping (callers hold self._lock) ------------------------------
    def _set_locked(self, rung: str | None = None,
                    probing: bool | None = None,
                    reason: str = "") -> None:
        old = PROBING if self._probing else self._rung
        old_rung = self._rung
        if rung is not None:
            self._rung = rung
            if rung != old_rung:
                # the label SOURCE moved (device kernel ↔ fallback ↔
                # stale) — bump the epoch so incremental label caches
                # built on the old rung's output invalidate themselves
                self._label_epoch += 1
            if rung != HEALTHY and old == HEALTHY:
                # entering the ladder: first probe after one base
                # interval, fresh success chain
                self._probe_ok = 0
                self._backoff_level = 0
                self._next_probe_at = self._clock() + self.probe_every
        if probing is not None:
            self._probing = probing
        new = PROBING if self._probing else self._rung
        if new == old:
            # a RUNG change under an active promotion chain (public
            # state stays PROBING) must still be visible: a fallback
            # that breaks mid-chain flips the serve to STALE labels,
            # and swallowing that edge would hide exactly the
            # condition operators alert on
            if self._rung == old_rung:
                return
            old, new = old_rung, self._rung
        if self._metrics is not None:
            self._metrics.inc("degrade_transitions")
            self._metrics.set(
                "degrade_state",
                STATE_GAUGE[PROBING if self._probing else self._rung],
            )
        if self._recorder is not None:
            self._recorder.record(
                "degrade.transition", frm=old, to=new, reason=reason
            )
        print(
            f"DEGRADE: {old} -> {new} ({reason})", file=sys.stderr,
            flush=True,
        )
