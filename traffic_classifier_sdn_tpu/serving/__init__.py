"""Pipelined serving: the serve loop as a bounded two-stage software
pipeline.

The reference classifies flows strictly serially — one blocking
``model.predict`` per flow inside the poll loop
(traffic_classifier.py:103-106) — and the tick-granular serve loop
inherits that shape: poll → parse → scatter → predict → render as one
synchronous chain, device idle while the host waits on telemetry, host
idle while the device computes. This package breaks the chain:

- ``serving.pipeline`` — the bounded host/device stage handoff
  (depth 1–2, explicit backpressure: ticks coalesce instead of queueing
  unboundedly), the device-stage worker thread, donated double-buffers
  for the feature matrix, and the dispatched read-side objects shared
  by ``cli.py`` and ``tools/bench_serve.py``.
- ``serving.warmup`` — AOT lowering of the serving fns at startup
  (``jax.jit(...).lower(...).compile()`` against the batcher's
  power-of-two bucket shapes) wired to JAX's persistent compilation
  cache, so the multi-second first-tick compile stall disappears and
  restarts — including checkpoint-rollback restarts — start hot.

docs/ARCHITECTURE.md (serve-loop diagram) and docs/OBSERVABILITY.md
(``stage.host``/``stage.device`` spans, ``queue_depth``,
``ticks_coalesced``, ``stage_overlap_s``) are the operator-facing story.
"""

from .pipeline import (
    FeatureStage,
    Handoff,
    ServePipeline,
    dispatch_read,
)

__all__ = [
    "FeatureStage",
    "Handoff",
    "ServePipeline",
    "dispatch_read",
]
