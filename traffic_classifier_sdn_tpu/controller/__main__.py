from .switch import main

main()
