"""Per-class actuation policy: the declarative ``--policy`` spec and
its compilation into OpenFlow 1.3 flow-mods.

The classifier's labels become switch programs here — and nowhere
else: this module is pure (spec string in, wire bytes out, no sockets,
no state), so every encoding is golden-testable byte-for-byte through
``openflow.parse_flow_mod`` and the hysteresis tier (serving/
actuation.py) owns *when* a compiled mod may touch a switch.

Spec grammar (comma-separated, one clause per class)::

    CLASS=queue:N     route via QoS queue N (set_queue + output NORMAL)
    CLASS=meter:N     rate-limit via meter N (meter + output NORMAL)
    CLASS=drop        empty instruction set — OF1.3 drop
    CLASS=mirror:P    copy to port P and forward normally

Classes without a clause are observe-only (classified, never
actuated). The open-set ``unknown`` label can never carry a clause:
rejecting traffic we cannot name is the classifier's job, programming
the switch on a guess is nobody's.

Policy rules install at priority ``POLICY_PRIORITY`` (above the
learning switch's priority-1 flows, below nothing else we emit) with
the rule id in the cookie, which is what makes per-rule accounting and
cookie-masked retraction exact.
"""

from __future__ import annotations

from dataclasses import dataclass

from . import openflow as of

# above controller/switch.py's priority-1 learning flows: a policy
# verdict must shadow plain L2 forwarding for the matched pair
POLICY_PRIORITY = 10

_KINDS_WITH_ARG = {"queue", "meter", "mirror"}
_KINDS_BARE = {"drop"}


@dataclass(frozen=True)
class PolicyAction:
    """One compiled per-class action. ``arg`` is the queue id, meter id
    or mirror port; 0 (unused) for drop."""

    kind: str
    arg: int = 0

    def describe(self) -> str:
        if self.kind == "drop":
            return "drop"
        unit = {"queue": "queue", "meter": "meter", "mirror": "port"}
        return f"{self.kind} {unit[self.kind]}={self.arg}"


def parse_policy(spec: str, classes: tuple[str, ...]) -> dict[str, PolicyAction]:
    """``"video=queue:1,bulk=meter:2,attack=drop"`` → {class: action}.

    Raises ``ValueError`` on unknown classes, unknown kinds, missing or
    malformed arguments, duplicate clauses, and any attempt to actuate
    the open-set ``unknown`` label.
    """
    out: dict[str, PolicyAction] = {}
    for clause in spec.split(","):
        clause = clause.strip()
        if not clause:
            continue
        name, sep, action = clause.partition("=")
        name = name.strip()
        if not sep or not name:
            raise ValueError(f"policy clause {clause!r}: want CLASS=ACTION")
        if name == "unknown":
            raise ValueError(
                "policy may not actuate 'unknown' — open-set rejections "
                "never touch the switch"
            )
        if name not in classes:
            raise ValueError(
                f"policy class {name!r} not in model classes "
                f"{sorted(classes)}"
            )
        if name in out:
            raise ValueError(f"duplicate policy clause for class {name!r}")
        kind, ksep, arg = action.strip().partition(":")
        kind = kind.strip()
        if kind in _KINDS_BARE:
            if ksep:
                raise ValueError(f"policy action {kind!r} takes no argument")
            out[name] = PolicyAction(kind)
        elif kind in _KINDS_WITH_ARG:
            try:
                value = int(arg)
            except ValueError:
                raise ValueError(
                    f"policy action {kind!r} needs an integer argument "
                    f"({clause!r})"
                ) from None
            if value < 0:
                raise ValueError(f"policy action argument must be >= 0 "
                                 f"({clause!r})")
            out[name] = PolicyAction(kind, value)
        else:
            raise ValueError(
                f"unknown policy action {kind!r} (want "
                f"queue:N | meter:N | drop | mirror:P)"
            )
    if not out:
        raise ValueError("empty --policy spec")
    return out


def compile_instructions(action: PolicyAction) -> bytes:
    """Action → OF1.3 instruction list (the flow-mod payload)."""
    if action.kind == "drop":
        return b""  # no instructions == drop in OF1.3
    if action.kind == "queue":
        return of.instruction_apply_actions(
            of.action_set_queue(action.arg)
            + of.action_output(of.OFPP_NORMAL)
        )
    if action.kind == "meter":
        return of.instruction_meter(action.arg) + of.instruction_apply_actions(
            of.action_output(of.OFPP_NORMAL)
        )
    if action.kind == "mirror":
        return of.instruction_apply_actions(
            of.action_output(action.arg)
            + of.action_output(of.OFPP_NORMAL)
        )
    raise ValueError(f"unknown policy action kind {action.kind!r}")


def compile_install(xid: int, src: str, dst: str, action: PolicyAction,
                    cookie: int) -> bytes:
    """(flow pair, action) → the ADD flow-mod the hysteresis tier pushes
    once a label has earned installation. The cookie is the rule id —
    accounting and retraction key on it."""
    return of.flow_mod(
        xid, POLICY_PRIORITY,
        of.encode_match(eth_src=src, eth_dst=dst),
        compile_instructions(action),
        cookie=cookie,
    )


def compile_retract(xid: int, src: str, dst: str, cookie: int) -> bytes:
    """The DELETE undoing :func:`compile_install` — cookie-masked so it
    removes exactly the one rule it names, never a colliding match."""
    return of.flow_mod(
        xid, POLICY_PRIORITY,
        of.encode_match(eth_src=src, eth_dst=dst),
        b"",
        command=of.OFPFC_DELETE,
        cookie=cookie,
        cookie_mask=0xFFFFFFFFFFFFFFFF,
    )


def compile_wipe(xid: int, src: str, dst: str) -> bytes:
    """Unmasked DELETE for the pair: clears every policy rule matching
    it regardless of cookie. Reconciliation uses this — a mod that
    landed on the switch but was accounted refused (lost barrier) left
    an orphan under a cookie the FSM no longer knows."""
    return of.flow_mod(
        xid, POLICY_PRIORITY,
        of.encode_match(eth_src=src, eth_dst=dst),
        b"",
        command=of.OFPFC_DELETE,
    )
