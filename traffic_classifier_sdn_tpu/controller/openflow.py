"""Minimal OpenFlow 1.3 wire protocol: exactly the subset the telemetry
layer needs (hello/echo/features, flow-mod, packet-in/out, multipart flow
stats), encoded/decoded with ``struct``.

This replaces the reference's dependency on the Ryu framework: the
reference's controller is Ryu's stock learning switch plus a stats poller
(simple_monitor_13.py:3,10 inherits simple_switch_13.SimpleSwitch13); here
the same OpenFlow 1.3 conversation is spoken directly, so the framework
needs no external SDN stack. Switches (e.g. Open vSwitch) connect to us
over TCP and the controller app (controller/switch.py) drives this module.

Only OpenFlow 1.3 (wire version 0x04) is supported — the version the
reference pins via OFP_VERSIONS implicitly through simple_switch_13.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

OFP_VERSION = 0x04
OFP_HEADER = struct.Struct("!BBHI")  # version, type, length, xid

# message types
OFPT_HELLO = 0
OFPT_ERROR = 1
OFPT_ECHO_REQUEST = 2
OFPT_ECHO_REPLY = 3
OFPT_FEATURES_REQUEST = 5
OFPT_FEATURES_REPLY = 6
OFPT_PACKET_IN = 10
OFPT_PACKET_OUT = 13
OFPT_FLOW_MOD = 14
OFPT_MULTIPART_REQUEST = 18
OFPT_MULTIPART_REPLY = 19
OFPT_BARRIER_REQUEST = 20
OFPT_BARRIER_REPLY = 21

# ports / groups / buffers
OFPP_NORMAL = 0xFFFFFFFA
OFPP_CONTROLLER = 0xFFFFFFFD
OFPP_FLOOD = 0xFFFFFFFB
OFPP_ANY = 0xFFFFFFFF
OFPG_ANY = 0xFFFFFFFF
OFP_NO_BUFFER = 0xFFFFFFFF
OFPTT_ALL = 0xFF

# flow-mod commands
OFPFC_ADD = 0
OFPFC_DELETE = 3

# multipart types
OFPMP_FLOW = 1
OFPMP_PORT_STATS = 4

# instruction / action types
OFPIT_APPLY_ACTIONS = 4
OFPIT_METER = 6
OFPAT_OUTPUT = 0
OFPAT_SET_QUEUE = 21

# error types (the two the actuation plane distinguishes)
OFPET_FLOW_MOD_FAILED = 5

# OXM (match TLV) basic-class fields
OXM_CLASS_BASIC = 0x8000
OXM_IN_PORT = 0
OXM_ETH_DST = 3
OXM_ETH_SRC = 4

_PACKET_IN_HEAD = struct.Struct("!IHBBQ")
_FLOW_STATS_HEAD = struct.Struct("!HBxIIHHHH4xQQQ")
_FEATURES_BODY = struct.Struct("!QIBB2xII")


def header(msg_type: int, length: int, xid: int) -> bytes:
    return OFP_HEADER.pack(OFP_VERSION, msg_type, length, xid)


def message(msg_type: int, xid: int, body: bytes = b"") -> bytes:
    return header(msg_type, OFP_HEADER.size + len(body), xid) + body


def mac_str(raw: bytes) -> str:
    return ":".join(f"{b:02x}" for b in raw)


def mac_bytes(mac: str) -> bytes:
    return bytes(int(p, 16) for p in mac.split(":"))


# ---------------------------------------------------------------------------
# OXM match encode/decode


def _oxm_header(field_id: int, length: int) -> bytes:
    return struct.pack("!I", (OXM_CLASS_BASIC << 16) | (field_id << 9) | length)


def encode_match(in_port: int | None = None, eth_src: str | None = None,
                 eth_dst: str | None = None) -> bytes:
    """ofp_match with OXM TLVs, padded to an 8-byte boundary."""
    fields = b""
    if in_port is not None:
        fields += _oxm_header(OXM_IN_PORT, 4) + struct.pack("!I", in_port)
    if eth_dst is not None:
        fields += _oxm_header(OXM_ETH_DST, 6) + mac_bytes(eth_dst)
    if eth_src is not None:
        fields += _oxm_header(OXM_ETH_SRC, 6) + mac_bytes(eth_src)
    length = 4 + len(fields)  # type + length prefix included in length
    pad = (8 - length % 8) % 8
    return struct.pack("!HH", 1, length) + fields + b"\x00" * pad


def decode_match(buf: bytes, off: int) -> tuple[dict, int]:
    """Parse one ofp_match at ``off``; returns (fields, next_offset) where
    next_offset is past the match padding."""
    mtype, mlen = struct.unpack_from("!HH", buf, off)
    out: dict = {}
    if mtype == 1:  # OXM
        end = off + mlen
        p = off + 4
        while p + 4 <= end:
            oxm, = struct.unpack_from("!I", buf, p)
            oclass = oxm >> 16
            ofield = (oxm >> 9) & 0x7F
            olen = oxm & 0xFF
            val = buf[p + 4 : p + 4 + olen]
            if oclass == OXM_CLASS_BASIC:
                if ofield == OXM_IN_PORT and olen == 4:
                    out["in_port"] = struct.unpack("!I", val)[0]
                elif ofield == OXM_ETH_DST and olen == 6:
                    out["eth_dst"] = mac_str(val)
                elif ofield == OXM_ETH_SRC and olen == 6:
                    out["eth_src"] = mac_str(val)
            p += 4 + olen
    return out, off + mlen + (8 - mlen % 8) % 8


# ---------------------------------------------------------------------------
# actions / instructions


def action_output(port: int, max_len: int = 0xFFFF) -> bytes:
    return struct.pack("!HHIH6x", OFPAT_OUTPUT, 16, port, max_len)


def action_set_queue(queue_id: int) -> bytes:
    return struct.pack("!HHI", OFPAT_SET_QUEUE, 8, queue_id)


def instruction_apply_actions(actions: bytes) -> bytes:
    return struct.pack("!HH4x", OFPIT_APPLY_ACTIONS, 8 + len(actions)) + actions


def instruction_meter(meter_id: int) -> bytes:
    return struct.pack("!HHI", OFPIT_METER, 8, meter_id)


def decode_instructions(instructions: bytes) -> list[dict]:
    """Structured view of an instruction list: one dict per instruction.

    apply_actions carries its actions decoded in order (output ports,
    queue ids); meter carries its meter id. Unknown instruction or
    action types decode as ``{"type": <int>}`` — never dropped, so a
    golden round-trip sees everything the encoder emitted.
    """
    out: list[dict] = []
    off = 0
    n = len(instructions)
    while off + 8 <= n:
        itype, ilen = struct.unpack_from("!HH", instructions, off)
        if ilen < 8 or off + ilen > n:
            raise ValueError(f"bad instruction length {ilen}")
        if itype == OFPIT_APPLY_ACTIONS:
            actions: list[dict] = []
            a = off + 8
            end = off + ilen
            while a + 8 <= end:
                atype, alen = struct.unpack_from("!HH", instructions, a)
                if alen < 8 or a + alen > end:
                    raise ValueError(f"bad action length {alen}")
                if atype == OFPAT_OUTPUT:
                    actions.append({
                        "type": "output",
                        "port": struct.unpack_from("!I", instructions, a + 4)[0],
                    })
                elif atype == OFPAT_SET_QUEUE:
                    actions.append({
                        "type": "set_queue",
                        "queue_id": struct.unpack_from(
                            "!I", instructions, a + 4
                        )[0],
                    })
                else:
                    actions.append({"type": atype})
                a += alen
            out.append({"type": "apply_actions", "actions": actions})
        elif itype == OFPIT_METER:
            out.append({
                "type": "meter",
                "meter_id": struct.unpack_from("!I", instructions, off + 4)[0],
            })
        else:
            out.append({"type": itype})
        off += ilen
    return out


def decode_output_port(instructions: bytes) -> int | None:
    """First OUTPUT action port inside an instruction list, or None."""
    off = 0
    n = len(instructions)
    while off + 8 <= n:
        itype, ilen = struct.unpack_from("!HH", instructions, off)
        if ilen < 8:
            return None
        if itype == OFPIT_APPLY_ACTIONS:
            a = off + 8
            end = off + ilen
            while a + 8 <= end:
                atype, alen = struct.unpack_from("!HH", instructions, a)
                if alen < 8:
                    return None
                if atype == OFPAT_OUTPUT and a + 8 <= end:
                    return struct.unpack_from("!I", instructions, a + 4)[0]
                a += alen
        off += ilen
    return None


# ---------------------------------------------------------------------------
# whole messages


def hello(xid: int) -> bytes:
    return message(OFPT_HELLO, xid)


def echo_reply(xid: int, payload: bytes = b"") -> bytes:
    return message(OFPT_ECHO_REPLY, xid, payload)


def features_request(xid: int) -> bytes:
    return message(OFPT_FEATURES_REQUEST, xid)


def features_reply(xid: int, datapath_id: int, n_buffers: int = 256,
                   n_tables: int = 254) -> bytes:
    body = _FEATURES_BODY.pack(datapath_id, n_buffers, n_tables, 0, 0x4F, 0)
    return message(OFPT_FEATURES_REPLY, xid, body)


def parse_features_reply(body: bytes) -> int:
    """→ datapath_id."""
    return _FEATURES_BODY.unpack_from(body)[0]


def flow_mod(xid: int, priority: int, match: bytes, instructions: bytes,
             buffer_id: int = OFP_NO_BUFFER, table_id: int = 0,
             command: int = OFPFC_ADD, cookie: int = 0,
             cookie_mask: int = 0) -> bytes:
    body = struct.pack(
        "!QQBBHHHIIIH2x",
        cookie, cookie_mask,
        table_id, command,
        0, 0,  # idle, hard timeout
        priority, buffer_id, OFPP_ANY, OFPG_ANY, 0,
    ) + match + instructions
    return message(OFPT_FLOW_MOD, xid, body)


def parse_flow_mod(body: bytes) -> dict:
    (cookie, cookie_mask, table_id, command, idle, hard, priority,
     buffer_id, out_port, out_group, flags) = struct.unpack_from(
        "!QQBBHHHIIIH2x", body
    )
    off = struct.calcsize("!QQBBHHHIIIH2x")
    match, off = decode_match(body, off)
    return {
        "priority": priority, "command": command, "buffer_id": buffer_id,
        "match": match, "instructions": body[off:],
        "cookie": cookie, "cookie_mask": cookie_mask, "table_id": table_id,
    }


def barrier_request(xid: int) -> bytes:
    return message(OFPT_BARRIER_REQUEST, xid)


def barrier_reply(xid: int) -> bytes:
    return message(OFPT_BARRIER_REPLY, xid)


def error_msg(xid: int, err_type: int, code: int,
              offending: bytes = b"") -> bytes:
    """OFPT_ERROR carrying (a prefix of) the offending message — the
    spec mandates at least its header, which is how the sender maps a
    refusal back to the flow-mod it issued."""
    return message(
        OFPT_ERROR, xid,
        struct.pack("!HH", err_type, code) + offending[:64],
    )


def parse_error(body: bytes) -> dict:
    """→ {type, code, offending_xid} — offending_xid recovered from the
    embedded original header when present (None otherwise)."""
    err_type, code = struct.unpack_from("!HH", body)
    offending_xid = None
    if len(body) >= 4 + OFP_HEADER.size:
        _, _, _, offending_xid = OFP_HEADER.unpack_from(body, 4)
    return {"type": err_type, "code": code, "offending_xid": offending_xid}


def packet_out(xid: int, buffer_id: int, in_port: int, actions: bytes,
               data: bytes = b"") -> bytes:
    body = struct.pack("!IIH6x", buffer_id, in_port, len(actions)) + actions
    if buffer_id == OFP_NO_BUFFER:
        body += data
    return message(OFPT_PACKET_OUT, xid, body)


def packet_in(xid: int, buffer_id: int, reason: int, match: bytes,
              frame: bytes, table_id: int = 0) -> bytes:
    body = (
        _PACKET_IN_HEAD.pack(buffer_id, len(frame), reason, table_id, 0)
        + match + b"\x00\x00" + frame
    )
    return message(OFPT_PACKET_IN, xid, body)


def parse_packet_in(body: bytes) -> dict:
    buffer_id, total_len, reason, table_id, cookie = _PACKET_IN_HEAD.unpack_from(
        body
    )
    off = _PACKET_IN_HEAD.size
    match, off = decode_match(body, off)
    frame = body[off + 2 :]  # 2 pad bytes before the ethernet frame
    out = {"buffer_id": buffer_id, "match": match, "frame": frame}
    if len(frame) >= 12:
        out["eth_dst"] = mac_str(frame[0:6])
        out["eth_src"] = mac_str(frame[6:12])
        out["eth_type"] = struct.unpack_from("!H", frame, 12)[0] if len(
            frame
        ) >= 14 else 0
    return out


def flow_stats_request(xid: int) -> bytes:
    body = struct.pack(
        "!HH4xB3xII4xQQ", OFPMP_FLOW, 0, OFPTT_ALL, OFPP_ANY, OFPG_ANY, 0, 0
    ) + encode_match()
    return message(OFPT_MULTIPART_REQUEST, xid, body)


def port_stats_request(xid: int) -> bytes:
    body = struct.pack("!HH4xI4x", OFPMP_PORT_STATS, 0, OFPP_ANY)
    return message(OFPT_MULTIPART_REQUEST, xid, body)


@dataclass
class FlowStat:
    priority: int
    packet_count: int
    byte_count: int
    match: dict = field(default_factory=dict)
    out_port: int | None = None


def flow_stats_reply(xid: int, stats: list[FlowStat]) -> bytes:
    entries = b""
    for s in stats:
        match = encode_match(
            in_port=s.match.get("in_port"),
            eth_src=s.match.get("eth_src"),
            eth_dst=s.match.get("eth_dst"),
        )
        instr = (
            instruction_apply_actions(action_output(s.out_port))
            if s.out_port is not None
            else b""
        )
        length = _FLOW_STATS_HEAD.size + len(match) + len(instr)
        entries += _FLOW_STATS_HEAD.pack(
            length, 0, 0, 0, s.priority, 0, 0, 0, 0,
            s.packet_count, s.byte_count,
        ) + match + instr
    body = struct.pack("!HH4x", OFPMP_FLOW, 0) + entries
    return message(OFPT_MULTIPART_REPLY, xid, body)


def parse_multipart_reply(body: bytes) -> tuple[int, list[FlowStat]]:
    """→ (multipart type, flow stats list; empty for non-flow types)."""
    mtype, flags = struct.unpack_from("!HH", body)
    stats: list[FlowStat] = []
    if mtype != OFPMP_FLOW:
        return mtype, stats
    off = 8
    n = len(body)
    while off + _FLOW_STATS_HEAD.size <= n:
        (length, table_id, dsec, dnsec, priority, idle, hard, flags_,
         cookie, pkts, byts) = _FLOW_STATS_HEAD.unpack_from(body, off)
        if length < _FLOW_STATS_HEAD.size:
            break
        match, moff = decode_match(body, off + _FLOW_STATS_HEAD.size)
        out_port = decode_output_port(body[moff : off + length])
        stats.append(FlowStat(priority, pkts, byts, match, out_port))
        off += length
    return mtype, stats


# ---------------------------------------------------------------------------
# stream framing


# Exception types a parser may raise on a malformed (but well-framed)
# message body — the connection loop drops such frames; anything else is
# a real bug and propagates. Single-sourced so the controller guard and
# the codec fuzz test cannot drift apart.
PARSE_ERRORS = (ValueError, struct.error, IndexError, KeyError)


class MessageReader:
    """Accumulates raw TCP bytes and yields complete OpenFlow messages as
    (type, xid, body) tuples."""

    def __init__(self):
        self._buf = b""

    def feed(self, data: bytes):
        self._buf += data
        out = []
        while len(self._buf) >= OFP_HEADER.size:
            version, mtype, length, xid = OFP_HEADER.unpack_from(self._buf)
            if length < OFP_HEADER.size:
                raise ValueError(f"bad OpenFlow length {length}")
            if len(self._buf) < length:
                break
            body = self._buf[OFP_HEADER.size : length]
            self._buf = self._buf[length:]
            out.append((mtype, xid, body))
        return out
