"""Standalone OpenFlow 1.3 controller (learning switch + telemetry
monitor) — the framework's replacement for the reference's Ryu layer."""

from .switch import Controller  # noqa: F401
