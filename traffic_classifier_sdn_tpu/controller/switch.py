"""Standalone OpenFlow 1.3 controller: learning switch + 1 Hz flow-stats
monitor emitting the telemetry line protocol.

This is the framework's own replacement for the reference's entire L2
layer — Ryu's stock ``SimpleSwitch13`` (MAC learning, priority-1 flow
installation; inherited at simple_monitor_13.py:3,10) plus the
``SimpleMonitor13`` poller (datapath registration :18-29, the 1 Hz stats
requester :31-47, and the ``data\\t…`` TSV logger :49-66) — implemented
directly over asyncio TCP with controller/openflow.py, so no external SDN
framework is needed. Open vSwitch (or the in-repo fake switch,
tools/fake_switch.py) connects to us; stdout speaks exactly the protocol
ingest/protocol.py parses.

Behavioral parity notes:
- flows are installed at priority 1 matching (in_port, eth_src, eth_dst),
  and the stats logger filters priority == 1 and sorts by
  (in_port, eth_dst) — same as simple_monitor_13.py:53-56
- port stats are requested but their replies are discarded — the
  reference does the same (requested at simple_monitor_13.py:46-47; no
  reply handler), and we keep the request for switch-side parity
- unlike the reference (green threads), this is a single asyncio loop:
  no shared-state races by construction
"""

from __future__ import annotations

import asyncio
import sys
import time
from dataclasses import dataclass, field

from . import openflow as of

ETH_TYPE_LLDP = 0x88CC


@dataclass
class Datapath:
    """One connected switch."""

    reader: asyncio.StreamReader
    writer: asyncio.StreamWriter
    dpid: int | None = None
    mac_to_port: dict = field(default_factory=dict)
    malformed: int = 0  # dropped-frame count (warnings rate-limited)
    _xid: int = 0

    def next_xid(self) -> int:
        self._xid = (self._xid + 1) & 0xFFFFFFFF
        return self._xid

    def send(self, msg: bytes) -> None:
        self.writer.write(msg)


class Controller:
    """Accepts switch connections and runs the learning-switch + monitor
    apps over them."""

    def __init__(self, host: str = "0.0.0.0", port: int = 6653,
                 poll_interval: float = 1.0, out=None):
        self.host = host
        self.port = port
        self.poll_interval = poll_interval
        self.out = out if out is not None else sys.stdout
        self.datapaths: dict[int, Datapath] = {}
        self._server: asyncio.AbstractServer | None = None
        self._monitor_task: asyncio.Task | None = None
        self._writers: set[asyncio.StreamWriter] = set()

    # -- lifecycle ---------------------------------------------------------
    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle_connection, self.host, self.port
        )
        self._monitor_task = asyncio.create_task(self._monitor())

    @property
    def bound_port(self) -> int:
        assert self._server is not None
        return self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._monitor_task is not None:
            self._monitor_task.cancel()
        # close live connections first: Python 3.12's wait_closed() blocks
        # until every connection handler has finished
        for w in list(self._writers):
            w.close()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    async def serve_forever(self) -> None:
        assert self._server is not None
        async with self._server:
            await self._server.serve_forever()

    # -- connection handling ----------------------------------------------
    async def _handle_connection(self, reader, writer) -> None:
        self._writers.add(writer)
        dp = Datapath(reader, writer)
        dp.send(of.hello(dp.next_xid()))
        dp.send(of.features_request(dp.next_xid()))
        await writer.drain()
        mr = of.MessageReader()
        try:
            while True:
                data = await reader.read(1 << 16)
                if not data:
                    break
                for mtype, xid, body in mr.feed(data):
                    try:
                        self._dispatch(dp, mtype, xid, body)
                    except of.PARSE_ERRORS as e:
                        # one malformed message from a buggy/hostile
                        # switch must not take the connection (or leak a
                        # traceback into the telemetry stream): drop the
                        # frame, keep serving — framing stays intact
                        # because MessageReader already consumed it.
                        # Rate-limited: a switch streaming garbage at
                        # line rate must not stall the event loop on
                        # synchronous stderr writes.
                        dp.malformed += 1
                        if dp.malformed <= 5:
                            print(
                                f"WARNING: dropped malformed OF message "
                                f"type={mtype} "
                                f"({type(e).__name__}: {e})"
                                + (" — further drops counted silently"
                                   if dp.malformed == 5 else ""),
                                file=sys.stderr,
                            )
                await writer.drain()
        except (ConnectionResetError, asyncio.CancelledError):
            pass
        except ValueError as e:
            # unrecoverable FRAMING error (bad header length): the byte
            # stream cannot be resynced — close this connection cleanly
            print(
                f"WARNING: closing datapath connection on framing error: "
                f"{e}",
                file=sys.stderr,
            )
        finally:
            # DEAD_DISPATCHER unregistration (simple_monitor_13.py:26-29)
            if dp.dpid is not None:
                self.datapaths.pop(dp.dpid, None)
            self._writers.discard(writer)
            writer.close()

    def _dispatch(self, dp: Datapath, mtype: int, xid: int, body: bytes):
        if mtype == of.OFPT_ECHO_REQUEST:
            dp.send(of.echo_reply(xid, body))
        elif mtype == of.OFPT_FEATURES_REPLY:
            dp.dpid = of.parse_features_reply(body)
            # MAIN_DISPATCHER registration (simple_monitor_13.py:20-25)
            self.datapaths[dp.dpid] = dp
            # table-miss: everything unmatched goes to the controller
            dp.send(
                of.flow_mod(
                    dp.next_xid(), priority=0, match=of.encode_match(),
                    instructions=of.instruction_apply_actions(
                        of.action_output(of.OFPP_CONTROLLER)
                    ),
                )
            )
        elif mtype == of.OFPT_PACKET_IN:
            self._packet_in(dp, body)
        elif mtype == of.OFPT_MULTIPART_REPLY:
            self._stats_reply(dp, body)
        # ERROR / port-stats replies / everything else: ignored, like the
        # reference's unhandled events

    # -- learning switch (SimpleSwitch13 semantics) ------------------------
    def _packet_in(self, dp: Datapath, body: bytes) -> None:
        pkt = of.parse_packet_in(body)
        frame = pkt["frame"]
        if len(frame) < 14 or pkt.get("eth_type") == ETH_TYPE_LLDP:
            return
        in_port = pkt["match"].get("in_port")
        if in_port is None:
            return
        src, dst = pkt["eth_src"], pkt["eth_dst"]
        dp.mac_to_port[src] = in_port
        out_port = dp.mac_to_port.get(dst, of.OFPP_FLOOD)
        actions = of.action_output(out_port)
        if out_port != of.OFPP_FLOOD:
            # install the forwarding flow so future packets skip the
            # controller; priority 1 = what the monitor reports on
            match = of.encode_match(in_port=in_port, eth_src=src, eth_dst=dst)
            if pkt["buffer_id"] != of.OFP_NO_BUFFER:
                dp.send(
                    of.flow_mod(
                        dp.next_xid(), priority=1, match=match,
                        instructions=of.instruction_apply_actions(actions),
                        buffer_id=pkt["buffer_id"],
                    )
                )
                return  # buffered packet is released by the flow-mod
            dp.send(
                of.flow_mod(
                    dp.next_xid(), priority=1, match=match,
                    instructions=of.instruction_apply_actions(actions),
                )
            )
        dp.send(
            of.packet_out(
                dp.next_xid(), pkt["buffer_id"], in_port, actions, frame
            )
        )

    # -- monitor (SimpleMonitor13 semantics) -------------------------------
    async def _monitor(self) -> None:
        while True:
            for dp in list(self.datapaths.values()):
                # per-dp guard, and OSError not just ConnectionReset: a
                # dead switch (EPIPE/ETIMEDOUT) must never kill the poll
                # loop for the others
                try:
                    dp.send(of.flow_stats_request(dp.next_xid()))
                    dp.send(of.port_stats_request(dp.next_xid()))
                    await dp.writer.drain()
                except (ConnectionError, OSError):
                    pass
            await asyncio.sleep(self.poll_interval)

    def _stats_reply(self, dp: Datapath, body: bytes) -> None:
        mtype, stats = of.parse_multipart_reply(body)
        if mtype != of.OFPMP_FLOW:
            return  # port stats: requested but unconsumed, like the ref
        now = int(time.time())
        lines = [
            "datapath         in-port  eth-dst           out-port packets  bytes",
            "---------------- -------- ----------------- -------- -------- --------",
        ]
        flows = [s for s in stats if s.priority == 1]
        flows.sort(
            key=lambda s: (s.match.get("in_port", 0), s.match.get("eth_dst", ""))
        )
        for s in flows:
            lines.append(
                "data\t{}\t{}\t{}\t{}\t{}\t{}\t{}\t{}".format(
                    now, dp.dpid, s.match.get("in_port", 0),
                    s.match.get("eth_src", "?"), s.match.get("eth_dst", "?"),
                    s.out_port if s.out_port is not None else 0,
                    s.packet_count, s.byte_count,
                )
            )
        print("\n".join(lines), file=self.out, flush=True)


def main(argv=None) -> None:
    import argparse

    p = argparse.ArgumentParser(
        description="OpenFlow 1.3 learning switch + flow-stats monitor "
        "(drop-in for `ryu run simple_monitor_13.py`)"
    )
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=6653)
    p.add_argument(
        "--poll", type=float, default=1.0,
        help="flow-stats poll interval seconds (reference: 1 Hz)",
    )
    args = p.parse_args(argv)

    async def run():
        c = Controller(args.host, args.port, args.poll)
        await c.start()
        await c.serve_forever()

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
