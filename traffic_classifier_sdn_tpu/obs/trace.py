"""Span tracer: where did that tick's 20 ms go?

A deliberately small subset of the Dapper model sized for a single
serving process: named spans with wall-clock start/duration, explicit
nesting (a thread-local stack — no context propagation machinery), and
two sinks wired at construction:

- a ``utils.metrics.Metrics`` registry — every completion is observed
  into the ``stage_<name>_s`` histogram, so the existing
  ``--metrics-every`` stderr line and ``snapshot()`` surface
  ``stage_*_p50/p99`` per-stage latency attribution for free;
- an ``obs.flight_recorder.FlightRecorder`` — every completion appends
  a structured ``span`` event (name, depth, parent, duration, error),
  which is what lets a post-mortem dump name the failing span.

Clock injection (``clock=time.perf_counter``) keeps timing logic
testable without sleeps: tests drive a fake monotonic counter and
assert exact durations. Spans are cheap — two clock reads, one list
push/pop, one histogram observe — so per-tick instrumentation (seven
spans) costs microseconds against a multi-ms tick.

Exception transparency: ``span()`` never swallows; an exception inside
a span propagates unchanged, with the span completed first and its
event marked ``error=<type name>`` so the recorder's last events show
exactly which stage died.
"""

from __future__ import annotations

import threading
import time
from collections.abc import Callable
from dataclasses import dataclass, field


@dataclass
class Span:
    """One completed (or in-flight) named timing region."""

    name: str
    start: float
    depth: int = 0
    parent: str | None = None
    end: float | None = None
    error: str | None = None
    attrs: dict = field(default_factory=dict)

    @property
    def duration(self) -> float:
        return (self.end - self.start) if self.end is not None else 0.0


class _SpanCtx:
    """Context manager returned by ``Tracer.span`` — a tiny hand-rolled
    class (not ``contextlib.contextmanager``) so entering a span does
    not allocate a generator per tick stage."""

    __slots__ = ("_tracer", "span")

    def __init__(self, tracer: Tracer, span: Span):
        self._tracer = tracer
        self.span = span

    def __enter__(self) -> Span:
        return self.span

    def __exit__(self, exc_type, exc, tb) -> bool:
        self._tracer._finish(self.span, exc_type)
        return False  # never swallow — the serve loop's policy decides


class Tracer:
    """Factory + sink wiring for spans.

    ``metrics`` and ``recorder`` are both optional: a Tracer with
    neither still tracks nesting (useful in tests), one with only
    ``metrics`` is the always-on serving default, and ``recorder``
    joins when the flight recorder is enabled. The span stack is
    thread-local, so concurrent threads (collector reader vs serve
    loop) each get their own nesting without locking the hot path —
    the recorder's ring does its own locking at the append.
    """

    METRIC_PREFIX = "stage_"

    def __init__(self, metrics=None, recorder=None,
                 clock: Callable[[], float] = time.perf_counter):
        self.metrics = metrics
        self.recorder = recorder
        self.clock = clock
        self._local = threading.local()

    def _stack(self) -> list[Span]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def span(self, name: str, **attrs) -> _SpanCtx:
        """Open a nested span; use as ``with tracer.span("predict"):``."""
        stack = self._stack()
        parent = stack[-1].name if stack else None
        s = Span(
            name=name, start=self.clock(), depth=len(stack),
            parent=parent, attrs=attrs,
        )
        stack.append(s)
        return _SpanCtx(self, s)

    def current(self) -> Span | None:
        """The innermost open span on this thread (None outside spans)."""
        stack = self._stack()
        return stack[-1] if stack else None

    def _finish(self, span: Span, exc_type) -> None:
        span.end = self.clock()
        if exc_type is not None:
            span.error = exc_type.__name__
        stack = self._stack()
        # the common case is a perfectly nested pop; tolerate a caller
        # finishing out of order rather than corrupting the stack
        if stack and stack[-1] is span:
            stack.pop()
        elif span in stack:
            stack.remove(span)
        if self.metrics is not None:
            # the pipeline's stage-boundary spans are named "stage.host"/
            # "stage.device" (docs/OBSERVABILITY.md); strip the family
            # prefix so their histograms land as stage_host_s rather
            # than the double-prefixed stage_stage.host_s
            base = span.name
            if base.startswith("stage."):
                base = base[len("stage."):]
            self.metrics.observe(
                f"{self.METRIC_PREFIX}{base}_s", span.duration
            )
        if self.recorder is not None:
            self.recorder.record(
                "span",
                name=span.name,
                parent=span.parent,
                depth=span.depth,
                duration_s=span.duration,
                error=span.error,
                **span.attrs,
            )
