"""Observability plane: structured tracing, crash forensics, exposition.

Three cooperating layers, each dependency-free (stdlib + the existing
``utils.metrics`` registry) and individually testable:

- ``obs.trace`` — a Dapper-style span tracer with explicit clock
  injection. The serve loop opens one ``tick`` span per poll tick with
  child spans for each pipeline stage (poll → parse → scatter → feature
  → predict → render → snapshot); completions land in per-stage
  ``Metrics`` histograms (``stage_<name>_s``), so ``--metrics-every``
  and ``Metrics.snapshot()`` gain ``stage_*_p50/p99`` latency
  attribution with no extra plumbing.
- ``obs.flight_recorder`` — a bounded, lock-guarded ring of recent
  structured events (span completions, monitor deaths/restarts,
  checkpoint saves/rollbacks, fault-site firings). On an unhandled
  serve-loop exception, supervisor terminal failure, or SIGTERM the CLI
  dumps the ring as a JSONL post-mortem: "what happened in the 2 s
  before it died", answerable after the fact.
- ``obs.exposition`` — a stdlib ``http.server`` thread serving
  ``/metrics`` (Prometheus text format), ``/healthz`` (liveness +
  staleness), and ``/events`` (flight-recorder tail as JSON), wired
  into ``cli.py`` behind ``--obs-port``.
- ``obs.device`` — the device-runtime half: compile/retrace telemetry
  off the ``jax.monitoring`` listener bus (``device.compile`` /
  ``device.retrace`` events, ``jit_compiles`` / ``jit_compile_s`` /
  ``retraces_after_warmup`` metrics), per-tick HBM gauges with a
  watermark, donation-effectiveness reconciliation on the
  double-buffered stages, the /healthz ``device`` block, and the
  on-demand ``/profile`` capture (``ProfilerCapture``).
- ``obs.perf_recorder`` — the black-box flight data recorder: per-tick
  samples committed to disk as atomic bounded segments
  (``perf-<seq>.jsonl``), jax-free on the write path, so a kill -9 or
  an 11-hour device wedge leaves hours of per-tick evidence readable
  via ``perf_recorder.replay``.
- ``obs.latency`` — record-level latency provenance: host-side emit
  stamps on every telemetry batch, per-hop boundary marks (fan-in
  queue enter/exit, batcher parse, scatter dispatch, device
  completion, render visibility), folded per render tick into the
  ``e2e_emit_to_render_s`` / ``queue_wait_s`` / ``batch_wait_s`` /
  ``wf_*`` waterfall histograms and the /healthz ``latency`` block —
  the live end-to-end budget the device-boundary "<1 ms" claim needs
  as context.

docs/OBSERVABILITY.md is the operator-facing catalog (metric names,
span taxonomy, scrape and post-mortem workflow).
"""

from .device import DeviceTelemetry, ProfilerBusy, ProfilerCapture
from .exposition import ExpositionServer, HealthState, prometheus_text
from .flight_recorder import FlightRecorder, dump_metrics_snapshot
from .latency import LatencyProvenance
from .perf_recorder import PerfRecorder
from .trace import Span, Tracer

__all__ = [
    "DeviceTelemetry",
    "ExpositionServer",
    "FlightRecorder",
    "HealthState",
    "LatencyProvenance",
    "PerfRecorder",
    "ProfilerBusy",
    "ProfilerCapture",
    "Span",
    "Tracer",
    "dump_metrics_snapshot",
    "prometheus_text",
]
