"""Black-box perf recorder: a bounded ON-DISK ring of per-tick samples.

The flight recorder (obs/flight_recorder.py) answers "what happened in
the 2 s before it died" — but only if something dumps the ring, and a
wedged process (ROADMAP item 4: three consecutive TPU windows dead
undiagnosed, one an 11-hour wedge) never reaches its own dump path.
This module is the crash-proof complement: per-tick samples (stage
timings, dirty-row counts, queue depths, degrade/drift states,
compile/HBM readings) accumulate in memory and commit to disk as whole
segments — ``perf-<seq:08d>.jsonl`` — via the same atomic temp+fsync+
rename discipline as the serving-checkpoint rotation. kill -9 at ANY
instant loses at most the in-memory partial segment; every committed
segment on disk is complete and parseable, so an 11-hour wedge leaves
hours of per-tick evidence with no cooperation from the dying process.

Design constraints:

- **jax-free.** The write path is pure stdlib — a wedged device runtime
  (the exact failure this records) can never wedge the recorder too.
- **Bounded.** At most ``keep_segments`` committed segments; older ones
  are pruned after each commit, so a week-long serve holds
  ``keep_segments × ticks_per_segment`` ticks of evidence and no more.
- **Absorbing.** A failed segment commit (fault site ``obs.perf_ring``,
  or a real ENOSPC) drops that segment with a counter
  (``perf_ring_dropped_segments``) and never surfaces to the serve
  tick — the black box must not stall the plane it records.
- **Leaf lock.** ``_lock`` guards only the in-memory buffer and
  counters; all file I/O happens strictly after release (single
  committer: the serve loop). Restarts resume numbering above the
  surviving segments so oldest-first order spans incarnations.

``replay(directory)`` is the forensic reader: every committed segment,
oldest first, as one sample list — it raises on a torn line, because
the atomic commit makes torn committed bytes a real bug, not weather.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time

from ..utils import faults
from ..utils.atomicio import atomic_write_bytes, sweep_stale_tmp
from .flight_recorder import _jsonable

_SEGMENT_RE = re.compile(r"^perf-(\d{8})\.jsonl$")


def segment_files(directory: str) -> list[tuple[int, str]]:
    """Committed ``(seq, path)`` pairs in ``directory``, oldest first."""
    try:
        names = os.listdir(directory)
    except FileNotFoundError:
        return []
    out = []
    for name in names:
        m = _SEGMENT_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(directory, name)))
    out.sort()
    return out


def replay(directory: str) -> list[dict]:
    """Parse every committed segment, oldest first, into one flat list
    of samples (``meta`` lines skipped). Strict: a line that fails to
    parse raises — committed segments are published atomically, so torn
    committed bytes mean a durability bug, and the forensic reader must
    say so rather than silently shorten the evidence."""
    samples = []
    for _, path in segment_files(directory):
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                event = json.loads(line)
                if event.get("kind") != "meta":
                    samples.append(event)
    return samples


class PerfRecorder:
    """Per-tick sample sink with atomic whole-segment rotation.

    ``record`` buffers one sample; every ``ticks_per_segment`` samples
    the buffer commits as the next segment file. ``flush`` commits a
    partial buffer (shutdown / dump paths). Single committer assumed
    (the serve loop); ``tail`` may be called from the exposition thread.
    """

    def __init__(self, directory: str, *, ticks_per_segment: int = 64,
                 keep_segments: int = 16, metrics=None, clock=time.time):
        if ticks_per_segment <= 0:
            raise ValueError(
                f"ticks_per_segment must be positive, got {ticks_per_segment}"
            )
        if keep_segments <= 0:
            raise ValueError(
                f"keep_segments must be positive, got {keep_segments}"
            )
        self.directory = os.path.abspath(directory)
        self.ticks_per_segment = int(ticks_per_segment)
        self.keep_segments = int(keep_segments)
        self._metrics = metrics
        self._clock = clock
        self._lock = threading.Lock()
        self._buf: list[dict] = []
        self._committed = 0
        self._dropped = 0
        self._last_segment: int | None = None
        os.makedirs(self.directory, exist_ok=True)
        # a kill -9 mid-commit cannot run atomicio's finally — collect
        # the orphaned temp the previous incarnation left behind
        sweep_stale_tmp(self.directory)
        existing = segment_files(self.directory)
        self._seq = existing[-1][0] + 1 if existing else 0

    # -- write --------------------------------------------------------------
    def record(self, sample: dict) -> None:
        """Buffer one per-tick sample; commits a full segment in-line
        (outside the lock) when the buffer reaches the segment size."""
        event = {"ts": self._clock()}
        for k, v in sample.items():
            event[k] = _jsonable(v)
        with self._lock:
            self._buf.append(event)
            if len(self._buf) < self.ticks_per_segment:
                return
            batch, self._buf = self._buf, []
            seq = self._seq
            self._seq += 1
        self._commit(seq, batch)

    def flush(self) -> str | None:
        """Commit the partial buffer as its own segment (None if empty).
        The shutdown/dump-path call — after it, every recorded sample
        is on disk."""
        with self._lock:
            if not self._buf:
                return None
            batch, self._buf = self._buf, []
            seq = self._seq
            self._seq += 1
        return self._commit(seq, batch)

    def _commit(self, seq: int, batch: list[dict]) -> str | None:
        meta = {
            "kind": "meta",
            "segment": seq,
            "samples": len(batch),
            "pid": os.getpid(),
            "committed_at": self._clock(),
        }
        lines = [json.dumps(meta, sort_keys=True)]
        lines.extend(json.dumps(e, sort_keys=True) for e in batch)
        payload = ("\n".join(lines) + "\n").encode()
        path = os.path.join(self.directory, f"perf-{seq:08d}.jsonl")
        try:
            faults.fault_point("obs.perf_ring")
            atomic_write_bytes(path, payload)
        except (faults.FaultInjected, OSError):
            # ABSORBED: the black box must never stall the serve — the
            # segment's samples are lost, the loss is counted, and the
            # next segment starts clean
            with self._lock:
                self._dropped += 1
            if self._metrics is not None:
                self._metrics.inc("perf_ring_dropped_segments")
            return None
        for _, old_path in segment_files(self.directory)[:-self.keep_segments]:
            try:
                os.unlink(old_path)
            except OSError:
                pass
        with self._lock:
            self._committed += 1
            self._last_segment = seq
        if self._metrics is not None:
            self._metrics.inc("perf_ring_segments")
        return path

    # -- read ---------------------------------------------------------------
    def tail(self, n: int) -> list[dict]:
        """The newest ``n`` samples, oldest first — in-memory buffer
        first, then committed segments newest-backwards as needed (the
        SIGUSR1 / post-mortem dump view)."""
        if n <= 0:
            return []
        with self._lock:
            out = list(self._buf)[-n:]
        need = n - len(out)
        if need > 0:
            older: list[dict] = []
            for _, path in reversed(segment_files(self.directory)):
                try:
                    with open(path, encoding="utf-8") as f:
                        seg = [
                            json.loads(ln) for ln in f if ln.strip()
                        ]
                except (OSError, ValueError):
                    continue  # dump path: tolerate, the strict reader is replay()
                older = [e for e in seg if e.get("kind") != "meta"] + older
                if len(older) >= need:
                    break
            out = older[-need:] + out
        return out

    def status(self) -> dict:
        with self._lock:
            return {
                "directory": self.directory,
                "buffered": len(self._buf),
                "segments_committed": self._committed,
                "segments_dropped": self._dropped,
                "last_segment": self._last_segment,
                "ticks_per_segment": self.ticks_per_segment,
                "keep_segments": self.keep_segments,
            }
