"""Record-level latency provenance: the live end-to-end budget plane.

The span tracer (obs/trace.py) answers *where did that tick's 20 ms
go?* — but only inside the serve process, per stage, per tick. This
module answers the question the "<1 ms p50" headline dodges: **how long
did a record spend between its source emitting it and its label
becoming visible in a render** — and in which hop. Every telemetry
batch carries host-side boundary stamps (all in the
``time.perf_counter`` domain, never on the wire):

- ``emit``    — the owning pump read/generated the batch
  (``protocol.stamp_records``: fan-in pump ``_deliver``, the
  collector's reader thread at pipe parse, or the CLI's direct-source
  arrival for unpumped sources)
- ``enq``/``deq`` — fan-in MPSC queue enter/exit (``ingest/fanin.py``;
  the per-source queue-wait the bounded queue design trades drops for)
- ``parse``   — the batch's records are through the batcher
  (``engine.ingest``)
- ``scatter`` — the tick's update scatter has been DISPATCHED (the
  host's last touch; the dispatch is async by design, so this is a
  dispatch boundary, not a device completion)
- ``device``  — the render's device work completed (the read side's
  blocking sync on the serve/device stage)
- ``render``  — the rows are printed: the label is operator-visible

Per render tick the serve loop folds the closed batches into
histograms (``utils.metrics.Metrics``, so ``--metrics-every``,
``snapshot()`` and ``/metrics`` all carry them):

- ``e2e_emit_to_render_s``     — render − emit, the headline number,
  plus per-source ``source_<sid>_e2e_s`` series
- ``queue_wait_s``             — deq − enq (fan-in sources only)
- ``batch_wait_s``             — scatter − (deq or emit): host
  batching/routing time before the device saw the tick
- the **waterfall** ``wf_queue_s`` / ``wf_parse_s`` / ``wf_scatter_s``
  / ``wf_device_s`` / ``wf_render_s`` — each is CUMULATIVE time since
  emit at that boundary, so the per-stage budget reads as
  non-decreasing quantiles and the increment between adjacent stages
  is that stage's own cost (``tools/bench_e2e_live.py`` publishes it)

Visibility semantics match the render pipeline exactly: a record's
e2e clock stops at the first render whose read side was dispatched
AFTER its scatter — ``seal()`` snapshots the closed set at dispatch
time, and a coalesced (superseded) render's sealed batches fold at the
render that actually printed, which is when their labels truly became
visible. Batches that never become visible are excluded: a dead
source's purged queue backlog (``FanInQueue.purge``) never produces an
entry, and ``drop_source`` discards a quarantine-evicted namespace's
pending entries (their rows were just cleared — folding them would
poison the freshness quantiles with labels nobody served).

``slo_s`` arms the breach hook: when the running e2e p99 crosses it,
the transition is recorded to the flight recorder
(``latency.slo_breach``, with the dominant stage) and the
``latency_slo_breached`` gauge flips — an edge event, not a per-tick
spam.

Thread model: the host stage adds/marks entries, the device stage (or
the serial loop) folds them; all shared state lives under ``_lock``,
which stays a LEAF lock — histogram observes and recorder appends
happen strictly after it releases (graftlock lock-order).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

# the waterfall boundaries, in pipeline order (metric: wf_<name>_s)
WATERFALL_STAGES = ("queue", "parse", "scatter", "device", "render")


@dataclass
class _Entry:
    """One telemetry batch's boundary stamps (perf_counter domain)."""

    sid: int
    n: int
    emit: float | None
    enq: float | None = None
    deq: float | None = None
    parse: float | None = None
    scatter: float | None = None
    device: float | None = None
    seal: int | None = None  # render generation that closes this entry


class LatencyProvenance:
    """Per-tick accumulator folding batch boundary stamps into the
    latency histograms. Driven by the serve loop:

    ``begin_tick(entries)`` → ``mark_parse()`` → ``mark_scatter()`` →
    ``seal()`` (at render dispatch, host stage) → ``mark_device(s)`` →
    ``render_visible(s)`` (after the rows printed — serial loop or the
    pipeline's device-stage job). ``entries`` are ``(sid, emit, enq,
    deq, n)`` tuples — the fan-in tier's ``pop_provenance()`` shape, or
    a single synthesized entry for direct sources.
    """

    def __init__(self, metrics, recorder=None,
                 clock=time.perf_counter, slo_s: float = 0.0):
        self.metrics = metrics
        self.recorder = recorder
        self.clock = clock
        self.slo_s = slo_s
        # guards every container below: the host stage appends/marks,
        # the device-stage worker seals-and-folds — held only for the
        # bookkeeping; observes/records happen after release (leaf lock)
        self._lock = threading.Lock()
        self._open: list[_Entry] = []      # this tick, pre-scatter
        self._pending: list[_Entry] = []   # scattered, awaiting render
        self._seal_seq = 0
        self._breached = False
        # optional per-entry fold tap: fn(entry, render_ts) per folded
        # stamped entry — tools/bench_e2e_live.py uses it to compute
        # per-batch stage increments (a sum of per-stage p50s that can
        # honestly reconcile against the e2e p50, instead of the
        # trivially-telescoping cumulative-quantile differences)
        self.on_fold = None

    # -- host stage --------------------------------------------------------
    def begin_tick(self, entries) -> None:
        """Register this serve tick's arrived batches. Unstamped
        batches (``emit`` None — an absorbed ``obs.stamp`` fire, a raw
        byte source) still flow through so the counters stay honest;
        they are skipped at fold time."""
        fresh = [
            _Entry(sid=int(sid), n=int(n), emit=emit, enq=enq, deq=deq)
            for sid, emit, enq, deq, n in entries
        ]
        unstamped = sum(1 for e in fresh if e.emit is None)
        with self._lock:
            self._open.extend(fresh)
        if unstamped:
            self.metrics.inc("latency_unstamped_batches", unstamped)

    def mark_parse(self) -> None:
        now = self.clock()
        with self._lock:
            for e in self._open:
                if e.parse is None:
                    e.parse = now

    def mark_scatter(self) -> None:
        """The tick's scatter is dispatched — the open batches are now
        device-visible and move to the render-pending set."""
        now = self.clock()
        with self._lock:
            for e in self._open:
                if e.scatter is None:
                    e.scatter = now
            self._pending.extend(self._open)
            self._open.clear()

    def seal(self) -> int:
        """Snapshot the render-pending set at read-side dispatch time:
        every pending entry without a seal joins this render
        generation. Returns the generation id the render job hands
        back to ``mark_device``/``render_visible`` — entries scattered
        AFTER the dispatch (the pipelined host stage keeps ingesting)
        wait for the next render, exactly like their table rows."""
        with self._lock:
            self._seal_seq += 1
            s = self._seal_seq
            for e in self._pending:
                if e.seal is None:
                    e.seal = s
        return s

    # -- device stage ------------------------------------------------------
    def mark_device(self, seal_id: int) -> None:
        """The render's device work completed for generation
        ``seal_id`` (and any earlier generation a coalesced render
        left behind)."""
        now = self.clock()
        with self._lock:
            for e in self._pending:
                if (e.seal is not None and e.seal <= seal_id
                        and e.device is None):
                    e.device = now

    def render_visible(self, seal_id: int) -> None:
        """The rows are printed: fold every entry of generation
        ``<= seal_id`` into the histograms and retire it. A superseded
        (coalesced) render's generations fold here too — this render
        is when their telemetry actually became visible."""
        now = self.clock()
        with self._lock:
            closed = [
                e for e in self._pending
                if e.seal is not None and e.seal <= seal_id
            ]
            self._pending = [
                e for e in self._pending
                if e.seal is None or e.seal > seal_id
            ]
        if closed:
            self._fold(closed, now)

    # -- lifecycle ---------------------------------------------------------
    def drop_source(self, sid: int) -> int:
        """Discard a namespace's un-folded entries (quarantine
        eviction just cleared its rows — nothing will ever render
        them). New entries for the sid cannot arrive: the source is
        DEAD and its queue backlog was purged before this call, so the
        per-source series stops accumulating here. Returns the number
        of discarded entries."""
        with self._lock:
            n = sum(
                1 for e in self._open + self._pending if e.sid == sid
            )
            self._open = [e for e in self._open if e.sid != sid]
            self._pending = [e for e in self._pending if e.sid != sid]
        if n:
            self.metrics.inc("latency_entries_discarded", n)
        return n

    # -- fold --------------------------------------------------------------
    def _fold(self, closed, render_ts: float) -> None:
        m = self.metrics
        for e in closed:
            if e.emit is None:
                continue  # unstamped: counted at begin_tick, never folded
            e2e = max(0.0, render_ts - e.emit)
            m.observe("e2e_emit_to_render_s", e2e)
            m.observe(f"source_{e.sid}_e2e_s", e2e)
            if e.enq is not None and e.deq is not None:
                m.observe("queue_wait_s", max(0.0, e.deq - e.enq))
            if e.scatter is not None:
                host_from = e.deq if e.deq is not None else e.emit
                m.observe("batch_wait_s",
                          max(0.0, e.scatter - host_from))
            # the cumulative waterfall: time-since-emit at each boundary
            bounds = (
                ("queue", e.deq if e.deq is not None else e.emit),
                ("parse", e.parse),
                ("scatter", e.scatter),
                ("device", e.device),
                ("render", render_ts),
            )
            for name, ts in bounds:
                if ts is not None:
                    m.observe(f"wf_{name}_s", max(0.0, ts - e.emit))
            if self.on_fold is not None:
                self.on_fold(e, render_ts)
        self._check_slo()

    def _check_slo(self) -> None:
        if self.slo_s <= 0:
            return
        h = self.metrics.histograms.get("e2e_emit_to_render_s")
        if h is None or not h.count:
            return
        p99 = h.percentile(99)
        breached = p99 > self.slo_s
        self.metrics.set("latency_slo_breached", 1.0 if breached else 0.0)
        if breached and not self._breached:
            self.metrics.inc("latency_slo_breaches")
            if self.recorder is not None:
                self.recorder.record(
                    "latency.slo_breach", e2e_p99_s=round(p99, 6),
                    slo_s=self.slo_s,
                    dominant_stage=self.status().get("dominant_stage"),
                )
        self._breached = breached

    # -- surfaces ----------------------------------------------------------
    def stage_increments(self) -> dict:
        """Per-stage p50 budget (seconds): the increment between
        adjacent waterfall boundaries — what each hop itself costs at
        the median. Missing stages (no samples yet) are omitted."""
        m = self.metrics
        p50 = {}
        for name in WATERFALL_STAGES:
            h = m.histograms.get(f"wf_{name}_s")
            if h is not None and h.count:
                p50[name] = h.percentile(50)
        out = {}
        prev = 0.0
        for name in WATERFALL_STAGES:
            if name not in p50:
                continue
            out[name] = max(0.0, p50[name] - prev)
            prev = p50[name]
        return out

    def status(self) -> dict:
        """The /healthz ``latency`` block: e2e p50/p99 plus the
        dominant stage (largest p50 increment in the waterfall)."""
        h = self.metrics.histograms.get("e2e_emit_to_render_s")
        if h is None or not h.count:
            return {"observed": False}
        p50, p99 = h.quantiles((50.0, 99.0))
        inc = self.stage_increments()
        dominant = max(inc, key=inc.get) if inc else None
        out = {
            "observed": True,
            "e2e_p50_s": round(p50, 6),
            "e2e_p99_s": round(p99, 6),
            "dominant_stage": dominant,
            "stage_p50_s": {k: round(v, 6) for k, v in inc.items()},
        }
        if self.slo_s > 0:
            out["slo_s"] = self.slo_s
            out["slo_breached"] = self._breached
        return out
