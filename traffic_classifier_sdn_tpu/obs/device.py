"""Device-runtime telemetry: compile/retrace accounting, HBM gauges,
donation effectiveness, and on-demand profiler capture.

All prior observability watches the HOST side of the serve (metrics,
flight recorder, latency provenance, syncguard). This module is the
device half — what the chip (or its CPU stand-in) is actually doing:

- **Compile/retrace telemetry.** ``DeviceTelemetry.attach`` subscribes
  to the ``jax.monitoring`` listener bus: every backend compile lands
  in ``jit_compiles`` / the ``jit_compile_s`` histogram plus a
  ``device.compile`` flight-recorder event carrying the program name
  and duration; compilation-cache hits/misses count alongside. The
  program name is not on the monitoring event (jax 0.4.x passes the
  duration alone), so a logging handler on ``jax._src.dispatch``
  captures the "Finished XLA compilation of jit(<name>)" line — which
  fires immediately BEFORE the duration event on the same thread — and
  the duration listener reads-and-clears it under the telemetry lock.
- **Retrace discipline, enforced live.** ``mark_warmup_complete`` arms
  the edge: any compile after it is a retrace — ``device.retrace``
  event + ``retraces_after_warmup`` counter. Each novel shape costs
  exactly one backend compile, so the event fires exactly once per
  novel shape (tests/test_device_obs.py pins this), turning the PR 4/8
  zero-retrace test discipline into a production runtime signal.
- **HBM accounting.** ``sample()`` polls ``device.memory_stats()`` per
  tick into ``device_memory_bytes`` / ``device_memory_peak_bytes``
  gauges and a watermark; backends without it (CPU) report None and
  everything degrades gracefully. ``note_donation`` reconciles expected
  vs observed buffer reuse on the double-buffered wire/feature stages
  (the probes live at the stages; this is just the ledger).
- **Listener discipline.** Callbacks fire on whatever thread compiles;
  ``_lock`` is a leaf held only for bookkeeping — metrics observes and
  recorder appends happen strictly after release (the obs/latency.py
  idiom). ``detach`` unregisters both listeners and restores the
  logger, so a finished run cannot haunt the next in-process.

``ProfilerCapture`` is the on-demand deep view: ``/profile?seconds=N``
on the obs server starts a ``jax.profiler`` trace into ``--obs-dir``
under a mutually-exclusive-capture guard — never on the hot path by
default, and a capture failure 500s the endpoint (fault site
``obs.profiler``), never the serve loop.

jax imports are lazy (attach/capture time): importing this module pulls
no device runtime, so the obs plane stays importable everywhere.
"""

from __future__ import annotations

import logging
import os
import re
import threading
import time

from ..utils import faults

# the jax.monitoring event keys this plane consumes (jax 0.4.x names)
BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
CACHE_MISS_EVENT = "/jax/compilation_cache/cache_misses"

_COMPILE_LOG_RE = re.compile(
    r"Finished XLA compilation of (?P<name>.+?) in [0-9.eE+-]+ sec"
)


class _CompileNameHandler(logging.Handler):
    """Captures the compiled program's name from the dispatch log line
    that precedes each backend-compile duration event."""

    def __init__(self, note):
        super().__init__(level=logging.DEBUG)
        self._note = note

    def emit(self, record) -> None:  # noqa: D102
        try:
            m = _COMPILE_LOG_RE.search(record.getMessage())
        except Exception:  # noqa: BLE001 — observation must not raise
            return
        if m:
            self._note(m.group("name"))


class DeviceTelemetry:
    """Compile/retrace/HBM/donation ledger behind the obs plane.

    Lifecycle: ``attach()`` before warmup, ``mark_warmup_complete()``
    after, ``sample()`` per tick, ``detach()`` in the serve's finally.
    Also a context manager (tests). Byte-transparent: everything lands
    in metrics/recorder/stderr surfaces, never stdout.
    """

    def __init__(self, metrics=None, recorder=None, clock=time.time):
        self._metrics = metrics
        self._recorder = recorder
        self._clock = clock
        self._lock = threading.Lock()
        self._armed = False
        self._warmed = False
        self._compiles = 0
        self._compile_s = 0.0
        self._cache_hits = 0
        self._cache_misses = 0
        self._retraces = 0
        self._pending_name: str | None = None
        self._last_program: str | None = None
        self._last_dispatch_at: float | None = None
        self._donation: dict[str, list[int]] = {}
        self._hbm_last: int | None = None
        self._hbm_watermark = 0
        self._backend = None
        self._platform = None
        self._device = None
        self._logger = None
        self._handler = None
        self._prior_level: int | None = None
        self._prior_propagate: bool | None = None

    # -- lifecycle ----------------------------------------------------------
    def attach(self) -> DeviceTelemetry:
        """Register the monitoring listeners + the name-capture logging
        handler. Idempotent; returns self."""
        with self._lock:
            if self._armed:
                return self
            self._armed = True
        import jax
        from jax import monitoring

        try:
            devices = jax.devices()
        except Exception:  # noqa: BLE001 — a dead backend must not kill obs
            devices = []
        dev = devices[0] if devices else None
        with self._lock:
            self._device = dev
            self._backend = getattr(dev, "device_kind", None)
            self._platform = getattr(dev, "platform", None)
        # the dispatch logger must emit at DEBUG for the compile line to
        # reach the handler; propagate=False keeps that DEBUG stream out
        # of the root handlers (no stderr spam) while armed
        logger = logging.getLogger("jax._src.dispatch")
        handler = _CompileNameHandler(self._note_program)
        self._prior_level = logger.level
        self._prior_propagate = logger.propagate
        logger.addHandler(handler)
        logger.setLevel(logging.DEBUG)
        logger.propagate = False
        self._logger, self._handler = logger, handler
        monitoring.register_event_duration_secs_listener(self._on_duration)
        monitoring.register_event_listener(self._on_event)
        return self

    def detach(self) -> None:
        """Unregister listeners and restore the dispatch logger.
        Idempotent — safe from the CLI's finally after any failure."""
        with self._lock:
            if not self._armed:
                return
            self._armed = False
        try:
            from jax._src import monitoring as mon

            mon._unregister_event_duration_listener_by_callback(
                self._on_duration
            )
            mon._unregister_event_listener_by_callback(self._on_event)
        except Exception:  # noqa: BLE001 — callbacks also no-op once disarmed
            pass
        logger, handler = self._logger, self._handler
        self._logger = self._handler = None
        if logger is not None and handler is not None:
            logger.removeHandler(handler)
            if self._prior_level is not None:
                logger.setLevel(self._prior_level)
            if self._prior_propagate is not None:
                logger.propagate = self._prior_propagate

    def __enter__(self) -> DeviceTelemetry:
        return self.attach()

    def __exit__(self, *exc) -> bool:
        self.detach()
        return False

    # -- listener callbacks --------------------------------------------------
    def _note_program(self, name: str) -> None:
        with self._lock:
            self._pending_name = name

    def _on_duration(self, event: str, duration: float, **kw) -> None:
        if event != BACKEND_COMPILE_EVENT:
            return
        with self._lock:
            if not self._armed:
                return
            name, self._pending_name = self._pending_name, None
            self._compiles += 1
            self._compile_s += duration
            if name is not None:
                self._last_program = name
            warmed = self._warmed
            if warmed:
                self._retraces += 1
        m, rec = self._metrics, self._recorder
        if m is not None:
            m.inc("jit_compiles")
            m.observe("jit_compile_s", duration)
            if warmed:
                m.inc("retraces_after_warmup")
        if rec is not None:
            rec.record("device.compile", program=name,
                       duration_s=round(duration, 6), after_warmup=warmed)
            if warmed:
                # edge-triggered: a compile after warmup is a discipline
                # breach — one event per novel program/shape
                rec.record("device.retrace", program=name,
                           duration_s=round(duration, 6))

    def _on_event(self, event: str, **kw) -> None:
        if event == CACHE_HIT_EVENT:
            with self._lock:
                if not self._armed:
                    return
                self._cache_hits += 1
            if self._metrics is not None:
                self._metrics.inc("compilation_cache_hits")
        elif event == CACHE_MISS_EVENT:
            with self._lock:
                if not self._armed:
                    return
                self._cache_misses += 1
            if self._metrics is not None:
                self._metrics.inc("compilation_cache_misses")

    # -- serve-loop hooks ----------------------------------------------------
    def mark_warmup_complete(self) -> None:
        """Arm the retrace edge: every compile from here on is a breach
        of the zero-retrace discipline."""
        with self._lock:
            self._warmed = True
            compiles, compile_s = self._compiles, self._compile_s
        if self._recorder is not None:
            self._recorder.record(
                "device.warmup_complete", jit_compiles=compiles,
                jit_compile_s=round(compile_s, 6),
            )

    def mark_dispatch(self) -> None:
        """The serve loop dispatched device work this tick — feeds the
        /healthz last-dispatch age (a wedged device shows a growing age
        while host ticks keep beating)."""
        with self._lock:
            self._last_dispatch_at = self._clock()

    def note_donation(self, stage: str, reused: bool) -> None:
        """One donation outcome from a double-buffered stage: the donated
        input's storage was (or was not) observed reused by the output."""
        with self._lock:
            ent = self._donation.setdefault(stage, [0, 0])
            ent[0] += 1
            if reused:
                ent[1] += 1
        m = self._metrics
        if m is not None:
            m.inc(f"donation_expected_{stage}")
            if reused:
                m.inc(f"donation_reused_{stage}")

    def sample(self) -> dict:
        """Per-tick poll: refresh the HBM gauges (graceful None on
        backends without memory_stats) and return the compact dict the
        perf recorder persists."""
        dev = self._device
        stats = None
        if dev is not None:
            try:
                stats = dev.memory_stats()
            except Exception:  # noqa: BLE001 — CPU backends raise/return None
                stats = None
        in_use = stats.get("bytes_in_use") if stats else None
        peak = stats.get("peak_bytes_in_use") if stats else None
        with self._lock:
            if in_use is not None:
                self._hbm_watermark = max(self._hbm_watermark, int(in_use))
            self._hbm_last = in_use
            watermark = self._hbm_watermark
            out = {
                "jit_compiles": self._compiles,
                "retraces_after_warmup": self._retraces,
                "hbm_bytes": in_use,
            }
        m = self._metrics
        if m is not None and in_use is not None:
            m.set("device_memory_bytes", in_use)
            m.set("device_memory_peak_bytes",
                  peak if peak is not None else watermark)
        return out

    # -- read ---------------------------------------------------------------
    def status(self) -> dict:
        """The /healthz ``device`` block."""
        now = self._clock()
        with self._lock:
            last_dispatch = self._last_dispatch_at
            return {
                "armed": self._armed,
                "backend": self._backend,
                "platform": self._platform,
                "jit_compiles": self._compiles,
                "jit_compile_s_total": round(self._compile_s, 6),
                "compilation_cache_hits": self._cache_hits,
                "compilation_cache_misses": self._cache_misses,
                "warmup_complete": self._warmed,
                "retraces_after_warmup": self._retraces,
                "last_compile_program": self._last_program,
                "hbm_bytes": self._hbm_last,
                "hbm_watermark_bytes": self._hbm_watermark or None,
                "last_dispatch_age_s": (
                    None if last_dispatch is None
                    else round(now - last_dispatch, 6)
                ),
                "donation": {
                    stage: {"expected": e, "reused": r}
                    for stage, (e, r) in sorted(self._donation.items())
                },
            }


class ProfilerBusy(RuntimeError):
    """A capture is already in progress (the endpoint's 409)."""


class ProfilerCapture:
    """On-demand ``jax.profiler`` trace capture into one directory.

    Mutually exclusive: a second ``capture`` while one runs raises
    ``ProfilerBusy`` immediately (the guard is a flag flipped under the
    leaf lock; the sleep happens outside it). Failures count and
    re-raise — the /profile endpoint turns them into a 500, the serve
    loop never sees them (fault site ``obs.profiler``).
    """

    MAX_SECONDS = 600.0

    def __init__(self, directory: str, metrics=None, recorder=None):
        self.directory = os.path.abspath(directory)
        self._metrics = metrics
        self._recorder = recorder
        self._lock = threading.Lock()
        self._active = False
        self._captures = 0
        self._failures = 0

    def capture(self, seconds: float) -> dict:
        seconds = float(seconds)
        if not 0.0 < seconds <= self.MAX_SECONDS:
            raise ValueError(
                f"seconds must be in (0, {self.MAX_SECONDS:g}], got {seconds}"
            )
        with self._lock:
            if self._active:
                raise ProfilerBusy("a profiler capture is already running")
            self._active = True
        t0 = time.perf_counter()
        try:
            faults.fault_point("obs.profiler")
            import jax

            os.makedirs(self.directory, exist_ok=True)
            jax.profiler.start_trace(self.directory)
            try:
                time.sleep(seconds)
            finally:
                jax.profiler.stop_trace()
        except Exception as e:
            with self._lock:
                self._failures += 1
                self._active = False
            if self._metrics is not None:
                self._metrics.inc("profiler_capture_failures")
            if self._recorder is not None:
                self._recorder.record("device.profile_failed", error=str(e))
            raise
        wall = time.perf_counter() - t0
        with self._lock:
            self._captures += 1
            self._active = False
        if self._metrics is not None:
            self._metrics.inc("profiler_captures")
        if self._recorder is not None:
            self._recorder.record("device.profile", seconds=seconds,
                                  wall_s=round(wall, 6))
        return {
            "directory": self.directory,
            "seconds": seconds,
            "wall_s": round(wall, 6),
        }

    def status(self) -> dict:
        with self._lock:
            return {
                "directory": self.directory,
                "active": self._active,
                "captures": self._captures,
                "failures": self._failures,
            }
