"""Scrapeable serving telemetry: /metrics, /healthz, /events.

A stdlib ``http.server`` thread an operator can point Prometheus at —
no client library, no third-party deps (the container image is fixed).
Four endpoints:

- ``/metrics`` — Prometheus text exposition format 0.0.4. Counters and
  gauges map directly; each ``utils.metrics.Histogram`` is rendered as
  a summary family: ``<name>{quantile="0.5"}`` / ``{quantile="0.99"}``
  (exact nearest-rank over the bounded sample window — window
  quantiles, the honest label for what they are), plus lifetime
  ``_sum`` and ``_count``. All families carry the ``tcsdn_`` prefix and
  sanitized names, so ``stage_predict_s`` scrapes as
  ``tcsdn_stage_predict_s``.
- ``/healthz`` — JSON liveness: collector alive, last-tick age vs the
  staleness threshold, checkpoint freshness when periodic snapshots are
  enabled. HTTP 200 while healthy, 503 once stale/dead — ready for a
  Kubernetes/Prometheus probe verbatim.
- ``/events`` — the flight-recorder tail as a JSON array (``?n=`` to
  bound), the live view of the same ring the post-mortem dump freezes.
- ``/profile?seconds=N`` — on-demand ``jax.profiler`` trace capture
  into the obs dir (obs/device.ProfilerCapture), armed only when the
  CLI runs with ``--obs-dir``. Mutually exclusive (409 while one runs),
  a failed capture is a 500 — never a serve-loop crash.

The server runs on a daemon thread (``ThreadingHTTPServer``; handlers
never block the serve loop — they read under the metrics/ring locks
only long enough to snapshot). ``stop()`` is a clean shutdown: socket
closed, thread joined, port released — wired into the CLI's
``finally`` so Ctrl-C never leaks the listener.
"""

from __future__ import annotations

import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from .device import ProfilerBusy

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
_PREFIX = "tcsdn_"

# the bounded-window quantiles exposed per histogram (label, percentile)
_QUANTILES = (("0.5", 50.0), ("0.9", 90.0), ("0.99", 99.0))


def _metric_name(name: str) -> str:
    return _PREFIX + _NAME_RE.sub("_", name)


def _fmt(value: float) -> str:
    """Prometheus-friendly float rendering (repr keeps full precision;
    integers shed their trailing .0 for counter readability)."""
    f = float(value)
    return str(int(f)) if f.is_integer() else repr(f)


def prometheus_text(metrics, now: float | None = None) -> str:
    """Render a ``utils.metrics.Metrics`` registry in Prometheus text
    format. ``now`` injects the uptime clock so golden tests are exact;
    the serving path leaves it None (wall clock)."""
    if now is None:
        now = time.time()
    # shallow-copy each family dict before iterating: the serve loop
    # registers new metrics concurrently, and iterating a resizing dict
    # raises; a dict() copy is atomic under the GIL
    counters = dict(metrics.counters)
    gauges = dict(metrics.gauges)
    histograms = dict(metrics.histograms)
    lines: list[str] = []
    up = _metric_name("uptime_seconds")
    lines.append(f"# HELP {up} Seconds since the metrics registry reset.")
    lines.append(f"# TYPE {up} gauge")
    lines.append(f"{up} {_fmt(max(0.0, now - metrics.started_at))}")
    for name in sorted(counters):
        pname = _metric_name(name)
        lines.append(f"# TYPE {pname} counter")
        lines.append(f"{pname} {_fmt(counters[name])}")
    for name in sorted(gauges):
        pname = _metric_name(name)
        lines.append(f"# TYPE {pname} gauge")
        lines.append(f"{pname} {_fmt(gauges[name])}")
    for name in sorted(histograms):
        h = histograms[name]
        pname = _metric_name(name)
        lines.append(
            f"# HELP {pname} Window quantiles are exact nearest-rank "
            f"over the newest {h.window} samples; sum/count are "
            f"lifetime."
        )
        lines.append(f"# TYPE {pname} summary")
        values = h.quantiles([q for _, q in _QUANTILES])
        for (label, _), v in zip(_QUANTILES, values):
            lines.append(f'{pname}{{quantile="{label}"}} {_fmt(v)}')
        lines.append(f"{pname}_sum {_fmt(h.total)}")
        lines.append(f"{pname}_count {h.count}")
    return "\n".join(lines) + "\n"


class HealthState:
    """Liveness/staleness aggregate behind ``/healthz``.

    The serve loop beats ``tick()`` once per poll tick and
    ``checkpoint()`` after each committed snapshot; ``probe`` (when
    set) reports whether the telemetry collector is alive. ``check``
    folds the three into one verdict: healthy until the last tick (or
    the start, before any tick) is older than ``max_tick_age_s``, the
    collector probe says dead, or — when a checkpoint cadence is
    declared — the last checkpoint is older than
    ``max_checkpoint_age_s``. Clock-injected and lock-guarded: beats
    come from the serve loop, reads from the exposition thread.
    """

    def __init__(self, clock=time.monotonic, max_tick_age_s: float = 30.0,
                 max_checkpoint_age_s: float | None = None):
        self._clock = clock
        self.max_tick_age_s = max_tick_age_s
        self.max_checkpoint_age_s = max_checkpoint_age_s
        self._lock = threading.Lock()
        self._started_at = clock()
        self._last_tick_at: float | None = None
        self._last_checkpoint_at: float | None = None
        self._model_loaded_at: float | None = None
        self._model_promoted_at: float | None = None
        self._ticks = 0
        self._probe = None
        self._degrade = None
        self._drift = None
        self._openset = None
        self._label_cache = None
        self._sources = None
        self._latency = None
        self._device = None
        self._actuation = None
        self._obs_port: int | None = None

    def model_loaded(self) -> None:
        """The serve registered its boot model — the ``model_age_s``
        staleness anchor. A serve that never promotes reports its age
        from here, so 'healthy but ancient' is visible without the
        drift loop being on at all."""
        with self._lock:
            self._model_loaded_at = self._clock()

    def model_promoted(self) -> None:
        """A fresh checkpoint was hot-promoted (serving/drift.py):
        ``model_age_s`` re-anchors here and
        ``model_promoted_age_s`` starts reporting."""
        with self._lock:
            self._model_promoted_at = self._clock()

    def set_drift(self, status_fn) -> None:
        """``status_fn() -> dict`` (serving/drift.DriftController
        .status): the drift loop's self-report, folded into /healthz as
        a ``drift`` object — state machine position, score, and the
        retrain/promotion/rollback counters."""
        with self._lock:
            self._drift = status_fn

    def set_degrade(self, status_fn) -> None:
        """``status_fn() -> dict`` (serving/degrade.DegradeLadder.status):
        the degradation ladder's self-report. Folded into /healthz as
        200-but-degraded — a degraded serve still produces every tick,
        so it must NOT probe-fail and get restarted into the same sick
        device; the ``degraded`` flag plus the ladder rung tell the
        operator (and the alerting rule) what actually needs attention."""
        with self._lock:
            self._degrade = status_fn

    def set_openset(self, status_fn) -> None:
        """``status_fn() -> dict`` (serving/openset.OpenSetGate
        .status): the open-set rejection tier's self-report — state
        (CALIBRATING/ARMED), the calibrated threshold and margin, and
        the rejection counters — folded into /healthz as an
        ``openset`` object."""
        with self._lock:
            self._openset = status_fn

    def set_label_cache(self, status_fn) -> None:
        """``status_fn() -> dict`` (serving/incremental.IncrementalLabels
        .status): the incremental label cache's self-report — mode,
        cache coverage (fraction of the table served from cache at the
        last render), rows re-predicted, and invalidation count —
        folded into /healthz as a ``label_cache`` object."""
        with self._lock:
            self._label_cache = status_fn

    def set_latency(self, status_fn) -> None:
        """``status_fn() -> dict`` (obs/latency.LatencyProvenance
        .status): the live end-to-end latency budget, folded into
        /healthz as a ``latency`` object — e2e p50/p99 since emit, the
        dominant stage of the waterfall, and the SLO-breach flag when
        ``--latency-slo`` is armed."""
        with self._lock:
            self._latency = status_fn

    def set_device(self, status_fn) -> None:
        """``status_fn() -> dict`` (obs/device.DeviceTelemetry.status):
        the device-runtime plane's self-report — backend/platform,
        compile and retrace counters, HBM watermark, last-dispatch age,
        donation effectiveness — folded into /healthz as a ``device``
        object. Informational, never a health verdict: a retrace or a
        high watermark is an alerting signal, not a restart reason."""
        with self._lock:
            self._device = status_fn

    def set_actuation(self, status_fn) -> None:
        """``status_fn() -> dict`` (serving/actuation.ActuationPlane
        .status): the actuation tier's self-report — mode, live state
        (push/dry-run/degraded/demoted), the rule FSM census, the exact
        intended == installed + refused + retracted ledger, and the
        flap counters — folded into /healthz as an ``actuation``
        object. Informational like ``device``: a degraded plane keeps
        serving classifications, so it never flips the verdict."""
        with self._lock:
            self._actuation = status_fn

    def set_obs_port(self, port: int) -> None:
        """The exposition server's ACTUAL bound port — the /healthz
        self-reference. With ``--obs-port 0`` (ephemeral bind) this is
        how a supervisor that parsed nothing from stderr still learns
        where the plane landed."""
        with self._lock:
            self._obs_port = int(port)

    def set_collector_probe(self, probe) -> None:
        """``probe() -> bool | None`` (None = no collector, e.g. replay
        sources — reported but never unhealthy)."""
        with self._lock:
            self._probe = probe

    def set_source_roster(self, roster_fn) -> None:
        """``roster_fn() -> list[dict]`` (ingest/fanin.FanInIngest
        .roster): the fan-in tier's per-source status — id, state
        (HEALTHY/RESTARTING/DEAD), lag since last delivery, drop and
        record counters, pending quarantine — folded into /healthz as a
        ``sources`` array. The single-boolean ``collector_alive`` keeps
        reporting alongside it (the fan-in tier feeds it via the
        collector probe), so pre-fan-in alerting rules survive the
        multi-source upgrade unchanged."""
        with self._lock:
            self._sources = roster_fn

    def tick(self) -> None:
        with self._lock:
            self._last_tick_at = self._clock()
            self._ticks += 1

    def checkpoint(self) -> None:
        with self._lock:
            self._last_checkpoint_at = self._clock()

    def check(self) -> tuple[bool, dict]:
        """(healthy, report) — the /healthz payload."""
        with self._lock:
            now = self._clock()
            last_tick = self._last_tick_at
            last_ckpt = self._last_checkpoint_at
            ticks = self._ticks
            probe = self._probe
            degrade = self._degrade
            drift = self._drift
            openset = self._openset
            label_cache = self._label_cache
            sources = self._sources
            latency = self._latency
            device = self._device
            actuation = self._actuation
            obs_port = self._obs_port
            model_loaded = self._model_loaded_at
            model_promoted = self._model_promoted_at
            started = self._started_at
        tick_age = now - (last_tick if last_tick is not None else started)
        stale = tick_age > self.max_tick_age_s
        collector_alive = None
        if probe is not None:
            try:
                collector_alive = probe()
            except Exception as e:  # noqa: BLE001 — health must not crash
                collector_alive = False
                probe_error = str(e)
            else:
                probe_error = None
        else:
            probe_error = None
        ckpt_age = None if last_ckpt is None else now - last_ckpt
        ckpt_stale = False
        if self.max_checkpoint_age_s is not None:
            # before the first checkpoint, freshness is measured from
            # start — a serve that never checkpoints must go unhealthy,
            # not report "no checkpoint yet" forever
            ckpt_stale = (
                (ckpt_age if ckpt_age is not None else now - started)
                > self.max_checkpoint_age_s
            )
        healthy = (
            not stale and collector_alive is not False and not ckpt_stale
        )
        report = {
            "healthy": healthy,
            "ticks": ticks,
            "last_tick_age_s": round(tick_age, 6),
            "max_tick_age_s": self.max_tick_age_s,
            "tick_stale": stale,
            "collector_alive": collector_alive,
            "checkpoint_age_s": (
                None if ckpt_age is None else round(ckpt_age, 6)
            ),
            "max_checkpoint_age_s": self.max_checkpoint_age_s,
            "checkpoint_stale": ckpt_stale,
            # model staleness relative to the live stream: age since
            # the last promotion (or boot load, before any) — an
            # operator distinguishes "healthy but ancient" from
            # "freshly promoted" without correlating logs
            "model_age_s": (
                None if model_loaded is None else round(
                    now - (
                        model_promoted if model_promoted is not None
                        else model_loaded
                    ), 6,
                )
            ),
            "model_promoted_age_s": (
                None if model_promoted is None
                else round(now - model_promoted, 6)
            ),
        }
        if probe_error is not None:
            report["collector_probe_error"] = probe_error
        if degrade is not None:
            try:
                dstatus = degrade()
            except Exception as e:  # noqa: BLE001 — health must not crash
                dstatus = {"state": "unknown", "error": str(e)}
            report["degrade"] = dstatus
            # 200-but-degraded: the serve still answers every tick, so
            # it stays "healthy" for the restart-probe — the rung is
            # the alerting signal, not a reason to kill the process
            report["degraded"] = dstatus.get("state") != "HEALTHY"
        if drift is not None:
            try:
                report["drift"] = drift()
            except Exception as e:  # noqa: BLE001 — health must not crash
                report["drift"] = {"state": "unknown", "error": str(e)}
        if openset is not None:
            try:
                report["openset"] = openset()
            except Exception as e:  # noqa: BLE001 — health must not crash
                report["openset"] = {"state": "unknown", "error": str(e)}
        if label_cache is not None:
            try:
                report["label_cache"] = label_cache()
            except Exception as e:  # noqa: BLE001 — health must not crash
                report["label_cache"] = {
                    "mode": "unknown", "error": str(e),
                }
        if sources is not None:
            try:
                report["sources"] = sources()
            except Exception as e:  # noqa: BLE001 — health must not crash
                report["sources"] = [{"state": "unknown",
                                      "error": str(e)}]
        if latency is not None:
            try:
                report["latency"] = latency()
            except Exception as e:  # noqa: BLE001 — health must not crash
                report["latency"] = {"observed": False, "error": str(e)}
        if device is not None:
            try:
                report["device"] = device()
            except Exception as e:  # noqa: BLE001 — health must not crash
                report["device"] = {"armed": False, "error": str(e)}
        if actuation is not None:
            try:
                report["actuation"] = actuation()
            except Exception as e:  # noqa: BLE001 — health must not crash
                report["actuation"] = {"state": "unknown", "error": str(e)}
        if obs_port is not None:
            report["obs_port"] = obs_port
        return healthy, report


class _Handler(BaseHTTPRequestHandler):
    # the server instance injects these via the class-factory below
    server_version = "tcsdn-obs/1"

    def _send(self, status: int, content_type: str, body: bytes) -> None:
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
        owner: ExpositionServer = self.server.owner  # type: ignore[attr-defined]
        url = urlparse(self.path)
        if url.path == "/metrics":
            body = prometheus_text(owner.metrics).encode()
            self._send(
                200, "text/plain; version=0.0.4; charset=utf-8", body
            )
        elif url.path == "/healthz":
            if owner.health is None:
                payload: dict = {"healthy": True, "detail": "no health state"}
                healthy = True
            else:
                healthy, payload = owner.health.check()
            body = json.dumps(payload, sort_keys=True).encode()
            self._send(
                200 if healthy else 503, "application/json", body
            )
        elif url.path == "/events":
            if owner.recorder is None:
                events: list = []
            else:
                n = None
                raw = parse_qs(url.query).get("n")
                if raw:
                    try:
                        n = max(0, int(raw[0]))
                    except ValueError:
                        self._send(400, "application/json",
                                   b'{"error": "n must be an integer"}')
                        return
                events = owner.recorder.tail(n)
            body = json.dumps(events).encode()
            self._send(200, "application/json", body)
        elif url.path == "/profile":
            # on-demand jax.profiler capture (obs/device.ProfilerCapture)
            # — blocks THIS handler thread for the capture window
            # (ThreadingHTTPServer: /metrics scrapes keep answering);
            # the busy guard makes concurrent requests a 409, so the
            # capture itself is never concurrent with another
            if owner.profiler is None:
                self._send(
                    404, "application/json",
                    b'{"error": "profiler not armed (serve with '
                    b'--obs-dir)"}',
                )
                return
            raw = parse_qs(url.query).get("seconds")
            try:
                seconds = float(raw[0]) if raw else 2.0
            except ValueError:
                self._send(400, "application/json",
                           b'{"error": "seconds must be a number"}')
                return
            try:
                result = owner.profiler.capture(seconds)
            except ProfilerBusy as e:
                self._send(409, "application/json",
                           json.dumps({"error": str(e)}).encode())
            except ValueError as e:
                self._send(400, "application/json",
                           json.dumps({"error": str(e)}).encode())
            except Exception as e:  # noqa: BLE001 — absorbed: 500, not a crash
                self._send(500, "application/json",
                           json.dumps({"error": str(e)}).encode())
            else:
                self._send(200, "application/json",
                           json.dumps(result, sort_keys=True).encode())
        else:
            self._send(404, "application/json", b'{"error": "not found"}')

    def log_message(self, fmt, *args) -> None:  # noqa: D102
        pass  # scrapes every few seconds must not spam stderr


class ExpositionServer:
    """Owns the HTTP listener thread. ``port=0`` binds an ephemeral
    port (tests); ``self.port`` is the actual bound port after
    ``start()``. The default bind is loopback — /events carries
    filesystem paths and failure detail, so reaching beyond the host
    (``host="0.0.0.0"`` for a real scrape target) is the caller's
    explicit choice (CLI: ``--obs-host``)."""

    def __init__(self, metrics, recorder=None, health=None,
                 port: int = 0, host: str = "127.0.0.1", profiler=None):
        self.metrics = metrics
        self.recorder = recorder
        self.health = health
        self.profiler = profiler
        self.host = host
        self.port = port
        self._server: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        if self._server is not None:
            raise RuntimeError("exposition server already started")
        server = ThreadingHTTPServer((self.host, self.port), _Handler)
        server.daemon_threads = True
        server.owner = self  # type: ignore[attr-defined]
        self._server = server
        self.port = server.server_address[1]
        self._thread = threading.Thread(
            target=server.serve_forever, name="tcsdn-obs-exposition",
            daemon=True,
        )
        self._thread.start()

    def stop(self) -> None:
        """Clean shutdown: stop accepting, close the socket, join the
        thread. Idempotent (the CLI's ``finally`` may race a crash)."""
        server, self._server = self._server, None
        thread, self._thread = self._thread, None
        if server is not None:
            server.shutdown()
            server.server_close()
        if thread is not None:
            thread.join(timeout=5.0)

    def __enter__(self) -> ExpositionServer:
        self.start()
        return self

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False
