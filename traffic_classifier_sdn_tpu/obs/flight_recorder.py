"""Flight recorder: the last N structured events, dumpable post-mortem.

The crash-forensics half of the observability plane. A bounded,
lock-guarded ring holds the most recent structured events — span
completions (via ``obs.trace.Tracer``), monitor deaths/restarts and
terminal supervisor failure (``ingest/supervisor.py``), checkpoint
saves/skips/rollbacks (``cli.py`` / ``io/serving_checkpoint.py``),
dropped-line counts, and fault-site firings (hooked through
``utils.faults.add_observer``). When the serve loop dies — unhandled
exception, supervisor budget exhausted, SIGTERM — the CLI dumps the
ring as JSONL: one event per line, newest last, preceded by a ``meta``
line naming the dump reason. That file answers "what happened in the
2 s before the collector died?" after the process is gone.

Design constraints:

- **Bounded.** ``deque(maxlen=capacity)`` — a week-long serve holds the
  newest ``capacity`` events and nothing else; recording never
  allocates beyond the ring.
- **Thread-safe.** Events arrive from the serve loop, the collector
  reader thread, and the exposition server thread; every ring access
  (append, tail, count) holds ``_lock``. Monotonic per-recorder
  sequence numbers make interleaving auditable in the dump.
- **Crash-ordered.** ``dump`` serializes under the lock then writes via
  ``utils.atomicio.atomic_write_bytes`` — a crash mid-dump never leaves
  a torn post-mortem masquerading as a complete one.
- **Self-limiting values.** Event fields are forced JSON-serializable at
  record time (``repr`` fallback), so a dump can never fail on an
  exotic payload — the one place that must not throw is the post-mortem
  path itself.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time

from ..utils import faults
from ..utils.atomicio import atomic_write_bytes

_JSON_SCALARS = (str, int, float, bool, type(None))


def _jsonable(value):
    """Clamp a field value to something json.dumps cannot refuse."""
    if isinstance(value, _JSON_SCALARS):
        return value
    if isinstance(value, (list, tuple)):
        return [_jsonable(v) for v in value]
    if isinstance(value, dict):
        return {str(k): _jsonable(v) for k, v in value.items()}
    return repr(value)


class FlightRecorder:
    """Bounded ring of recent structured events.

    ``clock`` injects the wall-clock source (``time.time``) so tests
    can pin timestamps; sequence numbers are monotonic regardless.
    """

    def __init__(self, capacity: int = 4096, clock=time.time):
        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        self.capacity = capacity
        self._clock = clock
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self._seq = 0
        self._dropped = 0  # events displaced by the bound (lifetime)

    # -- write --------------------------------------------------------------
    def record(self, kind: str, **fields) -> None:
        """Append one event; never raises on payload content."""
        event = {"kind": kind, "ts": self._clock()}
        for k, v in fields.items():
            event[k] = _jsonable(v)
        with self._lock:
            event["seq"] = self._seq
            self._seq += 1
            if len(self._ring) == self.capacity:
                self._dropped += 1
            self._ring.append(event)

    def fault_observer(self, site: str, hit: int, kind: str) -> None:
        """``utils.faults`` observer signature — register with
        ``faults.add_observer(recorder.fault_observer)`` so every fault
        firing lands in the ring with its site, hit count, and kind."""
        self.record("fault.fire", site=site, hit=hit, fault_kind=kind)

    def observing_faults(self):
        """Scoped registration as a context manager — the serve loop's
        idiom; always detaches so a finished run cannot leak an
        observer into the next (the registry is process-global)."""
        return faults.observing(self.fault_observer)

    # -- read ---------------------------------------------------------------
    def tail(self, n: int | None = None) -> list[dict]:
        """The newest ``n`` events (all when None), oldest first."""
        with self._lock:
            events = list(self._ring)
        if n is None:
            return events
        # n == 0 must mean "no events": events[-0:] is the WHOLE list
        return events[-n:] if n > 0 else []

    def count(self, kind: str | None = None) -> int:
        """Events currently in the ring (optionally of one kind)."""
        with self._lock:
            if kind is None:
                return len(self._ring)
            return sum(1 for e in self._ring if e["kind"] == kind)

    @property
    def events_seen(self) -> int:
        """Lifetime recorded count (ring length + displaced)."""
        with self._lock:
            return self._seq

    # -- post-mortem --------------------------------------------------------
    def dump(self, directory: str, reason: str) -> str:
        """Write the ring as a JSONL post-mortem into ``directory``.

        One event per line, oldest first, preceded by a ``meta`` line
        (reason, event count, ring displacement). The filename embeds
        the dump reason and this recorder's sequence frontier, so
        repeated dumps from one process never collide. Returns the
        written path."""
        events = self.tail()
        meta = {
            "kind": "meta",
            "reason": reason,
            "dumped_at": self._clock(),
            "events": len(events),
            "displaced": self._dropped,
            "pid": os.getpid(),
        }
        lines = [json.dumps(meta, sort_keys=True)]
        lines.extend(json.dumps(e, sort_keys=True) for e in events)
        payload = ("\n".join(lines) + "\n").encode()
        os.makedirs(directory, exist_ok=True)
        safe_reason = "".join(
            c if c.isalnum() or c in "-_." else "-" for c in reason
        )
        path = os.path.join(
            directory,
            f"flightrec-{os.getpid()}-{self._seq:08d}-{safe_reason}.jsonl",
        )
        # atomic: a torn post-mortem that parses halfway is worse than
        # none — the committed file is always complete
        atomic_write_bytes(path, payload)
        return path


def dump_metrics_snapshot(metrics, directory: str, reason: str) -> str:
    """Freeze a ``utils.metrics.Metrics`` snapshot as one JSON file in
    ``directory`` — the metrics half of the on-demand (SIGUSR1) dump:
    a flight-recorder JSONL answers *what happened*, this answers
    *what the counters and latency quantiles said when it did*.
    Same discipline as ``FlightRecorder.dump``: atomic write, reason
    embedded in the filename, repeated dumps never collide (the
    monotonic-ns suffix orders them)."""
    snap = metrics.snapshot()
    payload = json.dumps(
        {"kind": "metrics", "reason": reason, "snapshot": snap},
        sort_keys=True, default=repr,
    ).encode()
    os.makedirs(directory, exist_ok=True)
    safe_reason = "".join(
        c if c.isalnum() or c in "-_." else "-" for c in reason
    )
    path = os.path.join(
        directory,
        f"metrics-{os.getpid()}-{time.monotonic_ns()}-{safe_reason}.json",
    )
    atomic_write_bytes(path, payload)
    return path
