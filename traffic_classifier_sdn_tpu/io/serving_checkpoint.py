"""Warm-restart checkpoints for the SERVING state.

The reference loses every tracked flow on restart (its ``flows`` dict is
process memory, traffic_classifier.py:24) and its only persistence is
model pickles. Training-state resume lives in ``io/checkpoint.py``; this
module checkpoints the OTHER stateful half of the system — the live
serving spine — so a restarted classifier resumes with every flow's
counters, rates, and slot assignments intact:

- the device ``FlowTable`` (every SoA leaf, fetched to host numpy),
- the host flow index (per-slot flow keys + metadata + the
  sequential-assignment frontier; C++ engines export fingerprints via
  ``tc_engine_export_index``, Python engines their key dicts),
- the tick clock and render-freshness floor.

Restore rebuilds a ``FlowStateEngine`` that continues EXACTLY: existing
flows resolve to their old slots (same keys → same fingerprint map), the
mod-2³² delta math picks up from the stored ``*_lo`` counters, and idle
eviction keeps its clock. Bit-identical continuation is pinned by
``tests/test_serving_checkpoint.py``.

Key-space note: the Python index keys with BLAKE2b-64
(ingest/protocol.stable_flow_key) while the C++ engine fingerprints with
its wyhash-style mix — a checkpoint therefore records which index wrote
it and restores only onto the same kind (a clear error otherwise).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core import flow_table as ft

FORMAT_VERSION = 1

_TABLE_LEAVES = (
    "time_start", "in_use",
    *(f"fwd.{f}" for f in ft.DirState.__dataclass_fields__),
    *(f"rev.{f}" for f in ft.DirState.__dataclass_fields__),
)


def _get_leaf(table: ft.FlowTable, name: str):
    if "." in name:
        side, field = name.split(".")
        return getattr(getattr(table, side), field)
    return getattr(table, name)


def save(engine, path: str) -> None:
    """One ``.npz`` with the full serving state. Call between ticks (all
    pending records stepped) — pending host-side rows are not captured."""
    engine.step()  # flush: the device table is the only counter state
    data: dict = {
        "format_version": FORMAT_VERSION,
        "capacity": engine.table.capacity,
        "native": int(engine.native),
        "last_time": int(engine.last_time),
        "tick_floor": int(engine._tick_floor),
    }
    for name in _TABLE_LEAVES:
        data[f"table/{name}"] = np.asarray(_get_leaf(engine.table, name))

    if engine.native:
        fp, used, next_slot, free = engine.batcher.export_index()
        slots = np.nonzero(used)[0].astype(np.int64)
        src_b, dst_b = engine.batcher.export_meta(slots)
        src = np.array([s.decode() for s in src_b], dtype="U64")
        dst = np.array([s.decode() for s in dst_b], dtype="U64")
        keys = fp[slots]
    else:
        idx = engine.index
        slots = np.array(sorted(idx.slot_to_key), dtype=np.int64)
        keys = np.array(
            [np.uint64(idx.slot_to_key[int(s)]) for s in slots], np.uint64
        )
        src = np.array(
            [idx.slot_meta[int(s)][0] for s in slots], dtype="U64"
        )
        dst = np.array(
            [idx.slot_meta[int(s)][1] for s in slots], dtype="U64"
        )
        next_slot = idx.next_slot
        free = np.asarray(idx.free, np.uint32)
    data["index/slots"] = slots
    data["index/keys"] = keys
    data["index/src"] = src
    data["index/dst"] = dst
    data["index/next_slot"] = int(next_slot)
    # the free stack VERBATIM: allocation is LIFO, so preserving its exact
    # order is what makes post-restore slot assignment identical to a
    # never-stopped engine
    data["index/free"] = free
    np.savez_compressed(path, **data)


def restore(path: str, buckets=None):
    """Rebuild a ``FlowStateEngine`` from ``save`` output."""
    from ..ingest.batcher import DEFAULT_BUCKETS, FlowStateEngine

    z = np.load(path)
    if int(z["format_version"]) != FORMAT_VERSION:
        raise ValueError(
            f"serving checkpoint format {int(z['format_version'])} != "
            f"{FORMAT_VERSION}"
        )
    native = bool(int(z["native"]))
    if native:
        from ..native import engine as native_engine

        if not native_engine.available():
            raise RuntimeError(
                "checkpoint was written by the native (C++) index, which "
                "is unavailable here — its fingerprints are not "
                "compatible with the Python index's keys"
            )
    eng = FlowStateEngine(
        int(z["capacity"]), buckets=buckets or DEFAULT_BUCKETS,
        native=native,
    )

    leaves = {
        name: jnp.asarray(z[f"table/{name}"]) for name in _TABLE_LEAVES
    }

    def dirstate(side: str) -> ft.DirState:
        return ft.DirState(**{
            f: leaves[f"{side}.{f}"]
            for f in ft.DirState.__dataclass_fields__
        })

    eng.table = ft.FlowTable(
        time_start=leaves["time_start"],
        in_use=leaves["in_use"],
        fwd=dirstate("fwd"),
        rev=dirstate("rev"),
    )

    slots = z["index/slots"]
    keys = z["index/keys"]
    next_slot = int(z["index/next_slot"])
    last_time = int(z["last_time"])
    free = z["index/free"]
    if native:
        eng.batcher.import_index(
            slots, keys,
            np.char.encode(z["index/src"]), np.char.encode(z["index/dst"]),
            next_slot, last_time, free,
        )
    else:
        idx = eng.index
        for s, k, src, dst in zip(
            slots, keys, z["index/src"], z["index/dst"]
        ):
            idx.key_to_slot[int(k)] = int(s)
            idx.slot_to_key[int(s)] = int(k)
            idx.slot_meta[int(s)] = (str(src), str(dst))
        idx.free = [int(s) for s in free]
        idx.next_slot = next_slot
    eng._last_time = last_time
    eng._tick_floor = int(z["tick_floor"])
    return eng
