"""Warm-restart checkpoints for the SERVING state.

The reference loses every tracked flow on restart (its ``flows`` dict is
process memory, traffic_classifier.py:24) and its only persistence is
model pickles. Training-state resume lives in ``io/checkpoint.py``; this
module checkpoints the OTHER stateful half of the system — the live
serving spine — so a restarted classifier resumes with every flow's
counters, rates, and slot assignments intact:

- the device ``FlowTable`` (every SoA leaf, fetched to host numpy),
- the host flow index (per-slot flow keys + metadata + the
  sequential-assignment frontier; C++ engines export fingerprints via
  ``tc_engine_export_index``, Python engines their key dicts),
- the tick clock and render-freshness floor.

Restore rebuilds a ``FlowStateEngine`` that continues EXACTLY: existing
flows resolve to their old slots (same keys → same fingerprint map), the
mod-2³² delta math picks up from the stored ``*_lo`` counters, and idle
eviction keeps its clock. Bit-identical continuation is pinned by
``tests/test_serving_checkpoint.py``.

Key-space note: the Python index keys with BLAKE2b-64
(ingest/protocol.stable_flow_key) while the C++ engine fingerprints with
its wyhash-style mix — a checkpoint therefore records which index wrote
it and restores only onto the same kind (a clear error otherwise).

Durability (the crash-safety layer):

- ``save`` is **atomic**: the ``.npz`` is serialized to bytes, written to
  a temp file *in the target directory*, fsynced, and ``os.replace``d
  into place — a crash mid-save leaves the previous checkpoint intact,
  never a torn file under the final name.
- Every checkpoint embeds a **CRC32 of its own content** (over each
  array's name/dtype/shape/bytes). ``restore`` recomputes and rejects a
  mismatch with ``CorruptCheckpointError`` — on top of the zip
  per-member CRCs, so both torn files and bit flips are caught.
- ``save_rotating`` writes **tick-numbered** checkpoints
  (``ckpt-000000123.npz``) with keep-N pruning, and ``resolve_latest``
  returns the newest file that *passes validation* — a corrupt newest
  checkpoint means rollback to the previous one, not a crash.
- Rotation (sweep + save + prune) is serialized **per directory**
  behind an in-process lock. ``sweep_stale_tmp`` and keep-N pruning
  assume exactly one rotation pass in flight: a second in-process
  writer (an embedding caller snapshotting from its own thread — the
  kind of background writer the drift loop's threading makes easy to
  add) could otherwise have its in-flight temp swept as an "orphan",
  or its freshly committed member pruned by a pass that listed the
  directory pre-commit. The shipped CLI serves rotate from one thread
  today; the lock makes the single-writer assumption a guarantee
  instead of a convention (regression-tested by interleaving two
  rotation passes).
- Fault sites (utils/faults.py): ``serving_ckpt.write`` between temp
  write and rename, ``serving_ckpt.rename`` at the rename, and
  ``serving_ckpt.restore`` at restore entry. tests/test_chaos.py kills
  saves mid-write and proves the rollback + replay-convergence story.

Format v3 adds an optional ``feature_reference`` block — the drift
monitor's training-time per-feature/per-class population statistics
(serving/drift.py) — so a restored serve resumes drift detection
against the same reference instead of re-calibrating on already-drifted
traffic. v2 checkpoints (no block) still load; restore then reports no
reference and the monitor re-calibrates.
"""

from __future__ import annotations

import io
import os
import re
import threading
import zipfile
import zlib

import jax.numpy as jnp
import numpy as np

from ..core import flow_table as ft
from ..utils.atomicio import atomic_write_bytes, sweep_stale_tmp
from ..utils.faults import fault_point

FORMAT_VERSION = 3
# oldest format this build still restores (v1 predates the content
# checksum and is rejected as old-format, never as corruption)
MIN_FORMAT_VERSION = 2

_CKPT_RE = re.compile(r"^ckpt-(\d+)\.npz$")
_REF_PREFIX = "feature_reference/"

# Per-directory rotation locks: keep-N pruning and sweep_stale_tmp
# assume a single rotation pass in flight — serialize whole passes per
# directory so a second in-process writer (embedding callers; any
# future background snapshot path) cannot have its temp swept or its
# fresh member pruned mid-commit. Process-local by design: the rotation
# contract has always been single-process-per-directory; this turns the
# single-THREAD assumption into a guarantee.
_dir_locks: dict[str, threading.Lock] = {}
# *_lock-suffixed so graftlock and the locktrace witness track it
_dir_registry_lock = threading.Lock()


def _rotation_lock(directory: str) -> threading.Lock:
    key = os.path.abspath(directory)
    with _dir_registry_lock:
        lock = _dir_locks.get(key)
        if lock is None:
            lock = _dir_locks[key] = threading.Lock()
        return lock


class CorruptCheckpointError(ValueError):
    """A checkpoint file that cannot be trusted: torn write, bit flip,
    truncated archive, or missing keys. Names the offending file."""

_TABLE_LEAVES = (
    "time_start", "in_use",
    *(f"fwd.{f}" for f in ft.DirState.__dataclass_fields__),
    *(f"rev.{f}" for f in ft.DirState.__dataclass_fields__),
)


def _get_leaf(table: ft.FlowTable, name: str):
    if "." in name:
        side, field = name.split(".")
        return getattr(getattr(table, side), field)
    return getattr(table, name)


def _is_sharded(engine) -> bool:
    # ShardedFlowEngine holds a stacked ``tables`` pytree; the
    # single-device spine a flat ``table``
    return getattr(engine, "tables", None) is not None


def _fetch_leaf(engine, name: str) -> np.ndarray:
    """One table leaf in the GLOBAL (capacity+1,) slot layout, whichever
    spine wrote it: single-device leaves pass through; sharded leaves
    (n_shards, local+1) interleave by the engine's routing invariant —
    global slot g lives on shard g % n_shards at local row g // n_shards
    — so the on-disk format is spine-agnostic and a checkpoint restores
    across spine kinds. The global scratch row is written zeroed (each
    shard's scratch is a local scatter target, never global state)."""
    if not _is_sharded(engine):
        return np.asarray(_get_leaf(engine.table, name))
    stacked = np.asarray(_get_leaf(engine.tables, name))
    n, local = stacked.shape[0], stacked.shape[1] - 1
    cap = n * local
    glob = np.zeros((cap + 1,) + stacked.shape[2:], stacked.dtype)
    glob[:cap] = np.swapaxes(stacked[:, :local], 0, 1).reshape(
        (cap,) + stacked.shape[2:]
    )
    return glob


def _content_crc(data: dict) -> int:
    """CRC32 over every entry's name, dtype, shape, and raw bytes (sorted
    key order). Computed over the *uncompressed* content, so it survives
    recompression and catches in-memory corruption the zip layer never
    sees."""
    crc = 0
    for key in sorted(data):
        if key == "crc32":
            continue
        arr = np.ascontiguousarray(np.asarray(data[key]))
        meta = f"{key}|{arr.dtype.str}|{arr.shape}|".encode()
        crc = zlib.crc32(arr.tobytes(), zlib.crc32(meta, crc))
    return crc & 0xFFFFFFFF


def save(engine, path: str, feature_reference: dict | None = None) -> int:
    """One ``.npz`` with the full serving state, written atomically with
    an embedded content checksum. Call between ticks (all pending records
    stepped) — pending host-side rows are not captured. Returns the byte
    size of the written checkpoint (the metrics feed).

    ``feature_reference`` (a flat name→array dict, the drift monitor's
    reference population statistics) is embedded under the
    ``feature_reference/`` key prefix and covered by the same content
    CRC; ``restore`` hands it back on the engine."""
    engine.step()  # flush: the device table is the only counter state
    capacity = (
        engine.capacity if _is_sharded(engine) else engine.table.capacity
    )
    data: dict = {
        "format_version": FORMAT_VERSION,
        "capacity": capacity,
        "native": int(engine.native),
        "last_time": int(engine.last_time),
        "tick_floor": int(engine._tick_floor),
    }
    if feature_reference:
        for key, value in feature_reference.items():
            data[f"{_REF_PREFIX}{key}"] = np.asarray(value)
    for name in _TABLE_LEAVES:
        data[f"table/{name}"] = _fetch_leaf(engine, name)

    if engine.native:
        fp, used, next_slot, free = engine.batcher.export_index()
        slots = np.nonzero(used)[0].astype(np.int64)
        src_b, dst_b = engine.batcher.export_meta(slots)
        src = np.array([s.decode() for s in src_b], dtype="U64")
        dst = np.array([s.decode() for s in dst_b], dtype="U64")
        keys = fp[slots]
    else:
        idx = engine.index
        slots = np.array(sorted(idx.slot_to_key), dtype=np.int64)
        keys = np.array(
            [np.uint64(idx.slot_to_key[int(s)]) for s in slots], np.uint64
        )
        src = np.array(
            [idx.slot_meta[int(s)][0] for s in slots], dtype="U64"
        )
        dst = np.array(
            [idx.slot_meta[int(s)][1] for s in slots], dtype="U64"
        )
        next_slot = idx.next_slot
        free = np.asarray(idx.free, np.uint32)
    data["index/slots"] = slots
    data["index/keys"] = keys
    data["index/src"] = src
    data["index/dst"] = dst
    data["index/next_slot"] = int(next_slot)
    # the free stack VERBATIM: allocation is LIFO, so preserving its exact
    # order is what makes post-restore slot assignment identical to a
    # never-stopped engine
    data["index/free"] = free
    data["crc32"] = np.uint32(_content_crc(data))
    buf = io.BytesIO()
    np.savez_compressed(buf, **data)
    payload = buf.getvalue()
    # "write" fires mid-temp-write (torn temp, the SIGKILL state);
    # "rename" with a complete temp but no commit — either way the final
    # name still points at the previous checkpoint
    atomic_write_bytes(
        path, payload,
        mid_write_site="serving_ckpt.write",
        pre_rename_site="serving_ckpt.rename",
    )
    return len(payload)


def checkpoint_path(directory: str, tick: int) -> str:
    return os.path.join(directory, f"ckpt-{tick:09d}.npz")


def list_checkpoints(directory: str) -> list[tuple[int, str]]:
    """``(tick, path)`` for every rotation member, newest tick first."""
    out = []
    try:
        names = os.listdir(directory)
    except FileNotFoundError:
        return []
    for name in names:
        m = _CKPT_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(directory, name)))
    out.sort(reverse=True)
    return out


def save_rotating(engine, directory: str, tick: int, keep: int = 3,
                  feature_reference: dict | None = None) -> tuple[str, int]:
    """Atomic tick-numbered checkpoint + keep-N pruning.

    Pruning runs *after* the new checkpoint commits and never trims below
    ``keep`` survivors, so a corrupt newest file always leaves a valid
    predecessor for ``resolve_latest`` to roll back to. The whole pass
    (sweep + save + prune) holds the directory's rotation lock: a
    concurrent in-process writer's half-written temp must not be swept
    as an orphan, and its just-committed member must not be pruned by a
    rotation that listed the directory before the commit. Returns
    ``(path, bytes_written)``."""
    os.makedirs(directory, exist_ok=True)
    with _rotation_lock(directory):
        # collect orphaned temps from SIGKILLed predecessors — a real
        # kill can't run atomic_write_bytes's cleanup, and the
        # rotation's pruning only matches committed ckpt-*.npz names
        sweep_stale_tmp(directory)
        path = checkpoint_path(directory, tick)
        n = save(engine, path, feature_reference=feature_reference)  # graftlint: disable=blocking-under-lock -- serializing the whole sweep+save+prune file-I/O pass under the per-directory rotation lock IS the single-writer guarantee (see the lock's rationale above); the pass is bounded by one checkpoint write
        for _, old in list_checkpoints(directory)[max(keep, 1):]:
            try:
                os.unlink(old)
            except OSError:
                pass  # pruning is advisory; never fail a save over it
    return path, n


def _load_validated(path: str) -> dict:
    """One decompression pass: load the archive and verify format +
    content CRC. Raises ``CorruptCheckpointError`` (or ValueError for a
    genuine old-format file) — every read path shares this gate."""
    try:
        with np.load(path) as z:
            keys = set(z.files)
            if "format_version" not in keys:
                raise CorruptCheckpointError(
                    f"corrupt/incomplete serving checkpoint {path}: "
                    f"missing format_version"
                )
            # format first: a genuine pre-checksum (v1) file is an
            # old-format error, not a corruption claim. v2 (no
            # feature_reference block) still loads — backward compat.
            version = int(z["format_version"])
            if not MIN_FORMAT_VERSION <= version <= FORMAT_VERSION:
                raise ValueError(
                    f"serving checkpoint format {version} unsupported "
                    f"(this build reads {MIN_FORMAT_VERSION}.."
                    f"{FORMAT_VERSION}) ({path})"
                )
            if "crc32" not in keys:
                raise CorruptCheckpointError(
                    f"corrupt/incomplete serving checkpoint {path}: "
                    f"missing crc32"
                )
            data = {k: z[k] for k in keys}
    except (CorruptCheckpointError, ValueError):
        raise
    except (OSError, zipfile.BadZipFile, zlib.error, KeyError, EOFError) as e:
        # torn/truncated archives surface as any of these from the zip
        # layer (including its per-member CRC check) — name the file
        raise CorruptCheckpointError(
            f"corrupt/incomplete serving checkpoint {path}: {e}"
        ) from e
    stored = int(np.uint32(data["crc32"]))
    actual = _content_crc(data)
    if stored != actual:
        raise CorruptCheckpointError(
            f"corrupt serving checkpoint {path}: content CRC32 "
            f"{actual:#010x} != stored {stored:#010x} (bit flip or torn "
            f"write)"
        )
    return data


def validate(path: str) -> None:
    """Raise ``CorruptCheckpointError`` unless ``path`` is a complete,
    checksum-clean checkpoint of a supported format."""
    _load_validated(path)


def _resolve_and_load(
    directory: str, recorder=None
) -> tuple[str | None, dict | None]:
    """Newest member that validates, WITH its loaded content — so a
    directory restore decompresses the winner exactly once. Each
    invalid member skipped on the way down is a rollback: with a
    ``recorder`` (obs.FlightRecorder) it becomes a structured
    ``checkpoint.rollback`` event naming the rejected file, so the
    post-mortem trail shows that a newer-but-corrupt checkpoint was
    passed over — silent-looking recovery, made auditable."""
    for _, path in list_checkpoints(directory):
        try:
            return path, _load_validated(path)
        except (CorruptCheckpointError, ValueError) as e:
            if recorder is not None:
                recorder.record(
                    "checkpoint.rollback", rejected=path,
                    error=type(e).__name__, detail=str(e),
                )
            continue
    return None, None


def resolve_latest(directory: str) -> str | None:
    """The newest checkpoint in the rotation that passes ``validate`` —
    a torn or bit-flipped newest file means rollback to its predecessor,
    not a crash. None when no valid checkpoint exists."""
    return _resolve_and_load(directory)[0]


def _load_for_restore(path: str, recorder=None):
    """The shared restore prologue: fault site, directory resolution,
    required-key check, native-availability gate. Returns
    ``(resolved_path, content, native)`` — both spine restores build on
    the same validated load."""
    fault_point("serving_ckpt.restore")
    if os.path.isdir(path):
        resolved, z = _resolve_and_load(path, recorder=recorder)
        if resolved is None:
            raise CorruptCheckpointError(
                f"no valid serving checkpoint in directory {path}"
            )
        path = resolved
    else:
        z = _load_validated(path)
    if recorder is not None:
        recorder.record("checkpoint.restore", path=path)
    required = {
        "capacity", "native", "last_time", "tick_floor", "index/slots",
        "index/keys", "index/src", "index/dst", "index/next_slot",
        "index/free", *(f"table/{n}" for n in _TABLE_LEAVES),
    }
    missing = required - z.keys()
    if missing:
        raise CorruptCheckpointError(
            f"corrupt/incomplete serving checkpoint {path}: missing "
            f"entries {sorted(missing)}"
        )
    native = bool(int(z["native"]))
    if native:
        from ..native import engine as native_engine

        if not native_engine.available():
            raise RuntimeError(
                "checkpoint was written by the native (C++) index, which "
                "is unavailable here — its fingerprints are not "
                "compatible with the Python index's keys"
            )
    return path, z, native


def _import_index(eng, z, native: bool) -> None:
    """Rebuild the host flow index (either kind) and the engine clocks
    from checkpoint content. Slot ids are GLOBAL on both spines — the
    sharded engine keys its one index globally — so this is shared."""
    slots = z["index/slots"]
    keys = z["index/keys"]
    next_slot = int(z["index/next_slot"])
    last_time = int(z["last_time"])
    free = z["index/free"]
    if native:
        eng.batcher.import_index(
            slots, keys,
            np.char.encode(z["index/src"]), np.char.encode(z["index/dst"]),
            next_slot, last_time, free,
        )
    else:
        idx = eng.index
        for s, k, src, dst in zip(
            slots, keys, z["index/src"], z["index/dst"]
        ):
            idx.key_to_slot[int(k)] = int(s)
            idx.slot_to_key[int(s)] = int(k)
            idx.slot_meta[int(s)] = (str(src), str(dst))
        idx.free = [int(s) for s in free]
        idx.next_slot = next_slot
    eng._last_time = last_time
    eng._tick_floor = int(z["tick_floor"])


def _reference_block(z) -> dict | None:
    # v3 drift reference (absent in v2 checkpoints): handed back on the
    # engine so the CLI can re-seed the drift monitor — a restored serve
    # must not re-calibrate its reference on already-drifted traffic
    reference = {
        k[len(_REF_PREFIX):]: np.asarray(v)
        for k, v in z.items()
        if k.startswith(_REF_PREFIX)
    }
    return reference or None


def restore(path: str, buckets=None, recorder=None):
    """Rebuild a ``FlowStateEngine`` from ``save`` output. ``path`` may
    be a rotation directory, resolved through ``resolve_latest``.
    ``recorder`` receives rollback/restore events (obs plane)."""
    from ..ingest.batcher import DEFAULT_BUCKETS, FlowStateEngine

    path, z, native = _load_for_restore(path, recorder=recorder)
    eng = FlowStateEngine(
        int(z["capacity"]), buckets=buckets or DEFAULT_BUCKETS,
        native=native,
    )

    leaves = {
        name: jnp.asarray(z[f"table/{name}"]) for name in _TABLE_LEAVES
    }

    def dirstate(side: str) -> ft.DirState:
        return ft.DirState(**{
            f: leaves[f"{side}.{f}"]
            for f in ft.DirState.__dataclass_fields__
        })

    eng.table = ft.FlowTable(
        time_start=leaves["time_start"],
        in_use=leaves["in_use"],
        fwd=dirstate("fwd"),
        rev=dirstate("rev"),
    )

    _import_index(eng, z, native)
    eng.feature_reference = _reference_block(z)
    return eng


def restore_sharded(path: str, mesh, *, predict_fn=None, params=None,
                    table_rows: int = 64, incremental: bool = False,
                    buckets=None, recorder=None):
    """Rebuild a ``ShardedFlowEngine`` from ``save`` output — the same
    spine-agnostic on-disk format: each GLOBAL table leaf scatters back
    to shard g % n_shards at local row g // n_shards (the engine's
    routing invariant), so a checkpoint written by EITHER spine restores
    onto the mesh and a sharded checkpoint restores onto the
    single-device spine through plain ``restore``. The writer's
    native/Python index kind still binds (fingerprints differ). When
    ``incremental``, the cache/dirty pair boots all-dirty, so the first
    render re-predicts every restored row — never a stale label."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..ingest.batcher import DEFAULT_BUCKETS
    from ..parallel import table_sharded as ts
    from ..parallel.mesh import DATA_AXIS

    path, z, native = _load_for_restore(path, recorder=recorder)
    capacity = int(z["capacity"])
    n = mesh.shape[DATA_AXIS]
    if capacity % n:
        raise ValueError(
            f"checkpoint capacity {capacity} does not divide across "
            f"{n} shards ({path})"
        )
    eng = ts.ShardedFlowEngine(
        mesh, capacity, buckets=buckets or DEFAULT_BUCKETS,
        predict_fn=predict_fn, params=params, table_rows=table_rows,
        native=native, incremental=incremental,
    )
    local = capacity // n
    stacked = {}
    for name in _TABLE_LEAVES:
        glob = np.asarray(z[f"table/{name}"])
        arr = np.zeros((n, local + 1) + glob.shape[1:], glob.dtype)
        arr[:, :local] = np.swapaxes(
            glob[:capacity].reshape((local, n) + glob.shape[1:]), 0, 1
        )
        stacked[name] = arr

    def dirstate(side: str) -> ft.DirState:
        return ft.DirState(**{
            f: stacked[f"{side}.{f}"]
            for f in ft.DirState.__dataclass_fields__
        })

    eng.tables = jax.device_put(
        ft.FlowTable(
            time_start=stacked["time_start"],
            in_use=stacked["in_use"],
            fwd=dirstate("fwd"),
            rev=dirstate("rev"),
        ),
        NamedSharding(mesh, P(DATA_AXIS)),
    )
    _import_index(eng, z, native)
    eng.feature_reference = _reference_block(z)
    return eng
