"""Native checkpoint format: versioned orbax directories for every model
family and for resumable training state.

The reference's only persistence is ``pickle.dump`` of sklearn estimators
(notebooks, e.g. ``3_RandomForest.ipynb`` cell 19) loaded by an if-chain at
traffic_classifier.py:229-244 — unversioned, Python-only, and tied to the
exact sklearn build (its own pickles no longer load in modern sklearn,
SURVEY.md §2.2). This module replaces that with:

- ``save_model`` / ``load_model``: any of the six model-family Params
  pytrees → an orbax checkpoint directory plus a JSON manifest carrying the
  format version, model family, class names, and the non-array static
  fields (which are jit-static and must round-trip exactly);
- ``save_train_state`` / ``restore_train_state``: mid-training state
  (params + optimizer state + step) for crash-resume of the streaming
  trainers — the resume-in-training the reference lacks (SURVEY.md §5);
- importers compose: ``load_reference_model`` (sklearn pickle) → ``fit`` →
  ``save_model`` gives a pickle-free, forward-compatible artifact.

Crash safety: the manifest is the checkpoint's COMMIT RECORD. Arrays are
staged first (orbax writes them under a temp name and renames), then the
manifest is written atomically (temp file + fsync + ``os.replace``) —
a crash at any point leaves either the previous complete checkpoint or
the new one, never a directory whose manifest describes arrays that were
only half written. The ``train_ckpt.write`` fault site
(utils/faults.py) sits at the manifest commit so the chaos suite can
kill a save there and prove the previous state still restores.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import os
import shutil
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.atomicio import atomic_write_bytes, sweep_stale_tmp

FORMAT_VERSION = 1
_MANIFEST = "manifest.json"
_ARRAYS = "arrays"
_stage_counter = itertools.count()


def _commit_manifest(path: str, manifest: dict) -> None:
    """Atomically publish the manifest — the save's commit point."""
    atomic_write_bytes(
        os.path.join(path, _MANIFEST),
        json.dumps(manifest, indent=1).encode(),
        pre_rename_site="train_ckpt.write",
    )


def _stage_arrays(path: str, arrays: dict) -> str:
    """Write ``arrays`` under a fresh versioned dir name and return that
    name (manifest-relative). Staging to a new dir — never overwriting
    the dir the current manifest references — is what makes the manifest
    a real commit record: a crash mid-save leaves the old manifest
    pointing at old, complete arrays."""
    rel = f"{_ARRAYS}-{os.getpid()}-{next(_stage_counter)}"
    _checkpointer().save(
        os.path.join(os.path.abspath(path), rel), arrays, force=True
    )
    return rel


def _publish(path: str, manifest: dict, arrays_rel: str) -> None:
    """Commit the manifest, then GC every arrays dir it doesn't
    reference (stale staged dirs from crashed saves, and prior
    generations). On commit failure the staged dir is removed so crashed
    saves don't accumulate garbage."""
    manifest["arrays_dir"] = arrays_rel
    try:
        _commit_manifest(path, manifest)
    except BaseException:
        shutil.rmtree(os.path.join(path, arrays_rel), ignore_errors=True)
        raise
    for name in os.listdir(path):
        if name == arrays_rel:
            continue
        if name == _ARRAYS or name.startswith(f"{_ARRAYS}-"):
            shutil.rmtree(os.path.join(path, name), ignore_errors=True)
    # manifest temps a SIGKILLed predecessor left behind (a real kill
    # skips atomic_write_bytes's cleanup)
    sweep_stale_tmp(path)


def _arrays_dir(path: str, manifest: dict) -> str:
    # pre-durability checkpoints stored arrays at the fixed name
    return os.path.join(
        os.path.abspath(path), manifest.get("arrays_dir", _ARRAYS)
    )


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def _field_names(params) -> list[str]:
    if dataclasses.is_dataclass(params):
        return [f.name for f in dataclasses.fields(params)]
    if hasattr(params, "_fields"):  # NamedTuple (models/kmeans.Params)
        return list(params._fields)
    raise TypeError(f"unsupported params type {type(params)!r}")


def _split_fields(params) -> tuple[dict, dict]:
    """Partition params fields into (arrays, static python values)."""
    arrays, static = {}, {}
    for name in _field_names(params):
        v = getattr(params, name)
        if isinstance(v, (jax.Array, np.ndarray)):
            arrays[name] = np.asarray(v)
        else:
            static[name] = v
    return arrays, static


def save_model(path: str, name: str, params, classes=None) -> None:
    """Write a versioned model checkpoint directory.

    ``name`` is a MODEL_MODULES key (logreg/gnb/kmeans/knn/svc/forest);
    ``classes`` an optional sequence of label names stored for decode.
    """
    from ..models import MODEL_MODULES

    if name not in MODEL_MODULES:
        raise ValueError(f"unknown model family {name!r}")
    arrays, static = _split_fields(params)
    os.makedirs(path, exist_ok=True)
    rel = _stage_arrays(path, arrays)
    manifest = {
        "format_version": FORMAT_VERSION,
        "model": name,
        "static": static,
        "classes": list(classes) if classes is not None else None,
        "dtypes": {k: str(v.dtype) for k, v in arrays.items()},
    }
    _publish(path, manifest, rel)


def load_model(path: str):
    """Read a checkpoint directory → models.LoadedModel."""
    from ..models import MODEL_MODULES, make_loaded_model
    from ..models.base import ClassList

    with open(os.path.join(path, _MANIFEST)) as f:
        manifest = json.load(f)
    if manifest["format_version"] > FORMAT_VERSION:
        raise ValueError(
            f"checkpoint {path} has format_version "
            f"{manifest['format_version']} > supported {FORMAT_VERSION}"
        )
    name = manifest["model"]
    mod = MODEL_MODULES[name]
    raw = _checkpointer().restore(_arrays_dir(path, manifest))
    arrays = {
        k: jnp.asarray(v, dtype=manifest["dtypes"][k])
        for k, v in raw.items()
    }
    params = mod.Params(**arrays, **manifest["static"])
    classes = (
        ClassList(tuple(manifest["classes"]))
        if manifest["classes"]
        else None
    )
    return make_loaded_model(name, params, classes)


def save_train_state(path: str, state: Any, step: int) -> None:
    """Persist an arbitrary training-state pytree (e.g. train.logreg
    SGDState) + step counter for resume."""
    leaves, treedef = jax.tree_util.tree_flatten(state)
    arrays = {f"leaf_{i}": np.asarray(l) for i, l in enumerate(leaves)}
    os.makedirs(path, exist_ok=True)
    rel = _stage_arrays(path, arrays)
    _publish(
        path,
        {
            "format_version": FORMAT_VERSION,
            "kind": "train_state",
            "step": int(step),
            "n_leaves": len(leaves),
            "dtypes": {k: str(v.dtype) for k, v in arrays.items()},
        },
        rel,
    )


def restore_train_state(path: str, template: Any) -> tuple[Any, int]:
    """Restore a training-state pytree into ``template``'s structure.

    ``template`` is a freshly initialized state (same shapes/treedef) —
    the standard orbax restore-with-target pattern.
    """
    with open(os.path.join(path, _MANIFEST)) as f:
        manifest = json.load(f)
    raw = _checkpointer().restore(_arrays_dir(path, manifest))
    leaves_t, treedef = jax.tree_util.tree_flatten(template)
    if len(leaves_t) != manifest["n_leaves"]:
        raise ValueError(
            f"template has {len(leaves_t)} leaves, checkpoint "
            f"{manifest['n_leaves']}"
        )
    leaves = [
        jnp.asarray(raw[f"leaf_{i}"], dtype=manifest["dtypes"][f"leaf_{i}"])
        for i in range(len(leaves_t))
    ]
    return jax.tree_util.tree_unflatten(treedef, leaves), manifest["step"]
