"""The reference's offline training-data pipeline (C12) as a data module.

Mirrors the skeleton every training notebook repeats (SURVEY.md §3.4):
read the per-class CSVs (tab-delimited except game, which is comma-delimited),
concatenate, drop NaN rows (ping has exactly one), drop the 4 cumulative
columns to get the 12 model features, and encode labels alphabetically
(dns=0, game=1, ping=2, quake=3, telnet=4, voice=5 — pandas categorical
codes, ``1_log_Kmeans.ipynb`` cells 26-30).

Note: the notebooks trained on 6 classes (8897 rows) but
``6_quake_training_data.csv`` is absent from the repository (SURVEY.md §2,
C14), so pipelines built from ``datasets/`` see the 5 available classes
(7653 usable rows). Class count is always derived from the data, never
hardcoded.
"""

from __future__ import annotations

import csv
import os
from dataclasses import dataclass

import numpy as np

from ..core.features import (
    CSV_COLUMNS_16,
    FEATURE_INDICES_IN_16,
    LABEL_COLUMN,
    NUM_FEATURES,
)

REFERENCE_DATASET_FILES = {
    "dns": "dns_training_data.csv",
    "game": "game_training_data.csv",
    "ping": "ping_training_data.csv",
    "telnet": "telnet_training_data.csv",
    "voice": "voice_training_data.csv",
    # '6_quake_training_data.csv' (1244 rows) is referenced by the notebooks
    # but missing from the repository; included here so a user who has the
    # file can drop it in and get the full 6-class pipeline.
    "quake": "quake_training_data.csv",
}


def _read_csv(path: str) -> np.ndarray:
    """Read one training CSV into an (n, 16) float array, NaN for blanks.

    Delimiter is sniffed from the header line — the reference's game CSV is
    comma-delimited while the rest are tab-delimited (SURVEY.md §2, C14).
    """
    with open(path, newline="") as f:
        header_line = f.readline()
        delim = "," if header_line.count(",") > header_line.count("\t") else "\t"
        header = [h.strip() for h in header_line.strip().split(delim)]
        expected = list(CSV_COLUMNS_16) + [LABEL_COLUMN]
        if header != expected:
            raise ValueError(f"{path}: unexpected header {header[:3]}…")
        n_feat = len(CSV_COLUMNS_16)
        rows = []
        for rec in csv.reader(f, delimiter=delim):
            if not rec:
                continue
            # Ragged rows exist (ping has one truncated row — the NaN row the
            # notebooks dropna away, SURVEY.md §2 C14): pad to 16 features.
            vals = [
                float(v) if v.strip() != "" else np.nan for v in rec[:n_feat]
            ]
            vals += [np.nan] * (n_feat - len(vals))
            rows.append(vals)
    return np.asarray(rows, dtype=np.float64)


@dataclass(frozen=True)
class FlowDataset:
    """Labeled flow-statistics dataset in notebook feature order."""

    X16: np.ndarray  # (n, 16) full engineered features
    X: np.ndarray  # (n, 12) model features (cumulative cols dropped)
    y: np.ndarray  # (n,) int32 alphabetical label codes
    classes: tuple  # label names, alphabetical

    @property
    def n(self) -> int:
        return self.X.shape[0]


def load_reference_datasets(
    dataset_dir: str, dropna: bool = True
) -> FlowDataset:
    """Load all available per-class CSVs from ``dataset_dir``."""
    per_class = {}
    for label, fname in REFERENCE_DATASET_FILES.items():
        path = os.path.join(dataset_dir, fname)
        if os.path.exists(path):
            per_class[label] = _read_csv(path)
    if not per_class:
        raise FileNotFoundError(f"no training CSVs in {dataset_dir}")

    classes = tuple(sorted(per_class))  # alphabetical == pandas categorical
    X16 = np.concatenate([per_class[c] for c in classes], axis=0)
    y = np.concatenate(
        [np.full(len(per_class[c]), i, dtype=np.int32) for i, c in enumerate(classes)]
    )
    if dropna:
        keep = ~np.isnan(X16).any(axis=1)
        X16, y = X16[keep], y[keep]
    X = X16[:, list(FEATURE_INDICES_IN_16)]
    assert X.shape[1] == NUM_FEATURES
    return FlowDataset(X16=X16, X=X, y=y, classes=classes)


def train_test_split(
    ds: FlowDataset, test_size: float = 0.5, seed: int = 101
) -> tuple[FlowDataset, FlowDataset]:
    """Shuffled split with a fixed numpy PRNG seed.

    Functionally equivalent to the notebooks' 50/50
    ``train_test_split(random_state=101)`` (``1_log_Kmeans.ipynb`` cell 10);
    the permutation differs from sklearn's internal one, so accuracies are
    comparable, not bit-identical.
    """
    rng = np.random.RandomState(seed)
    perm = rng.permutation(ds.n)
    n_test = int(round(ds.n * test_size))
    test_idx, train_idx = perm[:n_test], perm[n_test:]

    def take(idx):
        return FlowDataset(
            X16=ds.X16[idx], X=ds.X[idx], y=ds.y[idx], classes=ds.classes
        )

    return take(train_idx), take(test_idx)
