"""Import the reference's pickled scikit-learn estimators into plain arrays.

The reference ships six fitted sklearn-1.0.1 estimators as raw pickles in
``models/`` (reference: traffic_classifier.py:229-243 loads them by
subcommand). Two of them (KNeighbors, RandomForestClassifier) embed Cython
extension types (``sklearn.neighbors._kd_tree.KDTree``,
``sklearn.tree._tree.Tree``) whose binary layout changed and no longer
unpickles in modern sklearn. We therefore never instantiate sklearn classes at
all: a stub Unpickler intercepts every ``sklearn.*`` global and captures the
constructor args and ``__setstate__`` payload verbatim, and per-model
extractors lift exactly the learned arrays documented in SURVEY.md §2.2 into
plain numpy dicts, ready to become JAX pytrees.

No sklearn import is required to load checkpoints (sklearn is only used by the
test suite for parity checks).
"""

from __future__ import annotations

import io
import pickle
from typing import Any

import numpy as np


class _SkStub:
    """Captures constructor args and pickled state of a sklearn object
    without executing any sklearn code."""

    def __init__(self, *args, **kwargs):
        self._reduce_args = args
        self._reduce_kwargs = kwargs

    def __setstate__(self, state):
        self._raw_state = state
        if isinstance(state, dict):
            self.__dict__.update(state)
        elif isinstance(state, tuple) and len(state) == 2:
            # pickle's 2-tuple state convention: (dict_state, slots_state)
            dict_state, slots_state = state
            if isinstance(dict_state, dict):
                self.__dict__.update(dict_state)
            if isinstance(slots_state, dict):
                self.__dict__.update(slots_state)


_stub_cache: dict[tuple[str, str], type] = {}


def _stub_class(module: str, name: str) -> type:
    key = (module, name)
    cls = _stub_cache.get(key)
    if cls is None:
        cls = type(name, (_SkStub,), {"_sk_module": module, "_sk_name": name})
        _stub_cache[key] = cls
    return cls


class _StubUnpickler(pickle.Unpickler):
    def find_class(self, module: str, name: str):
        if module.split(".")[0] == "sklearn":
            return _stub_class(module, name)
        return super().find_class(module, name)


def load_sklearn_pickle(path: str) -> Any:
    """Unpickle ``path`` with every sklearn class replaced by a stub."""
    with open(path, "rb") as f:
        return _StubUnpickler(io.BytesIO(f.read())).load()


def _classes(est) -> np.ndarray:
    return np.asarray(est.classes_)


# ---------------------------------------------------------------------------
# Per-model extraction → plain dict of numpy arrays (SURVEY.md §2.2 shapes).
# ---------------------------------------------------------------------------


def import_logreg(path: str) -> dict:
    """models/LogisticRegression → coef (C,12), intercept (C,), classes.

    Predict math (sklearn LogisticRegression.predict): argmax of
    ``X @ coef.T + intercept`` — the reference pickle is 4-class
    (classes_ = [dns, ping, telnet, voice]; SURVEY.md §2.2).
    """
    est = load_sklearn_pickle(path)
    return {
        "coef": np.asarray(est.coef_, dtype=np.float64),
        "intercept": np.asarray(est.intercept_, dtype=np.float64),
        "classes": _classes(est),
    }


def import_gnb(path: str) -> dict:
    """models/GaussianNB → theta (C,12), var (C,12), class_prior (C,)."""
    est = load_sklearn_pickle(path)
    var = getattr(est, "var_", None)
    if var is None:  # pre-1.0 pickles call it sigma_
        var = est.sigma_
    return {
        "theta": np.asarray(est.theta_, dtype=np.float64),
        "var": np.asarray(var, dtype=np.float64),
        "class_prior": np.asarray(est.class_prior_, dtype=np.float64),
        "classes": _classes(est),
    }


def import_kmeans(path: str) -> dict:
    """models/KMeans_Clustering → cluster_centers (K,12).

    The reference's checkpoint is the 4-cluster, 4-class era (SURVEY.md §2.2);
    the cluster→label map is handled by the label layer, not here.
    """
    est = load_sklearn_pickle(path)
    return {
        "cluster_centers": np.asarray(est.cluster_centers_, dtype=np.float64),
    }


def import_svc(path: str) -> dict:
    """models/SVC → support_vectors (S,12), dual_coef (C-1,S),
    intercept (C*(C-1)/2,), n_support (C,), gamma.

    Uses the private ``_dual_coef_`` / ``_intercept_`` (the exact arrays
    libsvm's ovo decision uses); sklearn's public ``dual_coef_`` is the
    negation-free view of the same data.
    """
    est = load_sklearn_pickle(path)
    d = est.__dict__
    dual = d.get("_dual_coef_", d.get("dual_coef_"))
    intercept = d.get("_intercept_", d.get("intercept_"))
    n_support = d.get("n_support_", d.get("_n_support"))
    return {
        "support_vectors": np.asarray(est.support_vectors_, dtype=np.float64),
        "dual_coef": np.asarray(dual, dtype=np.float64),
        "intercept": np.asarray(intercept, dtype=np.float64),
        "n_support": np.asarray(n_support, dtype=np.int32),
        "gamma": float(d["_gamma"]),
        "classes": _classes(est),
    }


def import_knn(path: str) -> dict:
    """models/KNeighbors → fit_X (N,12), y (N,), n_neighbors, classes.

    The pickle embeds a KDTree; we deliberately discard it — brute-force
    batched L2 + top-k is the idiomatic TPU replacement (SURVEY.md §2.3).
    """
    est = load_sklearn_pickle(path)
    return {
        "fit_X": np.asarray(est._fit_X, dtype=np.float64),
        "y": np.asarray(est._y, dtype=np.int32),
        "n_neighbors": int(est.n_neighbors),
        "classes": _classes(est),
    }


def _extract_tree(tree_stub) -> dict:
    """Pull the node arrays out of an sklearn.tree._tree.Tree — either a
    stub-unpickled one or a LIVE fitted tree (forest_dict_from_estimator).

    Tree.__reduce__ → (Tree, (n_features, n_classes_arr, n_outputs), state)
    with state = {'max_depth', 'node_count', 'nodes', 'values'}; ``nodes`` is
    a structured array with fields left_child, right_child, feature,
    threshold, impurity, n_node_samples, weighted_n_node_samples. A live
    Cython Tree exposes the same dict through ``__getstate__``.
    """
    state = getattr(tree_stub, "_raw_state", None)
    if state is None:
        state = tree_stub.__getstate__()
    nodes = state["nodes"]
    return {
        "left": np.asarray(nodes["left_child"], dtype=np.int32),
        "right": np.asarray(nodes["right_child"], dtype=np.int32),
        "feature": np.asarray(nodes["feature"], dtype=np.int32),
        "threshold": np.asarray(nodes["threshold"], dtype=np.float64),
        # (node_count, n_outputs=1, n_classes) class-count distributions
        "values": np.asarray(state["values"], dtype=np.float64)[:, 0, :],
        "max_depth": int(state["max_depth"]),
        "node_count": int(state["node_count"]),
    }


def import_forest(path: str) -> dict:
    """models/RandomForestClassifier → ragged per-tree node arrays, padded to
    the max node count so the ensemble is a dense (T, max_nodes, …) stack.

    Padding uses self-loop leaves (left=right=-1) with zero value rows, which
    the tensorized traversal in ops/tree_eval.py treats as inert.
    """
    return forest_dict_from_estimator(load_sklearn_pickle(path))


def forest_dict_from_estimator(est) -> dict:
    """The ``import_forest`` packing for an in-memory fitted
    ``RandomForestClassifier`` — ONE home for the dense-stack layout, so
    tests and tools that fuzz with freshly-fit forests exercise exactly
    the arrays the importer produces (max_depth and n_features derived,
    never hand-set)."""
    trees = [_extract_tree(t.tree_) for t in est.estimators_]
    n_trees = len(trees)
    max_nodes = max(t["node_count"] for t in trees)
    n_classes = trees[0]["values"].shape[1]

    left = np.full((n_trees, max_nodes), -1, dtype=np.int32)
    right = np.full((n_trees, max_nodes), -1, dtype=np.int32)
    feature = np.zeros((n_trees, max_nodes), dtype=np.int32)
    threshold = np.zeros((n_trees, max_nodes), dtype=np.float64)
    values = np.zeros((n_trees, max_nodes, n_classes), dtype=np.float64)
    for i, t in enumerate(trees):
        n = t["node_count"]
        left[i, :n] = t["left"]
        right[i, :n] = t["right"]
        feature[i, :n] = np.maximum(t["feature"], 0)  # leaves store -2
        threshold[i, :n] = t["threshold"]
        values[i, :n] = t["values"]

    return {
        "left": left,
        "right": right,
        "feature": feature,
        "threshold": threshold,
        "values": values,
        "max_depth": max(t["max_depth"] for t in trees),
        "classes": _classes(est),
        "n_features": int(est.n_features_in_),
    }


def f32_safe_thresholds(thr: np.ndarray) -> np.ndarray:
    """Round float64 split thresholds DOWN to float32 so that
    ``x ≤ f32(thr)`` agrees with sklearn's ``f32(x) ≤ f64(thr)`` for every
    float32 x: sklearn stores float64 midpoints of adjacent float32 feature
    values, and a midpoint that rounds *up* under f32 would flip the
    decision for a sample sitting exactly at the upper value."""
    t32 = thr.astype(np.float32)
    round_up = t32.astype(np.float64) > thr
    return np.where(
        round_up, np.nextafter(t32, np.float32(-np.inf)), t32
    ).astype(np.float32)


IMPORTERS = {
    "logreg": import_logreg,
    "gnb": import_gnb,
    "kmeans": import_kmeans,
    "svc": import_svc,
    "knn": import_knn,
    "forest": import_forest,
}

# Reference checkpoint filenames (reference: traffic_classifier.py:230-240).
REFERENCE_CHECKPOINTS = {
    "logreg": "LogisticRegression",
    "gnb": "GaussianNB",
    "kmeans": "KMeans_Clustering",
    "svc": "SVC",
    "knn": "KNeighbors",
    "forest": "RandomForestClassifier",
}
