"""Class-conditional workload generation — the framework's equivalent of
the reference's D-ITG generator scripts (SURVEY.md §2 C15: per-class
VoIP/Quake3/Telnet/CSa/DNS configs driven through Mininet hosts).

Instead of shaping live packets, flows here are *trace-driven*: each
generated conversation belongs to a traffic class, and its per-poll
counter deltas are sampled from that class's rows in the reference
training CSVs (the empirical per-tick delta distribution the real D-ITG
traffic produced). The emitted records speak the monitor line protocol
with cumulative counters, so the whole ingest → flow-table → feature
path computes the same statistics the classifiers were trained on —
making this both a demo workload and a labeled end-to-end accuracy
harness (ground truth is known per flow).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..io.datasets import load_reference_datasets
from .protocol import TelemetryRecord

# features16 column indices (core/features.py CSV_COLUMNS_16 order)
_FWD_DELTA_PKTS, _FWD_DELTA_BYTES = 2, 3
_REV_DELTA_PKTS, _REV_DELTA_BYTES = 10, 11


def class_delta_pools(dataset_dir: str) -> dict[str, np.ndarray]:
    """class name → (M, 4) array of [fwd Δpkts, fwd Δbytes, rev Δpkts,
    rev Δbytes] per-tick deltas observed in that class's CSV rows."""
    ds = load_reference_datasets(dataset_dir)
    names = np.asarray(ds.classes)
    pools = {}
    cols = [_FWD_DELTA_PKTS, _FWD_DELTA_BYTES,
            _REV_DELTA_PKTS, _REV_DELTA_BYTES]
    for ci, name in enumerate(names):
        rows = ds.X16[ds.y == ci]
        pools[str(name)] = np.maximum(rows[:, cols], 0.0)
    return pools


@dataclass
class ClassWorkload:
    """A population of flows, each assigned a traffic class, with deltas
    sampled from the class's empirical pool. Exposes ground truth."""

    pools: dict[str, np.ndarray]
    flows_per_class: int = 8
    seed: int = 0
    start_time: int = 1
    datapath: str = "1"
    labels: list = field(init=False)

    def __post_init__(self):
        self._rng = np.random.RandomState(self.seed)
        self.classes = sorted(self.pools)
        self.labels = [
            c for c in self.classes for _ in range(self.flows_per_class)
        ]
        n = len(self.labels)
        self._cum = np.zeros((n, 4), np.int64)
        self.t = self.start_time

    def _mac(self, i: int, side: int) -> str:
        b = (i * 2 + side + 1).to_bytes(6, "big")
        return ":".join(f"{x:02x}" for x in b)

    def flow_macs(self, i: int) -> tuple[str, str]:
        return self._mac(i, 0), self._mac(i, 1)

    def tick(self) -> list[TelemetryRecord]:
        out = []
        for i, cls in enumerate(self.labels):
            pool = self.pools[cls]
            row = pool[self._rng.randint(len(pool))]
            self._cum[i] += row.astype(np.int64)  # pools are clamped >= 0
            src, dst = self.flow_macs(i)
            out.append(TelemetryRecord(
                time=self.t, datapath=self.datapath, in_port="1",
                eth_src=src, eth_dst=dst, out_port="2",
                packets=int(self._cum[i, 0]), bytes=int(self._cum[i, 1]),
            ))
            out.append(TelemetryRecord(
                time=self.t, datapath=self.datapath, in_port="2",
                eth_src=dst, eth_dst=src, out_port="1",
                packets=int(self._cum[i, 2]), bytes=int(self._cum[i, 3]),
            ))
        self.t += 1
        return out
