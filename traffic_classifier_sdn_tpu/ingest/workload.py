"""Class-conditional workload generation — the framework's equivalent of
the reference's D-ITG generator scripts (SURVEY.md §2 C15: per-class
VoIP/Quake3/Telnet/CSa/DNS configs driven through Mininet hosts).

Instead of shaping live packets, flows here are *trace-driven*: each
generated conversation belongs to a traffic class, and its per-poll
counter deltas are sampled from that class's rows in the reference
training CSVs (the empirical per-tick delta distribution the real D-ITG
traffic produced). The emitted records speak the monitor line protocol
with cumulative counters, so the whole ingest → flow-table → feature
path computes the same statistics the classifiers were trained on —
making this both a demo workload and a labeled end-to-end accuracy
harness (ground truth is known per flow).

Open-world extensions (the F12 rejection tier's test fuel,
serving/openset.py):

- ``synthetic_delta_pools`` — class-shaped pools with no reference
  CSVs (hosts without the dataset tree still exercise the full path);
- ``novel_delta_pool`` — a traffic class the models were NEVER
  trained on: deltas far outside every known pool's range, the
  "unseen application" an open-world serve must reject;
- ``perturb_pools`` — adversarially-perturbed variants of known
  pools: each delta row nudged a bounded ``epsilon`` toward another
  class's mean (the hardest closed-world rows — near the decision
  boundaries — which a calibrated rejection threshold must NOT
  reject);
- ``OpenWorldWorkload`` — a closed-world population that starts
  emitting a novel class mid-stream at a known tick: the replay
  scenario behind the drift-attribution and rejection chaos tests.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..io.datasets import load_reference_datasets
from .protocol import TelemetryRecord

# features16 column indices (core/features.py CSV_COLUMNS_16 order)
_FWD_DELTA_PKTS, _FWD_DELTA_BYTES = 2, 3
_REV_DELTA_PKTS, _REV_DELTA_BYTES = 10, 11


def class_delta_pools(dataset_dir: str) -> dict[str, np.ndarray]:
    """class name → (M, 4) array of [fwd Δpkts, fwd Δbytes, rev Δpkts,
    rev Δbytes] per-tick deltas observed in that class's CSV rows."""
    ds = load_reference_datasets(dataset_dir)
    names = np.asarray(ds.classes)
    pools = {}
    cols = [_FWD_DELTA_PKTS, _FWD_DELTA_BYTES,
            _REV_DELTA_PKTS, _REV_DELTA_BYTES]
    for ci, name in enumerate(names):
        rows = ds.X16[ds.y == ci]
        pools[str(name)] = np.maximum(rows[:, cols], 0.0)
    return pools


def synthetic_delta_pools(
    n_classes: int = 4, rows: int = 512, seed: int = 0,
) -> dict[str, np.ndarray]:
    """Class-shaped synthetic delta pools for hosts without the
    reference CSV tree: class c's per-tick [fwd Δpkts, fwd Δbytes,
    rev Δpkts, rev Δbytes] draw from gamma distributions with
    class-specific scales (rates separated by ~4× per class — cleanly
    separable, like the real per-application traffic mixes)."""
    rng = np.random.RandomState(seed)
    pools = {}
    for c in range(n_classes):
        scale = 4.0 ** c
        pkts = rng.gamma(4.0, 2.0 * scale, (rows, 1))
        ratio = 40.0 + 10.0 * c  # class-specific bytes/packet
        pools[f"class{c}"] = np.concatenate(
            [
                pkts, pkts * ratio,
                pkts * 0.5, pkts * 0.5 * ratio,
            ],
            axis=1,
        ).round()
    return pools


def novel_delta_pool(
    pools: dict[str, np.ndarray], rows: int = 256, seed: int = 0,
    scale: float = 40.0,
) -> np.ndarray:
    """A traffic class the models were never trained on: per-tick
    deltas ``scale``× beyond every known pool's maximum, with an
    inverted forward/reverse ratio no known class exhibits. The
    open-world acceptance fuel: these flows must trip drift (as the
    ``unknown`` class) and keep being rejected after the retrain."""
    rng = np.random.RandomState(seed)
    hi = max(float(p.max()) for p in pools.values()) or 1.0
    base = hi * scale
    pkts = base * (1.0 + rng.rand(rows, 1))
    return np.concatenate(
        # reverse-heavy (known pools are forward-heavy or symmetric)
        [pkts * 0.1, pkts * 0.2, pkts, pkts * 8.0],
        axis=1,
    ).round()


def perturb_pools(
    pools: dict[str, np.ndarray], epsilon: float = 0.2, seed: int = 0,
) -> dict[str, np.ndarray]:
    """Adversarially-perturbed pools: each class's delta rows move a
    bounded fraction ``epsilon`` toward ANOTHER class's mean (the
    round-robin next class) — the boundary-hugging rows that maximize
    closed-world confusion. Ground truth keeps the source class, so
    these measure (a) how much accuracy the perturbation costs and
    (b) that a calibrated open-set threshold does NOT reject them
    (they remain inside the known world's envelope)."""
    if not 0.0 <= epsilon <= 1.0:
        raise ValueError("epsilon must be in [0, 1]")
    rng = np.random.RandomState(seed)
    names = sorted(pools)
    means = {c: pools[c].mean(axis=0) for c in names}
    out = {}
    for i, c in enumerate(names):
        target = means[names[(i + 1) % len(names)]]
        pool = np.asarray(pools[c], np.float64)
        # per-row jittered step bounded by epsilon — rows spread over
        # the whole boundary approach instead of collapsing to a line
        step = epsilon * rng.rand(pool.shape[0], 1)
        out[c] = np.maximum(
            pool + step * (target[None, :] - pool), 0.0
        ).round()
    return out


@dataclass
class ClassWorkload:
    """A population of flows, each assigned a traffic class, with deltas
    sampled from the class's empirical pool. Exposes ground truth.
    ``mac_base`` offsets the generated host addresses so two workloads
    (e.g. a closed-world base and a novel-class injection) can share
    one stream without flow-key collisions."""

    pools: dict[str, np.ndarray]
    flows_per_class: int = 8
    seed: int = 0
    start_time: int = 1
    datapath: str = "1"
    mac_base: int = 0
    labels: list = field(init=False)

    def __post_init__(self):
        self._rng = np.random.RandomState(self.seed)
        self.classes = sorted(self.pools)
        self.labels = [
            c for c in self.classes for _ in range(self.flows_per_class)
        ]
        n = len(self.labels)
        self._cum = np.zeros((n, 4), np.int64)
        self.t = self.start_time

    def _mac(self, i: int, side: int) -> str:
        b = (self.mac_base + i * 2 + side + 1).to_bytes(6, "big")
        return ":".join(f"{x:02x}" for x in b)

    def flow_macs(self, i: int) -> tuple[str, str]:
        return self._mac(i, 0), self._mac(i, 1)

    def tick(self) -> list[TelemetryRecord]:
        out = []
        for i, cls in enumerate(self.labels):
            pool = self.pools[cls]
            row = pool[self._rng.randint(len(pool))]
            self._cum[i] += row.astype(np.int64)  # pools are clamped >= 0
            src, dst = self.flow_macs(i)
            out.append(TelemetryRecord(
                time=self.t, datapath=self.datapath, in_port="1",
                eth_src=src, eth_dst=dst, out_port="2",
                packets=int(self._cum[i, 0]), bytes=int(self._cum[i, 1]),
            ))
            out.append(TelemetryRecord(
                time=self.t, datapath=self.datapath, in_port="2",
                eth_src=dst, eth_dst=src, out_port="1",
                packets=int(self._cum[i, 2]), bytes=int(self._cum[i, 3]),
            ))
        self.t += 1
        return out


@dataclass
class OpenWorldWorkload:
    """A closed-world population that starts emitting a NOVEL traffic
    class mid-stream: ticks before ``novel_start_tick`` are pure
    ``base``; from it on, the ``novel`` population's records ride the
    same stream (disjoint hosts via ``mac_base`` — no flow-key
    collisions). The deterministic replay scenario behind the
    open-world acceptance: calibrate on the closed phase, inject, and
    assert the drift trip attributes the ``unknown`` surge while the
    gate rejects exactly the novel flows (``novel_macs`` is the ground
    truth)."""

    base: ClassWorkload
    novel: ClassWorkload
    novel_start_tick: int = 16

    def __post_init__(self):
        # proper interval check on the generated MAC ranges — a base
        # workload with its own nonzero mac_base must not slip past a
        # zero-anchored guard. Population i occupies the half-open
        # address range [mac_base + 1, mac_base + 2·flows + 1): _mac
        # emits mac_base + 1 .. mac_base + 2·flows, so an exactly
        # adjacent packing (novel.mac_base == base.mac_base + 2·flows)
        # is legal
        b0 = self.base.mac_base + 1
        b1 = b0 + 2 * len(self.base.labels)
        n0 = self.novel.mac_base + 1
        n1 = n0 + 2 * len(self.novel.labels)
        if max(b0, n0) < min(b1, n1):
            raise ValueError(
                "novel workload's mac_base range overlaps the base "
                "population — flow keys would collide"
            )
        self.tick_no = 0

    def novel_macs(self) -> set:
        """The novel population's host addresses — per-flow ground
        truth for 'exactly the unseen flows were rejected'."""
        return {
            mac
            for i in range(len(self.novel.labels))
            for mac in self.novel.flow_macs(i)
        }

    def tick(self) -> list[TelemetryRecord]:
        self.tick_no += 1
        out = self.base.tick()
        if self.tick_no >= self.novel_start_tick:
            out.extend(self.novel.tick())
        return out
