"""Live telemetry collector: runs the OpenFlow monitor as a subprocess and
streams its line protocol without blocking the classify loop.

The reference blocks on ``p.stdout.readline()`` in its single thread
(traffic_classifier.py:147-149), coupling telemetry arrival to classify
latency. Here a reader thread drains the pipe into a queue and the classify
loop takes whatever has arrived per tick — the device never waits on the
pipe (SURVEY.md §2.3: eventlet green threads → host-side thread + device
ring).

Works with any command emitting the protocol: the real Ryu monitor
(``sudo ryu run simple_monitor_13.py``, reference traffic_classifier.py:22),
our fake monitor (tools/fake_monitor.py), or ``cat`` of a capture file.
"""

from __future__ import annotations

import os
import queue
import signal
import subprocess
import threading
import time

from ..utils.faults import FaultInjected, fault_bytes
from .protocol import TelemetryRecord, parse_line, stamp_records

# The reference's monitor launch command (traffic_classifier.py:22).
DEFAULT_MONITOR_CMD = "sudo ryu run simple_monitor_13.py"


class SubprocessCollector:
    """Spawn a monitor command and iterate parsed records."""

    def __init__(self, cmd: str = DEFAULT_MONITOR_CMD, queue_size: int = 1 << 16,
                 raw: bool = False, recorder=None, stamp: bool = False,
                 prov_clock=time.perf_counter):
        """``raw=True`` queues raw pipe chunks (bytes) instead of parsed
        TelemetryRecords — the zero-Python-per-line path for the native
        C++ engine (FlowStateEngine.ingest_bytes). ``recorder`` (an
        obs.FlightRecorder) receives a structured event per dropped-line
        burst, so a post-mortem shows where telemetry was lost.
        ``stamp=True`` emit-stamps each parsed record ON THE READER
        THREAD at pipe-parse time (obs/latency.py provenance — the
        truest host-side proxy for the monitor's emission, capturing
        queue-wait between the pipe and the serve loop; raw mode has no
        records to stamp and degrades to batch-arrival stamping in the
        serve loop)."""
        self.cmd = cmd
        self.raw = raw
        self._stamp = stamp and not raw
        self._prov_clock = prov_clock
        self._recorder = recorder  # set once here, read-only afterwards
        self._queue: queue.Queue = queue.Queue(maxsize=queue_size)
        self._proc: subprocess.Popen | None = None
        self._thread: threading.Thread | None = None
        # Written by the reader thread, read by the classify loop and
        # the supervisor's drain: every access holds _drop_lock
        # (graftlint's lock-discipline rule enforces this statically;
        # an unlocked += is two interpreter ops and can lose increments
        # under free-threaded builds or a mid-statement drain).
        self._drop_lock = threading.Lock()
        self._lines_dropped = 0
        # The reader thread's fault path calls stop(), which writes
        # self._proc = None while the classify loop may be inside
        # running/returncode/stop polling the same handle — a TOCTOU
        # that turns into AttributeError on .pid/.poll. Every _proc
        # access snapshots the handle under this lock; the Popen object
        # itself is thread-safe to poll once you hold a reference.
        self._proc_lock = threading.Lock()
        # stop() is terminal for this collector object (the supervisor
        # spawns a fresh one per incarnation): the flag closes the
        # spawn-vs-stop race now that start() spawns outside the lock
        self._stopped = False

    def start(self) -> None:
        # spawn OUTSIDE the lock: fork/exec can stall on a loaded host,
        # and _proc_lock is taken by running/returncode/stop from other
        # threads — only the handle PUBLICATION needs the lock
        # (graftlint blocking-under-lock surfaced this)
        proc = subprocess.Popen(
            self.cmd,
            shell=True,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            preexec_fn=os.setsid,
        )
        with self._proc_lock:
            published = not self._stopped
            if published:
                self._proc = proc
        if not published:
            # a concurrent stop() won the race while we were spawning:
            # the fresh monitor must not outlive it un-tracked — and
            # with no reader thread coming, WE must close the pipe and
            # reap the child (else: leaked fd + zombie until exit)
            self._kill_group(proc)
            if proc.stdout is not None:
                proc.stdout.close()
            try:
                proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                pass  # SIGTERM ignored: unreaped, but not our hang
            return
        self._thread = threading.Thread(target=self._reader, daemon=True)
        self._thread.start()

    def _reader(self) -> None:
        with self._proc_lock:
            proc = self._proc
        assert proc is not None and proc.stdout is not None
        if self.raw:
            stream = proc.stdout
            drop_seam = False
            while True:
                chunk = stream.read1(1 << 16)
                if not chunk:
                    break
                try:
                    # chaos seam (utils/faults "collector.read"):
                    # "truncate" loses the chunk's tail mid-record — the
                    # same framing hazard as a queue drop, so it poisons
                    # the seam to the NEXT chunk; "raise" kills the
                    # monitor mid-stream (the pipe dies with it),
                    # exercising the supervisor's death→drain→restart path
                    short = fault_bytes("collector.read", chunk)
                except FaultInjected:
                    self.stop()
                    return
                truncated = len(short) != len(chunk)
                if truncated:
                    lost = chunk.count(b"\n") - short.count(b"\n")
                    with self._drop_lock:
                        self._lines_dropped += lost
                    if self._recorder is not None:
                        self._recorder.record(
                            "collector.drop", cause="truncated_chunk",
                            lines=lost,
                        )
                    chunk = short
                if drop_seam:
                    # a dropped/truncated chunk broke line framing: poison
                    # the seam so the fragments on either side of the gap
                    # can't splice into one corrupted-but-parseable
                    # record. A bare "\n" is not enough — it would
                    # *terminate* the pre-gap partial line, letting a
                    # truncated counter parse as a smaller valid value
                    # (garbage negative delta). The NUL makes the pre-gap
                    # fragment unparseable (fails the data-prefix match /
                    # int parse), mirroring the supervisor's restart
                    # poison seam.
                    chunk = b"\x00\n" + chunk
                try:
                    self._queue.put_nowait(chunk)
                    drop_seam = truncated
                except queue.Full:
                    lost = chunk.count(b"\n")
                    with self._drop_lock:
                        self._lines_dropped += lost
                    if self._recorder is not None:
                        self._recorder.record(
                            "collector.drop", cause="queue_full",
                            lines=lost,
                        )
                    drop_seam = True
            return
        for line in proc.stdout:
            r = parse_line(line)
            if r is None:
                continue
            if self._stamp:
                # per line, reader-thread-side: an absorbed obs.stamp
                # fire leaves the record unstamped, never undelivered
                stamp_records((r,), self._prov_clock())
            try:
                self._queue.put_nowait(r)
            except queue.Full:
                # back-pressure: drop oldest-style accounting, keep newest
                with self._drop_lock:
                    self._lines_dropped += 1
                if self._recorder is not None:
                    self._recorder.record(
                        "collector.drop", cause="queue_full", lines=1,
                    )

    @property
    def lines_dropped(self) -> int:
        """Lines lost to queue overflow or injected truncation (same
        counter the pre-lock attribute exposed; the reader thread owns
        the writes, so reads synchronize on the same lock)."""
        with self._drop_lock:
            return self._lines_dropped

    def poll_records(self, max_records: int = 1 << 20) -> list[TelemetryRecord]:
        """Drain whatever has arrived (non-blocking)."""
        out = []
        try:
            while len(out) < max_records:
                out.append(self._queue.get_nowait())
        except queue.Empty:
            pass
        return out

    def wait_record(self, timeout: float) -> TelemetryRecord | None:
        try:
            return self._queue.get(timeout=timeout)
        except queue.Empty:
            return None

    @property
    def running(self) -> bool:
        with self._proc_lock:
            proc = self._proc
        return proc is not None and proc.poll() is None

    @property
    def returncode(self) -> int | None:
        """Exit status of the monitor process (None while running or
        before start)."""
        with self._proc_lock:
            proc = self._proc
        return proc.poll() if proc is not None else None

    @property
    def finished(self) -> bool:
        """Process exited AND the reader thread has drained the pipe to
        EOF — only then is every line the monitor ever wrote in the
        queue. Supervisors must wait for this, not just ``not running``:
        a fast monitor (cat of a capture) exits while megabytes are
        still in flight in the pipe."""
        if self.running:
            return False
        t = self._thread
        return t is None or not t.is_alive()

    def stop(self) -> None:
        """Terminate the monitor's process group (the reference's
        ``os.killpg`` teardown at traffic_classifier.py:222). Terminal:
        a start() racing this stop sees ``_stopped`` and kills its own
        fresh spawn instead of publishing it."""
        with self._proc_lock:
            self._stopped = True
            proc, self._proc = self._proc, None
        if proc is not None:
            self._kill_group(proc)

    @staticmethod
    def _kill_group(proc) -> None:
        if proc.poll() is None:
            try:
                os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
            except ProcessLookupError:
                pass

    def drain(self) -> list:
        """All queued items (records or raw chunks), non-blocking."""
        return self.poll_records()
