"""Failure detection and elastic recovery for the telemetry source.

The reference's failure handling is one ``p.poll()`` check that breaks
the ingest loop (traffic_classifier.py:150-151) — a dead monitor ends the
run. Here a supervisor wraps SubprocessCollector with crash detection,
exponential-backoff restart, and a restart budget, so a wedged or killed
monitor (controller crash, Ryu OOM, switch flap) costs seconds of
telemetry instead of the whole session. Flow state survives restarts: the
device flow table and the C++/Python flow index live in the classifier
process, and counters in the protocol are cumulative, so a restarted
monitor's first poll simply produces one large delta per flow (the same
thing the reference would see after a missed poll).

Restart semantics:
- a monitor that exits **0** finished on purpose (``cat capture.txt``,
  a bounded fake monitor) — no restart, the source just ends
- nonzero exit / signal death → restart after exponential backoff, up to
  ``max_restarts`` times
- records still queued at death are preserved and served before the new
  incarnation's output; in raw mode a ``b"\\x00\\n"`` poison-seam is
  injected so the dead monitor's trailing partial line is rejected by
  the parser (a bare newline would *complete* a truncated record) and
  can never splice with the first chunk of the new one (same framing
  hazard SubprocessCollector._reader guards against on queue overflow)
"""

from __future__ import annotations

import time
from collections import deque

from ..utils.faults import FaultInjected, fault_point
from .collector import SubprocessCollector


class SupervisedCollector:
    """SubprocessCollector with restart-on-crash and backoff.

    Same surface the CLI uses (start/stop/wait_record/poll_records/
    running/lines_dropped) so it drops into _tick_source unchanged.

    ``clock`` injects a monotonic time source so tests can assert the
    exact backoff schedule (base·2^restarts, capped) and the budget
    exhaustion path without real sleeps.
    """

    def __init__(self, cmd: str, raw: bool = False, max_restarts: int = 5,
                 backoff_base: float = 0.5, backoff_cap: float = 30.0,
                 metrics=None, clock=time.monotonic, recorder=None,
                 stamp: bool = False):
        self.cmd = cmd
        self.raw = raw
        # latency-provenance emit stamping, forwarded to every
        # collector incarnation (obs/latency.py)
        self.stamp = stamp
        self.max_restarts = max_restarts
        self.backoff_base = backoff_base
        self.backoff_cap = backoff_cap
        self.restarts = 0
        self._metrics = metrics
        self._clock = clock
        # flight recorder (obs/flight_recorder.py): monitor deaths,
        # restarts, and terminal failure become structured events so a
        # post-mortem dump shows the supervision ladder's last steps
        self._recorder = recorder
        self._collector: SubprocessCollector | None = None
        self._next_restart_at = 0.0
        self._done = False  # clean exit or budget exhausted
        self._stopped = False  # explicit stop(): terminal, overrides all
        self._carryover: deque = deque()  # preserved across restarts
        self._dropped_prior = 0  # lines_dropped from dead incarnations
        # why the supervision ended (None while live): "clean-exit" for
        # a monitor that exited 0, "restart-budget" once the ladder is
        # exhausted, "stopped" for an explicit stop(). The fan-in tier
        # (ingest/fanin.py) reads this to tell a finished replay source
        # from a crashed one — only the latter quarantines a namespace.
        self.terminal_reason: str | None = None

    # -- lifecycle ---------------------------------------------------------
    def _spawn(self) -> SubprocessCollector:
        """Collector factory — the seam chaos tests override to script
        incarnation lifecycles without real subprocesses."""
        return SubprocessCollector(
            self.cmd, raw=self.raw, recorder=self._recorder,
            stamp=self.stamp,
        )

    def start(self) -> None:
        self._collector = self._spawn()
        self._collector.start()

    def stop(self) -> None:
        """Terminal: ``running`` is False from here on, and ``_check``
        will never resurrect the monitor (without ``_done`` a subsequent
        ``wait_record`` would see a killed collector and restart it)."""
        self._done = True
        self._stopped = True
        if self.terminal_reason is None:
            self.terminal_reason = "stopped"
        if self._collector is not None:
            self._collector.stop()

    @property
    def lines_dropped(self) -> int:
        now = self._collector.lines_dropped if self._collector else 0
        return self._dropped_prior + now

    @property
    def running(self) -> bool:
        """True while the monitor runs OR a restart is still possible OR
        preserved records remain — the caller's loop condition. An
        explicit ``stop()`` is terminal regardless (preserved records
        stay drainable via ``poll_records``, but a caller polling
        ``running`` as its loop condition must terminate)."""
        if self._stopped:
            return False
        if self._carryover:
            return True
        if self._collector is not None and self._collector.running:
            return True
        return not self._done

    @property
    def phase(self) -> str:
        """Coarse supervision phase for per-source state reporting
        (fan-in roster, /healthz): ``running`` while the current monitor
        incarnation is alive, ``backoff`` between a death and its
        restart, ``done`` once supervision ended (clean exit, budget
        exhaustion, or explicit stop — ``terminal_reason`` says which).
        Reads only what the caller's own poll thread mutates, so it is
        safe from the thread that drives wait_record/poll_records."""
        if self._stopped or self._done:
            return "done"
        if self._collector is not None and self._collector.running:
            return "running"
        return "backoff"

    # -- supervision -------------------------------------------------------
    def _check(self) -> None:
        """Detect a dead monitor and restart it after backoff.

        Death is declared only once the collector is ``finished`` — the
        process exited AND its reader thread hit pipe EOF — so the drain
        below is complete by construction (no race with late chunks: a
        fast monitor can exit while most of its output is still in the
        pipe buffer). The dead incarnation is torn down immediately and
        exactly once, which also keeps lines_dropped single-counted."""
        if self._done:
            return
        c = self._collector
        now = self._clock()
        if c is not None:
            if not c.finished:
                return  # alive, or reader still draining the pipe
            self._carryover.extend(c.drain())
            self._dropped_prior += c.lines_dropped
            rc = c.returncode
            if self.raw:
                # poison + seam: a NUL makes the dead monitor's trailing
                # partial line unparseable (a bare \n would *complete* a
                # truncated record, e.g. a half-written byte counter),
                # and the \n stops it splicing with the new monitor's
                # first bytes
                self._carryover.append(b"\x00\n")
            c.stop()
            self._collector = None
            if rc == 0:
                self._done = True
                self.terminal_reason = "clean-exit"
                if self._recorder is not None:
                    self._recorder.record(
                        "monitor.clean_exit",
                        lines_dropped=self._dropped_prior,
                    )
                return
            if self._recorder is not None:
                self._recorder.record(
                    "monitor.death", returncode=rc,
                    restarts=self.restarts,
                    lines_dropped=self._dropped_prior,
                )
            if self.restarts >= self.max_restarts:
                self._done = True
                self.terminal_reason = "restart-budget"
                if self._recorder is not None:
                    self._recorder.record(
                        "supervisor.terminal",
                        reason="restart budget exhausted",
                        restarts=self.restarts,
                        max_restarts=self.max_restarts,
                        lines_dropped=self._dropped_prior,
                    )
                return
            delay = min(
                self.backoff_cap, self.backoff_base * (2 ** self.restarts)
            )
            self._next_restart_at = now + delay
            if self._metrics is not None:
                self._metrics.inc("monitor_deaths")
            return
        # collector already torn down: waiting out the backoff
        if now < self._next_restart_at:
            return
        self._next_restart_at = 0.0
        self.restarts += 1
        if self._metrics is not None:
            self._metrics.inc("monitor_restarts")
        if self._recorder is not None:
            self._recorder.record(
                "monitor.restart", attempt=self.restarts,
                max_restarts=self.max_restarts,
            )
        try:
            fault_point("supervisor.restart")
            self.start()
        except (FaultInjected, OSError, RuntimeError) as e:
            # spawn failure — injected (chaos) or real (Popen EMFILE/
            # ENOMEM, Thread.start): the attempt consumed a budget slot;
            # either give up (budget spent) or back off and try again —
            # the same ladder a crashing incarnation climbs
            if not isinstance(e, FaultInjected):
                import sys

                print(f"WARNING: monitor restart failed: {e}",
                      file=sys.stderr)
            self._collector = None
            if self._recorder is not None:
                self._recorder.record(
                    "monitor.spawn_failed", attempt=self.restarts,
                    error=type(e).__name__, detail=str(e),
                )
            if self.restarts >= self.max_restarts:
                self._done = True
                self.terminal_reason = "restart-budget"
                if self._recorder is not None:
                    self._recorder.record(
                        "supervisor.terminal",
                        reason="restart budget exhausted (spawn failure)",
                        restarts=self.restarts,
                        max_restarts=self.max_restarts,
                        lines_dropped=self._dropped_prior,
                    )
                return
            self._next_restart_at = now + min(
                self.backoff_cap, self.backoff_base * (2 ** self.restarts)
            )

    # -- collector surface -------------------------------------------------
    def wait_record(self, timeout: float):
        self._check()
        if self._carryover:
            return self._carryover.popleft()
        if self._collector is None:
            time.sleep(min(timeout, 0.05))
            return None
        rec = self._collector.wait_record(timeout=timeout)
        if rec is None:
            self._check()
            if self._carryover:
                return self._carryover.popleft()
        return rec

    def poll_records(self, max_records: int = 1 << 20):
        out = []
        while self._carryover and len(out) < max_records:
            out.append(self._carryover.popleft())
        if self._collector is not None and len(out) < max_records:
            out.extend(self._collector.poll_records(max_records - len(out)))
        return out
