"""Replay and synthetic telemetry sources — the first-class test seam the
reference lacks (SURVEY.md §4b: the line protocol at simple_monitor_13.py:66
is trivially fakeable; here it is an explicit interface).

Sources yield ``TelemetryRecord`` batches grouped by poll tick, so the whole
ingest→classify path runs without Mininet/OVS/Ryu: from a recorded monitor
capture, or from a synthetic flow population (used by benchmarks to generate
millions of concurrent flows).
"""

from __future__ import annotations

from dataclasses import dataclass
from collections.abc import Iterator

import numpy as np

from .protocol import TelemetryRecord, parse_line


def iter_capture(path: str) -> Iterator[list[TelemetryRecord]]:
    """Replay a recorded monitor stdout capture, yielding one list of
    records per poll timestamp (lines with equal time field)."""
    tick: list[TelemetryRecord] = []
    current_t = None
    with open(path, "rb") as f:
        for line in f:
            r = parse_line(line)
            if r is None:
                continue
            if current_t is not None and r.time != current_t and tick:
                yield tick
                tick = []
            current_t = r.time
            tick.append(r)
    if tick:
        yield tick


def iter_capture_bytes(path: str) -> Iterator[tuple[bytes, int]]:
    """Raw-wire replay for the native ingest path: yields ``(payload,
    n_records)`` per poll tick — the SAME tick boundaries as
    ``iter_capture`` (the time field of valid telemetry lines), but the
    payload is the capture's original line bytes, so the C++ parser sees
    exactly what was recorded and the record streams of the two
    iterators are identical (the byte-identity anchor for native-ingest
    fan-in). Invalid lines are dropped here like ``iter_capture`` drops
    them — the validation already ran to find the tick boundary."""
    tick: list[bytes] = []
    current_t = None
    with open(path, "rb") as f:
        for line in f:
            r = parse_line(line)
            if r is None:
                continue
            if current_t is not None and r.time != current_t and tick:
                yield b"".join(tick), len(tick)
                tick = []
            current_t = r.time
            if not line.endswith(b"\n"):
                line += b"\n"  # final capture line may lack the newline
            tick.append(line)
    if tick:
        yield b"".join(tick), len(tick)


@dataclass
class SyntheticFlows:
    """A population of bidirectional flows with per-class-like rate
    characteristics, emitted in the monitor's line protocol semantics
    (cumulative counters, 1 Hz polls).

    Each conversation produces two records per tick (one per direction),
    mimicking what the monitor logs for the two learned-switch flow entries
    of a host pair (simple_monitor_13.py:49-66).

    ``churn`` controls the per-tick updated-flow fraction: each tick a
    seeded random subset of ``round(churn * n_flows)`` conversations
    emits telemetry (counters advance), the rest stay silent — the knob
    behind the incremental-serving dirty sweep
    (tools/bench_serve.py --churn-fraction). At the default 1.0 the
    emission order and RNG consumption are unchanged from the
    historical all-flows-every-tick behavior.

    ``mac_base`` offsets the conversation index inside the 48-bit MAC
    space: N fan-in sources with disjoint bases emit disjoint host
    populations (ingest/fanin.py's multi-source load generator), so the
    aggregate looks like N real switches, not N copies of one. The
    default 0 reproduces the historical addresses exactly.
    """

    n_flows: int
    seed: int = 0
    start_time: int = 1
    churn: float = 1.0
    mac_base: int = 0

    def __post_init__(self):
        rng = np.random.RandomState(self.seed)
        self.pps_fwd = rng.gamma(2.0, 50.0, self.n_flows)
        self.pps_rev = rng.gamma(2.0, 40.0, self.n_flows)
        self.bpp_fwd = rng.uniform(60, 1400, self.n_flows)
        self.bpp_rev = rng.uniform(60, 1400, self.n_flows)
        self.cum_pkts_fwd = np.zeros(self.n_flows, np.int64)
        self.cum_bytes_fwd = np.zeros(self.n_flows, np.int64)
        self.cum_pkts_rev = np.zeros(self.n_flows, np.int64)
        self.cum_bytes_rev = np.zeros(self.n_flows, np.int64)
        self.t = self.start_time
        self._rng = rng

    def _mac(self, i: int, side: int) -> str:
        b = ((self.mac_base + i) * 2 + side).to_bytes(6, "big")
        return ":".join(f"{x:02x}" for x in b)

    def _active(self) -> np.ndarray:
        """This tick's emitting conversations (sorted, seeded)."""
        if self.churn >= 1.0:
            return np.arange(self.n_flows)
        k = int(round(self.churn * self.n_flows))
        if k <= 0:
            return np.empty(0, np.int64)
        return np.sort(self._rng.choice(self.n_flows, k, replace=False))

    def tick(self) -> list[TelemetryRecord]:
        act = self._active()
        dp = np.int64(self.pps_fwd[act] * self._rng.poisson(1.0, act.size))
        self.cum_pkts_fwd[act] += dp
        self.cum_bytes_fwd[act] += np.int64(dp * self.bpp_fwd[act])
        dr = np.int64(self.pps_rev[act] * self._rng.poisson(1.0, act.size))
        self.cum_pkts_rev[act] += dr
        self.cum_bytes_rev[act] += np.int64(dr * self.bpp_rev[act])
        out = []
        for i in (int(j) for j in act):
            src, dst = self._mac(i, 0), self._mac(i, 1)
            out.append(TelemetryRecord(
                time=self.t, datapath="1", in_port="1", eth_src=src,
                eth_dst=dst, out_port="2",
                packets=int(self.cum_pkts_fwd[i]),
                bytes=int(self.cum_bytes_fwd[i]),
            ))
            out.append(TelemetryRecord(
                time=self.t, datapath="1", in_port="2", eth_src=dst,
                eth_dst=src, out_port="1",
                packets=int(self.cum_pkts_rev[i]),
                bytes=int(self.cum_bytes_rev[i]),
            ))
        self.t += 1
        return out

    def tick_bytes(self) -> bytes:
        """One tick rendered straight to the monitor wire format — the
        bulk path for scale tests (2²⁰ flows): building TelemetryRecord
        objects per flow would dominate; this emits one bytes blob for
        ``FlowStateEngine.ingest_bytes``/the C++ engine."""
        act = self._active()
        dp = np.int64(self.pps_fwd[act] * self._rng.poisson(1.0, act.size))
        self.cum_pkts_fwd[act] += dp
        self.cum_bytes_fwd[act] += np.int64(dp * self.bpp_fwd[act])
        dr = np.int64(self.pps_rev[act] * self._rng.poisson(1.0, act.size))
        self.cum_pkts_rev[act] += dr
        self.cum_bytes_rev[act] += np.int64(dr * self.bpp_rev[act])
        if not hasattr(self, "_mac_cache"):
            self._mac_cache = [
                (self._mac(i, 0), self._mac(i, 1))
                for i in range(self.n_flows)
            ]
        t = self.t
        parts = []
        pf, bf = self.cum_pkts_fwd, self.cum_bytes_fwd
        pr, br = self.cum_pkts_rev, self.cum_bytes_rev
        for i in act:
            src, dst = self._mac_cache[i]
            parts.append(
                f"data\t{t}\t1\t1\t{src}\t{dst}\t2\t{pf[i]}\t{bf[i]}\n"
                f"data\t{t}\t1\t2\t{dst}\t{src}\t1\t{pr[i]}\t{br[i]}\n"
            )
        self.t += 1
        return "".join(parts).encode()
