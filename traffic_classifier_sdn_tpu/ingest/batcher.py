"""Host-side control plane: slot assignment, direction folding, and padded
update batches for the device flow table.

This is the TPU-era replacement for the reference's per-line dict mutation
loop (traffic_classifier.py:144-171). The host only decides *where* each
record goes (slot index + direction + create flag — cheap string/dict work);
all counter math happens on device in ``flow_table.apply_batch``.

Batches are padded to bucketed sizes (powers of two) so XLA compiles one
program per bucket instead of one per batch length (SURVEY.md §7 hard
part e), and the device state is donated between steps so updates are
in-place in HBM.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from collections.abc import Iterable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core import flow_table as ft
from .protocol import TelemetryRecord, stable_flow_key

_U32 = np.uint64(0xFFFFFFFF)


def bucket_size(n: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


def batch_emit_ts(batch) -> float | None:
    """The emit stamp of a direct-source poll batch, for the latency
    plane (obs/latency.py): a poll batch is stamped as one unit at its
    pump-read moment (``protocol.stamp_records``), so the first
    record's stamp speaks for the batch — one attribute read instead
    of an O(records) min-scan on the hot path. None for raw byte
    batches (the native fast path has no records host-side) and for
    unstamped batches; the caller degrades to its arrival clock."""
    if isinstance(batch, (bytes, bytearray)) or not batch:
        return None
    return getattr(batch[0], "emit_ts", None)


@dataclass
class SlotAssignment:
    slot: int
    is_fwd: bool
    is_create: bool


@dataclass
class FlowIndex:
    """key → slot map with direction folding (reference :157-165).

    Keys are namespaced by the record's telemetry source
    (``protocol.stable_flow_key(source=)``): the fan-in tier stamps
    each record with its source id, so N sources reporting identical
    flow tuples occupy N disjoint slot populations. ``slot_source``
    remembers each slot's namespace — the reverse map behind
    namespace-scoped eviction (a dead source's quarantine clears its
    own slots and no one else's). Slots that predate source tracking
    (restored checkpoints) read as source 0, the default namespace.
    """

    capacity: int
    key_to_slot: dict = field(default_factory=dict)
    slot_to_key: dict = field(default_factory=dict)
    slot_meta: dict = field(default_factory=dict)  # slot → (src, dst) for UI
    slot_source: dict = field(default_factory=dict)  # slot → source id
    free: list = field(default_factory=list)
    next_slot: int = 0

    def assign(self, r: TelemetryRecord) -> SlotAssignment | None:
        """Route one record; None when the table is full (the record is
        dropped, counted by the caller)."""
        key = stable_flow_key(r.datapath, r.eth_src, r.eth_dst, r.source)
        slot = self.key_to_slot.get(key)
        if slot is not None:
            return SlotAssignment(slot, True, False)
        rev_key = stable_flow_key(
            r.datapath, r.eth_dst, r.eth_src, r.source
        )
        slot = self.key_to_slot.get(rev_key)
        if slot is not None:
            return SlotAssignment(slot, False, False)
        if self.free:
            slot = self.free.pop()
        elif self.next_slot < self.capacity:
            slot = self.next_slot
            self.next_slot += 1
        else:
            return None
        self.key_to_slot[key] = slot
        self.slot_to_key[slot] = key
        self.slot_meta[slot] = (r.eth_src, r.eth_dst)
        if r.source:
            # sparse by design: the default namespace stays implicit so
            # single-source serves pay nothing (and restored indexes,
            # which predate source tracking, need no migration)
            self.slot_source[slot] = r.source
        return SlotAssignment(slot, True, True)

    def slots_for_source(self, source: int) -> list[int]:
        """Every live slot in ``source``'s namespace — the eviction set
        when that source's quarantine expires. O(tracked flows), but
        only walked on a source-death event, never per tick. Source 0
        (the default namespace) is the complement of the tagged slots."""
        if source:
            return [
                s for s, sid in self.slot_source.items() if sid == source
            ]
        return [
            s for s in self.slot_to_key if s not in self.slot_source
        ]

    def release_slot(self, slot: int) -> None:
        key = self.slot_to_key.pop(slot, None)
        if key is not None:
            self.key_to_slot.pop(key, None)
            self.slot_meta.pop(slot, None)
            self.slot_source.pop(slot, None)
            self.free.append(slot)

    def release_slots(self, slots) -> None:
        for s in slots:
            self.release_slot(int(s))


# Top bucket covers a full 2²⁰-record tick in ONE flush: each flush costs
# a device-link dispatch round trip (~65 ms on this rig's remote tunnel),
# so at the million-flow scale fewer, larger scatters beat many small ones.
DEFAULT_BUCKETS = (256, 1024, 4096, 16384, 65536, 262144, 1048576)


class Batcher:
    """Accumulates records for one poll tick and materializes a padded
    ``UpdateBatch``.

    Per (slot, direction) a batch can hold one create row *and* one update
    row (``apply_batch`` applies creates first, so this reproduces the
    reference's sequential create→update within one poll). A *third*
    same-direction record in one tick (two pending updates) cannot be
    expressed in a single scatter; ``add`` refuses it and the engine
    flushes the partial batch first, preserving exact sequential
    semantics."""

    def __init__(self, index: FlowIndex, buckets=DEFAULT_BUCKETS):
        self.index = index
        self.buckets = tuple(buckets)
        self.dropped = 0
        # (slot, is_fwd) → {"create": rec|None, "update": rec|None}
        self._pending: dict = {}

    def add(self, r: TelemetryRecord) -> bool:
        """True if accepted; False if the caller must flush() first (a
        same-direction update is already pending for this flow)."""
        a = self.index.assign(r)
        if a is None:
            self.dropped += 1
            return True
        entry = self._pending.setdefault(
            (a.slot, a.is_fwd), {"create": None, "update": None}
        )
        if a.is_create:
            entry["create"] = r
        elif entry["update"] is None:
            entry["update"] = r
        else:
            return False
        return True

    def __len__(self) -> int:
        return sum(
            (e["create"] is not None) + (e["update"] is not None)
            for e in self._pending.values()
        )

    def last_flush_was_conflict(self) -> bool:
        """Always False: one drain of this batcher's pending dict holds at
        most one create and one update row per (slot, direction), however
        many bucket-capped batches it spans — so consecutive flushes
        within a drain are always safe to coalesce into one scatter.
        (The native engine's generations CAN conflict; its override
        returns the real flag — see NativeBatcher.)"""
        return False

    def flush(self) -> ft.UpdateBatch | None:
        """Materialize up to one largest-bucket batch and clear what it
        consumed; None when empty. Rows beyond the largest bucket stay
        pending — call again until None (engine.step loops). Per-slot
        create rows always precede their update row across the split, so
        sequential semantics hold."""
        rows = []  # (slot, fwd, rec, is_create)
        for (s, fwd), e in self._pending.items():
            if e["create"] is not None:
                rows.append((s, fwd, e["create"], True))
            if e["update"] is not None:
                rows.append((s, fwd, e["update"], False))
        if not rows:
            return None
        self._pending.clear()
        if len(rows) > self.buckets[-1]:
            for s, fwd, r, create in rows[self.buckets[-1] :]:
                entry = self._pending.setdefault(
                    (s, fwd), {"create": None, "update": None}
                )
                entry["create" if create else "update"] = r
            rows = rows[: self.buckets[-1]]
        size = bucket_size(len(rows), self.buckets)
        slot = np.full(size, self.index.capacity, np.int32)  # scratch row pad
        time = np.zeros(size, np.int32)
        pkts_lo = np.zeros(size, np.uint32)
        pkts_f = np.zeros(size, np.float32)
        bytes_lo = np.zeros(size, np.uint32)
        bytes_f = np.zeros(size, np.float32)
        is_fwd = np.ones(size, bool)
        is_create = np.zeros(size, bool)
        for i, (s, fwd, r, create) in enumerate(rows):
            slot[i] = s
            time[i] = r.time
            pkts_lo[i] = np.uint64(r.packets) & _U32
            pkts_f[i] = np.float32(r.packets)
            bytes_lo[i] = np.uint64(r.bytes) & _U32
            bytes_f[i] = np.float32(r.bytes)
            is_fwd[i] = fwd
            is_create[i] = create
        return ft.UpdateBatch(
            slot=slot, time=time, pkts_lo=pkts_lo, pkts_f=pkts_f,
            bytes_lo=bytes_lo, bytes_f=bytes_f, is_fwd=is_fwd,
            is_create=is_create,
        )


# Donated so XLA updates the table in-place in HBM between poll ticks.
# The batch crosses as one packed (B, 4) compact or (B, 6) full uint32
# buffer (flow_table.pack_wire chooses per batch) and unpacks on device —
# one transfer per flush instead of eight. Public (not ``_apply``): the
# AOT warmup (serving/warmup.py) must prime THIS callable's compile
# cache per bucket shape — a separately-jitted apply_wire would warm a
# different cache and leave the first-tick stall in place.
apply_wire_jit = jax.jit(ft.apply_wire, donate_argnums=0)

# The dirty-tracking variant (incremental serving): the same scatter plus
# the per-slot dirty-bit update, fused so the packed wire crosses the
# link once. Both donated — table and dirty mask update in place in HBM.
apply_wire_dirty_jit = jax.jit(ft.apply_wire_dirty, donate_argnums=(0, 1))

# Eviction with cache invalidation fused in (see flow_table.clear_slots_dirty).
clear_slots_dirty_jit = jax.jit(ft.clear_slots_dirty, donate_argnums=1)


class HostSpine:
    """The shared host half of a serving spine — batcher/index wiring,
    record + raw-byte ingest (native C++ or Python fallback), the tick
    clock, and slot-metadata lookups. ``FlowStateEngine`` (single device)
    and ``parallel.table_sharded.ShardedFlowEngine`` (mesh-sharded) both
    build on this; each owns its device half (step/predict/render/evict).
    Subclass must call ``_init_spine`` and define ``step()``."""

    def _init_spine(self, capacity: int, buckets, native: bool) -> None:
        self.native = native
        if native:
            from ..native.engine import NativeBatcher

            self.index = None
            self.batcher = NativeBatcher(capacity, buckets)
        else:
            self.index = FlowIndex(capacity)
            self.batcher = Batcher(self.index, buckets)
        self.buckets = buckets
        # partial lines carried across ingest_bytes calls, PER SOURCE:
        # the fan-in raw path interleaves byte chunks from N sources,
        # and one source's half line must never be completed by another
        # source's next chunk (the native engine keeps the same map)
        self._tails: dict[int, bytes] = {}
        # native flush_wire dispatches in flight since the last device
        # sync — the step() staging-overwrite guard's cross-call state
        self._staged_flushes = 0
        # malformed-telemetry accounting for the Python fallback parser
        # ('data'-prefixed lines parse_line rejected) — the counterpart
        # of the C++ engine's per-source parse-error counters, so
        # native_parse_errors reads the same on either path
        self._parse_errors: dict[int, int] = {}
        self._last_time = 0
        # cumulative host→device update-batch bytes (padded wire matrices)
        # — lets serving benches report what actually crosses the link
        self.wire_bytes = 0
        # freshness floor for the activity-ranked render sample: flows
        # with telemetry newer than this count as active (see mark_tick)
        self._tick_floor = 0

    def ingest(self, records: Iterable[TelemetryRecord]) -> int:
        n = 0
        for r in records:
            if not self.batcher.add(r):
                # third same-direction record this tick: apply what we have,
                # then retry — keeps per-line sequential semantics exact
                self.step()
                self.batcher.add(r)
            if r.time > self._last_time:
                self._last_time = r.time
            n += 1
        return n

    @property
    def last_time(self) -> int:
        """Max telemetry timestamp ingested — the idle-eviction clock."""
        if self.native:
            return max(self._last_time, self.batcher.last_time)
        return self._last_time

    def ingest_bytes(self, data: bytes, source: int = 0) -> int:
        """Bulk raw-byte ingest (monitor pipe chunks). On the native path
        this never crosses into Python per line; the fallback parses with
        protocol.parse_line. ``source`` is the fan-in namespace the bytes
        belong to (0 = the legacy/default namespace) — the raw wire
        carries no source field, so the delivery path supplies it.
        Returns records parsed."""
        if self.native:
            return self.batcher.feed(data, source)
        from dataclasses import replace

        from .protocol import PREFIX, parse_line

        data = self._tails.get(source, b"") + data
        # split on \n only (not universal newlines) — same framing as the
        # native engine; the final element is the partial-line tail
        parts = data.split(b"\n")
        self._tails[source] = parts.pop()
        n = 0
        for line in parts:
            r = parse_line(line + b"\n")
            if r is not None:
                if source:
                    r = replace(r, source=source)
                self.ingest([r])
                n += 1
            elif line.startswith(PREFIX):
                # telemetry-shaped but unparseable = malformed (noise
                # lines are free) — mirror the C++ engine's accounting
                self._parse_errors[source] = (
                    self._parse_errors.get(source, 0) + 1
                )
        return n

    def parse_errors(self, source: int | None = None) -> int:
        """Malformed telemetry lines rejected by the parser (total, or
        one source's) — native and Python paths count identically."""
        if self.native:
            return self.batcher.parse_errors(source)
        if source is None:
            return sum(self._parse_errors.values())
        return self._parse_errors.get(source, 0)

    @property
    def dropped(self) -> int:
        return self.batcher.dropped

    def num_flows(self) -> int:
        """Tracked (in-use) flow count — O(1) host work."""
        if self.native:
            return self.batcher.num_flows()
        return len(self.index.slot_meta)

    def mark_tick(self) -> None:
        """Snapshot the freshness floor for the activity-ranked render —
        call at the START of each poll tick (before ingesting its
        records). Flows with telemetry strictly newer than the floor count
        as active; the snapshot is the max timestamp of all *previous*
        ticks, so skew between datapaths reporting within one tick cannot
        demote a busy flow. Never calling it degrades the ranking to
        all-time activity."""
        self._tick_floor = self.last_time

    @property
    def tick_floor(self) -> int:
        """The activity-ranking freshness floor snapped by the last
        ``mark_tick`` — the read-dispatch path (serving/pipeline.py)
        needs it to rank against exactly this tick's floor."""
        return self._tick_floor

    def _slot_meta_for(self, slots) -> dict:
        """slot → (eth_src, eth_dst) for exactly the given slots."""
        if self.native:
            out = {}
            for s in slots:
                meta = self.batcher.slot_meta(int(s))
                if meta is not None:
                    out[int(s)] = meta
            return out
        return {
            int(s): self.index.slot_meta[s]
            for s in slots
            if s in self.index.slot_meta
        }

    def step(self) -> bool:  # pragma: no cover - interface
        raise NotImplementedError


class FlowStateEngine(HostSpine):
    """The full host↔device ingest spine: records in, feature matrix out.

    Replaces the reference's ``run_ryu`` inner loop + ``flows`` dict
    (traffic_classifier.py:144-171) — but where the reference touches every
    flow object per line in Python, this applies one scatter per poll tick
    and keeps all state device-resident.
    """

    def __init__(self, capacity: int, buckets=DEFAULT_BUCKETS,
                 native: bool = False, track_dirty: bool = False):
        self.table = ft.make_table(capacity)
        self.dirty = None
        # obs/device.DeviceTelemetry.note_donation when the device plane
        # is armed (cli.py): per-apply reconciliation of expected vs
        # observed buffer reuse on the donated wire scatter. None = the
        # probe costs one attribute load per apply.
        self.donation_probe = None
        self._init_spine(capacity, buckets, native)
        if track_dirty:
            self.enable_dirty_tracking()

    def enable_dirty_tracking(self) -> None:
        """Start maintaining the per-slot dirty mask the incremental
        predict path consumes (serving/incremental.py). Initialized
        ALL-dirty: whatever the table already holds (a restored
        checkpoint, pre-enable ingest) predates the label cache, so the
        first incremental render must re-predict everything."""
        self.dirty = jnp.ones(self.table.capacity + 1, bool)

    def top_slots(self, n: int) -> list[int]:
        """Slots of the ≤n most active flows this tick, most active first
        (device ``top_k`` over |Δbytes|, gated to slots with telemetry
        newer than the ``mark_tick`` floor; see
        flow_table.top_active_slots). The UI sample follows live traffic
        instead of insertion order."""
        n = min(n, self.table.capacity)
        if n <= 0:
            return []
        idx, valid = ft.top_active_slots(
            self.table, n, np.int32(self._tick_floor)
        )
        idx = np.asarray(idx)
        return [int(s) for s in idx[np.asarray(valid)]]

    def render_sample(self, labels, n: int) -> list[tuple]:
        """Activity-ranked render rows with O(n) host transfer:
        ``(slot, label, fwd_active, rev_active)`` for the ≤n most active
        flows this tick, most active first. ``labels`` is the (capacity,)
        device vector from a full-table predict — it never crosses to the
        host (a whole-vector fetch at capacity 2²⁰ costs more tunnel time
        than the device predict; see flow_table.top_active_render)."""
        n = min(n, self.table.capacity)
        if n <= 0:
            return []
        idx, valid, lab, fa, ra = ft.top_active_render(
            self.table, labels, n, np.int32(self._tick_floor)
        )
        idx, valid = np.asarray(idx), np.asarray(valid)
        lab, fa, ra = np.asarray(lab), np.asarray(fa), np.asarray(ra)
        return [
            (int(s), int(c), bool(f), bool(r))
            for s, v, c, f, r in zip(idx, valid, lab, fa, ra)
            if v
        ]

    def slot_metadata(self, limit: int | None = None,
                      slots: Iterable[int] | None = None) -> dict:
        """slot → (eth_src, eth_dst) for in-use slots (UI table).

        ``slots`` fetches exactly those slots (preserving none; the dict is
        keyed by slot) — pair with ``top_slots`` for an activity-ranked
        sample. ``limit`` bounds host work to O(limit): at the 2²⁰-flow
        target a full dict copy (let alone rendering it) would dominate the
        tick, and the reference only ever prints dozens of flows
        (traffic_classifier.py:99-118)."""
        if slots is not None:
            return self._slot_meta_for(slots)
        if not self.native:
            items = self.index.slot_meta.items()
            if limit is None:
                return dict(items)
            import itertools

            return dict(itertools.islice(items, limit))
        out = {}
        in_use = np.asarray(self.table.in_use)[:-1]
        for s in np.nonzero(in_use)[0]:
            if limit is not None and len(out) >= limit:
                break
            meta = self.batcher.slot_meta(int(s))
            if meta is not None:
                out[int(s)] = meta
        return out

    def step(self) -> bool:
        """Flush all pending records into the device table; False if idle.
        Loops because one tick can exceed the largest batch bucket.

        Native path: the C++ engine emits each generation directly in
        the packed wire layout into pinned staging (flush_wire) — no
        per-flush UpdateBatch materialization, no pack_wire column
        pass; the Python fallback keeps the record-object route. Both
        feed the identical apply_wire scatter (the dirty-tracking
        variant fuses the incremental path's per-slot mark into the
        same dispatch, so the label cache rides for free)."""
        applied = False
        if self.native:
            # gate on pending records so the overwrite guard below only
            # runs ahead of a real flush — flush_wire itself writes the
            # staging buffer, so the sync must precede the CALL, but an
            # empty queue must not pay (or reset) it
            while len(self.batcher):
                if self._staged_flushes >= 2:
                    # the staging is double-buffered: flush k reuses
                    # flush k-2's buffer, and apply dispatch is async
                    # with the wire as a NON-donated (possibly
                    # zero-copy) host buffer — drain the in-flight
                    # applies before the C++ side overwrites it. The
                    # count persists ACROSS step() calls: the hazard
                    # spans ticks (this tick's first flush reuses the
                    # buffer staged two flushes ago, whichever tick
                    # dispatched its apply), so a per-call counter
                    # would leave consecutive single-flush steps
                    # unguarded. Near-free on the common path: the
                    # apply from two flushes back is all but always
                    # already retired.
                    jax.block_until_ready(self.table)
                    self._staged_flushes = 0
                if (w := self.batcher.flush_wire()) is None:
                    break
                self._apply_wire(w)
                self._staged_flushes += 1
                applied = True
            return applied
        while (batch := self.batcher.flush()) is not None:
            self._apply_wire(ft.pack_wire(batch))
            applied = True
        return applied

    def _apply_wire(self, w) -> None:
        """One packed wire batch into the device table (dirty-fused when
        the incremental label cache is live)."""
        self.wire_bytes += w.nbytes  # padded, i.e. what actually moves
        probe = self.donation_probe
        ptr = None
        if probe is not None:
            try:
                # the pointer must be read BEFORE the donating dispatch
                # consumes the input buffer (afterwards it is deleted)
                ptr = self.table.time_start.unsafe_buffer_pointer()
            except Exception:  # noqa: BLE001 — telemetry must not inject
                probe = None
        if self.dirty is None:
            self.table = apply_wire_jit(self.table, w)
        else:
            self.table, self.dirty = apply_wire_dirty_jit(
                self.table, self.dirty, w
            )
        if probe is not None:
            try:
                probe(
                    "wire",
                    self.table.time_start.unsafe_buffer_pointer() == ptr,
                )
            except Exception:  # noqa: BLE001 — telemetry must not inject
                pass

    def features(self):
        """(capacity, 12) device feature matrix (classifier input)."""
        return ft.features12(self.table)

    def stale_slots(self, now: int, idle_seconds: int) -> "np.ndarray":
        """Slot ids with no telemetry in either direction for
        ``idle_seconds`` — the decision half of idle eviction, split
        from the release half so the pipelined serve loop can ask "is
        an eviction due this tick?" from data time alone (identical
        across runs) and pay the render drain the release requires
        only on ticks that actually evict (cli._dispatch_render)."""
        # Flush pending records first: device last_time must be current,
        # and no stale pending row may outlive its slot's eviction (it
        # would scatter into a reassigned slot).
        self.step()
        # staleness is decided on device (core/flow_table.stale_mask) and
        # crosses to host bit-packed: capacity/8 bytes instead of a bool
        # per slot (1 MB -> 128 KB at 2²⁰ over the ~12 MB/s tunnel)
        stale = np.unpackbits(
            np.asarray(
                ft.stale_bits(self.table, np.int32(now), np.int32(idle_seconds))
            ),
            count=self.table.capacity + 1,
        ).astype(bool)[:-1]
        return np.nonzero(stale)[0]

    def evict_slots(self, slots: "np.ndarray") -> int:
        """Release an explicit slot batch chosen by ``stale_slots`` —
        the release half of idle eviction. Returns the evicted count."""
        return self._clear_and_release(slots)

    def evict_idle(self, now: int, idle_seconds: int) -> int:
        """Release flows with no telemetry in either direction for
        ``idle_seconds`` — the capacity-reclaim the reference lacks (its
        ``flows`` dict grows forever, traffic_classifier.py:24). Returns
        the number of evicted flows."""
        return self.evict_slots(self.stale_slots(now, idle_seconds))

    def _clear_and_release(self, slots: "np.ndarray") -> int:
        """Clear + release an explicit slot batch — the shared device
        half of idle eviction and namespace eviction (bucketed clears,
        dirty-bit invalidation when the label cache is live, one bulk
        index release)."""
        step = self.batcher.buckets[-1]
        capacity = self.table.capacity
        for i in range(0, slots.size, step):
            chunk = slots[i : i + step]
            size = bucket_size(chunk.size, self.batcher.buckets)
            padded = np.full(size, capacity, np.int32)
            padded[: chunk.size] = chunk
            if self.dirty is None:
                self.table = ft.clear_slots(self.table, padded)
            else:
                # eviction invalidates the label cache: the cleared
                # rows' features are zeros now, their cached labels lie
                self.table, self.dirty = clear_slots_dirty_jit(
                    self.table, self.dirty, padded
                )
        # one bulk call: the native path crosses ctypes once for the whole
        # eviction batch instead of once per slot
        (self.batcher if self.native else self.index).release_slots(slots)
        return int(slots.size)

    def slots_for_source(self, source: int) -> "np.ndarray":
        """The slots a source's namespace currently owns, spine-
        uniformly (Python index walk or native tag scan). The actuation
        plane's blast-radius hooks read this: quarantine retraction
        captures a namespace's slot set BEFORE ``evict_source`` releases
        it, and a fleet member's source span filters rendered rows."""
        if self.native:
            return self.batcher.slots_for_source(source).astype(np.int64)
        return np.asarray(
            sorted(self.index.slots_for_source(source)), np.int64
        )

    def evict_source(self, source: int) -> int:
        """Evict every flow in one telemetry source's namespace — the
        blast-radius boundary of the fan-in tier (ingest/fanin.py): a
        source whose quarantine expired loses exactly its own slots
        while every other namespace keeps serving untouched. Returns
        the number of evicted flows.

        Both spines: the Python index walks its sparse slot_source map;
        the C++ engine scans its per-slot namespace tags
        (tck_slots_for_source) — either way the slot set crosses once,
        the device rows clear in bucketed batches, and the index
        releases in bulk."""
        # flush first: a pending row for an about-to-clear slot would
        # scatter stale counters into a freed (reassignable) row — the
        # same ordering evict_idle enforces
        self.step()
        # drop the namespace's dangling partial line with its slots, on
        # BOTH spines: a restarted stream's first chunk must not
        # complete the dead incarnation's fragment (the fan-in queue's
        # \x00\n poison seam guards the same boundary from the delivery
        # side — this covers direct engine callers too)
        self._tails.pop(source, None)
        if self.native:
            self.batcher.reset_tail(source)
        slots = self.slots_for_source(source)
        return self._clear_and_release(slots)
