"""The telemetry line protocol between the OpenFlow monitor and the
classifier, plus stable flow keys.

The reference's Ryu app emits one TSV line per flow per 1 Hz poll:
``data\\t<time>\\t<datapath>\\t<in_port>\\t<eth_src>\\t<eth_dst>\\t<out_port>
\\t<packet_count>\\t<byte_count>`` (simple_monitor_13.py:49-66), and the
classifier parses it by prefix match + split (traffic_classifier.py:152-155).
This module speaks exactly that protocol so the framework can sit on an
unmodified monitor, a recorded capture, or a synthetic generator.

Flow keys: the reference uses Python's ``hash()`` of datapath+src+dst
(traffic_classifier.py:157), which is randomized per process — a documented
defect (SURVEY.md §2). We use a stable 64-bit BLAKE2b digest instead, with
the same direction-folding rule: a record keys to an existing reverse-key
flow as that flow's reverse direction (reference :161-165).
"""

from __future__ import annotations

import hashlib
import time as _time
from dataclasses import dataclass, field

from ..utils.faults import FaultInjected, fault_point

PREFIX = b"data"
_I64_MAX = (1 << 63) - 1


@dataclass(frozen=True)
class TelemetryRecord:
    """One parsed flow-stats line.

    ``source`` is NOT on the wire: it is the fan-in tier's namespace tag
    (ingest/fanin.py) stamped after parsing, folding the originating
    telemetry source into the flow key so two switches reporting the
    same (datapath, src, dst) tuple land in disjoint flow-table
    namespaces. Source 0 is the legacy/default namespace — a record
    that never crossed the fan-in tier keys exactly as before.

    ``emit_ts`` is NOT on the wire either: it is the latency-provenance
    plane's monotonic emit stamp (``time.perf_counter`` domain), set
    host-side at the moment the owning pump read/generated the record
    (``stamp_records``) and consumed by ``obs/latency.py`` to attribute
    where a record's end-to-end budget went. ``compare=False``: two
    records carrying the same telemetry are equal regardless of when
    they were stamped — identity, replay convergence, and checkpoint
    round-trips never see the stamp (``format_line`` does not emit it,
    ``parse_line`` never sets it).
    """

    time: int
    datapath: str
    in_port: str
    eth_src: str
    eth_dst: str
    out_port: str
    packets: int
    bytes: int
    source: int = 0
    emit_ts: float | None = field(default=None, compare=False)


def stamp_records(records, ts: float | None = None) -> bool:
    """Set each record's ``emit_ts`` in place (write-once: records that
    already carry a stamp keep it — a pump downstream of a stamping
    collector must not overwrite the earlier, truer emit moment).

    In-place via ``object.__setattr__`` on the frozen dataclass — the
    stamp is provenance metadata set exactly once by the owning pump
    BEFORE the batch is published to the queue (no concurrent reader
    exists yet), and the cost must stay out of the hot path: callers
    that own a whole poll batch stamp only its LEAD record
    (``records[:1]`` — one pump read is one emit moment; the 3%
    tick-p50 overhead budget the bench A/B pins at batch 16k rules out
    an O(records) loop), while per-line paths (the collector's reader)
    stamp each record as it parses. The wire fields stay immutable in
    every hand that receives the record.

    Fault site ``obs.stamp`` (ABSORBED): a stamping failure degrades
    this batch to unstamped — the latency plane skips it, telemetry
    flows untouched. Returns False when the fire absorbed the stamp.
    """
    try:
        fault_point("obs.stamp")
    except FaultInjected:
        return False  # ABSORBED: unstamped batch, telemetry undropped
    if ts is None:
        ts = _time.perf_counter()
    for r in records:
        if r.emit_ts is None:
            object.__setattr__(r, "emit_ts", ts)
    return True


def format_line(r: TelemetryRecord) -> bytes:
    """Render a record back to the wire format (for replay files, tests and
    the fake monitor)."""
    return (
        b"\t".join(
            str(x).encode()
            for x in (
                "data", r.time, r.datapath, r.in_port, r.eth_src,
                r.eth_dst, r.out_port, r.packets, r.bytes,
            )
        )
        + b"\n"
    )


def parse_line(line: bytes) -> TelemetryRecord | None:
    """Parse one monitor stdout line; None for non-telemetry lines
    (headers, Ryu logs — the reference filters by the same prefix)."""
    if not line.startswith(PREFIX):
        return None
    fields = line.rstrip(b"\n").split(b"\t")[1:]
    # exactly 8 fields after the prefix: the wire format emits exactly
    # 9 columns, so a line with trailing junk fields is corrupt — not
    # slop to ignore (the C++ parser rejects identically, and the
    # exactness is what lets the ingest.native_parse fault seam corrupt
    # a mid-line fragment by appending a bogus field)
    if len(fields) != 8:
        return None
    try:
        r = TelemetryRecord(
            time=int(fields[0]),
            datapath=fields[1].decode(),
            in_port=fields[2].decode(),
            eth_src=fields[3].decode(),
            eth_dst=fields[4].decode(),
            out_port=fields[5].decode(),
            packets=int(fields[6]),
            bytes=int(fields[7]),
        )
    except (ValueError, UnicodeDecodeError):
        return None
    # Counters are cumulative OFPFlowStats values: negative or >int64 is
    # malformed (a truncated/corrupt line), and the C++ fast path rejects
    # it the same way — a defined shared behavior instead of Python's
    # arbitrary-precision ints silently diverging from the native engine.
    # time shares the C++ parse_i64 bound (magnitude ≤ INT64_MAX).
    if not (0 <= r.packets <= _I64_MAX and 0 <= r.bytes <= _I64_MAX
            and -_I64_MAX <= r.time <= _I64_MAX):
        return None
    return r


def stable_flow_key(datapath: str, eth_src: str, eth_dst: str,
                    source: int = 0) -> int:
    """Stable 64-bit key over (datapath, src, dst) — replaces the
    reference's process-randomized ``hash()`` (traffic_classifier.py:157).

    ``source`` namespaces the key per telemetry source (fan-in ingest):
    nonzero source ids are folded into the digest, so N sources
    reporting the same flow tuple occupy N independent flow-table
    slots and one source's eviction storm can never clear another's
    rows. Source 0 produces the historical digest bit-for-bit —
    serving checkpoints written before the fan-in tier restore into
    the default namespace unchanged.
    """
    h = hashlib.blake2b(digest_size=8)
    # \x00 separators prevent ambiguity between concatenated fields (the
    # reference's bare string concat would collide 'ab'+'c' with 'a'+'bc').
    h.update(datapath.encode())
    h.update(b"\x00")
    h.update(eth_src.encode())
    h.update(b"\x00")
    h.update(eth_dst.encode())
    if source:
        # appended (not prepended) and gated on nonzero: the source-0
        # digest must stay byte-identical to the pre-fan-in key
        h.update(b"\x00")
        h.update(source.to_bytes(4, "little"))
    return int.from_bytes(h.digest(), "little")
