"""Fan-in ingest tier: many telemetry sources, one device, per-source
blast radius.

The reference binds the whole system to exactly one Ryu/OVS collector
subprocess (traffic_classifier.py:98-170), and until now the serve loop
inherited that assumption — one SupervisedCollector, one flow namespace.
This module scales the ingest tier horizontally: N independently
supervised sources (live monitor subprocesses, capture replays, synthetic
populations) feed ONE serve loop through a bounded MPSC queue, and each
source owns a disjoint flow-table namespace (its id folded into the
stable 64-bit flow key, ingest/protocol.stable_flow_key).

Blast-radius contract — the degrade-ladder pattern applied horizontally
(serving/degrade.py runs it vertically, device→host→stale):

- a producer is NEVER blocked and the queue is NEVER unbounded: on
  overflow the incoming batch is dropped and counted against ITS source
  (``FanInQueue``, fault site ``ingest.fanin_put``);
- per-source supervision state HEALTHY → RESTARTING → DEAD: a live
  source rides its own SupervisedCollector restart ladder (RESTARTING
  between incarnations); an uncleanly dead source (crash after budget,
  killed pump — fault site ``ingest.source_dead``) is quarantined and,
  after ``quarantine_s``, exactly its own namespace's slots are evicted
  (``FlowStateEngine.evict_source``) while every other source keeps
  serving fresh labels every tick;
- a restarted source re-registers into its OLD namespace: flow keys are
  deterministic in (source id, flow tuple), and the protocol's counters
  are cumulative, so the first post-restart poll is one large delta per
  flow — the same thing a supervisor restart always produced.

Tick semantics: one serve tick consumes AT MOST ONE poll batch per
source (``FanInQueue.take``), so a backlogged source cannot smear its
tick boundaries into a neighbor's, and single-source fan-in is
tick-for-tick identical to the direct collector path. Pull-paced sources
(capture/synthetic) support ``lockstep`` credits — the consumer grants
one emission per serve tick — which makes multi-source runs
deterministic (tests) and turns N synthetic sources into a repeatable
heavy-traffic load generator (tools/bench_serve.py --sources).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, replace

from ..utils.faults import FaultInjected, fault_point
from .protocol import TelemetryRecord, stamp_records

SOURCE_HEALTHY = "HEALTHY"
SOURCE_RESTARTING = "RESTARTING"
SOURCE_DEAD = "DEAD"

# numeric gauge encoding (source_<id>_state), mirroring degrade_state
_STATE_CODE = {SOURCE_HEALTHY: 0, SOURCE_RESTARTING: 1, SOURCE_DEAD: 2}


@dataclass(frozen=True)
class SourceSpec:
    """One telemetry source the fan-in tier supervises.

    ``kind`` selects the pump: ``cmd`` spawns a monitor command under a
    SupervisedCollector (restart ladder and all), ``capture`` replays a
    recorded monitor capture tick-by-tick, ``synthetic`` generates a
    flow population (ingest/replay.SyntheticFlows), ``feed`` pulls each
    poll tick's wire bytes from a caller-supplied script callable
    (``feed(tick_index) -> bytes | None`` — the scenario library's
    timeline seam; raw tiers only). ``sid`` is the
    namespace id folded into every record's flow key — 0 is the legacy
    namespace (records pass through unstamped, byte-compatible with the
    single-collector path). Pull-paced kinds emit every ``interval``
    seconds, or on consumer credits when ``lockstep`` (deterministic
    multi-source runs: one emission per serve tick)."""

    kind: str  # "cmd" | "capture" | "synthetic" | "feed"
    sid: int
    name: str = ""
    cmd: str = ""
    path: str = ""
    n_flows: int = 0
    seed: int = 0
    mac_base: int = 0
    # synthetic churn fraction: share of this source's flow population
    # emitting telemetry each tick (replay.SyntheticFlows churn — the
    # dirty-fraction knob behind incremental serving, per source)
    churn: float = 1.0
    max_ticks: int = 0  # synthetic bound (0 = unbounded)
    max_restarts: int = 5
    interval: float = 1.0
    lockstep: bool = False
    # "feed" kind only: per-tick wire-bytes script, compared by identity
    # (scenario timelines — see traffic_classifier_sdn_tpu/scenarios/)
    feed: object = None

    @property
    def label(self) -> str:
        return self.name or f"{self.kind}-{self.sid}"


def parse_source_spec(text: str, sid: int, *, max_restarts: int = 5,
                      interval: float = 1.0,
                      lockstep: bool = False) -> SourceSpec:
    """``KIND:ARG`` → SourceSpec (the --source-spec syntax): ``cmd:<shell
    command>``, ``capture:<path>``, ``synthetic:<n_flows>``."""
    kind, sep, arg = text.partition(":")
    if not sep or not arg:
        raise ValueError(
            f"source spec {text!r} is not KIND:ARG "
            f"(cmd:<command> | capture:<path> | synthetic:<n_flows>)"
        )
    common = dict(sid=sid, max_restarts=max_restarts, interval=interval,
                  lockstep=lockstep)
    if kind == "cmd":
        return SourceSpec(kind="cmd", cmd=arg, **common)
    if kind == "capture":
        return SourceSpec(kind="capture", path=arg, **common)
    if kind == "synthetic":
        try:
            n = int(arg)
        except ValueError:
            raise ValueError(
                f"synthetic source spec needs an integer flow count, "
                f"got {arg!r}"
            ) from None
        # disjoint MAC space per namespace so the aggregate looks like
        # N switches, not N copies of one (replay.SyntheticFlows)
        return SourceSpec(kind="synthetic", n_flows=n, seed=sid,
                          mac_base=sid * n, **common)
    raise ValueError(
        f"unknown source kind {kind!r} (cmd | capture | synthetic)"
    )


class FanInQueue:
    """Bounded MPSC batch queue between N source pumps and one serve
    loop, with per-source drop accounting.

    ``put`` never blocks: when the queued-record bound would be
    exceeded the INCOMING batch is dropped, counted against its source,
    and reported to the flight recorder — backpressure costs the noisy
    source its own telemetry, not its neighbors' latency (the same
    drop-don't-block rule SubprocessCollector's reader enforces on its
    own pipe queue). Records, not batches, are the bound: N bursty
    sources share one budget measured in what actually costs ingest
    time."""

    def __init__(self, max_records: int = 1 << 16, recorder=None,
                 prov_clock=time.perf_counter,
                 collect_provenance: bool = False):
        self.max_records = max_records
        self._recorder = recorder  # set once, read-only afterwards
        # latency provenance (obs/latency.py): enqueue/dequeue stamps
        # per batch, in the perf_counter domain the emit stamps use —
        # queue-wait is deq − enq. Collection is opt-in (the tier turns
        # it on with stamping) and the taken-entry buffer is bounded so
        # a consumer that never drains it cannot leak.
        self._prov_clock = prov_clock
        self._collect_prov = collect_provenance
        self._taken_prov: deque = deque(maxlen=4096)
        # guards every queue/counter access below: producers are the
        # source pump threads, the consumer is the serve loop, and the
        # drop counters are read by the obs roster — all cross-thread
        self._lock = threading.Lock()
        # (sid, payload, n_records, enq_ts, emit_ts) in arrival order;
        # payload is a record list (the Python-batcher path) or a raw
        # wire-format bytes blob (the native path — n and emit travel
        # explicitly because bytes can't carry a stamp attribute)
        self._batches: deque = deque()
        self._queued = 0  # records currently queued
        self._drops: dict[int, int] = {}  # sid → records dropped
        self._accepted: dict[int, int] = {}  # sid → records accepted
        # sid → accepted records later purged at eviction: a purge
        # re-classifies accepted→dropped, so the per-source accounting
        # identity the scenario gates check is
        #   emitted == accepted + (drops − purged)
        self._purged: dict[int, int] = {}
        # raw-mode framing poison: sources whose BYTE stream lost a
        # chunk (bound drop or eviction purge). Raw chunks can end
        # mid-line, and the consumer's per-source tail carry would
        # otherwise splice the pre-drop fragment onto the post-drop
        # chunk's head — a torn line that might parse as a wrong-but-
        # valid record. The next accepted byte batch is prefixed with
        # b"\x00\n" (the collector's torn-read poison idiom): the stale
        # tail terminates as an unparseable line (counted malformed if
        # telemetry-shaped) and framing resyncs at a real boundary.
        self._poisoned: set[int] = set()

    def put(self, sid: int, records: list) -> bool:
        """Enqueue one poll batch; False when it was dropped (bound hit
        or an injected enqueue failure — the chaos seam for a queue-full
        drop burst, ABSORBED here by design)."""
        return self._put(sid, records, len(records), None)

    def put_bytes(self, sid: int, data: bytes, n_records: int,
                  emit_ts: float | None = None) -> bool:
        """Raw-wire counterpart of ``put`` — the native-ingest delivery
        unit: one poll batch as wire-format bytes, its record count for
        the bound/accounting, and the pump-read emit stamp carried
        EXPLICITLY (the latency plane's provenance seam: a byte batch
        has no record object to stamp, so the emit moment rides the
        queue entry instead — same clock domain, same fold)."""
        return self._put(sid, data, n_records, emit_ts)

    def _put(self, sid: int, payload, n: int,
             emit_ts: float | None) -> bool:
        is_bytes = isinstance(payload, (bytes, bytearray))
        if n == 0:
            # empty poll (record path, or a genuinely empty byte tick)
            # — nothing to queue. Raw callers pass n >= 1 for any
            # nonempty payload (a newline-less pipe fragment counts as
            # one pending record), so no bytes are ever eaten here.
            return True
        dropped = False
        try:
            fault_point("ingest.fanin_put")
        except FaultInjected:
            dropped = True
        if not dropped:
            enq = self._prov_clock() if self._collect_prov else None
            with self._lock:
                if self._queued + n > self.max_records:
                    dropped = True
                else:
                    if is_bytes and sid in self._poisoned:
                        # terminate the consumer's stale pre-drop tail
                        # at an unparseable boundary (see _poisoned)
                        self._poisoned.discard(sid)
                        payload = b"\x00\n" + bytes(payload)
                    self._batches.append((sid, payload, n, enq, emit_ts))
                    self._queued += n
                    self._accepted[sid] = self._accepted.get(sid, 0) + n
        if dropped:
            with self._lock:
                self._drops[sid] = self._drops.get(sid, 0) + n
                if is_bytes:
                    self._poisoned.add(sid)
            # record OUTSIDE the queue lock: the ring has its own lock
            # and this one stays a leaf (graftlock lock-order)
            if self._recorder is not None:
                self._recorder.record(
                    "fanin.drop", source=sid, records=n,
                    cause="overflow",
                )
            return False
        return True

    def poison(self, sid: int) -> None:
        """Force a framing resync for ``sid``'s byte stream: the next
        accepted byte batch is prefixed with the ``b"\\x00\\n"`` seam
        (see ``_poisoned``). The tier calls this at namespace eviction
        and source restart — the CONSUMER's per-source tail can hold
        the dead incarnation's dangling half line even when the purge
        found an already-drained queue (nothing queued is not the same
        as nothing carried), and a restarted worker's fresh collector
        shares no seam with the old worker's last partial chunk."""
        with self._lock:
            self._poisoned.add(sid)

    def take(self, exclude=()) -> list[tuple[int, list]]:
        """Pop the OLDEST batch per source (arrival order preserved),
        skipping sources in ``exclude`` — one serve tick consumes at
        most one poll tick per source, so a backlogged source drains
        one batch per tick instead of smearing several poll ticks into
        one serve tick. With provenance collection on, each taken
        batch's ``(sid, emit, enq, deq, n)`` lands in the taken-entry
        buffer for ``pop_provenance`` — a PURGED batch never gets an
        entry, so a dead source's flushed backlog cannot poison the
        e2e quantiles."""
        deq = self._prov_clock() if self._collect_prov else None
        with self._lock:
            out: list[tuple[int, list]] = []
            kept: deque = deque()
            seen = set(exclude)
            while self._batches:
                sid, payload, n, enq, emit = self._batches.popleft()
                if sid in seen:
                    kept.append((sid, payload, n, enq, emit))
                else:
                    seen.add(sid)
                    out.append((sid, payload))
                    self._queued -= n
                    if deq is not None:
                        if emit is None and not isinstance(
                            payload, (bytes, bytearray)
                        ):
                            # record batches carry the stamp on their
                            # LEAD record (protocol.stamp_records)
                            emit = (
                                payload[0].emit_ts if payload else None
                            )
                        self._taken_prov.append((sid, emit, enq, deq, n))
            self._batches = kept
        return out

    def pop_provenance(self) -> list[tuple]:
        """Drain the taken-batch provenance entries accumulated since
        the last call — ``(sid, emit, enq, deq, n_records)`` per batch,
        the ``obs.latency.LatencyProvenance.begin_tick`` input shape.
        Empty unless the queue was built with provenance collection."""
        with self._lock:
            out = list(self._taken_prov)
            self._taken_prov.clear()
        return out

    def purge(self, sid: int) -> int:
        """Drop every queued batch from ``sid`` (counted against it) —
        the eviction-time flush: a dead source's backlog must not be
        ingested AFTER its namespace was cleared, or it would re-create
        slots in a namespace nothing will ever quarantine again.
        Returns the records dropped."""
        purged = 0
        purged_bytes = False
        with self._lock:
            kept: deque = deque()
            while self._batches:
                entry = self._batches.popleft()
                if entry[0] == sid:
                    purged += entry[2]
                    if isinstance(entry[1], (bytes, bytearray)):
                        purged_bytes = True
                else:
                    kept.append(entry)
            self._batches = kept
            if purged:
                self._queued -= purged
                self._drops[sid] = self._drops.get(sid, 0) + purged
                self._purged[sid] = self._purged.get(sid, 0) + purged
                if purged_bytes:
                    # a restarted incarnation's first chunk must not
                    # splice onto the evicted stream's dangling tail
                    self._poisoned.add(sid)
        if purged and self._recorder is not None:
            self._recorder.record(
                "fanin.drop", source=sid, records=purged,
                cause="namespace_evicted",
            )
        return purged

    @property
    def pending(self) -> int:
        """Records currently queued."""
        with self._lock:
            return self._queued

    def drops(self) -> dict[int, int]:
        """sid → records dropped (queue-full or injected), cumulative."""
        with self._lock:
            return dict(self._drops)

    def accepted(self) -> dict[int, int]:
        with self._lock:
            return dict(self._accepted)

    def purged(self) -> dict[int, int]:
        """sid → records that were ACCEPTED and later purged at
        eviction (a subset of ``drops()``): subtract these from the
        drop tally to recover put-time drops, closing the per-source
        accounting identity ``emitted == accepted + (drops − purged)``
        the scenario SLO gates assert."""
        with self._lock:
            return dict(self._purged)


class RawTick(list):
    """One serve tick of raw wire-format byte batches — ``[(sid,
    payload), ...]`` ordered by source id, the native-ingest fan-in
    delivery unit: the serve loop feeds each payload to the C++ engine
    under its source's namespace (``engine.ingest_bytes(data, sid)``)
    and no per-flow string ever crosses into Python."""


class SourceWorker:
    """One supervised telemetry source pumping into the shared queue.

    The pump is a daemon thread; its per-source state (HEALTHY /
    RESTARTING / DEAD, delivery counters, last-delivery clock) is read
    by the serve loop's supervision pass and the obs roster, so every
    access holds ``_state_lock``. A pump that dies for ANY reason —
    stream exhaustion, supervisor budget, injected ``ingest.source_dead``
    fire, even an unexpected exception — lands in DEAD with a ``clean``
    verdict: only an UNCLEAN death quarantines the namespace.

    ``raw`` selects wire-format byte delivery (the native-ingest fast
    path): the pump hands the queue one bytes blob per poll tick —
    capture sources replay their recorded line bytes, synthetic sources
    render straight to the wire (``SyntheticFlows.tick_bytes``), cmd
    sources forward raw pipe chunks — and the namespace is applied at
    the C++ keyer instead of a per-record ``replace`` pass."""

    def __init__(self, spec: SourceSpec, queue: FanInQueue, metrics=None,
                 recorder=None, clock=time.monotonic,
                 stamp: bool = False, prov_clock=time.perf_counter,
                 raw: bool = False):
        self.spec = spec
        self._raw = raw
        self._queue = queue
        self._metrics = metrics
        self._recorder = recorder
        self._clock = clock
        # latency provenance: stamp each delivered batch's records with
        # the pump-read moment (perf_counter domain, host-side only)
        self._stamp = stamp
        self._prov_clock = prov_clock
        self._state_lock = threading.Lock()
        self._state = SOURCE_HEALTHY
        self._clean = False
        self._killed = False
        self._records = 0
        self._emitted = 0  # records handed to the queue (accepted OR dropped)
        self._ticks = 0
        self._restarts = 0
        self._last_put_at: float | None = None
        self._coll = None  # cmd sources: the SupervisedCollector
        self._stop_evt = threading.Event()
        # one pending lockstep emission credit (consumer-granted,
        # pump-consumed) — a plain flag under _state_lock, polled by the
        # pump at 20 ms granularity
        self._credit_due = False
        self._thread: threading.Thread | None = None

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"tcsdn-fanin-{self.spec.label}",
        )
        self._thread.start()

    def stop(self) -> None:
        """Clean shutdown (tier teardown): the pump winds down as a
        CLEAN death — no quarantine, no namespace eviction."""
        self._stop_evt.set()
        with self._state_lock:
            coll = self._coll
        if coll is not None:
            coll.stop()

    def kill(self) -> None:
        """Simulate source death (tests/ops): same teardown as stop()
        but the death is UNCLEAN — the tier quarantines the namespace,
        exactly as if the pump had crashed."""
        with self._state_lock:
            self._killed = True
        self.stop()

    def grant(self) -> None:
        """One lockstep emission credit (the consumer's per-tick grant).
        Idempotent between emissions: double-granting before the pump
        consumed the credit collapses to one — the pump can never
        overrun the serve tick it was granted."""
        with self._state_lock:
            self._credit_due = True

    def join(self, timeout: float | None = None) -> None:
        t = self._thread
        if t is not None:
            t.join(timeout)

    # -- state surface -----------------------------------------------------
    @property
    def alive(self) -> bool:
        with self._state_lock:
            return self._state != SOURCE_DEAD

    @property
    def dead_unclean(self) -> bool:
        with self._state_lock:
            return self._state == SOURCE_DEAD and not self._clean

    def snapshot(self) -> dict:
        """Roster row: id, state, lag, counters (drops ride in from the
        queue at the tier level)."""
        with self._state_lock:
            state = self._state
            clean = self._clean
            records = self._records
            emitted = self._emitted
            ticks = self._ticks
            restarts = self._restarts
            last = self._last_put_at
        return {
            "id": self.spec.sid,
            "name": self.spec.label,
            "kind": self.spec.kind,
            "state": state,
            "clean": clean,
            "records": records,
            "emitted": emitted,
            "ticks": ticks,
            "restarts": restarts,
            "lag_s": (
                None if last is None
                else round(max(0.0, self._clock() - last), 3)
            ),
        }

    # -- pump --------------------------------------------------------------
    def _run(self) -> None:
        clean = False
        try:
            clean = self._pump()
        except FaultInjected:
            clean = False  # injected mid-stream death (chaos)
        except Exception as e:  # noqa: BLE001 — one source must not kill N
            import sys

            print(
                f"WARNING: telemetry source {self.spec.label} died: "
                f"{type(e).__name__}: {e}",
                file=sys.stderr,
            )
            clean = False
        finally:
            with self._state_lock:
                if self._killed:
                    clean = False
                self._state = SOURCE_DEAD
                self._clean = clean

    def _pump(self) -> bool:
        if self.spec.kind == "cmd":
            return self._pump_cmd()
        if self.spec.kind == "capture":
            return self._pump_capture()
        if self.spec.kind == "synthetic":
            return self._pump_synthetic()
        if self.spec.kind == "feed":
            return self._pump_feed()
        raise ValueError(f"unknown source kind {self.spec.kind!r}")

    def _deliver(self, records: list) -> None:
        """Stamp the namespace and enqueue one poll batch. Source 0 is
        the legacy namespace: records pass through object-identical (the
        single-source byte-compat path pays zero per-record work).

        With the latency plane armed, the batch is emit-stamped FIRST
        (this is the "source pump read" moment — ``protocol
        .stamp_records`` is write-once, so records a stamping collector
        already marked at pipe parse keep the earlier, truer stamp;
        an absorbed ``obs.stamp`` fire leaves the batch unstamped and
        delivery proceeds regardless), then namespace-stamped — the
        ``replace`` copies carry ``emit_ts`` through. Only the LEAD
        record is stamped: one pump read is one emit moment for the
        whole batch (``batcher.batch_emit_ts`` and the queue's
        provenance read exactly that), and a per-record loop at batch
        16k would cost ~4 ms/tick — past the 3% overhead budget — for
        zero extra information."""
        sid = self.spec.sid
        if self._stamp:
            stamp_records(records[:1], self._prov_clock())
        if sid:
            records = [replace(r, source=sid) for r in records]
        ok = self._queue.put(sid, records)
        with self._state_lock:
            self._ticks += 1
            self._emitted += len(records)
            if ok:
                self._records += len(records)
                self._last_put_at = self._clock()

    def _deliver_raw(self, data: bytes, n_records: int) -> None:
        """Raw-wire delivery: one wire-format blob per poll tick. The
        emit stamp rides the queue entry explicitly (``put_bytes``) —
        the provenance seam survives even though no record object
        exists host-side to stamp; an unstamped tier simply passes
        None. The namespace is NOT applied here: the consumer feeds the
        bytes to the C++ keyer under this source's id."""
        sid = self.spec.sid
        emit = self._prov_clock() if self._stamp else None
        ok = self._queue.put_bytes(sid, data, n_records, emit)
        with self._state_lock:
            self._ticks += 1
            self._emitted += n_records
            if ok:
                self._records += n_records
                self._last_put_at = self._clock()

    def _pace(self, first: bool) -> bool:
        """Gate one pull-paced emission; False when stopping. Lockstep
        waits for the consumer's credit (every tick, including the
        first); interval mode emits the first tick immediately and
        sleeps between the rest."""
        if self.spec.lockstep:
            while True:
                if self._stop_evt.is_set():
                    return False
                with self._state_lock:
                    due = self._credit_due
                    if due:
                        self._credit_due = False
                if due:
                    return not self._stop_evt.is_set()
                time.sleep(0.02)
        if first:
            return not self._stop_evt.is_set()
        if self.spec.interval > 0:
            return not self._stop_evt.wait(self.spec.interval)
        return not self._stop_evt.is_set()

    def _pump_capture(self) -> bool:
        from .replay import iter_capture, iter_capture_bytes

        if self._raw:
            for i, (data, n) in enumerate(
                iter_capture_bytes(self.spec.path)
            ):
                if not self._pace(first=i == 0):
                    return True  # stopped — clean
                fault_point("ingest.source_dead")
                self._deliver_raw(data, n)
            return True
        for i, tick in enumerate(iter_capture(self.spec.path)):
            if not self._pace(first=i == 0):
                return True  # stopped — clean
            fault_point("ingest.source_dead")
            self._deliver(tick)
        return True  # capture exhausted — clean end of stream

    def _pump_synthetic(self) -> bool:
        from .replay import SyntheticFlows

        syn = SyntheticFlows(
            n_flows=self.spec.n_flows, seed=self.spec.seed,
            mac_base=self.spec.mac_base, churn=self.spec.churn,
        )
        i = 0
        while self.spec.max_ticks <= 0 or i < self.spec.max_ticks:
            if not self._pace(first=i == 0):
                return True
            fault_point("ingest.source_dead")
            if self._raw:
                # straight to the wire format — per-record objects never
                # exist anywhere on the raw path (each record is one
                # line, so the newline count IS the record count)
                data = syn.tick_bytes()
                self._deliver_raw(data, data.count(b"\n"))
            else:
                self._deliver(syn.tick())
            i += 1
        return True

    def _pump_feed(self) -> bool:
        """Scripted wire-bytes source (scenario timelines): each poll
        tick hands the queue whatever ``spec.feed(tick_index)`` renders.
        ``None`` ends the stream (a clean death); ``b""`` is a silent
        tick — the pump delivers the one-newline noise line so a
        lockstep consumer still sees this source's batch for the tick
        (the parsers drop non-telemetry lines for free, and the queue
        counts the line as one emitted record, keeping the accounting
        identity exact). Raw tiers only: the script renders wire bytes,
        there is no record-object path to fall back to."""
        if not self._raw:
            raise ValueError(
                "feed sources render wire bytes — the fan-in tier must "
                "run raw (native ingest)"
            )
        gen = self.spec.feed
        if gen is None:
            raise ValueError("feed source needs spec.feed callable")
        i = 0
        while self.spec.max_ticks <= 0 or i < self.spec.max_ticks:
            if not self._pace(first=i == 0):
                return True
            fault_point("ingest.source_dead")
            data = gen(i)
            if data is None:
                return True  # script exhausted — clean end of stream
            if not data:
                data = b"\n"  # silent tick: one free-to-parse noise line
            self._deliver_raw(data, max(1, data.count(b"\n")))
            i += 1
        return True

    def _pump_cmd(self) -> bool:
        from .supervisor import SupervisedCollector

        coll = SupervisedCollector(
            self.spec.cmd, raw=self._raw,
            max_restarts=self.spec.max_restarts,
            metrics=self._metrics, recorder=self._recorder,
            # pipe-parse emit stamps on the reader thread: the truest
            # emission proxy (captures pipe→pump queue wait; _deliver's
            # write-once stamp then leaves these untouched). Raw mode
            # has no records to stamp — the pump-read moment rides the
            # queue entry instead (_deliver_raw).
            stamp=self._stamp and not self._raw,
        )
        with self._state_lock:
            self._coll = coll
        coll.start()
        try:
            while not self._stop_evt.is_set():
                rec = coll.wait_record(timeout=0.2)
                phase = coll.phase
                with self._state_lock:
                    self._restarts = coll.restarts
                    if self._state != SOURCE_DEAD:
                        self._state = (
                            SOURCE_RESTARTING if phase == "backoff"
                            else SOURCE_HEALTHY
                        )
                if rec is None:
                    if not coll.running:
                        break
                    continue
                fault_point("ingest.source_dead")
                time.sleep(0.05)  # let the 1 Hz burst of lines arrive
                if self._raw:
                    data = rec + b"".join(coll.poll_records())
                    # newline count bounds the record tally (noise lines
                    # included — the C++ parser does the real
                    # filtering). Floor 1: a pipe chunk ending mid-line
                    # can carry ZERO newlines, and a 0-record put would
                    # no-op — silently eating the fragment and tearing
                    # the engine's per-source tail framing.
                    self._deliver_raw(data, max(1, data.count(b"\n")))
                else:
                    self._deliver([rec, *coll.poll_records()])
            # clean iff we were stopped, or the monitor finished on
            # purpose — a restart-budget exhaustion is a real death
            return (
                self._stop_evt.is_set()
                or coll.terminal_reason != "restart-budget"
            )
        finally:
            coll.stop()


class FanInIngest:
    """The fan-in tier: owns N SourceWorkers, the MPSC queue, per-source
    supervision, and the quarantine→evict schedule.

    The serve loop drives ``ticks()`` (one merged record batch per serve
    tick) and calls ``take_evictions()`` each tick to learn which dead
    namespaces are due for eviction; the obs plane reads ``roster()``
    and ``alive()`` from its own thread. Supervision state shared across
    those threads lives under ``_roster_lock``."""

    def __init__(self, specs, queue_records: int = 1 << 16,
                 quarantine_s: float = 5.0, metrics=None, recorder=None,
                 clock=time.monotonic, stamp: bool = False,
                 prov_clock=time.perf_counter, raw: bool = False,
                 max_flaps: int = 5, flap_window_s: float = 60.0):
        specs = list(specs)
        sids = [s.sid for s in specs]
        if len(set(sids)) != len(sids):
            raise ValueError(f"duplicate source ids in specs: {sids}")
        if not specs:
            raise ValueError("fan-in tier needs at least one source")
        self.specs = specs
        self.quarantine_s = quarantine_s
        self._metrics = metrics
        self._recorder = recorder
        self._clock = clock
        # latency provenance (obs/latency.py): emit stamps at each
        # pump's _deliver + enq/deq stamps in the queue; the serve loop
        # drains pop_provenance() per assembled tick
        self._stamp = stamp
        self._prov_clock = prov_clock
        # raw-wire delivery (native ingest): every pump hands the queue
        # bytes, ticks() yields RawTick batches, and the namespace is
        # applied by the C++ keyer per (sid, payload) pair
        self.raw = raw
        self.queue = FanInQueue(
            queue_records, recorder=recorder, prov_clock=prov_clock,
            collect_provenance=stamp,
        )
        # guards the worker map and quarantine schedule: written by the
        # serve thread (supervision, restarts), read by the obs thread
        # (roster/healthz). Worker snapshots are taken OUTSIDE this lock
        # so it stays leaf-ordered above each worker's _state_lock.
        self._roster_lock = threading.Lock()
        self._workers: dict[int, SourceWorker] = {
            s.sid: SourceWorker(
                s, self.queue, metrics=metrics, recorder=recorder,
                clock=clock, stamp=stamp, prov_clock=prov_clock,
                raw=raw,
            )
            for s in specs
        }
        self._quarantine: dict[int, float] = {}  # sid → evict deadline
        self._dead_seen: set[int] = set()
        self._started = False
        # Flap escalation: a source flapping faster than quarantine_s
        # used to repeatedly cancel its pending quarantine via
        # restart_source — dying, restarting, dying again forever,
        # holding a namespace that never serves AND never evicts. After
        # ``max_flaps`` unclean deaths inside ``flap_window_s`` the sid
        # ESCALATES: further restarts are refused (unless forced), the
        # pending quarantine runs to completion, and the namespace
        # finally evicts. max_flaps=0 disables escalation.
        self.max_flaps = int(max_flaps)
        self.flap_window_s = float(flap_window_s)
        self._flap_times: dict[int, deque] = {}  # sid → unclean-death ts
        self._flaps: dict[int, int] = {}  # sid → lifetime unclean deaths
        self._escalated: set[int] = set()
        # records emitted by PRIOR incarnations of each sid: a restart
        # swaps in a fresh worker (emitted=0), but the accounting
        # identity emitted == accepted + (drops − purged) spans the
        # namespace's whole lifetime, so the roster folds this back in
        self._emitted_base: dict[int, int] = {}

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        with self._roster_lock:
            if self._started:
                return
            self._started = True
            workers = list(self._workers.values())
        for w in workers:
            w.start()

    def stop(self) -> None:
        with self._roster_lock:
            workers = list(self._workers.values())
        for w in workers:
            w.stop()
        for w in workers:
            w.join(timeout=5.0)

    def kill_source(self, sid: int) -> None:
        """Ops/test seam: kill one source mid-serve (unclean death —
        the quarantine path)."""
        with self._roster_lock:
            w = self._workers[sid]
        w.kill()

    def restart_source(self, sid: int, *, force: bool = False) -> bool:
        """Re-register a dead source into its OLD namespace: a fresh
        worker under the same source id produces the same flow keys, so
        its flows resume in their existing slots (cumulative counters →
        one large first delta, the supervisor-restart story). A pending
        quarantine is cancelled — the namespace is live again, evicting
        it would throw away state the restart just reclaimed.

        A flap-ESCALATED sid is refused (returns False, recorded as
        ``fanin.restart_refused``): cancelling its quarantine yet again
        is exactly the livelock escalation exists to break. ``force``
        is the operator override — it clears the escalation and the
        flap window, then restarts normally."""
        with self._roster_lock:
            escalated = sid in self._escalated
            if escalated and force:
                self._escalated.discard(sid)
                self._flap_times.pop(sid, None)
                escalated = False
        if escalated:
            if self._recorder is not None:
                self._recorder.record(
                    "fanin.restart_refused", source=sid,
                    cause="flap_escalated",
                )
            if self._metrics is not None:
                self._metrics.inc("source_restarts_refused")
            return False
        with self._roster_lock:
            old = self._workers[sid]
        old.stop()
        old.join(timeout=5.0)
        emitted = old.snapshot()["emitted"]
        fresh = SourceWorker(
            old.spec, self.queue, metrics=self._metrics,
            recorder=self._recorder, clock=self._clock,
            stamp=self._stamp, prov_clock=self._prov_clock,
            raw=self.raw,
        )
        with self._roster_lock:
            self._quarantine.pop(sid, None)
            self._dead_seen.discard(sid)
            self._workers[sid] = fresh
            self._emitted_base[sid] = (
                self._emitted_base.get(sid, 0) + emitted
            )
            started = self._started
        if self.raw:
            # a restart can land BEFORE the quarantine evicts (it
            # cancels the pending quarantine above), so no eviction
            # poison fires — yet the dead worker's last pipe chunk may
            # have ended mid-line. The fresh worker's collector carries
            # no seam with that fragment; resync the consumer's tail
            # framing before the new stream's first chunk.
            self.queue.poison(sid)
        if self._recorder is not None:
            self._recorder.record("fanin.source_restart", source=sid)
        if self._metrics is not None:
            self._metrics.inc("source_restarts")
        if started:
            fresh.start()
        return True

    # -- supervision -------------------------------------------------------
    def _supervise(self) -> None:
        """One supervision pass (serve thread): detect fresh unclean
        deaths and start their quarantine clocks."""
        with self._roster_lock:
            workers = list(self._workers.values())
        now = self._clock()
        for w in workers:
            if not w.dead_unclean:
                continue
            sid = w.spec.sid
            escalate = False
            flaps = 0
            with self._roster_lock:
                fresh = sid not in self._dead_seen
                if fresh:
                    self._dead_seen.add(sid)
                    self._quarantine[sid] = now + self.quarantine_s
                    # flap bookkeeping: every fresh unclean death is one
                    # flap; escalate once the windowed count hits the cap
                    self._flaps[sid] = self._flaps.get(sid, 0) + 1
                    flaps = self._flaps[sid]
                    if self.max_flaps > 0:
                        window = self._flap_times.setdefault(sid, deque())
                        window.append(now)
                        while window and window[0] < now - self.flap_window_s:
                            window.popleft()
                        if (len(window) >= self.max_flaps
                                and sid not in self._escalated):
                            self._escalated.add(sid)
                            escalate = True
            if fresh:
                if self._metrics is not None:
                    self._metrics.inc("source_deaths")
                if self._recorder is not None:
                    self._recorder.record(
                        "fanin.source_dead", source=sid,
                        name=w.spec.label,
                        quarantine_s=self.quarantine_s,
                    )
            if escalate:
                if self._metrics is not None:
                    self._metrics.inc("source_flap_escalations")
                if self._recorder is not None:
                    self._recorder.record(
                        "fanin.flap_escalated", source=sid,
                        flaps=flaps, window_s=self.flap_window_s,
                        max_flaps=self.max_flaps,
                    )

    def take_evictions(self) -> list[int]:
        """Sids whose quarantine expired since the last call — the serve
        loop evicts their namespaces (FlowStateEngine.evict_source).
        A sid stays pending until taken, so a caller that must defer
        (pipelined render in flight) simply asks again next tick. The
        sid's queued backlog is purged here: batches the dead source
        enqueued before dying must not be ingested after the eviction
        (they would re-create slots in a namespace nothing will ever
        quarantine again)."""
        now = self._clock()
        out: list[int] = []
        with self._roster_lock:
            for sid, deadline in list(self._quarantine.items()):
                if now >= deadline:
                    del self._quarantine[sid]
                    out.append(sid)
        for sid in out:
            self.queue.purge(sid)
            if self.raw:
                # the purge poisons only when it found queued byte
                # batches — but the consumer may have drained the dead
                # source's last chunk already, leaving its dangling
                # half line in the engine's per-source tail. Poison
                # unconditionally: eviction is the namespace boundary,
                # and anything the old incarnation left mid-line must
                # not be completed by a restarted stream's first chunk.
                self.queue.poison(sid)
        return out

    # -- serve-loop surface ------------------------------------------------
    @property
    def running(self) -> bool:
        """True while any source can still deliver or records remain
        queued — the serve loop's stream-end condition."""
        with self._roster_lock:
            workers = list(self._workers.values())
        return any(w.alive for w in workers) or self.queue.pending > 0

    def alive(self) -> bool:
        """Collector-probe shape for /healthz back-compat: the tier is
        'alive' while ANY source can still deliver telemetry (per-source
        detail lives in the roster)."""
        with self._roster_lock:
            workers = list(self._workers.values())
        return any(w.alive for w in workers)

    def ticks(self, tick_timeout: float = 2.0, poll_s: float = 0.02):
        """Yield one merged record batch per serve tick until every
        source ended and the queue drained — the generator cli's
        ``_tick_source`` plugs into the serve loop. Deterministic merge:
        batches are ordered by source id within a tick (slot assignment
        then depends only on the record streams, not thread timing)."""
        self.start()
        try:
            while True:
                batch = self._next_tick(tick_timeout, poll_s)
                if batch:
                    yield batch
                elif not self.running:
                    break
        finally:
            self.stop()

    def _next_tick(self, timeout: float, poll_s: float):
        """Assemble one serve tick: grant this tick's lockstep credits,
        then collect at most one batch per source until every live
        lockstep source delivered (or died), the timeout passed, or the
        stream ended. Interval-paced and push (cmd) sources ride along
        whenever their batches arrive."""
        with self._roster_lock:
            workers = list(self._workers.values())
        lockstep_pending: set[int] = set()
        for w in workers:
            if w.spec.lockstep and w.alive:
                w.grant()
                lockstep_pending.add(w.spec.sid)
        deadline = self._clock() + timeout
        got: list[tuple[int, list]] = []
        got_sids: set[int] = set()
        while True:
            self._supervise()
            for sid, recs in self.queue.take(exclude=got_sids):
                got_sids.add(sid)
                lockstep_pending.discard(sid)
                got.append((sid, recs))
            if lockstep_pending:
                # a lockstep source that died/ended between the grant
                # and its emission can never deliver — stop waiting
                with self._roster_lock:
                    live = {
                        sid for sid in lockstep_pending
                        if self._workers[sid].alive
                    }
                lockstep_pending = live
            if got and not lockstep_pending:
                break
            if self._clock() >= deadline:
                break
            if not self.running:
                break
            time.sleep(poll_s)
        if not got:
            return None
        # sid-sorted merge either way: slot assignment then depends only
        # on the record streams, not thread arrival timing
        got.sort(key=lambda b: b[0])
        if self.raw:
            self._publish_metrics()
            return RawTick(got)
        merged: list[TelemetryRecord] = []
        for _sid, recs in got:
            merged.extend(recs)
        self._publish_metrics()
        return merged

    # -- obs surface -------------------------------------------------------
    def pop_provenance(self) -> list[tuple]:
        """This tick's taken-batch provenance — ``(sid, emit, enq, deq,
        n)`` per batch consumed since the last call (obs/latency.py's
        ``begin_tick`` shape). Empty unless the tier was built with
        ``stamp=True``."""
        return self.queue.pop_provenance()

    def roster(self) -> list[dict]:
        """Per-source status rows for /healthz and the metrics plane:
        id, state, lag since last delivery, drop/record counters, and
        the pending quarantine deadline when one is running."""
        drops = self.queue.drops()
        now = self._clock()
        with self._roster_lock:
            workers = sorted(
                self._workers.values(), key=lambda w: w.spec.sid
            )
            quarantine = dict(self._quarantine)
            flaps = dict(self._flaps)
            escalated = set(self._escalated)
            emitted_base = dict(self._emitted_base)
        out = []
        for w in workers:
            snap = w.snapshot()
            snap["drops"] = drops.get(w.spec.sid, 0)
            snap["emitted"] += emitted_base.get(w.spec.sid, 0)
            snap["flaps"] = flaps.get(w.spec.sid, 0)
            snap["escalated"] = w.spec.sid in escalated
            q = quarantine.get(w.spec.sid)
            if q is not None:
                snap["quarantine_expires_s"] = round(max(0.0, q - now), 3)
            out.append(snap)
        return out

    def _publish_metrics(self) -> None:
        m = self._metrics
        if m is None:
            return
        roster = self.roster()
        m.set("fanin_sources", len(roster))
        m.set("fanin_queued_records", self.queue.pending)
        m.set(
            "fanin_sources_dead",
            sum(1 for r in roster if r["state"] == SOURCE_DEAD),
        )
        total_drops = 0
        for r in roster:
            sid = r["id"]
            m.set(f"source_{sid}_state", _STATE_CODE[r["state"]])
            m.set(f"source_{sid}_drops", r["drops"])
            m.set(f"source_{sid}_flaps", r["flaps"])
            total_drops += r["drops"]
            if r["lag_s"] is not None:
                m.set(f"source_{sid}_lag_s", r["lag_s"])
        m.set("fanin_records_dropped", total_drops)


def specs_from_cli(source: str, n_sources: int, spec_texts, *,
                   capture: str | None = None,
                   monitor_cmd: str | None = None,
                   synthetic_flows: int = 1024, max_restarts: int = 5,
                   interval: float = 1.0, lockstep: bool = False,
                   max_ticks: int = 0) -> list[SourceSpec]:
    """Resolve the CLI's fan-in flags into SourceSpecs.

    Explicit ``--source-spec KIND:ARG`` entries win (mixed tiers, sids
    by position). Otherwise ``--sources N`` builds N homogeneous sources
    from the base ``--source``: synthetic splits the flow population
    into N disjoint namespaces (per-source seed and MAC space), replay
    plays the same capture into N namespaces, ryu/controller spawns N
    monitor subprocesses of the same command."""
    if spec_texts:
        return [
            parse_source_spec(
                t, sid, max_restarts=max_restarts, interval=interval,
                lockstep=lockstep,
            )
            for sid, t in enumerate(spec_texts)
        ]
    if n_sources < 1:
        raise ValueError("--sources must be >= 1")
    common = dict(max_restarts=max_restarts, interval=interval,
                  lockstep=lockstep)
    if source == "synthetic":
        per = max(1, synthetic_flows // n_sources)
        return [
            SourceSpec(kind="synthetic", sid=sid, n_flows=per, seed=sid,
                       mac_base=sid * per, max_ticks=max_ticks, **common)
            for sid in range(n_sources)
        ]
    if source == "replay":
        if not capture:
            raise ValueError("--source replay needs --capture FILE")
        return [
            SourceSpec(kind="capture", sid=sid, path=capture, **common)
            for sid in range(n_sources)
        ]
    if source in ("ryu", "controller"):
        if not monitor_cmd:
            raise ValueError(
                f"--sources with --source {source} needs the resolved "
                f"monitor command"
            )
        if n_sources > 1 and "{sid}" not in monitor_cmd:
            # N copies of the byte-identical command fight over the same
            # port/socket: N-1 of them flap through their restart
            # ladders into DEAD — broken by construction, so refuse
            raise ValueError(
                "N live sources need distinct monitor commands: put "
                "'{sid}' in --monitor-cmd (expanded to 0..N-1 per "
                "source) or use repeated --source-spec cmd:..."
            )
        return [
            SourceSpec(
                kind="cmd", sid=sid,
                cmd=monitor_cmd.replace("{sid}", str(sid)), **common,
            )
            for sid in range(n_sources)
        ]
    raise ValueError(f"--sources does not support --source {source}")
