"""CLI: ``python -m traffic_classifier_sdn_tpu.analysis_static``.

Exit status: 0 clean, 1 findings, 2 usage error. ``tools/lint.sh``
wraps this together with the generic ruff/mypy baseline.
"""

from __future__ import annotations

import argparse
import os
import sys

from .framework import (
    LintRunner,
    _iter_py_files,
    collect_modules,
    render_report,
    render_sarif,
)
from .rules import ALL_RULES


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m traffic_classifier_sdn_tpu.analysis_static",
        description="graftlint: project-native static analysis "
                    "(JAX/ctypes/concurrency invariants)",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="files or directories to lint (default: the package)",
    )
    parser.add_argument(
        "--json", action="store_true",
        help="machine-readable findings on stdout "
             "(schema_version-stamped)",
    )
    parser.add_argument(
        "--sarif", metavar="PATH",
        help="also write the findings as SARIF 2.1.0 to PATH, so CI "
             "can annotate them inline (always written, clean or not)",
    )
    parser.add_argument(
        "--lock-graph", metavar="PATH",
        help="write the static lock-order graph (JSON with embedded "
             "DOT) to PATH — the docs/artifacts/lock_order_graph.json "
             "artifact and the runtime witness's cross-check input",
    )
    parser.add_argument(
        "--sync-budget", metavar="PATH",
        help="write the hot-path expected-sync ledger (JSON) to PATH "
             "— the docs/artifacts/hot_path_sync_budget.json artifact "
             "and the syncguard runtime witness's cross-check input",
    )
    parser.add_argument(
        "--select",
        help="comma-separated rule ids to run (default: all)",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print rule ids and descriptions, then exit",
    )
    args = parser.parse_args(argv)

    rules = [cls() for cls in ALL_RULES]
    all_ids = [r.id for r in rules]
    if args.list_rules:
        for r in rules:
            print(f"{r.id:22s} {r.description}")
        return 0
    if args.select:
        wanted = {s.strip() for s in args.select.split(",") if s.strip()}
        if not wanted:
            # running zero rules would print "clean" for a tree that
            # was never linted — a typo'd --select must not pass a gate
            print("--select given but no rule ids parsed",
                  file=sys.stderr)
            return 2
        unknown = wanted - {r.id for r in rules}
        if unknown:
            print(f"unknown rule id(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
        rules = [r for r in rules if r.id in wanted]

    paths = args.paths or [
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ]
    for p in paths:
        if not os.path.exists(p):
            print(f"no such path: {p}", file=sys.stderr)
            return 2
        if not os.path.isdir(p) and not p.endswith(".py"):
            # _iter_py_files would silently skip it and the run would
            # report "clean" for a target that was never linted
            print(f"not a directory or .py file: {p}", file=sys.stderr)
            return 2
    if not any(True for _ in _iter_py_files(paths)):
        # a directory holding zero .py files (typo'd data dir, emptied
        # by a refactor) would otherwise report "clean" for a target
        # that was never linted — same hazard as the non-.py guard
        print("no .py files found under the given path(s)",
              file=sys.stderr)
        return 2

    findings = LintRunner(rules, known_ids=all_ids).run(paths)
    if args.sarif:
        with open(args.sarif, "w", encoding="utf-8") as f:
            f.write(render_sarif(findings, rules))
    if args.lock_graph or args.sync_budget:
        import json as _json

        # display paths pinned to the parent of the TOPMOST enclosing
        # package (walking up through __init__.py), NOT the cwd and
        # not the scanned subtree: a subpackage scan
        # (`--lock-graph g.json pkg/serving`) must still emit
        # `pkg/serving/...` site keys, because the runtime witnesses
        # (locktrace, syncguard) normalize their observed frames
        # against the package root — anything else makes every
        # observed site "unmapped". A fresh parse, not the lint run's
        # modules: the pin changes every display path, and finding
        # paths must stay cwd-relative for editor links.
        anchor = os.path.commonpath(
            [os.path.abspath(p) for p in paths]
        )
        if os.path.isfile(anchor):
            anchor = os.path.dirname(anchor)
        while os.path.exists(os.path.join(anchor, "__init__.py")):
            anchor = os.path.dirname(anchor)
        modules, parse_errs = collect_modules(paths,
                                              relative_to=anchor)
        for fnd in parse_errs:
            # a lock or sync site in an unparseable file would
            # silently vanish from the artifact — say so (the lint
            # findings above already fail the run on the parse error)
            print(f"artifact export: skipping unparseable {fnd.path}: "
                  f"{fnd.message}", file=sys.stderr)
        if args.lock_graph:
            from .graftlock import build_graph_report

            with open(args.lock_graph, "w", encoding="utf-8") as f:
                _json.dump(build_graph_report(modules), f, indent=2,
                           sort_keys=True)
                f.write("\n")
        if args.sync_budget:
            from .graftsync import build_sync_report

            with open(args.sync_budget, "w", encoding="utf-8") as f:
                _json.dump(build_sync_report(modules), f, indent=2,
                           sort_keys=True)
                f.write("\n")
    print(render_report(findings, as_json=args.json))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
