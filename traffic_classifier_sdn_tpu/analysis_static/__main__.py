"""CLI: ``python -m traffic_classifier_sdn_tpu.analysis_static``.

Exit status: 0 clean, 1 findings, 2 usage error. ``tools/lint.sh``
wraps this together with the generic ruff/mypy baseline.
"""

from __future__ import annotations

import argparse
import os
import sys

from .framework import LintRunner, _iter_py_files, render_report
from .rules import ALL_RULES


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m traffic_classifier_sdn_tpu.analysis_static",
        description="graftlint: project-native static analysis "
                    "(JAX/ctypes/concurrency invariants)",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="files or directories to lint (default: the package)",
    )
    parser.add_argument(
        "--json", action="store_true",
        help="machine-readable findings on stdout",
    )
    parser.add_argument(
        "--select",
        help="comma-separated rule ids to run (default: all)",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print rule ids and descriptions, then exit",
    )
    args = parser.parse_args(argv)

    rules = [cls() for cls in ALL_RULES]
    all_ids = [r.id for r in rules]
    if args.list_rules:
        for r in rules:
            print(f"{r.id:22s} {r.description}")
        return 0
    if args.select:
        wanted = {s.strip() for s in args.select.split(",") if s.strip()}
        if not wanted:
            # running zero rules would print "clean" for a tree that
            # was never linted — a typo'd --select must not pass a gate
            print("--select given but no rule ids parsed",
                  file=sys.stderr)
            return 2
        unknown = wanted - {r.id for r in rules}
        if unknown:
            print(f"unknown rule id(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
        rules = [r for r in rules if r.id in wanted]

    paths = args.paths or [
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ]
    for p in paths:
        if not os.path.exists(p):
            print(f"no such path: {p}", file=sys.stderr)
            return 2
        if not os.path.isdir(p) and not p.endswith(".py"):
            # _iter_py_files would silently skip it and the run would
            # report "clean" for a target that was never linted
            print(f"not a directory or .py file: {p}", file=sys.stderr)
            return 2
    if not any(True for _ in _iter_py_files(paths)):
        # a directory holding zero .py files (typo'd data dir, emptied
        # by a refactor) would otherwise report "clean" for a target
        # that was never linted — same hazard as the non-.py guard
        print("no .py files found under the given path(s)",
              file=sys.stderr)
        return 2

    findings = LintRunner(rules, known_ids=all_ids).run(paths)
    print(render_report(findings, as_json=args.json))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
