"""Rule framework: findings, suppressions, and the lint runner.

Kept deliberately dependency-free (``ast`` + ``tokenize`` only) so the
linter can run in any environment the package itself runs in — including
the tier-1 self-enforcement test — with no extra tooling installed.

Suppression contract
--------------------
A finding on line N is suppressed by a comment ON THAT LINE::

    lib.fn()  # graftlint: disable=ctypes-abi -- prototype set in _load

The ``-- reason`` clause is mandatory: a disable comment without a
non-empty reason raises a ``bad-suppression`` finding at the comment,
and ``bad-suppression`` itself cannot be suppressed (otherwise the
escape hatch would be its own escape hatch). Unknown rule ids in a
disable list are also ``bad-suppression`` findings — a typo'd id would
silently stop suppressing after a rule rename.
"""

from __future__ import annotations

import ast
import io
import json
import os
import re
import tokenize
from dataclasses import asdict, dataclass
from collections.abc import Iterable, Iterator, Sequence

BAD_SUPPRESSION = "bad-suppression"
PARSE_ERROR = "parse-error"

_DISABLE_RE = re.compile(
    r"graftlint:\s*disable=(?P<ids>[A-Za-z0-9_,\s-]+?)"
    r"(?:\s+--\s*(?P<reason>.*\S)?)?\s*$"
)


@dataclass(frozen=True)
class Finding:
    """One rule violation at one source location."""

    rule: str
    path: str
    line: int
    message: str
    severity: str = "error"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def to_dict(self) -> dict:
        return asdict(self)


@dataclass
class Suppression:
    line: int
    ids: tuple[str, ...]
    reason: str | None


class ModuleInfo:
    """One parsed source file plus everything rules need from it."""

    def __init__(self, path: str, display_path: str, source: str):
        self.path = path
        self.display_path = display_path
        self.source = source
        self.tree: ast.Module | None = None
        self.parse_error: SyntaxError | None = None
        try:
            self.tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            self.parse_error = e
        self.suppressions: dict[int, Suppression] = {}
        self.bad_suppressions: list[tuple[int, str]] = []
        self._scan_comments()
        # Line → end line of the enclosing SIMPLE statement, so a
        # trailing disable comment on the closing line of a multi-line
        # call still suppresses the finding anchored at the first line.
        # Compound statements (def/if/with/...) are excluded: a comment
        # inside their body must never blanket-suppress the header.
        self._stmt_end: dict[int, int] = {}
        if self.tree is not None:
            simple = (ast.Expr, ast.Assign, ast.AugAssign,
                      ast.AnnAssign, ast.Return, ast.Raise, ast.Assert,
                      ast.Delete)
            for node in ast.walk(self.tree):
                if (
                    isinstance(node, simple)
                    and node.end_lineno is not None
                    and node.end_lineno > node.lineno
                ):
                    for ln in range(node.lineno, node.end_lineno + 1):
                        self._stmt_end[ln] = max(
                            self._stmt_end.get(ln, 0), node.end_lineno
                        )

    def _scan_comments(self) -> None:
        # tokenize (not a raw-line regex) so the directive is only
        # honored in real comments, never inside string literals
        try:
            tokens = tokenize.generate_tokens(
                io.StringIO(self.source).readline
            )
            comments = [
                (t.start[0], t.string)
                for t in tokens
                if t.type == tokenize.COMMENT
            ]
        except (tokenize.TokenError, IndentationError, SyntaxError):
            return
        for line, text in comments:
            m = _DISABLE_RE.search(text)
            if m is None:
                if "graftlint:" in text:
                    self.bad_suppressions.append(
                        (line, "malformed graftlint directive "
                               "(expected 'graftlint: disable=<ids> "
                               "-- <reason>')")
                    )
                continue
            ids = tuple(
                s.strip() for s in m.group("ids").split(",") if s.strip()
            )
            reason = m.group("reason")
            if not reason:
                self.bad_suppressions.append(
                    (line, "suppression without a reason: append "
                           "' -- <why this is safe>'")
                )
                # keep the suppression inactive: an unjustified disable
                # must not hide the underlying finding either
                continue
            self.suppressions[line] = Suppression(line, ids, reason)

    def suppressed(self, finding: Finding) -> bool:
        if finding.rule == BAD_SUPPRESSION:
            return False
        end = self._stmt_end.get(finding.line, finding.line)
        for ln in range(finding.line, end + 1):
            s = self.suppressions.get(ln)
            if s is not None and finding.rule in s.ids:
                return True
        return False


class Rule:
    """Base class: one invariant, one stable id, per-module findings.

    Subclasses override ``check_module``. Rules that need the whole
    scanned tree at once (cross-file registries) instead override
    ``check_project``, which runs after every module has been parsed.
    """

    id: str = ""
    severity: str = "error"
    description: str = ""

    def check_module(self, mod: ModuleInfo) -> Iterator[Finding]:
        return iter(())

    def check_project(
        self, modules: Sequence[ModuleInfo]
    ) -> Iterator[Finding]:
        return iter(())

    def finding(self, mod: ModuleInfo, line: int, message: str) -> Finding:
        return Finding(self.id, mod.display_path, line, message,
                       self.severity)


def _iter_py_files(paths: Iterable[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs
                    if d not in ("__pycache__", ".git")
                )
                for name in sorted(files):
                    if name.endswith(".py"):
                        yield os.path.join(root, name)
        elif p.endswith(".py"):
            yield p


def collect_modules(
    paths: Iterable[str], relative_to: str | None = None
) -> tuple[list[ModuleInfo], list[Finding]]:
    """Parse every .py under ``paths`` exactly once (overlapping inputs
    deduped by realpath). Returns the parsed modules plus parse-error
    findings for the rest. ``relative_to`` pins display paths against a
    fixed root (the lock-graph artifact must not depend on the caller's
    cwd); default is cwd-relative, same as before."""
    modules: list[ModuleInfo] = []
    findings: list[Finding] = []
    visited: set[str] = set()
    for path in _iter_py_files(paths):
        # overlapping inputs (`lint.sh pkg pkg/sub`) must not parse
        # a file twice: duplicate findings, duplicate registries
        real = os.path.realpath(path)
        if real in visited:
            continue
        visited.add(real)
        display = os.path.relpath(path, relative_to)
        if display.startswith(".."):
            display = path
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
        except (OSError, UnicodeDecodeError) as e:
            findings.append(Finding(PARSE_ERROR, display, 1, str(e)))
            continue
        mod = ModuleInfo(path, display, source)
        if mod.parse_error is not None:
            findings.append(Finding(
                PARSE_ERROR, display,
                mod.parse_error.lineno or 1,
                f"syntax error: {mod.parse_error.msg}",
            ))
            continue
        modules.append(mod)
    return modules, findings


class LintRunner:
    """Parse once, run every rule, apply suppressions."""

    def __init__(self, rules: Sequence[Rule],
                 known_ids: Iterable[str] | None = None):
        ids = [r.id for r in rules]
        assert len(ids) == len(set(ids)), f"duplicate rule ids: {ids}"
        self.rules = list(rules)
        # known_ids may be wider than the rules being RUN (a --select
        # scoped run): a suppression naming a real-but-unselected rule
        # is valid, not a bad-suppression
        self.known_ids = (
            set(known_ids if known_ids is not None else ids)
            | {BAD_SUPPRESSION, PARSE_ERROR}
        )

    def run(self, paths: Iterable[str]) -> list[Finding]:
        modules, findings = collect_modules(paths)
        by_path = {m.display_path: m for m in modules}
        raw: list[Finding] = []
        for mod in modules:
            for line, msg in mod.bad_suppressions:
                raw.append(Finding(BAD_SUPPRESSION, mod.display_path,
                                   line, msg))
            for s in mod.suppressions.values():
                unknown = [i for i in s.ids if i not in self.known_ids]
                if unknown:
                    raw.append(Finding(
                        BAD_SUPPRESSION, mod.display_path, s.line,
                        f"unknown rule id(s) in disable list: "
                        f"{', '.join(unknown)}",
                    ))
            for rule in self.rules:
                raw.extend(rule.check_module(mod))
        for rule in self.rules:
            raw.extend(rule.check_project(modules))

        emitted = set()
        for f in raw:
            mod = by_path.get(f.path)
            if mod is not None and mod.suppressed(f):
                continue
            if f not in emitted:  # e.g. a def nested in a module-level
                emitted.add(f)    # `if` is walked by two scope passes
                findings.append(f)
        findings.sort(key=lambda f: (f.path, f.line, f.rule))
        return findings


def lint_paths(paths: Iterable[str],
               rules: Sequence[Rule] | None = None) -> list[Finding]:
    """Convenience wrapper: lint ``paths`` with ``rules`` (default: all
    project rules) and return the surviving findings."""
    if rules is None:
        from .rules import ALL_RULES

        rules = [cls() for cls in ALL_RULES]
    return LintRunner(rules).run(paths)


# the --json report schema: 2 added schema_version itself (the field
# consumers key migrations on) — the findings array is unchanged
JSON_SCHEMA_VERSION = 2


def render_report(findings: Sequence[Finding], as_json: bool) -> str:
    if as_json:
        return json.dumps(
            {"schema_version": JSON_SCHEMA_VERSION,
             "findings": [f.to_dict() for f in findings],
             "count": len(findings)},
            indent=2,
        )
    if not findings:
        return "graftlint: clean"
    lines = [f.render() for f in findings]
    lines.append(f"graftlint: {len(findings)} finding(s)")
    return "\n".join(lines)


def render_sarif(findings: Sequence[Finding],
                 rules: Sequence[Rule]) -> str:
    """SARIF 2.1.0 — the interchange format CI annotators consume
    (GitHub code scanning et al.), so a graftlint finding lands as an
    inline annotation on the offending line instead of a log grep.
    ``tools/lint.sh`` records the written path in its JSON summary."""
    rule_meta = [
        {
            "id": r.id,
            "shortDescription": {"text": r.description or r.id},
            "defaultConfiguration": {
                "level": "error" if r.severity == "error" else "warning"
            },
        }
        for r in rules
    ]
    known = {r.id for r in rules}
    extra = sorted(
        {f.rule for f in findings} - known
    )  # bad-suppression / parse-error
    rule_meta.extend(
        {"id": rid, "shortDescription": {"text": rid}} for rid in extra
    )
    results = [
        {
            "ruleId": f.rule,
            "level": "error" if f.severity == "error" else "warning",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": f.path.replace(os.sep, "/")
                    },
                    "region": {"startLine": max(1, f.line)},
                },
            }],
        }
        for f in findings
    ]
    doc = {
        "$schema": (
            "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
            "master/Schemata/sarif-schema-2.1.0.json"
        ),
        "version": "2.1.0",
        "runs": [{
            "tool": {
                "driver": {
                    "name": "graftlint",
                    "informationUri": "docs/STATIC_ANALYSIS.md",
                    "rules": rule_meta,
                },
            },
            "results": results,
        }],
    }
    return json.dumps(doc, indent=2)
