"""graftlock: whole-program concurrency analysis.

PR 2's ``lock-discipline`` rule is per-function: it proves shared state
is accessed *under a* lock. Nothing there proves locks are acquired in a
CONSISTENT ORDER across threads, that no unbounded blocking call runs
while a lock is held, or that every spawned worker thread has a
reachable retire path — exactly the bug classes PRs 5–6 fixed by hand
(the SIGTERM ring-lock deadlock deferral, the per-drift-cycle
watchdog-thread leak). This module makes the analyzer find them:

``lock-order``
    Builds an interprocedural call graph over the scanned tree plus a
    lock-acquisition summary per function, propagates held-lock sets
    through call edges into a global lock-order graph, and reports any
    cycle — two threads interleaving the two acquisition chains of an
    AB/BA cycle deadlock with both locks held forever. Also reports a
    re-acquisition of a non-reentrant ``threading.Lock`` already held
    on the same path (self-deadlock, the single-thread variant).

``blocking-under-lock``
    Flags unbounded blocking operations — zero-arg ``Thread.join()`` /
    ``queue.get()`` / ``Event.wait()`` / ``communicate()``, subprocess
    spawns, ``open()``/pipe reads, ``block_until_ready`` — reachable
    (transitively, through the call graph) while any project lock is
    held. A wedged blocking call under a lock wedges every thread that
    ever takes that lock; the flight-recorder ring held across a slow
    dump would freeze the whole obs plane, which is why the recorder
    snapshots under the lock and writes outside it.

``thread-lifecycle``
    Every ``threading.Thread(...)`` constructed in the scanned tree
    must be daemonized or have a reachable ``join`` on its binding in
    the owning class's surface (a local bound from the attribute — the
    ``thread, self._thread = self._thread, None`` swap idiom — counts).
    A non-daemon worker with no retire path keeps the interpreter alive
    after the serve exits; a daemon-less leak per drift cycle is the
    watchdog-thread bug PR 6 fixed by hand.

Bounded-blocking allowlist policy (docs/STATIC_ANALYSIS.md):

- A ``wait``/``join``/``get``/``communicate`` call with a REAL timeout
  (a non-``None`` value, positional or keyword) is bounded — the
  watchdog's deadline-guarded ``self._lock.wait(left)`` waits are the
  model. The explicit unbounded spellings — ``join(None)``,
  ``wait(timeout=None)``, ``get(True)``, ``communicate(data)`` — do
  not pass as bounded.
- A zero-arg ``Condition.wait()`` on the lock being held is exempt
  *with respect to that lock*: waiting releases the condition it waits
  on. It still blocks every OTHER held lock, and is flagged for those.
- Everything else intentional carries a reasoned
  ``# graftlint: disable=blocking-under-lock -- <why bounded>``
  suppression (e.g. the serving-checkpoint rotation lock, whose whole
  point is serializing the sweep+save+prune file I/O pass).

Lock identity is lockdep-style: a lock is keyed by its owning class
attribute (``serving/degrade.py::DeviceWatchdog._lock``), module global
(``native/forest.py::_lock``), or lock-returning factory
(``io/serving_checkpoint.py::_rotation_lock()``) — one node per lock
*class*, not per instance, which is what lets the runtime witness
(``utils/locktrace.py``) map observed acquisitions back onto this graph
via construction sites. ``build_graph_report`` exports the graph (JSON
+ DOT) as ``docs/artifacts/lock_order_graph.json`` so review can diff
concurrency structure across PRs.

Resolution is deliberately syntactic-plus-conventions: ``self.m()``,
module functions, package-relative imports, nested defs, attributes
typed by ``self.x = ClassName(...)`` assignments or parameter
annotations, and ``property`` accesses on typed attributes. Untyped
attributes fall back to the curated convention map ``_ATTR_TYPE_HINTS``
(``_recorder`` is always the FlightRecorder, etc.); the runtime witness
cross-check exists precisely to catch edges this static pass misses.
"""

from __future__ import annotations

import ast
import os
from collections.abc import Iterator, Sequence
from dataclasses import dataclass, field

from .framework import Finding, ModuleInfo, Rule

LOCK_ORDER = "lock-order"
BLOCKING_UNDER_LOCK = "blocking-under-lock"
THREAD_LIFECYCLE = "thread-lifecycle"

# attribute-name → class-name conventions for attrs whose constructor
# the scanner cannot see (objects built by the CLI and passed down).
# Resolved against the scanned tree by class NAME; a hint naming a
# class absent from the scan is simply inert.
_ATTR_TYPE_HINTS = {
    "_recorder": "FlightRecorder",
    "_metrics": "Metrics",
    "_health": "HealthState",
    "_tracer": "Tracer",
    "_watchdog": "DeviceWatchdog",
    "_retrainer": "BackgroundRetrainer",
    "_handoff": "Handoff",
    "_gate": "DriftGate",
}

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}


def _dotted(node: ast.AST) -> str | None:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _terminal(node: ast.AST) -> str | None:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _is_lock_name(name: str | None) -> bool:
    return name is not None and (name == "_lock" or name.endswith("_lock"))


# ---------------------------------------------------------------------------
# project index
# ---------------------------------------------------------------------------


@dataclass
class _ClassInfo:
    name: str
    mod: ModuleInfo
    node: ast.ClassDef
    methods: dict[str, ast.FunctionDef] = field(default_factory=dict)
    properties: set[str] = field(default_factory=set)
    # attr → {(module_path, class_name)} — from self.x = Cls(...) /
    # annotated-parameter assignment / the curated hint table
    attr_types: dict[str, set[tuple[str, str]]] = field(
        default_factory=dict
    )


class _Project:
    """Symbol tables over one scanned module set: functions, classes,
    import aliases, and attribute types — everything call resolution
    needs, built once before the per-function walks."""

    def __init__(self, modules: Sequence[ModuleInfo]):
        self.modules = [m for m in modules if m.tree is not None]
        self._real_to_mod = {
            os.path.realpath(m.path): m for m in self.modules
        }
        self.functions: dict[str, dict[str, ast.FunctionDef]] = {}
        self.classes: dict[str, dict[str, _ClassInfo]] = {}
        # module path → local name → ("module", path) | ("symbol", path,
        # name) | ("class", path, name)
        self.imports: dict[str, dict[str, tuple]] = {}
        # module path → global name → {(path, class_name)} for
        # module-level x = Cls(...) assignments (LazyLib handles)
        self.global_types: dict[str, dict[str, set[tuple[str, str]]]] = {}
        self.classes_by_name: dict[str, list[tuple[str, str]]] = {}
        for m in self.modules:
            self._index_defs(m)
        for m in self.modules:
            self._index_imports(m)
        for m in self.modules:
            self._index_types(m)

    # -- defs ---------------------------------------------------------------
    def _index_defs(self, m: ModuleInfo) -> None:
        fns: dict[str, ast.FunctionDef] = {}
        classes: dict[str, _ClassInfo] = {}
        assert m.tree is not None
        for node in m.tree.body:
            if isinstance(node, ast.FunctionDef):
                fns[node.name] = node
            elif isinstance(node, ast.ClassDef):
                ci = _ClassInfo(node.name, m, node)
                for item in node.body:
                    if not isinstance(item, ast.FunctionDef):
                        continue
                    ci.methods[item.name] = item
                    if any(
                        _terminal(d) == "property"
                        for d in item.decorator_list
                    ):
                        ci.properties.add(item.name)
                classes[node.name] = ci
                self.classes_by_name.setdefault(node.name, []).append(
                    (m.display_path, node.name)
                )
        self.functions[m.display_path] = fns
        self.classes[m.display_path] = classes

    # -- imports ------------------------------------------------------------
    def _find_module(self, base: str) -> ModuleInfo | None:
        for cand in (base + ".py", os.path.join(base, "__init__.py")):
            mod = self._real_to_mod.get(os.path.realpath(cand))
            if mod is not None:
                return mod
        return None

    def _find_by_suffix(self, parts: list[str]) -> ModuleInfo | None:
        """Absolute-import resolution: the scanned module whose real
        path ends with ``parts`` (as a module or a package)."""
        suffixes = (
            os.sep + os.path.join(*parts) + ".py",
            os.sep + os.path.join(*parts, "__init__.py"),
        )
        for real, mod in self._real_to_mod.items():
            if real.endswith(suffixes):
                return mod
        return None

    def _index_imports(self, m: ModuleInfo) -> None:
        table: dict[str, tuple] = {}
        base_dir = os.path.dirname(os.path.abspath(m.path))
        assert m.tree is not None
        for node in ast.walk(m.tree):
            if isinstance(node, ast.ImportFrom):
                if node.level:
                    d = base_dir
                    for _ in range(node.level - 1):
                        d = os.path.dirname(d)
                    root = (
                        os.path.join(d, *node.module.split("."))
                        if node.module else d
                    )
                    base_mod = self._find_module(root)
                else:
                    parts = (node.module or "").split(".")
                    root = None
                    base_mod = self._find_by_suffix(parts) if parts[0] else None
                for alias in node.names:
                    name = alias.asname or alias.name
                    sub = None
                    if node.level and root is not None:
                        sub = self._find_module(
                            os.path.join(root, alias.name)
                        )
                    elif not node.level and node.module:
                        sub = self._find_by_suffix(
                            (node.module + "." + alias.name).split(".")
                        )
                    if sub is not None:
                        table[name] = ("module", sub.display_path)
                    elif base_mod is not None:
                        target = base_mod.display_path
                        if alias.name in self.classes.get(target, {}):
                            table[name] = ("class", target, alias.name)
                        else:
                            table[name] = ("symbol", target, alias.name)
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    mod = self._find_by_suffix(alias.name.split("."))
                    if mod is not None:
                        name = alias.asname or alias.name
                        if "." not in name:
                            table[name] = ("module", mod.display_path)
        self.imports[m.display_path] = table

    # -- attribute / global typing ------------------------------------------
    def _resolve_class_ref(
        self, m: ModuleInfo, expr: ast.AST
    ) -> tuple[str, str] | None:
        """``Cls`` / ``mod.Cls`` / imported class name → (path, class)."""
        if isinstance(expr, ast.Name):
            if expr.id in self.classes.get(m.display_path, {}):
                return (m.display_path, expr.id)
            imp = self.imports.get(m.display_path, {}).get(expr.id)
            if imp is not None and imp[0] == "class":
                return (imp[1], imp[2])
        elif isinstance(expr, ast.Attribute) and isinstance(
            expr.value, ast.Name
        ):
            imp = self.imports.get(m.display_path, {}).get(expr.value.id)
            if imp is not None and imp[0] == "module":
                if expr.attr in self.classes.get(imp[1], {}):
                    return (imp[1], expr.attr)
        return None

    def _annotation_class(
        self, m: ModuleInfo, ann: ast.AST | None
    ) -> tuple[str, str] | None:
        if ann is None:
            return None
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            name = ann.value.strip().strip("\"'")
            if name in self.classes.get(m.display_path, {}):
                return (m.display_path, name)
            hits = self.classes_by_name.get(name)
            return hits[0] if hits else None
        if isinstance(ann, ast.BinOp):  # "Cls | None"
            return (self._annotation_class(m, ann.left)
                    or self._annotation_class(m, ann.right))
        ref = self._resolve_class_ref(m, ann)
        if ref is not None:
            return ref
        name = _terminal(ann)
        if name:
            hits = self.classes_by_name.get(name)
            if hits:
                return hits[0]
        return None

    def _index_types(self, m: ModuleInfo) -> None:
        assert m.tree is not None
        globals_: dict[str, set[tuple[str, str]]] = {}
        for node in m.tree.body:
            if isinstance(node, ast.Assign):
                refs = {
                    r for sub in ast.walk(node.value)
                    if isinstance(sub, ast.Call)
                    and (r := self._resolve_class_ref(m, sub.func))
                }
                if refs:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            globals_.setdefault(t.id, set()).update(refs)
        self.global_types[m.display_path] = globals_
        for ci in self.classes[m.display_path].values():
            for fn in ci.methods.values():
                params = {
                    a.arg: self._annotation_class(m, a.annotation)
                    for a in (fn.args.posonlyargs + fn.args.args
                              + fn.args.kwonlyargs)
                }
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Assign):
                        continue
                    for t in node.targets:
                        if not (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                        ):
                            continue
                        refs = {
                            r for sub in ast.walk(node.value)
                            if isinstance(sub, ast.Call)
                            and (r := self._resolve_class_ref(m, sub.func))
                        }
                        for sub in ast.walk(node.value):
                            if isinstance(sub, ast.Name) and params.get(
                                sub.id
                            ):
                                refs.add(params[sub.id])
                        if refs:
                            ci.attr_types.setdefault(
                                t.attr, set()
                            ).update(refs)
            for attr, cls_name in _ATTR_TYPE_HINTS.items():
                if attr not in ci.attr_types:
                    hits = self.classes_by_name.get(cls_name)
                    if hits:
                        ci.attr_types[attr] = {hits[0]}

    def class_info(self, path: str, name: str) -> _ClassInfo | None:
        return self.classes.get(path, {}).get(name)


# ---------------------------------------------------------------------------
# per-function summaries
# ---------------------------------------------------------------------------


@dataclass
class _Blocking:
    kind: str
    line: int
    label: str
    receiver_lock: str | None  # condition-own-lock exemption


@dataclass
class _Summary:
    mod: ModuleInfo
    cls: str | None
    name: str
    node: ast.FunctionDef
    acquires: list[tuple[str, int]] = field(default_factory=list)
    # intra-function nested acquisitions: (a, a_line, b, b_line)
    edges: list[tuple[str, int, str, int]] = field(default_factory=list)
    # (callee summary key, call line, held [(lock, line)...])
    calls: list[tuple[int, int, tuple]] = field(default_factory=list)
    blocking: list[_Blocking] = field(default_factory=list)


class _Analysis:
    """The one interprocedural pass the three rules and the graph
    export all share (memoized per scanned module set)."""

    def __init__(self, modules: Sequence[ModuleInfo]):
        self.project = _Project(modules)
        self.summaries: dict[int, _Summary] = {}  # id(fn node) → summary
        self._fn_key: dict[int, int] = {}
        # lock id → {"kind", "constructed_at": [(path, line)]}
        self.lock_nodes: dict[str, dict] = {}
        self._closure_acq: dict[int, dict] = {}
        self._closure_blk: dict[int, dict] = {}
        self.edges: dict[tuple[str, str], dict] = {}
        self.self_edges: list[dict] = []
        self.blocking_hits: list[dict] = []  # filled by the scan walk
        self._scan_constructions()
        for m in self.project.modules:
            self._scan_module(m)
        self._compute_closures()
        self._propagate()

    # -- lock keys ----------------------------------------------------------
    def _lock_key(self, expr: ast.AST, m: ModuleInfo,
                  cls: str | None) -> str | None:
        if isinstance(expr, ast.Attribute) and _is_lock_name(expr.attr):
            base = expr.value
            if isinstance(base, ast.Name) and base.id == "self":
                owner = cls if cls is not None else "<module>"
                return f"{m.display_path}::{owner}.{expr.attr}"
            if (
                isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self"
                and cls is not None
            ):
                ci = self.project.class_info(m.display_path, cls)
                types = ci.attr_types.get(base.attr) if ci else None
                if types:
                    tpath, tcls = sorted(types)[0]
                    return f"{tpath}::{tcls}.{expr.attr}"
                return (f"{m.display_path}::{cls}"
                        f".{base.attr}.{expr.attr}")
            return None
        if isinstance(expr, ast.Name) and _is_lock_name(expr.id):
            return f"{m.display_path}::{expr.id}"
        if isinstance(expr, ast.Call):
            name = _terminal(expr.func)
            if _is_lock_name(name):
                # lock-returning factory: key by the factory, resolved
                # to its defining module when imported
                imp = self.project.imports.get(
                    m.display_path, {}
                ).get(name or "")
                if imp is not None and imp[0] == "symbol":
                    return f"{imp[1]}::{name}()"
                return f"{m.display_path}::{name}()"
        return None

    # -- construction sites (the witness mapping + kind table) --------------
    def _scan_constructions(self) -> None:
        for m in self.project.modules:
            assert m.tree is not None
            stack: list[tuple[ast.AST, str | None, str | None]] = [
                (m.tree, None, None)
            ]
            while stack:
                node, cls, fn = stack.pop()
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, ast.ClassDef):
                        stack.append((child, child.name, fn))
                    elif isinstance(child, ast.FunctionDef):
                        stack.append((child, cls, child.name))
                    else:
                        stack.append((child, cls, fn))
                if not isinstance(node, ast.Assign) or not isinstance(
                    node.value, ast.Call
                ):
                    continue
                ctor = _dotted(node.value.func)
                if ctor is None:
                    continue
                head, _, tail = ctor.rpartition(".")
                if tail not in _LOCK_CTORS or head not in (
                    "", "threading"
                ):
                    continue
                key = None
                for t in node.targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                        and cls is not None
                        and _is_lock_name(t.attr)
                    ):
                        key = f"{m.display_path}::{cls}.{t.attr}"
                    elif isinstance(t, ast.Name) and _is_lock_name(t.id):
                        if fn is None and cls is None:
                            key = f"{m.display_path}::{t.id}"
                if key is None and fn is not None and _is_lock_name(fn):
                    # built inside a lock-returning factory (the
                    # per-directory rotation-lock registry shape)
                    key = f"{m.display_path}::{fn}()"
                if key is None:
                    continue
                entry = self.lock_nodes.setdefault(
                    key, {"kind": tail, "constructed_at": []}
                )
                entry["constructed_at"].append(
                    (m.display_path, node.lineno)
                )

    # -- the function walk --------------------------------------------------
    def _scan_module(self, m: ModuleInfo) -> None:
        assert m.tree is not None
        for node in m.tree.body:
            if isinstance(node, ast.FunctionDef):
                self._scan_function(m, None, node)
            elif isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, ast.FunctionDef):
                        self._scan_function(m, node.name, item)

    def _local_defs(self, fn: ast.FunctionDef) -> dict[str, ast.FunctionDef]:
        return {
            n.name: n for n in ast.walk(fn)
            if isinstance(n, ast.FunctionDef) and n is not fn
        }

    def _scan_function(self, m: ModuleInfo, cls: str | None,
                       fn: ast.FunctionDef) -> None:
        if id(fn) in self.summaries:
            return
        s = _Summary(m, cls, fn.name, fn)
        self.summaries[id(fn)] = s
        local_defs = self._local_defs(fn)
        for nested in local_defs.values():
            if id(nested) not in self.summaries:
                self._scan_function(m, cls, nested)

        def resolve(call: ast.Call) -> list[ast.FunctionDef]:
            func = call.func
            out: list[ast.FunctionDef] = []
            if isinstance(func, ast.Name):
                name = func.id
                if name in local_defs:
                    return [local_defs[name]]
                mod_fns = self.project.functions.get(m.display_path, {})
                if name in mod_fns:
                    return [mod_fns[name]]
                ref = self.project._resolve_class_ref(m, func)
                if ref is not None:
                    ci = self.project.class_info(*ref)
                    init = ci.methods.get("__init__") if ci else None
                    return [init] if init is not None else []
                imp = self.project.imports.get(
                    m.display_path, {}
                ).get(name)
                if imp is not None and imp[0] == "symbol":
                    target = self.project.functions.get(imp[1], {})
                    if imp[2] in target:
                        return [target[imp[2]]]
                return []
            if not isinstance(func, ast.Attribute):
                return []
            base, attr = func.value, func.attr
            if isinstance(base, ast.Name):
                if base.id == "self" and cls is not None:
                    ci = self.project.class_info(m.display_path, cls)
                    if ci and attr in ci.methods:
                        return [ci.methods[attr]]
                    return []
                imp = self.project.imports.get(
                    m.display_path, {}
                ).get(base.id)
                if imp is not None and imp[0] == "module":
                    target = self.project.functions.get(imp[1], {})
                    if attr in target:
                        return [target[attr]]
                    if attr in self.project.classes.get(imp[1], {}):
                        ci = self.project.class_info(imp[1], attr)
                        init = ci.methods.get("__init__") if ci else None
                        return [init] if init is not None else []
                for ref in sorted(self.project.global_types.get(
                    m.display_path, {}
                ).get(base.id, ())):
                    ci = self.project.class_info(*ref)
                    if ci and attr in ci.methods:
                        out.append(ci.methods[attr])
                return out
            if (
                isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self"
                and cls is not None
            ):
                ci = self.project.class_info(m.display_path, cls)
                types = ci.attr_types.get(base.attr, ()) if ci else ()
                for ref in sorted(types):
                    tci = self.project.class_info(*ref)
                    if tci and attr in tci.methods:
                        out.append(tci.methods[attr])
            return out

        def property_targets(node: ast.Attribute) -> list[ast.FunctionDef]:
            """``self.x`` / ``self.attr.x`` attribute LOADS that invoke
            a property on a known class — a lock acquired inside a
            property is as real as one inside a method call."""
            base = node.value
            if isinstance(base, ast.Name) and base.id == "self" and cls:
                ci = self.project.class_info(m.display_path, cls)
                if ci and node.attr in ci.properties:
                    return [ci.methods[node.attr]]
            if (
                isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self"
                and cls is not None
            ):
                ci = self.project.class_info(m.display_path, cls)
                types = ci.attr_types.get(base.attr, ()) if ci else ()
                return [
                    tci.methods[node.attr]
                    for ref in sorted(types)
                    if (tci := self.project.class_info(*ref))
                    and node.attr in tci.properties
                ]
            return []

        def visit(node: ast.AST, held: tuple) -> None:
            if isinstance(node, ast.FunctionDef) and node is not fn:
                return  # nested defs get their own summaries
            if isinstance(node, (ast.With, ast.AsyncWith)):
                new_held = held
                for item in node.items:
                    # items enter left-to-right: item i's expression
                    # evaluates with items <i ALREADY held (`with
                    # self._lock, open(p):` runs the open under the
                    # lock)
                    visit(item.context_expr, new_held)
                    key = self._lock_key(item.context_expr, m, cls)
                    if key is not None:
                        line = item.context_expr.lineno
                        s.acquires.append((key, line))
                        for a, al in new_held:
                            s.edges.append((a, al, key, line))
                        new_held = new_held + ((key, line),)
                for child in node.body:
                    visit(child, new_held)
                return
            if isinstance(node, ast.Call):
                b = self._classify_blocking(node, m, cls)
                if b is not None:
                    s.blocking.append(b)
                    # direct (same-function) blocking under locks held
                    # RIGHT HERE — recorded in this one walk so the
                    # with-entry rule lives in exactly one place
                    for a, al in held:
                        if b.receiver_lock is not None and (
                            b.receiver_lock == a
                        ):
                            continue  # waiting releases that lock
                        self.blocking_hits.append({
                            "lock": a, "kind": b.kind,
                            "path": m.display_path, "line": b.line,
                            "chain": [
                                (m.display_path, al,
                                 f"acquires {_short(a)}"),
                                (m.display_path, b.line,
                                 f"blocks on {b.label}"),
                            ],
                        })
                # explicit .acquire() on a lock expression: summary +
                # edge only (no release tracking — the with form is the
                # package idiom; acquire() is the rare manual case)
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "acquire"
                ):
                    key = self._lock_key(node.func.value, m, cls)
                    if key is not None:
                        s.acquires.append((key, node.lineno))
                        for a, al in held:
                            s.edges.append((a, al, key, node.lineno))
                callees = resolve(node)
                for c in callees:
                    if id(c) not in self.summaries:
                        # method of a class scanned in another module
                        owner = self._owner_of(c)
                        if owner is not None:
                            self._scan_function(owner[0], owner[1], c)
                    if id(c) in self.summaries:
                        s.calls.append((id(c), node.lineno, held))
            elif isinstance(node, ast.Attribute) and isinstance(
                node.ctx, ast.Load
            ):
                for c in property_targets(node):
                    if id(c) not in self.summaries:
                        owner = self._owner_of(c)
                        if owner is not None:
                            self._scan_function(owner[0], owner[1], c)
                    if id(c) in self.summaries:
                        s.calls.append((id(c), node.lineno, held))
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for child in fn.body:
            visit(child, ())

    def _owner_of(
        self, fn: ast.FunctionDef
    ) -> tuple[ModuleInfo, str | None] | None:
        for path, classes in self.project.classes.items():
            for ci in classes.values():
                if fn in ci.methods.values():
                    return ci.mod, ci.name
        for path, fns in self.project.functions.items():
            if fn in fns.values():
                for m in self.project.modules:
                    if m.display_path == path:
                        return m, None
        return None

    # -- blocking classification --------------------------------------------
    @staticmethod
    def _bounds(call: ast.Call, timeout_pos: int) -> bool:
        """True when the call supplies a REAL timeout: a non-None value
        at positional index ``timeout_pos`` or as ``timeout=``. The
        explicit unbounded spellings — ``join(None)``,
        ``wait(timeout=None)`` — must not pass as bounded."""

        def real(v: ast.AST) -> bool:
            return not (
                isinstance(v, ast.Constant) and v.value is None
            )

        if len(call.args) > timeout_pos:
            return real(call.args[timeout_pos])
        for k in call.keywords:
            if k.arg == "timeout":
                return real(k.value)
        return False

    def _classify_blocking(self, call: ast.Call, m: ModuleInfo,
                           cls: str | None) -> _Blocking | None:
        func = call.func
        kw = {k.arg for k in call.keywords}
        if isinstance(func, ast.Name):
            if func.id == "open":
                return _Blocking("file-io", call.lineno, "open()", None)
            return None
        if not isinstance(func, ast.Attribute):
            return None
        attr = func.attr
        dotted = _dotted(func) or ""
        if dotted.startswith(("os.path.", "posixpath.", "str.")):
            return None
        if dotted.startswith("subprocess.") and attr in (
            "run", "call", "check_call", "check_output", "Popen"
        ):
            return _Blocking(
                "subprocess", call.lineno, f"{dotted}()", None
            )
        recv_lock = self._lock_key(func.value, m, cls)
        if attr == "wait":
            if not self._bounds(call, 0):
                return _Blocking(
                    "wait", call.lineno, f"{dotted or attr}()",
                    recv_lock,
                )
        elif attr == "wait_for":
            if not self._bounds(call, 1):
                return _Blocking(
                    "wait", call.lineno, f"{dotted or attr}()",
                    recv_lock,
                )
        elif attr == "join":
            # a Thread/Process/handoff join with no real timeout —
            # join() and the explicit join(None)/join(timeout=None)
            # spellings alike (str.join's iterable is a non-None arg,
            # so it reads as bounded and never lands here)
            if not self._bounds(call, 0):
                return _Blocking(
                    "join", call.lineno, f"{dotted or attr}()", None
                )
        elif attr == "get":
            # queue.get signature is (block=True, timeout=None); a
            # positional first arg that is the literal True is the
            # explicit blocking spelling. Other positional firsts are
            # ambiguous with dict.get(key) and stay exempt.
            block_true = bool(call.args) and isinstance(
                call.args[0], ast.Constant
            ) and call.args[0].value is True
            plain = (not call.args and "block" not in kw
                     and not self._bounds(call, 1))
            if plain or (block_true and not self._bounds(call, 1)):
                return _Blocking(
                    "queue-get", call.lineno, f"{dotted or attr}()",
                    None,
                )
        elif attr == "communicate":
            # communicate(input=..., timeout=...): only a real timeout
            # bounds it — the input payload does not
            if not self._bounds(call, 1):
                return _Blocking(
                    "subprocess", call.lineno,
                    f"{dotted or attr}()", None,
                )
        elif attr == "block_until_ready":
            return _Blocking(
                "device-sync", call.lineno,
                f"{dotted or attr}()", None,
            )
        elif attr in ("read", "read1", "readline", "readlines",
                      "recv", "accept", "sendall"):
            # receiver heuristics keep dict/str methods out; these
            # names on pipes/sockets block on the peer
            if dotted.startswith(("self._queue.", "np.", "json.")):
                return None
            return _Blocking(
                "io", call.lineno, f"{dotted or attr}()", None
            )
        return None

    # -- propagation --------------------------------------------------------
    def _compute_closures(self) -> None:
        """Transitive (acquired, blocking) per function, by monotone
        fixed-point over the call graph — sets only ever grow and keys
        are bounded by the lock/blocking-site population, so this is
        linear-ish and safe on call cycles AND on diamond-shaped call
        graphs (a memo-at-top-only recursion re-walks every diamond:
        exponential in depth — measured 37 s at depth 20).

        ``_closure_acq[key]``: lock id → representative chain (list of
        (path, line, what)); ``_closure_blk[key]``: (kind, path, line)
        → (chain, receiver_lock). The first chain found wins — findings
        need one concrete path, not all of them."""
        for key, s in self.summaries.items():
            acq: dict[str, list] = {}
            for lock, line in s.acquires:
                acq.setdefault(
                    lock, [(s.mod.display_path, line,
                            f"acquires {_short(lock)}")]
                )
            blk: dict[tuple, tuple] = {}
            for b in s.blocking:
                blk.setdefault(
                    (b.kind, s.mod.display_path, b.line),
                    ([(s.mod.display_path, b.line,
                       f"blocks on {b.label}")],
                     b.receiver_lock),
                )
            self._closure_acq[key] = acq
            self._closure_blk[key] = blk
        changed = True
        while changed:
            changed = False
            for key, s in self.summaries.items():
                acq = self._closure_acq[key]
                blk = self._closure_blk[key]
                for callee, line, _held in s.calls:
                    c = self.summaries.get(callee)
                    if c is None:
                        continue
                    hop = (s.mod.display_path, line,
                           f"calls {c.cls + '.' if c.cls else ''}"
                           f"{c.name}")
                    for lock, chain in self._closure_acq[callee].items():
                        if lock not in acq:
                            acq[lock] = [hop, *chain]
                            changed = True
                    for bkey, (chain, recv) in (
                        self._closure_blk[callee].items()
                    ):
                        if bkey not in blk:
                            blk[bkey] = ([hop, *chain], recv)
                            changed = True

    def _closure(self, key: int) -> tuple[dict, dict]:
        return self._closure_acq[key], self._closure_blk[key]

    def _propagate(self) -> None:
        for key, s in self.summaries.items():
            for a, al, b, bl in s.edges:
                self._add_edge(
                    a, b,
                    [(s.mod.display_path, al, f"acquires {_short(a)}"),
                     (s.mod.display_path, bl, f"acquires {_short(b)}")],
                )
            for callee, line, held in s.calls:
                if callee not in self.summaries:
                    continue
                acq, blk = self._closure(callee)
                c = self.summaries[callee]
                hop = (s.mod.display_path, line,
                       f"calls {c.cls + '.' if c.cls else ''}{c.name}")
                for a, al in held:
                    pre = [(s.mod.display_path, al,
                            f"acquires {_short(a)}"), hop]
                    for b, chain in acq.items():
                        self._add_edge(a, b, pre + chain)
                    for (kind, bpath, bline), (chain, recv) in (
                        blk.items()
                    ):
                        if recv is not None and recv == a:
                            continue  # waiting releases the held lock
                        self.blocking_hits.append({
                            "lock": a, "kind": kind,
                            "path": s.mod.display_path, "line": line,
                            "chain": pre + chain,
                        })

    def _add_edge(self, a: str, b: str, chain: list) -> None:
        if a == b:
            kind = self.lock_nodes.get(a, {}).get("kind")
            if kind == "Lock":
                self.self_edges.append({"lock": a, "chain": chain})
            return
        self.edges.setdefault((a, b), {"chain": chain})

    # -- cycles -------------------------------------------------------------
    def cycles(self) -> list[list[tuple[str, str]]]:
        """Distinct lock-order cycles as edge lists, shortest first.
        One cycle is reported per distinct node set."""
        adj: dict[str, set[str]] = {}
        for a, b in self.edges:
            adj.setdefault(a, set()).add(b)
        out: list[list[tuple[str, str]]] = []
        seen_sets: set[frozenset] = set()
        for a, b in sorted(self.edges):
            # shortest path b → a closes the cycle through (a, b)
            path = self._shortest_path(adj, b, a)
            if path is None:
                continue
            nodes = frozenset([a, *path])
            if nodes in seen_sets:
                continue
            seen_sets.add(nodes)
            # path is b→…→a inclusive; prepend the closing edge a→b
            cyc = [(a, b)]
            for i in range(len(path) - 1):
                cyc.append((path[i], path[i + 1]))
            out.append(cyc)
        out.sort(key=len)
        return out

    @staticmethod
    def _shortest_path(adj: dict, src: str, dst: str) -> list[str] | None:
        if src == dst:
            return [src]
        prev: dict[str, str] = {}
        frontier = [src]
        visited = {src}
        while frontier:
            nxt = []
            for n in frontier:
                for m2 in sorted(adj.get(n, ())):
                    if m2 in visited:
                        continue
                    visited.add(m2)
                    prev[m2] = n
                    if m2 == dst:
                        path = [dst]
                        while path[-1] != src:
                            path.append(prev[path[-1]])
                        return list(reversed(path))
                    nxt.append(m2)
            frontier = nxt
        return None


def _short(lock_id: str) -> str:
    return lock_id.split("::", 1)[-1]


def _chain_text(chain: list) -> str:
    return " -> ".join(f"{p}:{ln} ({what})" for p, ln, what in chain)


_ANALYSIS_CACHE: list[tuple[tuple[int, ...], _Analysis]] = []


def analyze(modules: Sequence[ModuleInfo]) -> _Analysis:
    key = tuple(id(m) for m in modules)
    for k, a in _ANALYSIS_CACHE:
        if k == key:
            return a
    a = _Analysis(modules)
    _ANALYSIS_CACHE.append((key, a))
    del _ANALYSIS_CACHE[:-4]
    return a


# ---------------------------------------------------------------------------
# the rules
# ---------------------------------------------------------------------------


class LockOrderRule(Rule):
    id = LOCK_ORDER
    description = (
        "locks must be acquired in one global order: any cycle in the "
        "interprocedural lock-order graph is a deadlock two threads "
        "can reach (AB/BA); re-acquiring a held non-reentrant Lock on "
        "the same path is the single-thread variant"
    )

    def check_project(
        self, modules: Sequence[ModuleInfo]
    ) -> Iterator[Finding]:
        a = analyze(modules)
        for cyc in a.cycles():
            chains = []
            for e in cyc:
                chain = a.edges[e]["chain"]
                chains.append(
                    f"{_short(e[0])} -> {_short(e[1])} via "
                    f"{_chain_text(chain)}"
                )
            first = a.edges[cyc[0]]["chain"][0]
            yield self.finding(
                _mod_proxy(modules, first[0]), first[1],
                "lock-order cycle between "
                + " and ".join(_short(x) for x in
                               dict.fromkeys(n for e in cyc for n in e))
                + ": " + "; ".join(chains)
                + " — two threads interleaving these chains deadlock "
                  "with both locks held",
            )
        for se in a.self_edges:
            site = se["chain"][0]
            yield self.finding(
                _mod_proxy(modules, site[0]), site[1],
                f"non-reentrant Lock {_short(se['lock'])} re-acquired "
                f"while already held on the same path: "
                f"{_chain_text(se['chain'])} — this deadlocks the "
                "acquiring thread against itself",
            )


class BlockingUnderLockRule(Rule):
    id = BLOCKING_UNDER_LOCK
    description = (
        "no unbounded blocking call (zero-arg join/get/wait/"
        "communicate, subprocess spawn, file/pipe I/O, "
        "block_until_ready) may be reachable while a project lock is "
        "held; timeouts bound it, a Condition.wait releases only its "
        "own lock"
    )

    def check_project(
        self, modules: Sequence[ModuleInfo]
    ) -> Iterator[Finding]:
        a = analyze(modules)
        seen: set[tuple] = set()
        for hit in a.blocking_hits:
            key = (hit["path"], hit["line"], hit["lock"], hit["kind"])
            if key in seen:
                continue
            seen.add(key)
            yield self.finding(
                _mod_proxy(modules, hit["path"]), hit["line"],
                f"unbounded {hit['kind']} blocking while holding "
                f"{_short(hit['lock'])}: {_chain_text(hit['chain'])} — "
                "every thread that takes this lock wedges behind the "
                "slow/blocked call; bound it with a timeout or move it "
                "outside the lock",
            )


class ThreadLifecycleRule(Rule):
    id = THREAD_LIFECYCLE
    description = (
        "every threading.Thread must be daemonized or have a reachable "
        "join/retire path on its binding (a non-daemon worker with no "
        "join keeps the process alive; an unretired per-cycle worker "
        "is a thread leak)"
    )

    def check_module(self, mod: ModuleInfo) -> Iterator[Finding]:
        if mod.tree is None:
            return
        # class-level pass: Thread(...) assigned to self.<attr> needs a
        # join on that attr (or an alias local) somewhere in the class
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_scope(mod, node, is_class=True)
        yield from self._check_scope(mod, mod.tree, is_class=False)

    def _thread_calls(self, scope: ast.AST) -> list[ast.Call]:
        out = []
        for node in ast.walk(scope):
            if isinstance(node, ast.ClassDef) and node is not scope:
                continue
            if isinstance(node, ast.Call) and _terminal(
                node.func
            ) == "Thread":
                dotted = _dotted(node.func) or "Thread"
                if dotted in ("Thread", "threading.Thread"):
                    out.append(node)
        return out

    def _check_scope(self, mod: ModuleInfo, scope: ast.AST,
                     is_class: bool) -> Iterator[Finding]:
        threads = self._thread_calls(scope)
        if not threads:
            return
        in_classes = set()
        if not is_class:
            for node in ast.walk(scope):
                if isinstance(node, ast.ClassDef):
                    in_classes.update(
                        id(c) for c in self._thread_calls(node)
                    )
        src = mod.source
        for call in threads:
            if not is_class and id(call) in in_classes:
                continue  # owned by the class-level pass
            if any(
                k.arg == "daemon"
                and isinstance(k.value, ast.Constant)
                and k.value.value is True
                for k in call.keywords
            ):
                continue
            binding = self._binding_of(scope, call)
            if binding is not None and self._has_retire(
                scope, src, binding
            ):
                continue
            what = binding if binding is not None else "<unbound>"
            yield self.finding(
                mod, call.lineno,
                f"Thread bound to {what} is neither daemonized "
                "(daemon=True) nor joined anywhere in its owning "
                f"{'class' if is_class else 'scope'} — a non-daemon "
                "worker with no retire path outlives the serve (or "
                "leaks one thread per cycle); daemonize it or join it "
                "from the shutdown surface",
            )

    def _binding_of(self, scope: ast.AST, call: ast.Call) -> str | None:
        for node in ast.walk(scope):
            if not isinstance(node, ast.Assign) or node.value is not call:
                continue
            for t in node.targets:
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    return f"self.{t.attr}"
                if isinstance(t, ast.Name):
                    return t.id
        return None

    def _has_retire(self, scope: ast.AST, src: str,
                    binding: str) -> bool:
        attr = binding.removeprefix("self.")
        aliases = {binding}
        # locals assigned FROM the binding (incl. the tuple-swap
        # `thread, self._t = self._t, None` idiom) join on its behalf
        for node in ast.walk(scope):
            if not isinstance(node, ast.Assign):
                continue
            targets, values = node.targets, [node.value]
            if (
                len(node.targets) == 1
                and isinstance(node.targets[0], ast.Tuple)
                and isinstance(node.value, ast.Tuple)
            ):
                targets = node.targets[0].elts
                values = node.value.elts
            for t, v in zip(targets, values):
                if isinstance(t, ast.Name) and (
                    _dotted(v) == binding
                    or (binding.startswith("self.")
                        and _dotted(v) == f"self.{attr}")
                ):
                    aliases.add(t.id)
        for node in ast.walk(scope):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"
                and _dotted(node.func.value) in aliases
            ):
                return True
            # daemonized after construction: t.daemon = True
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and t.attr == "daemon"
                        and _dotted(t.value) in aliases
                        and isinstance(node.value, ast.Constant)
                        and node.value.value is True
                    ):
                        return True
        return False


class _ModProxy:
    """Finding factory shim: project-level rules anchor findings on
    modules OTHER than a single ``mod`` argument — this adapts a
    display path to the ``Rule.finding`` signature."""

    def __init__(self, display_path: str):
        self.display_path = display_path


def _mod_proxy(modules: Sequence[ModuleInfo], path: str):
    for m in modules:
        if m.display_path == path:
            return m
    return _ModProxy(path)


GRAFTLOCK_RULES = (
    LockOrderRule,
    BlockingUnderLockRule,
    ThreadLifecycleRule,
)


# ---------------------------------------------------------------------------
# graph export (the artifact + the runtime-witness cross-check input)
# ---------------------------------------------------------------------------


GRAPH_SCHEMA_VERSION = 1


def build_graph_report(modules: Sequence[ModuleInfo]) -> dict:
    """The static lock-order graph as a JSON-ready dict (with an
    embedded DOT rendering): nodes keyed by lock class with their
    construction sites, edges with full acquisition chains, and any
    cycles. ``docs/artifacts/lock_order_graph.json`` is this, generated
    from the repo root, so future PRs diff concurrency structure in
    review and ``utils/locktrace.py`` cross-checks observed runtime
    edges against it."""
    a = analyze(modules)
    node_ids = sorted(
        set(a.lock_nodes)
        | {n for e in a.edges for n in e}
        | {h["lock"] for h in a.blocking_hits}
    )
    nodes = []
    for nid in node_ids:
        meta = a.lock_nodes.get(nid, {})
        nodes.append({
            "id": nid,
            "kind": meta.get("kind"),
            "constructed_at": sorted(
                f"{p}:{ln}" for p, ln in meta.get("constructed_at", ())
            ),
        })
    edges = [
        {
            "from": aid, "to": bid,
            "chain": [f"{p}:{ln} ({what})"
                      for p, ln, what in a.edges[(aid, bid)]["chain"]],
        }
        for aid, bid in sorted(a.edges)
    ]
    cycles = [
        [list(e) for e in cyc] for cyc in a.cycles()
    ]
    dot_lines = ["digraph lock_order {"]
    for n in nodes:
        dot_lines.append(f'  "{n["id"]}";')
    for e in edges:
        dot_lines.append(f'  "{e["from"]}" -> "{e["to"]}";')
    dot_lines.append("}")
    return {
        "schema_version": GRAPH_SCHEMA_VERSION,
        "nodes": nodes,
        "edges": edges,
        "cycles": cycles,
        "dot": "\n".join(dot_lines),
    }
