"""graftlint — project-native static analysis for the jax_graft layers.

The reference implementation is a 315-line script whose heavy numerics
hide inside sklearn's compiled internals; this reproduction replaced
that surface with jitted JAX, Pallas kernels, threaded ingest, and
ctypes-wrapped C++ evaluators — exactly the layers where silent
invariant violations (host syncs inside jit, un-typed CDLL calls,
unlocked cross-thread mutation, unregistered fault sites) produce
wrong-but-plausible results rather than crashes. graftlint encodes
those invariants as AST rules and enforces them in tier-1
(tests/test_graftlint.py runs the whole package through it and asserts
zero findings), so the guarantee compounds across every future PR.

Run it:

    python -m traffic_classifier_sdn_tpu.analysis_static <paths> [--json]
    tools/lint.sh            # graftlint + ruff + mypy one-shot gate

Suppress a finding with a trailing comment that CARRIES A REASON::

    x = risky()  # graftlint: disable=rule-id -- why this is safe

A ``disable`` comment without a reason is itself a finding
(``bad-suppression``) that cannot be suppressed. Each rule is
documented in docs/STATIC_ANALYSIS.md.
"""

from .framework import Finding, LintRunner, Rule, lint_paths
from .rules import ALL_RULES

__all__ = ["Finding", "LintRunner", "Rule", "ALL_RULES", "lint_paths"]
