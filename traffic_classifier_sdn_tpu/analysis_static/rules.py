"""The project rules. Each encodes an invariant one of the
framework's layers relies on but Python cannot enforce at runtime:

====================  =====================================================
``jit-purity``        host effects inside traced code run at TRACE time
                      (once), not per call — timestamps freeze, RNG draws
                      repeat, ``.item()``/``float()``/``np.asarray()``
                      force device→host syncs inside the hot path
``retrace-hazard``    Python scalars / shape-varying literals at jitted
                      call sites recompile per distinct value/structure;
                      dtype-less array literals weak-type and retrace
``ctypes-abi``        a CDLL symbol called without ``argtypes``/``restype``
                      defaults every argument to int and truncates 64-bit
                      pointers/returns silently on LP64 — wrong-but-
                      plausible results, not crashes
``lock-discipline``   attributes written from a ``threading.Thread`` target
                      and touched elsewhere race unless every access holds
                      the owning ``*_lock``
``fault-site-registry``  every injection seam must use a site registered in
                      ``utils.faults.SITES`` and every registered site must
                      have a chaos test, or the chaos matrix silently
                      stops covering a durability seam
``atomic-io``         ad-hoc ``open(.., "w")`` + ``os.replace`` re-implements
                      (usually wrongly: no fsync, wrong temp dir) what
                      ``utils.atomicio.atomic_write_bytes`` already proves
                      under fault injection
``lock-order``        a cycle in the global lock-order graph is a deadlock
                      two threads can reach (AB/BA); whole-program, see
                      ``graftlock.py``
``blocking-under-lock``  an unbounded blocking call reachable while a lock
                      is held wedges every thread that ever takes that lock
``thread-lifecycle``  a non-daemon thread with no reachable join outlives
                      the serve; an unretired per-cycle worker is a leak
``implicit-sync``     a device→host sync (np.asarray/.item()/int()/
                      truthiness/iteration on a device value) on a serve
                      hot path blocks the tick; whole-program, see
                      ``graftsync.py``
``transfer-discipline``  a per-tick host→device upload re-pays the
                      transfer every tick unless warmup-primed or
                      epoch-cached
``donation-hazard``   a buffer passed at a donated argument position is
                      dead afterwards; referencing it reads freed memory
``sync-under-lock``   a device sync while holding a project lock wedges
                      every thread that takes that lock
====================  =====================================================

Rules are deliberately module-local and syntactic (no type inference, no
import following) so a finding is always explainable by pointing at the
flagged line; the suppression-with-reason escape hatch covers the
residue. docs/STATIC_ANALYSIS.md documents each rule's failure mode.
"""

from __future__ import annotations

import ast
import os
import re
from collections.abc import Iterator, Sequence

from .framework import Finding, ModuleInfo, Rule, _iter_py_files

_JIT_MARKERS = {"jit", "pjit", "shard_map"}


def _walk_excluding_defs(root: ast.AST) -> Iterator[ast.AST]:
    """ast.walk that does not descend into nested def/class bodies —
    the per-scope traversal both atomic-io and fault-site-registry
    need so one scope's state never leaks into another's."""
    stack: list[ast.AST] = [root]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if not isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef,
                        ast.ClassDef)
            ):
                stack.append(child)


def _dotted(node: ast.AST) -> str | None:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _terminal(node: ast.AST) -> str | None:
    """Last attribute segment: 'c' for a.b.c, 'x' for x."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _contains_jit_marker(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if _terminal(sub) in _JIT_MARKERS and isinstance(
            sub, (ast.Name, ast.Attribute)
        ):
            return True
    return False


def _is_literal_payload(node: ast.AST) -> bool:
    """A Python literal an array could be built from: number/bool, or a
    (possibly nested) list/tuple of them — the 'array literal' case that
    has no inherent dtype."""
    if isinstance(node, ast.Constant):
        return isinstance(node.value, (int, float, complex, bool))
    if isinstance(node, ast.UnaryOp) and isinstance(
        node.op, (ast.USub, ast.UAdd)
    ):
        return _is_literal_payload(node.operand)
    if isinstance(node, (ast.List, ast.Tuple)):
        return all(_is_literal_payload(e) for e in node.elts)
    return False


# ---------------------------------------------------------------------------
# jit-purity
# ---------------------------------------------------------------------------


class JitPurityRule(Rule):
    id = "jit-purity"
    description = (
        "no host-side effects (time.time, np.random, print, .item()/"
        "float()/np.asarray() syncs) inside jax.jit/pjit/shard_map-traced "
        "functions"
    )

    _TIME_CALLS = {"time", "monotonic", "perf_counter", "time_ns",
                   "monotonic_ns", "perf_counter_ns"}
    _NP_SYNC = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}

    def _jit_roots(self, mod: ModuleInfo) -> list[ast.AST]:
        """Function bodies traced by jit: decorated defs, defs whose name
        is wrapped by a jit call, and lambdas passed to jit directly."""
        roots: list[ast.AST] = []
        wrapped_names: set[str] = set()
        assert mod.tree is not None
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(_contains_jit_marker(d) for d in node.decorator_list):
                    roots.append(node)
            elif isinstance(node, ast.Call) and _terminal(
                node.func
            ) in _JIT_MARKERS:
                for arg in node.args[:1]:
                    if isinstance(arg, ast.Lambda):
                        roots.append(arg)
                    elif isinstance(arg, ast.Name):
                        wrapped_names.add(arg.id)
        if wrapped_names:
            for node in ast.walk(mod.tree):
                if (
                    isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and node.name in wrapped_names
                    and node not in roots
                ):
                    roots.append(node)
        return roots

    def check_module(self, mod: ModuleInfo) -> Iterator[Finding]:
        if mod.tree is None:
            return
        seen: set[tuple[int, str]] = set()
        for root in self._jit_roots(mod):
            for node in ast.walk(root):
                if not isinstance(node, ast.Call):
                    continue
                msg = self._classify(node)
                if msg is None:
                    continue
                key = (node.lineno, msg)
                if key in seen:
                    continue
                seen.add(key)
                yield self.finding(mod, node.lineno, msg)

    def _classify(self, call: ast.Call) -> str | None:
        func = call.func
        dotted = _dotted(func)
        if dotted is not None:
            head, _, tail = dotted.partition(".")
            if head == "time" and tail in self._TIME_CALLS:
                return (f"'{dotted}()' inside a jitted function is "
                        "evaluated once at trace time, not per call")
            if head in ("np", "numpy") and tail.startswith("random."):
                return (f"'{dotted}' inside a jitted function draws at "
                        "trace time; use jax.random with a threaded key")
            if dotted in self._NP_SYNC:
                return (f"'{dotted}()' on a traced value forces a "
                        "device→host sync (and fails under jit); use "
                        "jnp equivalents")
        if isinstance(func, ast.Attribute) and func.attr == "item":
            return (".item() forces a blocking device→host sync inside "
                    "a jitted function")
        if isinstance(func, ast.Name):
            if func.id == "print":
                return ("print() inside a jitted function runs at trace "
                        "time only; use jax.debug.print for per-call "
                        "output")
            if func.id == "float" and call.args and not isinstance(
                call.args[0], ast.Constant
            ):
                return ("float() on a traced value forces a device→host "
                        "sync inside a jitted function")
        return None


# ---------------------------------------------------------------------------
# retrace-hazard
# ---------------------------------------------------------------------------

_ARRAY_MODS = ("np", "numpy", "jnp")
# positional index at which dtype may appear for each constructor
_CTOR_DTYPE_POS = {"zeros": 1, "ones": 1, "empty": 1, "full": 2}
_CONVERTERS = {"array", "asarray"}


class RetraceHazardRule(Rule):
    id = "retrace-hazard"
    description = (
        "array literals need an explicit dtype; jitted call sites must "
        "not take bare Python scalars or shape-varying literals"
    )

    def check_module(self, mod: ModuleInfo) -> Iterator[Finding]:
        if mod.tree is None:
            return
        yield from self._implicit_dtype(mod)
        yield from self._jitted_call_sites(mod)

    def _implicit_dtype(self, mod: ModuleInfo) -> Iterator[Finding]:
        assert mod.tree is not None
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func)
            if dotted is None:
                continue
            head, _, fn = dotted.rpartition(".")
            if head not in _ARRAY_MODS:
                continue
            has_dtype_kw = any(
                k.arg == "dtype" for k in node.keywords
            )
            if fn in _CTOR_DTYPE_POS:
                if has_dtype_kw or len(node.args) > _CTOR_DTYPE_POS[fn]:
                    continue
                yield self.finding(
                    mod, node.lineno,
                    f"'{dotted}()' without an explicit dtype: the "
                    "default is platform/x64-flag dependent and "
                    "weak-types under jit — state the dtype",
                )
            elif fn in _CONVERTERS and node.args and _is_literal_payload(
                node.args[0]
            ):
                if has_dtype_kw or len(node.args) > 1:
                    continue
                yield self.finding(
                    mod, node.lineno,
                    f"'{dotted}()' on a Python literal without a dtype: "
                    "literals carry no dtype, so this weak-types (and "
                    "can retrace) under jit — state the dtype",
                )

    def _jitted_names(self, mod: ModuleInfo) -> set[str]:
        """Module-local names bound to jitted callables WITHOUT static
        args (static-arg jits legitimately take Python scalars)."""
        assert mod.tree is not None
        names: set[str] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                call = node.value
                if _terminal(call.func) in _JIT_MARKERS and not any(
                    k.arg in ("static_argnums", "static_argnames")
                    for k in call.keywords
                ):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            names.add(t.id)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for d in node.decorator_list:
                    if not _contains_jit_marker(d):
                        continue
                    static = isinstance(d, ast.Call) and any(
                        k.arg in ("static_argnums", "static_argnames")
                        for call_node in ast.walk(d)
                        if isinstance(call_node, ast.Call)
                        for k in call_node.keywords
                    )
                    if not static:
                        names.add(node.name)
        return names

    def _jitted_call_sites(self, mod: ModuleInfo) -> Iterator[Finding]:
        assert mod.tree is not None
        jitted = self._jitted_names(mod)
        if not jitted:
            return
        for node in ast.walk(mod.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in jitted
            ):
                continue
            for arg in list(node.args) + [k.value for k in node.keywords]:
                if isinstance(arg, (ast.List, ast.Tuple, ast.Dict)):
                    yield self.finding(
                        mod, node.lineno,
                        f"container literal passed to jitted "
                        f"'{node.func.id}': each distinct structure "
                        "recompiles — pass an array with a stable shape",
                    )
                elif isinstance(arg, ast.Constant) and isinstance(
                    arg.value, (int, float)
                ) and not isinstance(arg.value, bool):
                    yield self.finding(
                        mod, node.lineno,
                        f"bare Python scalar passed to jitted "
                        f"'{node.func.id}': weak-typed operand that "
                        "retraces per distinct value — pass a dtyped "
                        "array or mark the argument static",
                    )


# ---------------------------------------------------------------------------
# ctypes-abi
# ---------------------------------------------------------------------------


# ---- cross-language ABI comparison (ctypes ↔ extern "C") -----------------
#
# Width/kind categories. ctypes argument passing is by value, so what
# matters is pointer-vs-integer-vs-float and the width; const-ness and
# signedness drift are calling-convention-safe and deliberately NOT
# flagged (flagging them would teach people to suppress the rule).

_C_TYPE_CATEGORY = {
    "void": "void",
    "bool": "i8", "char": "i8", "int8_t": "i8", "uint8_t": "i8",
    "int16_t": "i16", "uint16_t": "i16", "short": "i16",
    "int": "i32", "unsigned": "i32", "int32_t": "i32",
    "uint32_t": "i32",
    "int64_t": "i64", "uint64_t": "i64", "size_t": "i64",
    "ssize_t": "i64", "intptr_t": "i64", "uintptr_t": "i64",
    "float": "f32",
    "double": "f64",
}

_PY_CTYPE_CATEGORY = {
    "c_void_p": "ptr", "c_char_p": "ptr", "c_wchar_p": "ptr",
    "c_bool": "i8", "c_int8": "i8", "c_uint8": "i8", "c_byte": "i8",
    "c_ubyte": "i8", "c_char": "i8",
    "c_int16": "i16", "c_uint16": "i16", "c_short": "i16",
    "c_ushort": "i16",
    "c_int": "i32", "c_uint": "i32", "c_int32": "i32",
    "c_uint32": "i32",
    "c_int64": "i64", "c_uint64": "i64", "c_size_t": "i64",
    "c_ssize_t": "i64", "c_longlong": "i64", "c_ulonglong": "i64",
    "c_float": "f32", "c_double": "f64",
    # c_long is LP64/LLP64-dependent: never compared
}

_CFN_RE = re.compile(
    r"(?m)^\s*((?:const\s+)?[A-Za-z_]\w*(?:\s*\*)*)"  # return type
    r"\s+([A-Za-z_]\w*)\s*\(([^)]*)\)\s*\{"           # name(params) {
)


def _c_category(decl: str) -> str | None:
    d = decl.strip()
    if not d or d == "...":
        return None
    if "*" in d:
        return "ptr"
    toks = [t for t in d.replace("const", " ").split() if t]
    if not toks:
        return None
    # drop the parameter name when present ("uint32_t capacity")
    ty = toks[0] if len(toks) == 1 else " ".join(toks[:-1])
    return _C_TYPE_CATEGORY.get(ty)


def _parse_extern_c(text: str) -> dict[str, tuple]:
    """symbol → (return category, (arg categories...)) for every
    function defined inside an ``extern "C" { ... }`` region. An
    unknown type maps to None in its position (skipped in comparison);
    a symbol defined twice with different shapes is dropped."""
    out: dict[str, tuple] = {}
    dropped: set[str] = set()
    pos = 0
    while True:
        m = re.search(r'extern\s+"C"\s*\{', text[pos:])
        if m is None:
            break
        start = pos + m.end()
        depth = 1
        i = start
        while i < len(text) and depth:
            if text[i] == "{":
                depth += 1
            elif text[i] == "}":
                depth -= 1
            i += 1
        region = text[start:i]
        pos = i
        for fm in _CFN_RE.finditer(region):
            ret, name, params = fm.groups()
            ret_cat = "ptr" if "*" in ret else _C_TYPE_CATEGORY.get(
                ret.replace("const", " ").strip()
            )
            p = params.strip()
            if p in ("", "void"):
                args: tuple = ()
            else:
                args = tuple(_c_category(a) for a in p.split(","))
            sig = (ret_cat, args)
            if name in out and out[name] != sig:
                dropped.add(name)
            out[name] = sig
    for name in dropped:
        out.pop(name, None)
    return out


_EXTERN_C_CACHE: dict[str, dict[str, tuple]] = {}


def _native_symbols(py_path: str) -> dict[str, tuple]:
    """The union extern-"C" symbol table of every sibling ``*.cpp``
    of ``py_path`` (symbols are uniquely prefixed per lib, so the
    union is unambiguous)."""
    d = os.path.dirname(os.path.realpath(py_path))
    cached = _EXTERN_C_CACHE.get(d)
    if cached is not None:
        return cached
    table: dict[str, tuple] = {}
    try:
        names = sorted(os.listdir(d))
    except OSError:
        names = []
    for n in names:
        if not n.endswith(".cpp"):
            continue
        try:
            with open(os.path.join(d, n), encoding="utf-8") as f:
                table.update(_parse_extern_c(f.read()))
        except OSError:
            continue
    _EXTERN_C_CACHE[d] = table
    return table


class CtypesAbiRule(Rule):
    id = "ctypes-abi"
    description = (
        "every symbol called on a LazyLib/CDLL handle needs argtypes AND "
        "restype declared (defaults truncate 64-bit values silently), "
        "and the declaration must match the extern \"C\" definition in "
        "the sibling .cpp (arity and per-position width/kind)"
    )

    _SKIP = {"load"}

    def check_module(self, mod: ModuleInfo) -> Iterator[Finding]:
        if mod.tree is None:
            return
        uses_cdll = any(
            isinstance(n, ast.Call)
            and _terminal(n.func) in ("LazyLib", "CDLL")
            for n in ast.walk(mod.tree)
        )
        if not uses_cdll:
            return
        handles = self._handle_names(mod.tree)
        # One loaded lib: every handle name aliases it (the `lib` local
        # in _load() IS the `self._lib` at the call sites), so declared
        # prototypes are keyed by symbol alone. Multiple libs in one
        # module: a prototype on one handle says nothing about the
        # other lib's same-named symbol, so the key includes the handle.
        n_libs = sum(
            1 for n in ast.walk(mod.tree)
            if isinstance(n, ast.Call)
            and _terminal(n.func) in ("LazyLib", "CDLL")
        )
        per_handle = n_libs > 1

        def key(handle: str | None, sym: str):
            return (handle, sym) if per_handle else sym

        declared: dict[object, set[str]] = {}
        # symbol → {"argtypes"/"restype": (value expr, line)} for the
        # cross-language comparison (C symbols are globally unique)
        protos: dict[str, dict[str, tuple[ast.AST, int]]] = {}
        called: dict[tuple[object, str], int] = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and t.attr in (
                        "argtypes", "restype"
                    ):
                        sym = _terminal(t.value)
                        if sym is not None and isinstance(
                            t.value, ast.Attribute
                        ):
                            handle = _terminal(t.value.value)
                            declared.setdefault(
                                key(handle, sym), set()
                            ).add(t.attr)
                            protos.setdefault(sym, {})[t.attr] = (
                                node.value, t.lineno
                            )
            elif isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ):
                base = _terminal(node.func.value)
                if base in handles and (
                    node.func.attr not in self._SKIP
                ):
                    called.setdefault(
                        (key(base, node.func.attr), node.func.attr),
                        node.lineno,
                    )
        for (k, sym), line in sorted(
            called.items(), key=lambda kv: kv[1]
        ):
            missing = {"argtypes", "restype"} - declared.get(k, set())
            if missing:
                yield self.finding(
                    mod, line,
                    f"CDLL symbol '{sym}' called without declared "
                    f"{' and '.join(sorted(missing))} — ctypes then "
                    "assumes C int everywhere, silently truncating "
                    "64-bit pointers/values on LP64",
                )
        yield from self._check_cross_language(mod, protos)

    # ---- cross-language: argtypes/restype vs the extern "C" source
    @staticmethod
    def _py_category(expr: ast.AST) -> str | None:
        if isinstance(expr, ast.Constant) and expr.value is None:
            return "void"
        if isinstance(expr, ast.Call):
            t = _terminal(expr.func)
            if t in ("POINTER", "ndpointer"):
                return "ptr"
            return None
        t = _terminal(expr)
        if t is None:
            return None
        return _PY_CTYPE_CATEGORY.get(t)

    @classmethod
    def _eval_argtypes(cls, expr: ast.AST) -> list | None:
        """Statically evaluate an argtypes expression to a category
        list, handling ``[A] + [B] * 8``-style computed lists. None if
        the shape cannot be evaluated (never guessed)."""
        if isinstance(expr, (ast.List, ast.Tuple)):
            return [cls._py_category(e) for e in expr.elts]
        if isinstance(expr, ast.BinOp):
            if isinstance(expr.op, ast.Add):
                left = cls._eval_argtypes(expr.left)
                right = cls._eval_argtypes(expr.right)
                if left is None or right is None:
                    return None
                return left + right
            if isinstance(expr.op, ast.Mult):
                seq, count = expr.left, expr.right
                if isinstance(seq, ast.Constant):
                    seq, count = count, seq
                elems = cls._eval_argtypes(seq)
                if (
                    elems is not None
                    and isinstance(count, ast.Constant)
                    and isinstance(count.value, int)
                ):
                    return elems * count.value
        return None

    def _check_cross_language(
        self, mod: ModuleInfo,
        protos: dict[str, dict[str, tuple[ast.AST, int]]],
    ) -> Iterator[Finding]:
        native = _native_symbols(mod.path)
        if not native:
            return
        for sym in sorted(protos):
            sig = native.get(sym)
            if sig is None:
                continue  # not one of ours (dlopen'd elsewhere)
            c_ret, c_args = sig
            decls = protos[sym]
            if "argtypes" in decls:
                expr, line = decls["argtypes"]
                py_args = self._eval_argtypes(expr)
                if py_args is not None:
                    if len(py_args) != len(c_args):
                        yield self.finding(
                            mod, line,
                            f"CDLL symbol '{sym}': argtypes declares "
                            f"{len(py_args)} argument(s) but the "
                            f"extern \"C\" definition takes "
                            f"{len(c_args)} — arity drift corrupts "
                            "the stack/registers silently",
                        )
                    else:
                        for i, (p, c) in enumerate(
                            zip(py_args, c_args)
                        ):
                            if p is None or c is None or p == c:
                                continue
                            yield self.finding(
                                mod, line,
                                f"CDLL symbol '{sym}': argtypes[{i}] "
                                f"is {p} but the extern \"C\" "
                                f"definition takes {c} — width/kind "
                                "mismatch truncates or misreads the "
                                "value",
                            )
            if "restype" in decls:
                expr, line = decls["restype"]
                p = self._py_category(expr)
                if p is not None and c_ret is not None and p != c_ret:
                    yield self.finding(
                        mod, line,
                        f"CDLL symbol '{sym}': restype is {p} but "
                        f"the extern \"C\" definition returns "
                        f"{c_ret} — the returned value is truncated "
                        "or reinterpreted",
                    )

    def _handle_names(self, tree: ast.Module) -> set[str]:
        """Names holding a CDLL handle: the conventional lib/_lib plus
        anything assigned from ``CDLL(...)`` or a ``.load()`` call on a
        name assigned from ``LazyLib(...)`` — a handle bound to another
        name must not escape the rule."""
        lazy_objs: set[str] = set()
        handles: set[str] = {"lib", "_lib"}
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign) or not isinstance(
                node.value, ast.Call
            ):
                continue
            ctor = _terminal(node.value.func)
            if ctor in ("LazyLib", "CDLL"):
                for t in node.targets:
                    name = _terminal(t)
                    if name is not None:
                        lazy_objs.add(name)
                        if ctor == "CDLL":
                            handles.add(name)
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and isinstance(node.value.func, ast.Attribute)
                and node.value.func.attr == "load"
                and _terminal(node.value.func.value) in lazy_objs
            ):
                for t in node.targets:
                    name = _terminal(t)
                    if name is not None:
                        handles.add(name)
        return handles


# ---------------------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------------------


class LockDisciplineRule(Rule):
    id = "lock-discipline"
    description = (
        "attributes written from a threading.Thread target method must "
        "be accessed under the owning *_lock everywhere in the class "
        "(container-mutator calls like .append/.update count as writes)"
    )

    # Mutation hides behind method calls as often as behind assignment:
    # an event ring appended from a reader thread (the obs
    # flight-recorder shape) races exactly like a counter `+=`, but a
    # store-only scan never sees it. These are the stdlib container
    # mutators; deliberately NOT queue.Queue's put/get names — Queue
    # does its own locking, and flagging it would teach people to
    # suppress the rule rather than fix real races.
    _CONTAINER_MUTATORS = {
        "append", "appendleft", "extend", "extendleft", "add", "insert",
        "remove", "discard", "pop", "popleft", "popitem", "clear",
        "update", "setdefault",
    }

    def check_module(self, mod: ModuleInfo) -> Iterator[Finding]:
        if mod.tree is None:
            return
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(mod, node)

    def _thread_targets(self, cls: ast.ClassDef) -> set[str]:
        targets: set[str] = set()
        for node in ast.walk(cls):
            if isinstance(node, ast.Call) and _terminal(
                node.func
            ) == "Thread":
                for k in node.keywords:
                    if (
                        k.arg == "target"
                        and isinstance(k.value, ast.Attribute)
                        and isinstance(k.value.value, ast.Name)
                        and k.value.value.id == "self"
                    ):
                        targets.add(k.value.attr)
        return targets

    def _self_attr_accesses(
        self, fn: ast.AST
    ) -> list[tuple[str, int, bool, bool]]:
        """(attr, line, is_store, under_lock) for every ``self.X``
        access in ``fn``, tracking enclosing ``with self.*_lock:``."""
        out: list[tuple[str, int, bool, bool]] = []

        def is_lock_expr(e: ast.AST) -> bool:
            t = _terminal(e)
            return t is not None and (
                t == "_lock" or t.endswith("_lock")
            )

        def visit(node: ast.AST, locked: bool) -> None:
            if isinstance(node, ast.With):
                entered = locked or any(
                    is_lock_expr(item.context_expr)
                    for item in node.items
                )
                for item in node.items:
                    visit(item.context_expr, locked)
                for child in node.body:
                    visit(child, entered)
                return
            if isinstance(node, ast.Attribute) and isinstance(
                node.value, ast.Name
            ) and node.value.id == "self":
                out.append((
                    node.attr, node.lineno,
                    isinstance(node.ctx, (ast.Store, ast.Del)), locked,
                ))
            for child in ast.iter_child_nodes(node):
                visit(child, locked)

        visit(fn, False)
        return out

    def _check_class(
        self, mod: ModuleInfo, cls: ast.ClassDef
    ) -> Iterator[Finding]:
        thread_methods = self._thread_targets(cls)
        if not thread_methods:
            return
        methods = {
            n.name: n
            for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        # closure over self.<method>() calls: a helper invoked (even
        # indirectly) from the target runs ON the worker thread, so its
        # stores are just as shared as the target's own
        on_thread: set[str] = set()
        work = [n for n in thread_methods if n in methods]
        while work:
            name = work.pop()
            if name in on_thread:
                continue
            on_thread.add(name)
            for node in ast.walk(methods[name]):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "self"
                    and node.func.attr in methods
                ):
                    work.append(node.func.attr)
        shared: set[str] = set()
        for name in on_thread:
            for attr, _line, is_store, _locked in (
                self._self_attr_accesses(methods[name])
            ):
                if is_store:
                    shared.add(attr)
            for node in ast.walk(methods[name]):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in self._CONTAINER_MUTATORS
                    and isinstance(node.func.value, ast.Attribute)
                    and isinstance(node.func.value.value, ast.Name)
                    and node.func.value.value.id == "self"
                ):
                    shared.add(node.func.value.attr)
        if not shared:
            return
        for name, fn in methods.items():
            # __init__ runs before the thread exists (happens-before
            # via Thread.start), so unlocked initialization is safe
            if name == "__init__":
                continue
            for attr, line, is_store, locked in (
                self._self_attr_accesses(fn)
            ):
                if attr in shared and not locked:
                    kind = "written" if is_store else "read"
                    ctx = (
                        "its Thread target method"
                        if name in thread_methods
                        else f"'{name}'"
                    )
                    yield self.finding(
                        mod, line,
                        f"'self.{attr}' is {kind} in {ctx} without "
                        f"holding a lock, but it is mutated from the "
                        f"thread started with target=self."
                        f"{'/'.join(sorted(thread_methods))} — guard "
                        "every access with the owning *_lock",
                    )


# ---------------------------------------------------------------------------
# fault-site-registry
# ---------------------------------------------------------------------------


class FaultSiteRegistryRule(Rule):
    id = "fault-site-registry"
    description = (
        "every fault seam must use a site registered in utils.faults."
        "SITES, and every registered site needs a chaos test"
    )

    def check_project(
        self, modules: Sequence[ModuleInfo]
    ) -> Iterator[Finding]:
        registry_mod: ModuleInfo | None = None
        sites: dict[str, int] = {}
        for mod in modules:
            if os.path.basename(mod.path) != "faults.py" or (
                mod.tree is None
            ):
                continue
            found = self._extract_sites(mod)
            if found is not None:
                registry_mod = mod
                sites = found
        external_registry = False
        if registry_mod is None:
            loaded = self._load_external_registry(modules)
            if loaded is not None:
                registry_mod, sites = loaded
                external_registry = True
        used: dict[str, tuple[ModuleInfo, int]] = {}
        for mod in modules:
            if mod is registry_mod or mod.tree is None:
                continue
            for site, line, literal in self._site_uses(mod):
                if not literal:
                    yield self.finding(
                        mod, line,
                        "fault site must be a string literal (the "
                        "registry cross-check cannot audit a computed "
                        "site name)",
                    )
                    continue
                used.setdefault(site, (mod, line))
                if registry_mod is not None and site not in sites:
                    yield self.finding(
                        mod, line,
                        f"fault site '{site}' is not registered in "
                        "utils.faults.SITES — register it (with a "
                        "description) so the chaos matrix can cover it",
                    )
        if registry_mod is None:
            if used:
                mod, line = next(iter(used.values()))
                yield self.finding(
                    mod, line,
                    "fault sites are used but no SITES registry was "
                    "found in a faults.py module in the scanned tree",
                )
            return
        if external_registry or not self._full_package_scan(
            registry_mod, modules
        ):
            # Partial scan (registry outside the linted paths, OR in a
            # scanned subtree that omits the rest of its package): only
            # the use→registry direction is auditable — a site used
            # solely outside the scanned subtree would be a false
            # "never used" positive, so registry-side checks are skipped.
            return
        chaos_src = self._chaos_source(registry_mod)
        for site, line in sorted(sites.items()):
            if site not in used:
                yield self.finding(
                    registry_mod, line,
                    f"registered fault site '{site}' is never used at "
                    "any seam — remove it or thread it through",
                )
            if chaos_src is not None and site not in chaos_src:
                yield self.finding(
                    registry_mod, line,
                    f"registered fault site '{site}' has no chaos test: "
                    "tests/test_chaos.py never references it",
                )

    def _extract_sites(self, mod: ModuleInfo) -> dict[str, int] | None:
        """The ``SITES = {...}`` literal as {site: lineno}, or None if
        this module defines no registry."""
        assert mod.tree is not None
        for node in ast.walk(mod.tree):
            if not (
                isinstance(node, (ast.Assign, ast.AnnAssign))
                and isinstance(node.value, ast.Dict)
            ):
                continue
            targets = (
                node.targets
                if isinstance(node, ast.Assign)
                else [node.target]
            )
            if not any(
                isinstance(t, ast.Name) and t.id == "SITES"
                for t in targets
            ):
                continue
            return {
                k.value: k.lineno
                for k in node.value.keys
                if isinstance(k, ast.Constant)
                and isinstance(k.value, str)
            }
        return None

    def _full_package_scan(
        self, registry_mod: ModuleInfo,
        modules: Sequence[ModuleInfo],
    ) -> bool:
        """True iff every module of the registry's package is in the
        scanned set — the only case where 'registered but never used'
        is a provable claim rather than a partial-scan artifact."""
        root = os.path.dirname(os.path.abspath(registry_mod.path))
        if os.path.basename(root) == "utils":
            root = os.path.dirname(root)
        scanned = {os.path.abspath(m.path) for m in modules}
        return all(
            os.path.abspath(p) in scanned
            for p in _iter_py_files([root])
        )

    def _load_external_registry(
        self, modules: Sequence[ModuleInfo]
    ) -> tuple[ModuleInfo, dict[str, int]] | None:
        """Locate and parse ``utils/faults.py`` near the scanned files
        when the registry module itself is outside the linted paths
        (e.g. ``graftlint traffic_classifier_sdn_tpu/ingest``), so a
        subtree scan can still audit the use→registry direction instead
        of reporting a spurious missing-registry finding."""
        seen: set[str] = set()
        for mod in modules:
            d = os.path.dirname(os.path.abspath(mod.path))
            for _ in range(6):
                candidate = os.path.join(d, "utils", "faults.py")
                if candidate not in seen:
                    seen.add(candidate)
                    if os.path.exists(candidate):
                        try:
                            with open(candidate, encoding="utf-8") as f:
                                source = f.read()
                        except OSError:
                            continue
                        reg = ModuleInfo(candidate, candidate, source)
                        if reg.tree is None:
                            continue
                        sites = self._extract_sites(reg)
                        if sites is not None:
                            return reg, sites
                d = os.path.dirname(d)
        return None

    def _site_uses(
        self, mod: ModuleInfo
    ) -> Iterator[tuple[str, int, bool]]:
        """(site, line, is_literal) for fault_point/fault_bytes calls
        and ``*_site=`` keyword arguments. Forwarding exemption is
        scoped per enclosing function: only that function's OWN
        ``*_site`` parameters count — a same-named local computed in
        another function must not slip past the literal check."""
        assert mod.tree is not None
        yield from self._scope_site_uses(mod.tree, set())
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                params = {
                    a.arg
                    for a in (
                        node.args.args + node.args.kwonlyargs
                        + node.args.posonlyargs
                    )
                    if a.arg.endswith("_site")
                }
                yield from self._scope_site_uses(node, params)

    def _scope_site_uses(
        self, root: ast.AST, param_names: set[str]
    ) -> Iterator[tuple[str, int, bool]]:
        for node in _walk_excluding_defs(root):
            if not isinstance(node, ast.Call):
                continue
            t = _terminal(node.func)
            if t in ("fault_point", "fault_bytes") and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and isinstance(
                    arg.value, str
                ):
                    yield arg.value, node.lineno, True
                elif not (
                    isinstance(arg, ast.Name) and arg.id in param_names
                ):
                    # forwarding a *_site parameter is fine — the
                    # literal is audited at the original call site
                    yield "", node.lineno, False
            for k in node.keywords:
                if k.arg is None or not k.arg.endswith("_site"):
                    continue
                v = k.value
                if isinstance(v, ast.Constant) and isinstance(
                    v.value, str
                ):
                    yield v.value, node.lineno, True
                elif not (
                    isinstance(v, ast.Name) and v.id in param_names
                ):
                    # same contract as the positional form: a computed
                    # site name cannot be audited against the registry
                    yield "", node.lineno, False

    def _chaos_source(self, registry_mod: ModuleInfo) -> str | None:
        d = os.path.dirname(os.path.abspath(registry_mod.path))
        for _ in range(6):
            candidate = os.path.join(d, "tests", "test_chaos.py")
            if os.path.exists(candidate):
                try:
                    with open(candidate, encoding="utf-8") as f:
                        return f.read()
                except OSError:
                    return None
            parent = os.path.dirname(d)
            if parent == d:
                break
            d = parent
        return None


# ---------------------------------------------------------------------------
# atomic-io
# ---------------------------------------------------------------------------


class AtomicIoRule(Rule):
    id = "atomic-io"
    description = (
        "write+rename outside utils/atomicio.py: use atomic_write_bytes "
        "(temp-in-target-dir + fsync + os.replace, chaos-tested)"
    )

    # 'a' deliberately absent: an append is not a whole-file rewrite,
    # so atomic_write_bytes is not a valid replacement and pairing an
    # append with an unrelated rename would be a false positive
    _WRITE_MODES = ("w", "x")

    def check_module(self, mod: ModuleInfo) -> Iterator[Finding]:
        if mod.tree is None or mod.path.replace(os.sep, "/").endswith(
            "utils/atomicio.py"
        ):
            return
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # each def is its own scope, nested defs included (they
                # get their own pass): a write inside a nested helper
                # must not pair with a rename in the enclosing body
                yield from self._check_scope(
                    mod, _walk_excluding_defs(node)
                )
        # the module top level is a scope too: script-style
        # write+rename (including under `if __name__ == "__main__":`)
        # must not bypass the rule just because no def wraps it. The
        # shallow walk stops at def/class boundaries so a write inside
        # a nested def cannot pair with an unrelated top-level rename.
        yield from self._check_scope(
            mod, _walk_excluding_defs(mod.tree)
        )

    def _check_scope(
        self, mod: ModuleInfo, nodes: Iterator[ast.AST]
    ) -> Iterator[Finding]:
        opens_for_write = False
        renames: list[int] = []
        for node in nodes:
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Name) and node.func.id == "open":
                mode = None
                if len(node.args) > 1 and isinstance(
                    node.args[1], ast.Constant
                ):
                    mode = node.args[1].value
                for k in node.keywords:
                    if k.arg == "mode" and isinstance(
                        k.value, ast.Constant
                    ):
                        mode = k.value.value
                if isinstance(mode, str) and any(
                    c in mode for c in self._WRITE_MODES
                ):
                    opens_for_write = True
            elif _dotted(node.func) in ("os.replace", "os.rename"):
                renames.append(node.lineno)
        if opens_for_write:
            for line in renames:
                yield self.finding(
                    mod, line,
                    "ad-hoc write+rename: use utils.atomicio."
                    "atomic_write_bytes (this pattern, minus the fsync "
                    "and temp-dir subtleties it re-implements, is "
                    "already chaos-tested there)",
                )


from .graftlock import (  # noqa: E402 — graftlock imports framework only
    BlockingUnderLockRule,
    LockOrderRule,
    ThreadLifecycleRule,
)
from .graftsync import (  # noqa: E402 — graftsync imports graftlock only
    DonationHazardRule,
    ImplicitSyncRule,
    SyncUnderLockRule,
    TransferDisciplineRule,
)

ALL_RULES = (
    JitPurityRule,
    RetraceHazardRule,
    CtypesAbiRule,
    LockDisciplineRule,
    FaultSiteRegistryRule,
    AtomicIoRule,
    # graftlock: the whole-program concurrency pass (graftlock.py)
    LockOrderRule,
    BlockingUnderLockRule,
    ThreadLifecycleRule,
    # graftsync: the whole-program device-boundary pass (graftsync.py)
    ImplicitSyncRule,
    TransferDisciplineRule,
    DonationHazardRule,
    SyncUnderLockRule,
)
