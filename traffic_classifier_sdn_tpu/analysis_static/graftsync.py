"""graftsync: whole-program host↔device boundary analysis.

graftlock (PR 7) proved the concurrency invariants the runtime cannot;
this module does the same for the OTHER silent performance killer: a
hidden device→host sync or a per-tick host→device upload on the serve
hot path. PRs 4/8/11/12 each hand-hardened individual sync seams (the
lazy rejection-count drain, the deferred calibration fold, the
per-epoch stat upload) by reviewer vigilance alone — graftsync makes
the analyzer find the next one before the chip window pays for it.

The pass reuses graftlock's interprocedural infrastructure (call
graph, import resolution, attribute typing, held-lock summaries) and
adds a device-taint layer on top:

hot-path classification
    Functions reachable (through graftlock's call edges) from the
    serve tick's per-tick surfaces — ``dispatch_read``, the pipeline
    host/device stages, the ``*Read.rows`` render boundary, the
    incremental/degrade/drift/openset predict wrappers — are HOT; the
    rest (warmup, CLI setup, checkpoint restore, bench scaffolding) is
    cold and free to sync. A function named ``serve_tick`` is a hot
    root by convention, which is how out-of-tree fixtures opt in.

``implicit-sync``
    ``np.asarray``/``.item()``/``int()``/``float()``/``bool()``/
    ``len()``/truthiness/iteration on a device-array-typed value
    reachable on a hot path. Every allowed instance carries a reasoned
    suppression NAMING ITS DEFERRAL DISCIPLINE (see ``DISCIPLINES``) —
    the PR 8 ``_drain_pending_count`` sites are the canonical
    examples. A suppression whose reason names no discipline is a
    ``bad-suppression`` finding, which cannot itself be suppressed.

``transfer-discipline``
    ``jax.device_put`` / an implicit host-array upload
    (``jnp.asarray``/``jnp.array`` of a host value, or an np-dtype
    scalar fed to a jitted call) inside a per-tick path, unless routed
    through a warmup-primed or epoch-cached seam — exactly the
    per-tick stat re-upload bug PR 12 review caught by hand. Fresh
    wire data crossing to the device is the workload, not a finding:
    only provably host-side re-uploads (np scalar constructors, host
    conversions feeding jits) are flagged.

``donation-hazard``
    A buffer passed at a donated argument position
    (``donate_argnums``) is dead; referencing it afterwards returns
    garbage (or errors) on platforms that honor donation. The donated
    alias set flows through assignments and call edges — a helper that
    forwards its parameter into a donated position donates its
    caller's buffer too. Rebinding the name revives it (the
    ``buf = donated_fn(buf)`` idiom).

``sync-under-lock``
    Any sync/transfer while holding a project lock, composing
    graftlock's held-lock summaries with the new sync summaries. A
    device sync can take arbitrarily long on a busy accelerator; a
    thread that syncs under a lock wedges every thread that ever
    takes that lock — the same failure mode as blocking-under-lock,
    at the device boundary.

``build_sync_report`` exports the per-tick expected-sync ledger
(``docs/artifacts/hot_path_sync_budget.json``): every allowlisted sync
site with its discipline and reason, the hot-function spans, and the
per-serve-path (serial/pipelined/incremental/degraded) ledgers. The
runtime witness (``utils/syncguard.py``) cross-checks every observed
sync against this budget by construction site — an unknown sync is a
resolver hole, exactly like locktrace's unknown-edge check.

Resolution is syntactic-plus-conventions, like graftlock: a value is
device-typed if it flows from a ``jax.jit``-wrapped callable (module
names bound to ``jax.jit(...)`` or ending ``_jit``), a ``jnp.*`` call,
``jax.device_put``, a ``jax.Array`` annotation, an attribute a scanned
method assigns a device value to, or a call to a scanned function that
returns one (a monotone fixed point). The witness exists precisely to
catch the syncs this static pass misses.
"""

from __future__ import annotations

import ast
import os
from collections.abc import Iterator, Sequence
from dataclasses import dataclass, field

from . import graftlock
from .framework import BAD_SUPPRESSION, Finding, ModuleInfo, Rule
from .graftlock import _chain_text, _mod_proxy, _short

IMPLICIT_SYNC = "implicit-sync"
TRANSFER_DISCIPLINE = "transfer-discipline"
DONATION_HAZARD = "donation-hazard"
SYNC_UNDER_LOCK = "sync-under-lock"

# The deferral-discipline vocabulary: a suppression of implicit-sync /
# transfer-discipline must name exactly how the sync is kept off the
# per-tick critical path (docs/STATIC_ANALYSIS.md documents each).
DISCIPLINES = (
    "deferred-drain",    # drained lazily off the dispatch edge (PR 8)
    "epoch-cached",      # uploaded once per label epoch, cached on device
    "warmup-primed",     # primed once at warmup, never re-paid per tick
    "render-sync",       # the render boundary: labels must reach the host
    "watchdog-guarded",  # the degrade ladder's deadline-bounded host fetch
    "cold-path",         # hot-reachable in the graph, cold by construction
    "tick-plan",         # an O(1) planning scalar the host must read to
                         # size this tick's dispatch (e.g. the dirty count)
    "host-native",       # the value is already host-resident (host-native
                         # predict variant) — the conversion is a no-op
)


def _dotted(node: ast.AST) -> str | None:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _terminal(node: ast.AST) -> str | None:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


# ---------------------------------------------------------------------------
# hot roots
# ---------------------------------------------------------------------------

# (path suffix | None, class matcher | None, function name). A class
# matcher starting with "*" is a suffix match ("*Read" hits RankedRead,
# IncFullRead, ...). These are the per-tick surfaces of the four serve
# compositions; everything transitively callable from them is hot.
_SERVE_PATH_ROOTS: dict[str, tuple[tuple, ...]] = {
    "serial": (
        ("cli.py", None, "_print_table"),
        ("serving/openset.py", "OpenSetGate", "__call__"),
        ("serving/drift.py", "DriftGate", "__call__"),
    ),
    "pipelined": (
        ("serving/pipeline.py", None, "dispatch_read"),
        ("serving/pipeline.py", "ServePipeline", "submit"),
        ("serving/pipeline.py", "ServePipeline", "_run"),
        ("serving/pipeline.py", "FeatureStage", "features"),
        ("serving/pipeline.py", "*Read", "rows"),
        ("cli.py", None, "_dispatch_render"),
        ("cli.py", None, "_print_ranked"),
    ),
    "incremental": (
        ("serving/incremental.py", "IncrementalLabels", "labels"),
        ("serving/incremental.py", "IncrementalLabels", "dispatch"),
        ("serving/incremental.py", "IncrementalLabels", "finish"),
        ("serving/incremental.py", "*Read", "rows"),
    ),
    "degraded": (
        ("serving/degrade.py", "DegradeLadder", "__call__"),
    ),
}

# np-dtype scalar constructors: building one is host-side and free, but
# feeding it to a jitted call uploads a fresh scalar every tick.
_NP_SCALAR_CTORS = {
    "int8", "int16", "int32", "int64", "uint8", "uint16", "uint32",
    "uint64", "float16", "float32", "float64", "bool_",
}

_SYNC_BUILTINS = {"int", "float", "bool", "len"}

# Attribute reads on a device value that stay host-side: array/pytree
# metadata, not data (shape tuples, dtypes, the capacity/n_flows
# host-int properties).
_HOST_META_ATTRS = {
    "shape", "dtype", "ndim", "size", "weak_type", "sharding",
    "capacity", "n_flows", "at",
}

# jax.* callables that return CALLABLES (or host values), not device
# arrays — everything else under jax.* is assumed to stay device-side
_JAX_TRANSFORMS = {
    "jax.jit", "jax.pjit", "jax.vmap", "jax.pmap", "jax.grad",
    "jax.value_and_grad", "jax.checkpoint", "jax.custom_vjp",
    "jax.custom_jvp", "jax.named_call", "jax.eval_shape",
}

# parameter-name → class-name conventions, the graftlock
# _ATTR_TYPE_HINTS idiom at function boundaries: the serve plumbing
# passes these untyped, and losing the chain at the first hop would
# blind the pass to `engine.table.fwd.active`-style device reads
_PARAM_CLASS_HINTS = {
    "engine": "FlowStateEngine",
    "eng": "FlowStateEngine",
    "table": "FlowTable",
}


def _root_match(s, spec: tuple) -> bool:
    path_suffix, cls, name = spec
    if s.name != name:
        return False
    if path_suffix is not None and not s.mod.display_path.replace(
        os.sep, "/"
    ).endswith(path_suffix):
        return False
    if cls is None:
        return s.cls is None
    if s.cls is None:
        return False
    if cls.startswith("*"):
        return s.cls.endswith(cls[1:])
    return s.cls == cls


def _is_hot_root(s) -> bool:
    if s.name == "serve_tick":  # the fixture/out-of-tree convention
        return True
    return any(
        _root_match(s, spec)
        for specs in _SERVE_PATH_ROOTS.values()
        for spec in specs
    )


# ---------------------------------------------------------------------------
# per-function sync scan
# ---------------------------------------------------------------------------


@dataclass
class _SyncEvent:
    rule: str          # IMPLICIT_SYNC | TRANSFER_DISCIPLINE
    kind: str          # "np.asarray", ".item()", "device_put", ...
    line: int
    what: str          # human-readable value description
    held: tuple = ()   # ((lock, line), ...) at the event


@dataclass
class _Donation:
    line: int
    name: str          # the donated binding ("buf" / "self._cache")
    callee: str        # the donated callable's name
    use_line: int      # the post-donation reference


@dataclass
class _FnSync:
    events: list[_SyncEvent] = field(default_factory=list)
    donations: list[_Donation] = field(default_factory=list)
    returns_device: bool = False
    device_attr_writes: set[str] = field(default_factory=set)
    donates_params: set[int] = field(default_factory=set)


class _SyncAnalysis:
    """The device-boundary layer over graftlock's interprocedural base:
    per-function sync/transfer/donation summaries, the hot-path set,
    and sync closures for the under-lock composition."""

    def __init__(self, modules: Sequence[ModuleInfo]):
        self.lock = graftlock.analyze(modules)
        self.project = self.lock.project
        # module path → jit-bound module-global names
        self.jit_names: dict[str, set[str]] = {}
        # module path → name → frozenset(donated positions)
        self.donated: dict[str, dict[str, frozenset]] = {}
        for m in self.project.modules:
            self._index_module(m)
        self.fn_sync: dict[int, _FnSync] = {}
        # (module path, class) → device-typed attribute names
        self.device_attrs: dict[tuple[str, str], set[str]] = {}
        # struct.PyTreeNode subclasses: instances ARE device values
        # (fields are device arrays or nested device pytrees)
        self.pytree_classes: set[str] = set()
        for m in self.project.modules:
            assert m.tree is not None
            for node in ast.walk(m.tree):
                if isinstance(node, ast.ClassDef) and any(
                    _terminal(b) == "PyTreeNode" for b in node.bases
                ):
                    self.pytree_classes.add(node.name)
        # class-level jax.Array / pytree-typed field annotations seed
        # the device-attr sets the method-scan fixed point then grows
        for m in self.project.modules:
            assert m.tree is not None
            for node in ast.walk(m.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                for st in node.body:
                    if not (isinstance(st, ast.AnnAssign)
                            and isinstance(st.target, ast.Name)):
                        continue
                    ann = st.annotation
                    if (
                        _dotted(ann) in ("jax.Array", "jnp.ndarray")
                        or _terminal(ann) == "Array"
                        or _terminal(ann) in self.pytree_classes
                    ):
                        self.device_attrs.setdefault(
                            (m.display_path, node.name), set()
                        ).add(st.target.id)
        # summary key → device-tainted parameter indices (flowed from
        # call sites — including constructor calls, which is how a
        # device output handed to a Read object's __init__ taints the
        # attribute its rows() later converts)
        self.param_taint: dict[int, set[int]] = {}
        self._pt_dirty = False
        self._fixed_point()
        # summary key → chain of qualnames from a hot root
        self.hot: dict[int, tuple[str, ...]] = {}
        self._compute_hot()
        # summary key → {(path, line, kind): chain}
        self.sync_closure: dict[int, dict] = {}
        self._compute_sync_closures()

    # -- module-level indexes -----------------------------------------------
    def _index_module(self, m: ModuleInfo) -> None:
        jits: set[str] = set()
        donated: dict[str, frozenset] = {}
        assert m.tree is not None
        for node in ast.walk(m.tree):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            call = node.value
            if _terminal(call.func) not in ("jit", "pjit", "shard_map"):
                continue
            names = [t.id for t in node.targets
                     if isinstance(t, ast.Name)]
            if not names:
                continue
            jits.update(names)
            for k in call.keywords:
                if k.arg != "donate_argnums":
                    continue
                pos = self._const_positions(k.value)
                if pos:
                    for n in names:
                        donated[n] = pos
        self.jit_names[m.display_path] = jits
        self.donated[m.display_path] = donated

    @staticmethod
    def _const_positions(node: ast.AST) -> frozenset:
        if isinstance(node, ast.Constant) and isinstance(node.value, int):
            return frozenset([node.value])
        if isinstance(node, (ast.Tuple, ast.List)):
            out = set()
            for e in node.elts:
                if isinstance(e, ast.Constant) and isinstance(
                    e.value, int
                ):
                    out.add(e.value)
            return frozenset(out)
        return frozenset()

    def _is_jit_name(self, m: ModuleInfo, name: str) -> bool:
        if name.endswith("_jit"):
            return True
        if name in self.jit_names.get(m.display_path, ()):
            return True
        imp = self.project.imports.get(m.display_path, {}).get(name)
        if imp is not None and imp[0] == "symbol":
            return imp[2] in self.jit_names.get(imp[1], ())
        return False

    def _donated_positions(self, m: ModuleInfo, name: str) -> frozenset:
        d = self.donated.get(m.display_path, {}).get(name)
        if d:
            return d
        imp = self.project.imports.get(m.display_path, {}).get(name)
        if imp is not None and imp[0] == "symbol":
            return self.donated.get(imp[1], {}).get(imp[2], frozenset())
        return frozenset()

    # -- fixed point over return-taint / attr-taint / donation params -------
    def _fixed_point(self) -> None:
        for _ in range(8):  # monotone; tiny bound in practice
            changed = False
            self._pt_dirty = False
            for key, s in self.lock.summaries.items():
                fs = self._scan_function(s)
                prev = self.fn_sync.get(key)
                if (
                    prev is None
                    or fs.returns_device != prev.returns_device
                    or fs.donates_params != prev.donates_params
                    or fs.device_attr_writes != prev.device_attr_writes
                ):
                    changed = True
                self.fn_sync[key] = fs
                if s.cls is not None and fs.device_attr_writes:
                    slot = self.device_attrs.setdefault(
                        (s.mod.display_path, s.cls), set()
                    )
                    if not fs.device_attr_writes <= slot:
                        slot |= fs.device_attr_writes
                        changed = True
            if not changed and not self._pt_dirty:
                break

    # -- the per-function walk ----------------------------------------------
    def _scan_function(self, s) -> _FnSync:
        m, cls, fn = s.mod, s.cls, s.node
        fs = _FnSync()
        # line → callee summary keys (from graftlock's resolved calls)
        line_calls: dict[int, list[int]] = {}
        for callee, line, _held in s.calls:
            line_calls.setdefault(line, []).append(callee)

        tainted: set[str] = set()
        host_np: set[str] = set()
        dead: dict[str, tuple[int, str]] = {}  # name → (line, callee)
        param_types: dict[str, tuple[str, str]] = {}
        params = (fn.args.posonlyargs + fn.args.args
                  + fn.args.kwonlyargs)
        for i, a in enumerate(params):
            ann = a.annotation
            if ann is None:
                hint = _PARAM_CLASS_HINTS.get(a.arg)
                hits = (self.project.classes_by_name.get(hint)
                        if hint else None)
                if hits:
                    param_types[a.arg] = hits[0]
                continue
            d = _dotted(ann)
            if (
                d in ("jax.Array", "jnp.ndarray")
                or _terminal(ann) == "Array"
                or _terminal(ann) in self.pytree_classes
            ):
                tainted.add(a.arg)
                continue
            ref = self.project._annotation_class(m, ann)
            if ref is not None:
                param_types[a.arg] = ref
        for i in self.param_taint.get(id(fn), ()):
            if i < len(params):
                tainted.add(params[i].arg)
        # serve-root predict wrappers take the dispatched feature
        # matrix as an untyped ``X`` (device-resident on the device
        # serve paths; the host-native variant's conversions are then
        # no-ops — the safe overapproximation): seed it, or the taint
        # dies at the wrapper boundary no caller resolves into
        if _is_hot_root(s) and any(p.arg == "X" for p in params):
            tainted.add("X")
        self_offset = 1 if (cls is not None and params
                            and params[0].arg == "self") else 0

        def attr_device(node: ast.Attribute) -> bool:
            base = node.value
            if isinstance(base, ast.Name):
                if base.id == "self" and cls is not None:
                    return node.attr in self.device_attrs.get(
                        (m.display_path, cls), ()
                    )
                ref = param_types.get(base.id)
                if ref is not None:
                    return node.attr in self.device_attrs.get(ref, ())
            return False

        def binding(node: ast.AST) -> str | None:
            """A donation-trackable binding: a bare local, or self.X."""
            if isinstance(node, ast.Name):
                return node.id
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
            ):
                return f"self.{node.attr}"
            return None

        def taint_of(node: ast.AST, held: tuple) -> str | None:
            """'dev' | 'host_np' | None; emits events as a side effect
            (each expression is evaluated exactly once, in source
            order, so donation checks see the pre-statement state)."""
            if isinstance(node, ast.Name):
                b = node.id
                if isinstance(node.ctx, ast.Load) and b in dead:
                    dline, callee = dead.pop(b)  # report once
                    fs.donations.append(
                        _Donation(dline, b, callee, node.lineno)
                    )
                if b in tainted:
                    return "dev"
                if b in host_np:
                    return "host_np"
                return None
            if isinstance(node, ast.Attribute):
                bnd = binding(node)
                if (
                    bnd is not None
                    and isinstance(node.ctx, ast.Load)
                    and bnd in dead
                ):
                    dline, callee = dead.pop(bnd)
                    fs.donations.append(
                        _Donation(dline, bnd, callee, node.lineno)
                    )
                base_t = taint_of(node.value, held)
                if attr_device(node):
                    return "dev"
                # a field of a device pytree is device-resident;
                # metadata reads (shape/dtype/capacity) stay host
                if base_t == "dev" and node.attr not in (
                    _HOST_META_ATTRS
                ):
                    return "dev"
                return None
            if isinstance(node, ast.Call):
                return call_taint(node, held)
            if isinstance(node, ast.Subscript):
                t = taint_of(node.value, held)
                taint_of(node.slice, held)
                return t
            if isinstance(node, (ast.BinOp,)):
                lt = taint_of(node.left, held)
                rt = taint_of(node.right, held)
                return "dev" if "dev" in (lt, rt) else None
            if isinstance(node, ast.UnaryOp):
                return taint_of(node.operand, held)
            if isinstance(node, ast.Compare):
                ts = [taint_of(node.left, held)] + [
                    taint_of(c, held) for c in node.comparators
                ]
                # identity tests never inspect the value — `x is None`
                # on a device array is sync-free
                if all(isinstance(op, (ast.Is, ast.IsNot))
                       for op in node.ops):
                    return None
                return "dev" if "dev" in ts else None
            if isinstance(node, ast.IfExp):
                taint_of(node.test, held)
                bt = taint_of(node.body, held)
                ot = taint_of(node.orelse, held)
                return "dev" if "dev" in (bt, ot) else None
            if isinstance(node, (ast.Tuple, ast.List)):
                ts = [taint_of(e, held) for e in node.elts]
                return "dev" if "dev" in ts else None
            if isinstance(node, ast.BoolOp):
                ts = [taint_of(v, held) for v in node.values]
                return "dev" if "dev" in ts else None
            if isinstance(node, ast.Starred):
                return taint_of(node.value, held)
            if isinstance(node, (ast.JoinedStr, ast.FormattedValue)):
                for child in ast.iter_child_nodes(node):
                    taint_of(child, held)
                return None
            if isinstance(node, (ast.ListComp, ast.SetComp,
                                 ast.GeneratorExp, ast.DictComp)):
                # generators bind before the element expression runs,
                # so a device iterable taints its comprehension target
                def bind(t: ast.AST) -> None:
                    if isinstance(t, ast.Name):
                        tainted.add(t.id)
                    elif isinstance(t, (ast.Tuple, ast.List)):
                        for e in t.elts:
                            bind(e)
                for comp in node.generators:
                    if taint_of(comp.iter, held) == "dev":
                        sync(node.lineno, "iteration",
                             "comprehension over a device array",
                             held)
                        bind(comp.target)
                    for cond in comp.ifs:
                        taint_of(cond, held)
                if isinstance(node, ast.DictComp):
                    taint_of(node.key, held)
                    taint_of(node.value, held)
                else:
                    taint_of(node.elt, held)
                return None
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.expr):
                    taint_of(child, held)
            return None

        def sync(line: int, kind: str, what: str, held: tuple) -> None:
            fs.events.append(
                _SyncEvent(IMPLICIT_SYNC, kind, line, what, held)
            )

        def transfer(line: int, kind: str, what: str,
                     held: tuple) -> None:
            fs.events.append(
                _SyncEvent(TRANSFER_DISCIPLINE, kind, line, what, held)
            )

        def apply_donation(call: ast.Call, positions: frozenset,
                           callee_name: str, is_method: bool) -> None:
            for pos in positions:
                idx = pos - (1 if is_method else 0)
                if idx < 0 or idx >= len(call.args):
                    continue
                bnd = binding(call.args[idx])
                if bnd is not None:
                    dead[bnd] = (call.lineno, callee_name)

        def call_taint(call: ast.Call, held: tuple) -> str | None:
            func = call.func
            d = _dotted(func) or ""
            name = func.id if isinstance(func, ast.Name) else None
            arg_taints = [taint_of(a, held) for a in call.args]
            for k in call.keywords:
                taint_of(k.value, held)
            a0 = arg_taints[0] if arg_taints else None

            # ---- device→host sync sinks
            if d in ("np.asarray", "np.array", "numpy.asarray",
                     "numpy.array"):
                if a0 == "dev":
                    sync(call.lineno, d.split(".")[0] + "."
                         + d.split(".")[-1],
                         f"{d}() on a device array", held)
                return "host_np" if a0 == "dev" else None
            if name in _SYNC_BUILTINS:
                if a0 == "dev":
                    sync(call.lineno, f"{name}()",
                         f"{name}() on a device value", held)
                return None
            if isinstance(func, ast.Attribute) and func.attr in (
                "item", "tolist"
            ):
                base_t = taint_of(func.value, held)
                if base_t == "dev":
                    sync(call.lineno, f".{func.attr}()",
                         f".{func.attr}() on a device value", held)
                return None

            # ---- explicit device→host fetch
            if d in ("jax.device_get", "device_get"):
                if a0 == "dev":
                    sync(call.lineno, "device_get",
                         "jax.device_get() fetches to host", held)
                return None
            # transforms return callables/host shapes, not arrays
            if d in _JAX_TRANSFORMS:
                return None

            # ---- host→device transfer sinks
            if d in ("jax.device_put", "device_put"):
                transfer(call.lineno, "device_put",
                         "explicit jax.device_put", held)
                return "dev"
            head = d.split(".")[0] if d else ""
            if head == "jnp" or d.startswith("jax.numpy."):
                tail = d.rsplit(".", 1)[-1]
                if tail in ("asarray", "array") and call.args and (
                    a0 != "dev"
                ):
                    transfer(call.lineno, "jnp." + tail,
                             f"jnp.{tail}() uploads a host array",
                             held)
                return "dev"
            if d.startswith("jax.") or head == "jax":
                return "dev"  # jax.* ops stay device-side

            # ---- np scalar ctors (host-side; upload checked at jits)
            if head in ("np", "numpy") and d.rsplit(".", 1)[-1] in (
                _NP_SCALAR_CTORS
            ):
                return "host_np"

            # ---- jitted callables
            if name is not None and self._is_jit_name(m, name):
                positions = self._donated_positions(m, name)
                if positions:
                    apply_donation(call, positions, name, False)
                for i, t in enumerate(arg_taints):
                    if t == "host_np":
                        transfer(
                            call.lineno, "scalar-upload",
                            f"np scalar fed to jitted '{name}' "
                            f"(argument {i}) uploads per call", held,
                        )
                return "dev"
            if isinstance(func, ast.Attribute) and (
                func.attr.endswith("_jit")
            ):
                taint_of(func.value, held)
                return "dev"

            # ---- the model-predict convention: predict wrappers are
            # jit-compiled score surfaces returning device labels (the
            # host-native variants overapproximate to device, which is
            # the safe direction — their np.asarray is then a no-op)
            if (name in ("predict", "_predict")) or (
                isinstance(func, ast.Attribute)
                and func.attr.endswith("predict")
            ):
                if isinstance(func, ast.Attribute):
                    taint_of(func.value, held)
                return "dev"

            # ---- project calls: return taint, donation forwarding,
            # and parameter-taint propagation (constructor calls
            # resolve to __init__, so a device argument taints the
            # attribute the ctor stores it in)
            dev_result = False
            called = name or _terminal(func)
            for callee in line_calls.get(call.lineno, ()):
                cs = self.fn_sync.get(callee)
                csum = self.lock.summaries.get(callee)
                if cs is None or csum is None:
                    continue
                if csum.name != called and csum.name != "__init__":
                    continue
                offset = 1 if csum.cls is not None else 0
                for i, t in enumerate(arg_taints):
                    if t != "dev":
                        continue
                    slot = self.param_taint.setdefault(callee, set())
                    if isinstance(call.args[i], ast.Starred):
                        # *args of a device-tainted container: the
                        # positional mapping is unknowable — taint
                        # every callee parameter (how
                        # _calibrate_tick(*pending) carries the
                        # previous tick's device pair)
                        want = set(range(offset,
                                         len(csum.node.args.args)))
                    else:
                        want = {i + offset}
                    if not want <= slot:
                        slot |= want
                        self._pt_dirty = True
                # keyword arguments flow by name (how
                # _Pending(idx=idx, X=Xd) carries device handles into
                # the read object the device stage later converts)
                callee_params = (csum.node.args.posonlyargs
                                 + csum.node.args.args
                                 + csum.node.args.kwonlyargs)
                for kw in call.keywords:
                    if kw.arg is None:
                        continue
                    if taint_of(kw.value, held) != "dev":
                        continue
                    for pi, p in enumerate(callee_params):
                        if p.arg == kw.arg:
                            slot = self.param_taint.setdefault(
                                callee, set()
                            )
                            if pi not in slot:
                                slot.add(pi)
                                self._pt_dirty = True
                            break
                if csum.name != "__init__" and cs.returns_device:
                    dev_result = True
                if cs.donates_params:
                    apply_donation(
                        call, frozenset(cs.donates_params),
                        csum.name, csum.cls is not None,
                    )
            if dev_result:
                return "dev"
            if called in self.pytree_classes:
                return "dev"  # constructing a device pytree

            # method call on a device value keeps it device-side
            if isinstance(func, ast.Attribute):
                if taint_of(func.value, held) == "dev":
                    return "dev"
            return None

        def assign_target(t: ast.AST, value_taint: str | None) -> None:
            if isinstance(t, ast.Name):
                dead.pop(t.id, None)  # rebinding revives the name
                tainted.discard(t.id)
                host_np.discard(t.id)
                if value_taint == "dev":
                    tainted.add(t.id)
                elif value_taint == "host_np":
                    host_np.add(t.id)
            elif isinstance(t, ast.Attribute):
                bnd = binding(t)
                if bnd is not None:
                    dead.pop(bnd, None)
                    if value_taint == "dev" and bnd.startswith("self."):
                        fs.device_attr_writes.add(t.attr)
            elif isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    assign_target(e, value_taint)
            elif isinstance(t, ast.Starred):
                assign_target(t.value, value_taint)

        def truthiness(test: ast.AST, held: tuple) -> None:
            t = taint_of(test, held)
            if t == "dev":
                sync(test.lineno, "truthiness",
                     "truth test on a device value", held)

        def visit_stmt(node: ast.AST, held: tuple) -> None:
            if isinstance(node, ast.ClassDef):
                return  # nested classes get their own summaries
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a nested def is a closure over the enclosing frame
                # and runs on the enclosing hot path (watchdog bodies,
                # worker thunks) — charge its syncs here, with the
                # enclosing taint env resolving its free variables,
                # the same inline treatment lambdas already get
                for child in node.body:
                    visit_stmt(child, held)
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                new_held = held
                for item in node.items:
                    taint_of(item.context_expr, new_held)
                    key = self.lock._lock_key(item.context_expr, m, cls)
                    if key is not None:
                        new_held = new_held + (
                            (key, item.context_expr.lineno),
                        )
                    if item.optional_vars is not None:
                        assign_target(item.optional_vars, None)
                for child in node.body:
                    visit_stmt(child, new_held)
                return
            if isinstance(node, ast.Assign):
                vt = taint_of(node.value, held)
                for t in node.targets:
                    assign_target(t, vt)
                return
            if isinstance(node, ast.AnnAssign):
                vt = taint_of(node.value, held) if node.value else None
                assign_target(node.target, vt)
                return
            if isinstance(node, ast.AugAssign):
                vt = taint_of(node.value, held)
                tt = taint_of(node.target, held)
                assign_target(
                    node.target, "dev" if "dev" in (vt, tt) else vt
                )
                return
            if isinstance(node, ast.Return):
                if node.value is not None:
                    if taint_of(node.value, held) == "dev":
                        fs.returns_device = True
                return
            if isinstance(node, (ast.If, ast.While)):
                truthiness(node.test, held)
                for child in node.body:
                    visit_stmt(child, held)
                for child in node.orelse:
                    visit_stmt(child, held)
                return
            if isinstance(node, (ast.For, ast.AsyncFor)):
                it = taint_of(node.iter, held)
                if it == "dev":
                    sync(node.iter.lineno, "iteration",
                         "for-loop over a device array", held)
                assign_target(node.target,
                              "dev" if it == "dev" else None)
                for child in node.body:
                    visit_stmt(child, held)
                for child in node.orelse:
                    visit_stmt(child, held)
                return
            if isinstance(node, ast.Try):
                for seq in (node.body, node.orelse, node.finalbody):
                    for child in seq:
                        visit_stmt(child, held)
                for h in node.handlers:
                    for child in h.body:
                        visit_stmt(child, held)
                return
            if isinstance(node, ast.Expr):
                taint_of(node.value, held)
                return
            if isinstance(node, (ast.Assert,)):
                taint_of(node.test, held)
                return
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.stmt):
                    visit_stmt(child, held)
                elif isinstance(child, ast.expr):
                    taint_of(child, held)

        # donation via *parameters*: a param forwarded into a donated
        # position makes this function donate it on the caller's behalf
        param_names = {a.arg: i for i, a in enumerate(params)}
        for child in fn.body:
            visit_stmt(child, ())
        # a parameter that ended up in the dead set (donated and never
        # revived) marks this function as donating it on the caller's
        # behalf — the caller's argument is dead too
        for bnd in dead:
            idx = param_names.get(bnd)
            if idx is not None:
                fs.donates_params.add(idx)
        return fs

    # -- hot-path reachability ----------------------------------------------
    def _compute_hot(self) -> None:
        frontier: list[int] = []
        for key, s in self.lock.summaries.items():
            if _is_hot_root(s):
                self.hot[key] = (self._qual(s),)
                frontier.append(key)
        while frontier:
            nxt: list[int] = []
            for key in frontier:
                s = self.lock.summaries[key]
                chain = self.hot[key]
                for callee, _line, _held in s.calls:
                    if callee in self.hot:
                        continue
                    c = self.lock.summaries.get(callee)
                    if c is None:
                        continue
                    self.hot[callee] = chain + (self._qual(c),)
                    nxt.append(callee)
            frontier = nxt

    def reachable_from(self, specs: Sequence[tuple]) -> set[int]:
        seen: set[int] = set()
        frontier = [
            key for key, s in self.lock.summaries.items()
            if any(_root_match(s, spec) for spec in specs)
        ]
        seen.update(frontier)
        while frontier:
            nxt = []
            for key in frontier:
                for callee, _line, _held in (
                    self.lock.summaries[key].calls
                ):
                    if callee not in seen and (
                        callee in self.lock.summaries
                    ):
                        seen.add(callee)
                        nxt.append(callee)
            frontier = nxt
        return seen

    @staticmethod
    def _qual(s) -> str:
        return (f"{s.mod.display_path}::"
                + (f"{s.cls}." if s.cls else "") + s.name)

    # -- sync closures (for sync-under-lock) --------------------------------
    def _compute_sync_closures(self) -> None:
        for key, s in self.lock.summaries.items():
            own: dict = {}
            fs = self.fn_sync.get(key)
            if fs is not None:
                for ev in fs.events:
                    own.setdefault(
                        (s.mod.display_path, ev.line, ev.kind),
                        [(s.mod.display_path, ev.line,
                          f"syncs via {ev.kind}")],
                    )
            self.sync_closure[key] = own
        changed = True
        while changed:
            changed = False
            for key, s in self.lock.summaries.items():
                mine = self.sync_closure[key]
                for callee, line, _held in s.calls:
                    sub = self.sync_closure.get(callee)
                    if not sub:
                        continue
                    c = self.lock.summaries[callee]
                    hop = (s.mod.display_path, line,
                           f"calls {c.cls + '.' if c.cls else ''}"
                           f"{c.name}")
                    for skey, chain in sub.items():
                        if skey not in mine:
                            mine[skey] = [hop, *chain]
                            changed = True


_SYNC_CACHE: list[tuple[tuple[int, ...], _SyncAnalysis]] = []


def sync_analyze(modules: Sequence[ModuleInfo]) -> _SyncAnalysis:
    key = tuple(id(m) for m in modules)
    for k, a in _SYNC_CACHE:
        if k == key:
            return a
    a = _SyncAnalysis(modules)
    _SYNC_CACHE.append((key, a))
    del _SYNC_CACHE[:-4]
    return a


def _hot_chain_text(chain: Sequence[str]) -> str:
    return " -> ".join(chain)


# ---------------------------------------------------------------------------
# the rules
# ---------------------------------------------------------------------------


def _discipline_of(reason: str | None) -> str | None:
    if not reason:
        return None
    for d in DISCIPLINES:
        if d in reason:
            return d
    return None


class ImplicitSyncRule(Rule):
    id = IMPLICIT_SYNC
    description = (
        "no device→host sync (np.asarray/.item()/int()/float()/bool()/"
        "len()/truthiness/iteration on a device value) on a serve "
        "hot path; allowed seams carry a suppression naming their "
        "deferral discipline"
    )

    def check_project(
        self, modules: Sequence[ModuleInfo]
    ) -> Iterator[Finding]:
        a = sync_analyze(modules)
        seen: set[tuple] = set()
        for key, chain in a.hot.items():
            s = a.lock.summaries[key]
            fs = a.fn_sync.get(key)
            if fs is None:
                continue
            for ev in fs.events:
                if ev.rule != IMPLICIT_SYNC:
                    continue
                fkey = (s.mod.display_path, ev.line, ev.kind)
                if fkey in seen:
                    continue
                seen.add(fkey)
                yield self.finding(
                    _mod_proxy(modules, s.mod.display_path), ev.line,
                    f"{ev.what} on the serve hot path (hot via "
                    f"{_hot_chain_text(chain)}) blocks the tick on "
                    "the device — defer it off the dispatch edge, or "
                    "allowlist it with a reasoned suppression naming "
                    f"its discipline ({', '.join(DISCIPLINES)})",
                )
        # allowlist policy: a suppression of the sync rules whose
        # reason names no deferral discipline is a bad suppression —
        # the allowlist must say HOW the sync stays off the tick, not
        # just that someone wanted it quiet. Emitted as
        # bad-suppression, which cannot itself be suppressed.
        for mod in modules:
            for s in mod.suppressions.values():
                if not {IMPLICIT_SYNC, TRANSFER_DISCIPLINE} & set(
                    s.ids
                ):
                    continue
                if _discipline_of(s.reason) is None:
                    yield Finding(
                        BAD_SUPPRESSION, mod.display_path, s.line,
                        "sync allowlist entry must name its deferral "
                        "discipline in the reason — one of: "
                        + ", ".join(DISCIPLINES),
                    )


class TransferDisciplineRule(Rule):
    id = TRANSFER_DISCIPLINE
    description = (
        "no per-tick host→device upload (device_put, jnp.asarray of a "
        "host value, np scalar fed to a jit) on a serve hot path "
        "unless routed through a warmup-primed or epoch-cached seam"
    )

    def check_project(
        self, modules: Sequence[ModuleInfo]
    ) -> Iterator[Finding]:
        a = sync_analyze(modules)
        seen: set[tuple] = set()
        for key, chain in a.hot.items():
            s = a.lock.summaries[key]
            fs = a.fn_sync.get(key)
            if fs is None:
                continue
            for ev in fs.events:
                if ev.rule != TRANSFER_DISCIPLINE:
                    continue
                fkey = (s.mod.display_path, ev.line, ev.kind)
                if fkey in seen:
                    continue
                seen.add(fkey)
                yield self.finding(
                    _mod_proxy(modules, s.mod.display_path), ev.line,
                    f"{ev.what} on the serve hot path (hot via "
                    f"{_hot_chain_text(chain)}): a per-tick upload "
                    "re-pays the transfer every tick — cache it on "
                    "device (epoch-cached), prime it at warmup, or "
                    "allowlist it with a reasoned suppression naming "
                    "its discipline",
                )


class DonationHazardRule(Rule):
    id = DONATION_HAZARD
    description = (
        "a buffer passed at a donated argument position "
        "(donate_argnums) is dead — referencing it afterwards reads "
        "freed device memory; rebind the name from the call's result"
    )

    def check_project(
        self, modules: Sequence[ModuleInfo]
    ) -> Iterator[Finding]:
        a = sync_analyze(modules)
        seen: set[tuple] = set()
        for key, s in a.lock.summaries.items():
            fs = a.fn_sync.get(key)
            if fs is None:
                continue
            for don in fs.donations:
                fkey = (s.mod.display_path, don.use_line, don.name)
                if fkey in seen:
                    continue
                seen.add(fkey)
                yield self.finding(
                    _mod_proxy(modules, s.mod.display_path),
                    don.use_line,
                    f"'{don.name}' was donated to '{don.callee}' at "
                    f"line {don.line} (donate_argnums) and referenced "
                    "again here — donated buffers are dead after the "
                    "call; use the call's result (the "
                    "`buf = donated_fn(buf)` idiom) or pass a copy",
                )


class SyncUnderLockRule(Rule):
    id = SYNC_UNDER_LOCK
    description = (
        "no device sync/transfer while holding a project lock "
        "(directly or transitively): a sync can take arbitrarily long "
        "on a busy device, wedging every thread that takes that lock"
    )

    def check_project(
        self, modules: Sequence[ModuleInfo]
    ) -> Iterator[Finding]:
        a = sync_analyze(modules)
        seen: set[tuple] = set()

        def emit(path: str, line: int, lock: str, kind: str,
                 chain: list) -> Iterator[Finding]:
            fkey = (path, line, lock, kind)
            if fkey in seen:
                return
            seen.add(fkey)
            yield self.finding(
                _mod_proxy(modules, path), line,
                f"device sync/transfer ({kind}) while holding "
                f"{_short(lock)}: {_chain_text(chain)} — a busy "
                "device stalls every thread that takes this lock; "
                "move the sync outside the lock or snapshot under "
                "the lock and sync outside",
            )

        for key, s in a.lock.summaries.items():
            fs = a.fn_sync.get(key)
            if fs is not None:
                for ev in fs.events:
                    for lock, lline in ev.held:
                        yield from emit(
                            s.mod.display_path, ev.line, lock, ev.kind,
                            [(s.mod.display_path, lline,
                              f"acquires {_short(lock)}"),
                             (s.mod.display_path, ev.line,
                              f"syncs via {ev.kind}")],
                        )
            for callee, line, held in s.calls:
                if not held:
                    continue
                sub = a.sync_closure.get(callee)
                if not sub:
                    continue
                c = a.lock.summaries[callee]
                hop = (s.mod.display_path, line,
                       f"calls {c.cls + '.' if c.cls else ''}{c.name}")
                for (spath, sline, kind), chain in sub.items():
                    for lock, lline in held:
                        yield from emit(
                            spath, sline, lock, kind,
                            [(s.mod.display_path, lline,
                              f"acquires {_short(lock)}"),
                             hop, *chain],
                        )


GRAFTSYNC_RULES = (
    ImplicitSyncRule,
    TransferDisciplineRule,
    DonationHazardRule,
    SyncUnderLockRule,
)


# ---------------------------------------------------------------------------
# the sync-budget export (the artifact + the runtime witness's input)
# ---------------------------------------------------------------------------


BUDGET_SCHEMA_VERSION = 1


def _suppression_for(mod: ModuleInfo, line: int) -> tuple | None:
    """The (discipline, reason) of a sync-rule suppression covering
    ``line`` (same enclosing-statement widening the framework uses),
    or None."""
    end = mod._stmt_end.get(line, line)
    for ln in range(line, end + 1):
        s = mod.suppressions.get(ln)
        if s is None:
            continue
        if not {IMPLICIT_SYNC, TRANSFER_DISCIPLINE} & set(s.ids):
            continue
        d = _discipline_of(s.reason)
        if d is not None:
            return d, s.reason
    return None


def build_sync_report(modules: Sequence[ModuleInfo]) -> dict:
    """The per-tick expected-sync ledger as a JSON-ready dict:
    hot-function spans, every allowlisted sync site with its
    discipline/reason, and the per-serve-path ledgers. Committed as
    ``docs/artifacts/hot_path_sync_budget.json`` (generated from the
    repo root) and kept current by a tier-1 test the way
    ``lock_order_graph.json`` is; ``utils/syncguard.py`` cross-checks
    observed runtime syncs against it by construction site."""
    a = sync_analyze(modules)
    by_path = {m.display_path: m for m in modules}

    hot_functions: dict[str, dict] = {}
    spans: dict[str, list[list[int]]] = {}
    for key, chain in sorted(
        a.hot.items(), key=lambda kv: kv[1]
    ):
        s = a.lock.summaries[key]
        qual = a._qual(s)
        node = s.node
        hot_functions[qual] = {
            "path": s.mod.display_path.replace(os.sep, "/"),
            "lines": [node.lineno, node.end_lineno or node.lineno],
            "hot_via": list(chain),
        }
        spans.setdefault(
            s.mod.display_path.replace(os.sep, "/"), []
        ).append([node.lineno, node.end_lineno or node.lineno])
    for p in spans:
        spans[p].sort()

    allowed: list[dict] = []
    site_index: dict[int, list[str]] = {}  # summary key → its sites
    for key in a.hot:
        s = a.lock.summaries[key]
        fs = a.fn_sync.get(key)
        mod = by_path.get(s.mod.display_path)
        if fs is None or mod is None:
            continue
        for ev in fs.events:
            sup = _suppression_for(mod, ev.line)
            if sup is None:
                continue
            site = (f"{s.mod.display_path.replace(os.sep, '/')}"
                    f":{ev.line}")
            entry = {
                "site": site,
                "rule": ev.rule,
                "kind": ev.kind,
                "discipline": sup[0],
                "reason": sup[1],
                "function": a._qual(s),
                "count_per_tick": 1,
            }
            if not any(e["site"] == site and e["kind"] == ev.kind
                       for e in allowed):
                allowed.append(entry)
            site_index.setdefault(key, []).append(site)
    allowed.sort(key=lambda e: (e["site"], e["kind"]))

    serve_paths: dict[str, list[dict]] = {}
    for path_name, specs in _SERVE_PATH_ROOTS.items():
        reach = a.reachable_from(specs)
        ledger: list[dict] = []
        for key in sorted(reach & set(site_index)):
            for site in site_index[key]:
                for e in allowed:
                    if e["site"] == site and not any(
                        le["site"] == site and le["kind"] == e["kind"]
                        for le in ledger
                    ):
                        ledger.append({
                            "site": site,
                            "kind": e["kind"],
                            "count_per_tick": e["count_per_tick"],
                            "reason": e["reason"],
                        })
        serve_paths[path_name] = sorted(
            ledger, key=lambda e: (e["site"], e["kind"])
        )

    return {
        "schema_version": BUDGET_SCHEMA_VERSION,
        "hot_roots": sorted(
            a._qual(a.lock.summaries[key])
            for key, chain in a.hot.items() if len(chain) == 1
        ),
        "hot_functions": hot_functions,
        "hot_spans": spans,
        "allowed_syncs": allowed,
        "serve_paths": serve_paths,
        "disciplines": list(DISCIPLINES),
    }
