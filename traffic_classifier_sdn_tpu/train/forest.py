"""On-device random-forest training via histogram split search.

Replaces sklearn's Cython CART builder (``3_RandomForest.ipynb`` cell 13;
reference checkpoint ``models/RandomForestClassifier``: 100 gini trees,
bootstrap, max_features=sqrt; SURVEY.md §2.3, §7 hard part d). Exact
split enumeration is pointer-chasing and data-dependent — hostile to XLA —
so this builder uses the standard accelerator-friendly reformulation
(LightGBM/XGBoost-style quantile histograms, level-wise growth):

- Features are pre-binned on the host into ``n_bins`` quantile bins whose
  edges are actual data values, making the binned comparison
  ``bin(x) <= b  ⟺  x <= edges[b]`` exact — so the trained tree evaluates
  identically through the unbinned predict path (ops/tree_eval.py).
- Trees grow breadth-first in a perfect binary layout: at depth ``d`` one
  scatter-add builds the (nodes, features, bins, classes) class-count
  histogram for every node at once, a cumulative sum turns it into all
  left/right split candidates, and the gini surrogate
  ``Σc nL_c²/nL + Σc nR_c²/nR`` (maximizing ⇔ minimizing weighted child
  impurity) is evaluated for every (node, feature, bin) in one shot.
- Per-node feature subsampling (max_features) uses a top-k mask over
  uniform scores; bootstrap resampling becomes per-sample integer weights.
- The whole builder is ``jit``-compiled with static depth; trees run in a
  ``lax.scan`` over per-tree PRNG keys, so 100 trees compile once.

The output is a models/forest.Params node stack — the same format the
sklearn-checkpoint importer produces — so sharded predict
(parallel/forest_sharded.py) and the GEMM/Pallas kernels apply unchanged.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..models import forest


def make_bins(X: np.ndarray, n_bins: int) -> np.ndarray:
    """Per-feature candidate thresholds: (F, n_bins-1) sorted data values.

    Edges are taken from the data (quantile ``method='lower'``) so every
    threshold is exactly representable and the bin/raw comparisons agree.
    """
    X = np.asarray(X, np.float32)
    qs = np.linspace(0.0, 1.0, n_bins - 1)
    edges = np.quantile(X, qs, axis=0, method="lower").T.astype(np.float32)
    return np.sort(edges, axis=1)


def bin_features(X: np.ndarray, edges: np.ndarray) -> np.ndarray:
    """Map raw features to bin ids: bin(x) = #{edges < x} ∈ [0, n_bins-1]."""
    X = np.asarray(X, np.float32)
    out = np.empty(X.shape, np.int32)
    for f in range(X.shape[1]):
        out[:, f] = np.searchsorted(edges[f], X[:, f], side="left")
    return out


def resolve_max_features(max_features, n_features: int) -> int:
    """sklearn's ``max_features='sqrt'`` rule, shared by the single-device
    and distributed fits (a drifted copy would silently break their
    bit-identity)."""
    if max_features == "sqrt":
        return max(1, int(np.sqrt(n_features)))
    return int(max_features)


def _bootstrap_weights(k_boot, n_total: int, window_start, window_len: int,
                       axis_name: str | None = None):
    """Multiplicities of rows [window_start, window_start + window_len)
    under a global ``n_total``-draw bootstrap resample.

    Picks are generated in fixed-size chunks and scattered only into the
    caller's window, so peak memory is O(chunk + window) — a sharded fit
    never materializes the global weight vector (each device keeps its own
    row window). Deterministic in (key, n_total) alone: every shard layout
    sees the same global resample, which is what keeps the distributed fit
    bit-identical to the single-device one."""
    chunk = min(n_total, 1 << 20)
    n_chunks = -(-n_total // chunk)
    keys = jax.random.split(k_boot, n_chunks)
    cidx = jnp.arange(chunk)

    def body(i, w):
        p = jax.random.randint(keys[i], (chunk,), 0, n_total)
        valid = i * chunk + cidx < n_total  # mask the final partial chunk
        local = p - window_start
        in_win = valid & (local >= 0) & (local < window_len)
        # out-of-window picks land on the drop slot (index window_len)
        return w.at[jnp.where(in_win, local, window_len)].add(
            in_win.astype(jnp.float32)
        )

    w0 = jnp.zeros(window_len + 1, jnp.float32)
    if axis_name is not None:
        # the loop body's output varies per device (window_start comes
        # from axis_index), so the initial carry must carry the same
        # varying-manner type or the scan carry check rejects it
        w0 = jax.lax.pcast(w0, axis_name, to="varying")
    w = jax.lax.fori_loop(0, n_chunks, body, w0)
    return w[:window_len]


@partial(
    jax.jit,
    static_argnames=(
        "n_classes", "max_depth", "n_bins", "max_features", "bootstrap",
        "axis_name", "n_total_rows",
    ),
)
def _build_tree(
    key,
    Xb,  # (N, F) int32 binned features (the LOCAL shard when distributed)
    y,  # (N,) int32
    edges,  # (F, B-1) f32 candidate thresholds
    mask=None,  # (N,) f32 row validity (0 at distributed padding rows)
    *,
    n_classes: int,
    max_depth: int,
    n_bins: int,
    max_features: int,
    bootstrap: bool,
    axis_name: str | None = None,
    n_total_rows: int | None = None,
):
    """One tree. With ``axis_name`` set (inside shard_map over a sharded
    row axis), per-level class counts and histograms are psum'd, so every
    device reaches the SAME split decisions — counts are integer-valued
    f32 (exact under reassociation below 2²⁴ rows), making the
    distributed fit bit-identical to the single-device one. Randomness
    (bootstrap picks, feature subsampling) derives from the replicated
    ``key`` over the GLOBAL row count, so it is shard-layout-invariant."""
    N, F = Xb.shape
    E = n_bins - 1  # candidate split count per feature
    M = 2 ** (max_depth + 1) - 1  # perfect-layout node capacity
    n_total = N if n_total_rows is None else n_total_rows

    k_boot, k_feat = jax.random.split(key)
    if bootstrap:
        # global resample from the replicated key, scattered into this
        # device's row window only (O(chunk + N) memory per device)
        start = (
            0 if axis_name is None else jax.lax.axis_index(axis_name) * N
        )
        w = _bootstrap_weights(k_boot, n_total, start, N, axis_name)
    else:
        w = jnp.ones(N, jnp.float32)
    if mask is not None:
        w = w * mask

    def _global(a):
        return a if axis_name is None else jax.lax.psum(a, axis_name)

    left = jnp.full(M, -1, jnp.int32)
    right = jnp.full(M, -1, jnp.int32)
    feature = jnp.zeros(M, jnp.int32)
    threshold = jnp.zeros(M, jnp.float32)
    values = jnp.zeros((M, n_classes), jnp.float32)

    pos = jnp.zeros(N, jnp.int32)  # node index *within* the current level
    wa = w  # per-sample weight, zeroed once its node goes leaf

    feat_keys = jax.random.split(k_feat, max_depth)
    fi = jnp.arange(F)

    for d in range(max_depth + 1):
        n_nodes = 2 ** d
        off = n_nodes - 1  # global offset of this level

        cnt = jnp.zeros((n_nodes, n_classes), jnp.float32)
        cnt = _global(cnt.at[pos, y].add(wa))  # (nodes, C) class counts
        n_node = jnp.sum(cnt, axis=1)  # (nodes,)
        values = jax.lax.dynamic_update_slice_in_dim(values, cnt, off, 0)

        if d == max_depth:
            break  # deepest level: all leaves

        # Class-count histogram over (node, feature, bin, class); one
        # psum per level when distributed (the only communication).
        H = jnp.zeros((n_nodes, F, n_bins, n_classes), jnp.float32)
        H = _global(
            H.at[pos[:, None], fi[None, :], Xb, y[:, None]].add(
                wa[:, None]
            )
        )

        # All left/right candidates at once: L[n,f,b,c] = count with
        # bin <= b; split b keeps bins [0..b] left ⟺ x <= edges[f, b].
        L = jnp.cumsum(H, axis=2)[:, :, :E, :]  # (nodes, F, E, C)
        nL = jnp.sum(L, axis=-1)
        R = cnt[:, None, None, :] - L
        nR = n_node[:, None, None] - nL
        score = jnp.sum(L * L, -1) / jnp.maximum(nL, 1.0) + jnp.sum(
            R * R, -1
        ) / jnp.maximum(nR, 1.0)
        score = jnp.where((nL > 0) & (nR > 0), score, -jnp.inf)

        # Per-node feature subsampling (sklearn max_features): keep the
        # top-`max_features` of per-(node, feature) uniform scores.
        if max_features < F:
            u = jax.random.uniform(feat_keys[d], (n_nodes, F))
            kth = jax.lax.top_k(u, max_features)[0][:, -1]
            score = jnp.where(
                (u >= kth[:, None])[:, :, None], score, -jnp.inf
            )

        flat = score.reshape(n_nodes, F * E)
        best = jnp.argmax(flat, axis=1)
        best_gain = jnp.take_along_axis(flat, best[:, None], 1)[:, 0]
        f_star = (best // E).astype(jnp.int32)
        b_star = (best % E).astype(jnp.int32)

        # Positive impurity decrease ⟺ child score beats the parent's
        # Σc cnt²/n; pure or <2-sample nodes become leaves.
        parent_score = jnp.sum(cnt * cnt, 1) / jnp.maximum(n_node, 1.0)
        is_split = (
            (best_gain > parent_score + 1e-3)
            & (n_node >= 2.0)
            & (jnp.max(cnt, axis=1) < n_node)
        )

        child_off = 2 * n_nodes - 1
        kid = jnp.arange(n_nodes, dtype=jnp.int32)
        left = jax.lax.dynamic_update_slice_in_dim(
            left, jnp.where(is_split, child_off + 2 * kid, -1), off, 0
        )
        right = jax.lax.dynamic_update_slice_in_dim(
            right, jnp.where(is_split, child_off + 2 * kid + 1, -1), off, 0
        )
        feature = jax.lax.dynamic_update_slice_in_dim(
            feature, jnp.where(is_split, f_star, 0), off, 0
        )
        threshold = jax.lax.dynamic_update_slice_in_dim(
            threshold,
            jnp.where(is_split, edges[f_star, b_star], 0.0),
            off,
            0,
        )

        # Route samples one level down; samples in leaf nodes go inert.
        sf = f_star[pos]
        sb = b_star[pos]
        go_left = jnp.take_along_axis(Xb, sf[:, None], 1)[:, 0] <= sb
        wa = jnp.where(is_split[pos], wa, 0.0)
        pos = 2 * pos + jnp.where(go_left, 0, 1)

    return left, right, feature, threshold, values


def fit(
    X,
    y,
    n_classes: int,
    *,
    n_trees: int = 100,
    max_depth: int = 10,
    n_bins: int = 128,
    max_features: int | str = "sqrt",
    bootstrap: bool = True,
    seed: int = 0,
) -> forest.Params:
    """Fit a random forest on device; returns predict-ready node stacks."""
    X = np.asarray(X, np.float32)
    y_np = np.asarray(y, np.int32)
    F = X.shape[1]
    max_features = resolve_max_features(max_features, F)

    edges = make_bins(X, n_bins)
    Xb = jnp.asarray(bin_features(X, edges))
    yj = jnp.asarray(y_np)
    ej = jnp.asarray(edges)

    build = partial(
        _build_tree,
        n_classes=n_classes,
        max_depth=max_depth,
        n_bins=n_bins,
        max_features=int(max_features),
        bootstrap=bootstrap,
    )
    keys = jax.random.split(jax.random.PRNGKey(seed), n_trees)
    left, right, feature, threshold, values = jax.lax.map(
        lambda k: build(k, Xb, yj, ej), keys
    )
    return forest.Params(
        left=left,
        right=right,
        feature=feature,
        threshold=threshold,
        values=values,
        max_depth=max_depth,
    )
