"""On-device random-forest training via histogram split search.

Replaces sklearn's Cython CART builder (``3_RandomForest.ipynb`` cell 13;
reference checkpoint ``models/RandomForestClassifier``: 100 gini trees,
bootstrap, max_features=sqrt; SURVEY.md §2.3, §7 hard part d). Exact
split enumeration is pointer-chasing and data-dependent — hostile to XLA —
so this builder uses the standard accelerator-friendly reformulation
(LightGBM/XGBoost-style quantile histograms, level-wise growth):

- Features are pre-binned on the host into ``n_bins`` quantile bins whose
  edges are actual data values, making the binned comparison
  ``bin(x) <= b  ⟺  x <= edges[b]`` exact — so the trained tree evaluates
  identically through the unbinned predict path (ops/tree_eval.py).
- Trees grow breadth-first in a perfect binary layout: at depth ``d`` one
  scatter-add builds the (nodes, features, bins, classes) class-count
  histogram for every node at once, a cumulative sum turns it into all
  left/right split candidates, and the gini surrogate
  ``Σc nL_c²/nL + Σc nR_c²/nR`` (maximizing ⇔ minimizing weighted child
  impurity) is evaluated for every (node, feature, bin) in one shot.
- Per-node feature subsampling (max_features) uses a top-k mask over
  uniform scores; bootstrap resampling becomes per-sample integer weights.
- The whole builder is ``jit``-compiled with static depth; trees run in a
  ``lax.scan`` over per-tree PRNG keys, so 100 trees compile once.

The output is a models/forest.Params node stack — the same format the
sklearn-checkpoint importer produces — so sharded predict
(parallel/forest_sharded.py) and the GEMM/Pallas kernels apply unchanged.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..models import forest


def make_bins(X: np.ndarray, n_bins: int) -> np.ndarray:
    """Per-feature candidate thresholds: (F, n_bins-1) sorted data values.

    Edges are taken from the data (quantile ``method='lower'``) so every
    threshold is exactly representable and the bin/raw comparisons agree.
    """
    X = np.asarray(X, np.float32)
    qs = np.linspace(0.0, 1.0, n_bins - 1)
    edges = np.quantile(X, qs, axis=0, method="lower").T.astype(np.float32)
    return np.sort(edges, axis=1)


def bin_features(X: np.ndarray, edges: np.ndarray) -> np.ndarray:
    """Map raw features to bin ids: bin(x) = #{edges < x} ∈ [0, n_bins-1]."""
    X = np.asarray(X, np.float32)
    out = np.empty(X.shape, np.int32)
    for f in range(X.shape[1]):
        out[:, f] = np.searchsorted(edges[f], X[:, f], side="left")
    return out


@partial(
    jax.jit,
    static_argnames=(
        "n_classes", "max_depth", "n_bins", "max_features", "bootstrap"
    ),
)
def _build_tree(
    key,
    Xb,  # (N, F) int32 binned features
    y,  # (N,) int32
    edges,  # (F, B-1) f32 candidate thresholds
    *,
    n_classes: int,
    max_depth: int,
    n_bins: int,
    max_features: int,
    bootstrap: bool,
):
    N, F = Xb.shape
    E = n_bins - 1  # candidate split count per feature
    M = 2 ** (max_depth + 1) - 1  # perfect-layout node capacity

    k_boot, k_feat = jax.random.split(key)
    if bootstrap:
        picks = jax.random.randint(k_boot, (N,), 0, N)
        w = jnp.zeros(N, jnp.float32).at[picks].add(1.0)
    else:
        w = jnp.ones(N, jnp.float32)

    left = jnp.full(M, -1, jnp.int32)
    right = jnp.full(M, -1, jnp.int32)
    feature = jnp.zeros(M, jnp.int32)
    threshold = jnp.zeros(M, jnp.float32)
    values = jnp.zeros((M, n_classes), jnp.float32)

    pos = jnp.zeros(N, jnp.int32)  # node index *within* the current level
    wa = w  # per-sample weight, zeroed once its node goes leaf

    feat_keys = jax.random.split(k_feat, max_depth)
    fi = jnp.arange(F)

    for d in range(max_depth + 1):
        n_nodes = 2 ** d
        off = n_nodes - 1  # global offset of this level

        cnt = jnp.zeros((n_nodes, n_classes), jnp.float32)
        cnt = cnt.at[pos, y].add(wa)  # (nodes, C) node class counts
        n_node = jnp.sum(cnt, axis=1)  # (nodes,)
        values = jax.lax.dynamic_update_slice_in_dim(values, cnt, off, 0)

        if d == max_depth:
            break  # deepest level: all leaves

        # Class-count histogram over (node, feature, bin, class).
        H = jnp.zeros((n_nodes, F, n_bins, n_classes), jnp.float32)
        H = H.at[pos[:, None], fi[None, :], Xb, y[:, None]].add(
            wa[:, None]
        )

        # All left/right candidates at once: L[n,f,b,c] = count with
        # bin <= b; split b keeps bins [0..b] left ⟺ x <= edges[f, b].
        L = jnp.cumsum(H, axis=2)[:, :, :E, :]  # (nodes, F, E, C)
        nL = jnp.sum(L, axis=-1)
        R = cnt[:, None, None, :] - L
        nR = n_node[:, None, None] - nL
        score = jnp.sum(L * L, -1) / jnp.maximum(nL, 1.0) + jnp.sum(
            R * R, -1
        ) / jnp.maximum(nR, 1.0)
        score = jnp.where((nL > 0) & (nR > 0), score, -jnp.inf)

        # Per-node feature subsampling (sklearn max_features): keep the
        # top-`max_features` of per-(node, feature) uniform scores.
        if max_features < F:
            u = jax.random.uniform(feat_keys[d], (n_nodes, F))
            kth = jax.lax.top_k(u, max_features)[0][:, -1]
            score = jnp.where(
                (u >= kth[:, None])[:, :, None], score, -jnp.inf
            )

        flat = score.reshape(n_nodes, F * E)
        best = jnp.argmax(flat, axis=1)
        best_gain = jnp.take_along_axis(flat, best[:, None], 1)[:, 0]
        f_star = (best // E).astype(jnp.int32)
        b_star = (best % E).astype(jnp.int32)

        # Positive impurity decrease ⟺ child score beats the parent's
        # Σc cnt²/n; pure or <2-sample nodes become leaves.
        parent_score = jnp.sum(cnt * cnt, 1) / jnp.maximum(n_node, 1.0)
        is_split = (
            (best_gain > parent_score + 1e-3)
            & (n_node >= 2.0)
            & (jnp.max(cnt, axis=1) < n_node)
        )

        child_off = 2 * n_nodes - 1
        kid = jnp.arange(n_nodes, dtype=jnp.int32)
        left = jax.lax.dynamic_update_slice_in_dim(
            left, jnp.where(is_split, child_off + 2 * kid, -1), off, 0
        )
        right = jax.lax.dynamic_update_slice_in_dim(
            right, jnp.where(is_split, child_off + 2 * kid + 1, -1), off, 0
        )
        feature = jax.lax.dynamic_update_slice_in_dim(
            feature, jnp.where(is_split, f_star, 0), off, 0
        )
        threshold = jax.lax.dynamic_update_slice_in_dim(
            threshold,
            jnp.where(is_split, edges[f_star, b_star], 0.0),
            off,
            0,
        )

        # Route samples one level down; samples in leaf nodes go inert.
        sf = f_star[pos]
        sb = b_star[pos]
        go_left = jnp.take_along_axis(Xb, sf[:, None], 1)[:, 0] <= sb
        wa = jnp.where(is_split[pos], wa, 0.0)
        pos = 2 * pos + jnp.where(go_left, 0, 1)

    return left, right, feature, threshold, values


def fit(
    X,
    y,
    n_classes: int,
    *,
    n_trees: int = 100,
    max_depth: int = 10,
    n_bins: int = 128,
    max_features: int | str = "sqrt",
    bootstrap: bool = True,
    seed: int = 0,
) -> forest.Params:
    """Fit a random forest on device; returns predict-ready node stacks."""
    X = np.asarray(X, np.float32)
    y_np = np.asarray(y, np.int32)
    F = X.shape[1]
    if max_features == "sqrt":
        max_features = max(1, int(np.sqrt(F)))

    edges = make_bins(X, n_bins)
    Xb = jnp.asarray(bin_features(X, edges))
    yj = jnp.asarray(y_np)
    ej = jnp.asarray(edges)

    build = partial(
        _build_tree,
        n_classes=n_classes,
        max_depth=max_depth,
        n_bins=n_bins,
        max_features=int(max_features),
        bootstrap=bootstrap,
    )
    keys = jax.random.split(jax.random.PRNGKey(seed), n_trees)
    left, right, feature, threshold, values = jax.lax.map(
        lambda k: build(k, Xb, yj, ej), keys
    )
    return forest.Params(
        left=left,
        right=right,
        feature=feature,
        threshold=threshold,
        values=values,
        max_depth=max_depth,
    )
