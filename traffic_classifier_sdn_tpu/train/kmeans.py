"""KMeans training: Lloyd iterations as jit matmul + argmin + segment-sum,
with k-means++ seeding and the n_init restarts *vmapped* — all restarts run
as one batched program instead of sklearn's sequential loop
(SURVEY.md §2.3: replaces the Elkan/Lloyd Cython path).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from ..models import kmeans


def _assign(X, centers):
    # Difference form, matching models/kmeans.py: the dot-product expansion
    # cancels catastrophically in f32 at this data's ~8e8 feature scale
    # (and its d² can even go negative, corrupting the k-means++ weights).
    diff = X[:, None, :] - centers[None, :, :]  # (N, K, F)
    d2 = jnp.sum(diff * diff, axis=-1)
    return jnp.argmin(d2, axis=1), d2


def _plusplus_init(key, X, k: int):
    """k-means++ seeding (jit-safe: fori over k)."""
    n = X.shape[0]
    key, sub = jax.random.split(key)
    first = jax.random.randint(sub, (), 0, n)
    centers = jnp.zeros((k, X.shape[1]), X.dtype).at[0].set(X[first])

    def body(i, carry):
        centers, key = carry
        _, d2 = _assign(X, centers)
        # distance to nearest already-chosen center (cols ≥ i are zeros rows:
        # mask them out with +inf so they don't attract)
        valid = jnp.arange(k) < i
        dmin = jnp.min(jnp.where(valid[None, :], d2, jnp.inf), axis=1)
        dmin = jnp.maximum(dmin, 0.0)
        key, sub = jax.random.split(key)
        probs = dmin / jnp.maximum(jnp.sum(dmin), 1e-30)
        idx = jax.random.choice(sub, n, p=probs)
        return centers.at[i].set(X[idx]), key

    centers, _ = jax.lax.fori_loop(1, k, body, (centers, key))
    return centers


def _lloyd(X, centers0, n_iter: int):
    def body(_, centers):
        labels, _ = _assign(X, centers)
        onehot = jax.nn.one_hot(labels, centers.shape[0], dtype=X.dtype)
        counts = jnp.sum(onehot, axis=0)
        sums = jnp.matmul(
            onehot.T, X, precision=jax.lax.Precision.HIGHEST
        )
        new = sums / jnp.maximum(counts[:, None], 1.0)
        # empty cluster: keep previous center (sklearn relocates; for this
        # data empty clusters don't arise — documented simplification)
        return jnp.where(counts[:, None] > 0, new, centers)

    centers = jax.lax.fori_loop(0, n_iter, body, centers0)
    labels, d2 = _assign(X, centers)
    inertia = jnp.sum(jnp.take_along_axis(d2, labels[:, None], axis=1))
    return centers, inertia


@partial(jax.jit, static_argnums=(2, 3, 4))
def _fit_impl(key, X, k, n_init, n_iter):
    keys = jax.random.split(key, n_init)
    init_centers = jax.vmap(lambda kk: _plusplus_init(kk, X, k))(keys)
    centers, inertia = jax.vmap(lambda c0: _lloyd(X, c0, n_iter))(init_centers)
    best = jnp.argmin(inertia)
    return centers[best], inertia[best]


def fit(
    X, k: int = 4, *, n_init: int = 10, n_iter: int = 50, seed: int = 0
) -> tuple[kmeans.Params, float]:
    X = jnp.asarray(X, jnp.float32)
    centers, inertia = _fit_impl(jax.random.key(seed), X, k, n_init, n_iter)
    import numpy as np

    params = kmeans.from_numpy({"cluster_centers": np.asarray(centers)})
    return params, float(inertia)
