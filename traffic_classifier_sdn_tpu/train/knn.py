"""KNN "training" — corpus registration as device arrays.

sklearn's ``KNeighborsClassifier.fit`` builds a KDTree
(``4_knearest.ipynb`` cell 13; SURVEY.md §2.3). TPUs have no pointer-chasing
tree structures; the idiomatic fit is to lay the training matrix out as a
dense device array (two-float split for parity-exact f32 distances) so
predict is one MXU matmul + ``lax.top_k`` (models/knn.py). For corpora
bigger than one chip's HBM, shard with parallel/knn_sharded.py.
"""

from __future__ import annotations

import numpy as np

from ..models import knn


def fit(X, y, *, n_neighbors: int = 5, n_classes: int | None = None,
        dtype=None) -> knn.Params:
    """Register the training corpus; returns predict-ready Params."""
    import jax.numpy as jnp

    y = np.asarray(y)
    if n_classes is None:
        n_classes = int(y.max()) + 1
    return knn.from_numpy(
        {
            "fit_X": np.asarray(X, np.float64),
            "y": y.astype(np.int32),
            "n_neighbors": n_neighbors,
            "classes": np.arange(n_classes),
        },
        dtype=dtype or jnp.float32,
    )
