"""On-device logistic-regression training.

Replaces sklearn's liblinear/lbfgs fit (``1_log_Kmeans.ipynb`` cell 43;
SURVEY.md §2.3): the same regularized objective sklearn optimizes —
``C·Σ softmax-CE + ½‖W‖²`` with the intercept unpenalized — minimized with
BFGS on-device (the parameter vector is tiny: C·(F+1)), plus a
minibatch/streaming train step for the data-parallel path (grads averaged
across the mesh's data axis by XLA when the batch is sharded).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax

from ..models import logreg

_HI = jax.lax.Precision.HIGHEST


def _ce_loss(coef, intercept, X, y, n_classes, l2_inv_C):
    logits = jnp.matmul(X, coef.T, precision=_HI) + intercept
    ce = optax.softmax_cross_entropy_with_integer_labels(logits, y)
    # sklearn minimizes C·Σce + ½‖W‖² ⇔ Σce + (1/C)·½‖W‖²
    return jnp.sum(ce) + 0.5 * l2_inv_C * jnp.sum(coef * coef)


def fit(
    X,
    y,
    n_classes: int,
    *,
    C: float = 1.0,
    max_iter: int = 200,
    feature_scale: bool = False,
) -> logreg.Params:
    """Full-batch L-BFGS fit on raw features — matching sklearn's objective
    *and* geometry (the L2 penalty is on raw-feature coefficients; measured:
    raw-feature L-BFGS reproduces sklearn's test accuracy exactly, while
    standardize-then-fold-back converges to a different, worse regularized
    optimum). ``feature_scale=True`` is kept for experimentation only.
    The returned Params operate on raw features, exactly like the
    reference's pickles (no online scaler — SURVEY.md §3.5)."""
    # float64 when x64 is on (sklearn-exact parity mode, the test
    # harness); plain float32 otherwise — avoids the per-run truncation
    # warning in production CLIs.
    X = jnp.asarray(X, jnp.float64 if jax.config.jax_enable_x64 else jnp.float32)
    y = jnp.asarray(y, jnp.int32)
    F = X.shape[1]

    if feature_scale:
        mu = jnp.mean(X, axis=0)
        sd = jnp.where(jnp.std(X, axis=0) == 0, 1.0, jnp.std(X, axis=0))
        Xs = (X - mu) / sd
    else:
        mu = jnp.zeros(F, X.dtype)
        sd = jnp.ones(F, X.dtype)
        Xs = X

    def flat_loss(w):
        coef = w[: n_classes * F].reshape(n_classes, F)
        intercept = w[n_classes * F:]
        return _ce_loss(coef, intercept, Xs, y, n_classes, 1.0 / C)

    w0 = jnp.zeros(n_classes * F + n_classes, Xs.dtype)
    solver = optax.lbfgs()
    opt_state = solver.init(w0)
    value_and_grad = optax.value_and_grad_from_state(flat_loss)

    @jax.jit
    def step(carry, _):
        w, opt_state = carry
        value, grad = value_and_grad(w, state=opt_state)
        updates, opt_state = solver.update(
            grad, opt_state, w, value=value, grad=grad, value_fn=flat_loss
        )
        w = optax.apply_updates(w, updates)
        return (w, opt_state), value

    (w, _), _ = jax.lax.scan(step, (w0, opt_state), None, length=max_iter)

    coef_s = w[: n_classes * F].reshape(n_classes, F)
    intercept_s = w[n_classes * F:]
    # Fold standardization back: logits = (x−μ)/σ·Wᵀ+b = x·(W/σ)ᵀ + (b − W·μ/σ)
    coef = coef_s / sd[None, :]
    intercept = intercept_s - jnp.sum(coef_s * (mu / sd)[None, :], axis=1)
    return logreg.Params(
        coef=jnp.asarray(coef, jnp.float32),
        intercept=jnp.asarray(intercept, jnp.float32),
    )


class SGDState(NamedTuple):
    params: logreg.Params
    opt_state: optax.OptState


def fit_sgd(
    X,
    y,
    n_classes: int,
    *,
    learning_rate: float = 1e-2,
    batch_size: int = 256,
    n_steps: int = 2000,
    seed: int = 0,
    checkpoint_dir: str | None = None,
    checkpoint_every: int = 0,
    stop_at_step: int | None = None,
) -> logreg.Params:
    """Minibatch Adam trainer with periodic train-state checkpointing and
    crash resume — the resume-in-training the reference lacks entirely
    (SURVEY.md §5: its only persistence is pickle.dump of a finished
    estimator, e.g. 3_RandomForest.ipynb cell 19).

    The minibatch schedule is keyed by the *absolute* step index, so a run
    that dies and resumes from its last checkpoint replays exactly the
    remaining schedule: final params are bit-identical to an uninterrupted
    run (tests/test_checkpoint.py asserts this). ``checkpoint_every`` is
    config.TrainConfig.checkpoint_every; 0 disables saving.
    ``stop_at_step`` truncates the run mid-flight (the kill hook used by
    the resume test).
    """
    import os

    import numpy as np

    from ..io import checkpoint as ckpt

    X = np.asarray(X, np.float32)
    y_np = np.asarray(y, np.int32)
    n = X.shape[0]

    init, train_step = make_sgd(learning_rate)
    state = init(n_classes=n_classes, n_features=X.shape[1])
    start_step = 0
    if checkpoint_dir is not None and os.path.exists(
        os.path.join(checkpoint_dir, "manifest.json")
    ):
        state, start_step = ckpt.restore_train_state(checkpoint_dir, state)

    for step in range(start_step, n_steps):
        if stop_at_step is not None and step >= stop_at_step:
            break  # simulated kill: no save beyond the last periodic one
        rng = np.random.RandomState((seed * 1_000_003 + step) & 0x7FFFFFFF)
        idx = rng.randint(0, n, batch_size)
        state, _ = train_step(state, jnp.asarray(X[idx]), jnp.asarray(y_np[idx]))
        done = step + 1
        if (
            checkpoint_dir is not None
            and checkpoint_every > 0
            and (done % checkpoint_every == 0 or done == n_steps)
        ):
            ckpt.save_train_state(checkpoint_dir, state, done)

    return state.params


def make_sgd(learning_rate: float = 1e-3):
    """Streaming/minibatch trainer for the data-parallel training path
    (the dryrun's full train step jits this over a sharded batch; XLA
    inserts the cross-chip grad reduction)."""
    tx = optax.adam(learning_rate)

    def init(n_classes: int, n_features: int) -> SGDState:
        p = logreg.Params(
            coef=jnp.zeros((n_classes, n_features), jnp.float32),
            intercept=jnp.zeros(n_classes, jnp.float32),
        )
        return SGDState(params=p, opt_state=tx.init(p))

    @jax.jit
    def train_step(state: SGDState, X, y):
        def loss_fn(p):
            logits = logreg.scores(p, X)
            return jnp.mean(
                optax.softmax_cross_entropy_with_integer_labels(logits, y)
            )

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return SGDState(params, opt_state), loss

    return init, train_step
