"""On-device RBF-SVC training: batched one-vs-one dual ascent.

Replaces libsvm's sequential SMO fit (``2_SVM.ipynb`` cell 13; reference
checkpoint ``models/SVC``; SURVEY.md §2.3, §7 hard part d). SMO updates one
α pair at a time — inherently serial and shape-dynamic, hostile to XLA —
so this trainer uses the accelerator-friendly reformulation:

- The intercept's equality constraint ``Σ tᵃαᵃ = 0`` is removed by
  augmenting the kernel with a constant (``K+1``), the classic
  bias-regularized SVM: the dual becomes a pure box-constrained QP,
  ``max Σα − ½αᵀQα, 0 ≤ α ≤ C`` with ``Q = ttᵀ ⊙ (K+1)``, and the
  intercept is recovered as ``b = Σ tᵃαᵃ``.
- Each of the C·(C−1)/2 ovo subproblems is solved by projected gradient
  ascent with Nesterov momentum (FISTA), step 1/λmax estimated by power
  iteration — every iteration is one dense symmetric matvec on the MXU.
- All pairs run through one ``lax.scan`` body, padded to the largest pair,
  so the 15 binary SVMs compile once and stream through the chip.

The full train-set kernel is computed once with the two-float (hi/lo)
difference form (models/svc.py numerical notes: raw features reach ~8e8, so
the dot-product expansion of ‖x−s‖² cancels catastrophically in f32),
chunked so the (chunk, N, F) difference tensor stays small in HBM.

The result is packed directly into models/svc.Params (dense per-pair
coefficients over the support vectors), so the Pallas/XLA predict paths and
sharded serving apply to retrained models unchanged.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..models import svc


def _pad_to(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def rbf_kernel_matrix(
    X: np.ndarray, gamma: float, chunk: int = 256
) -> jax.Array:
    """Full (N, N) RBF kernel, f32, hi/lo-exact distances, row-chunked."""
    N = X.shape[0]
    Xhi, Xlo = svc.split_hilo(X)
    Np = _pad_to(N, chunk)
    pad = Np - N
    Xhi_p = jnp.pad(Xhi, ((0, pad), (0, 0)))
    Xlo_p = jnp.pad(Xlo, ((0, pad), (0, 0)))
    g = jnp.float32(gamma)

    def block(args):
        bh, bl = args  # (chunk, F)
        diff = (bh[:, None, :] - Xhi[None, :, :]) + (
            bl[:, None, :] - Xlo[None, :, :]
        )
        return jnp.exp(-g * jnp.sum(diff * diff, axis=-1))  # (chunk, N)

    nb = Np // chunk
    blocks = jax.lax.map(
        block,
        (
            Xhi_p.reshape(nb, chunk, -1),
            Xlo_p.reshape(nb, chunk, -1),
        ),
    )
    return blocks.reshape(Np, N)[:N]


@partial(jax.jit, static_argnames=("n_iters", "power_iters"))
def _solve_pair(K, idx, t, Cbox, *, n_iters: int, power_iters: int):
    """FISTA on one padded ovo box QP; returns α (Smax,)."""
    Kp = K[idx[:, None], idx[None, :]] + 1.0  # bias-augmented
    valid = t != 0.0

    def matvec(v):
        return t * jnp.matmul(
            Kp, t * v, precision=jax.lax.Precision.HIGHEST
        )

    # Power iteration for λmax(Q) → step size. (The norm guard also keeps
    # all-padding pairs — t ≡ 0, reachable when the pair axis is padded
    # for sharding — NaN-free; their α clamps to the [0, 0] box anyway.)
    v0 = valid.astype(jnp.float32)
    v0 = v0 / jnp.maximum(jnp.linalg.norm(v0), 1e-12)

    def pw(_, v):
        w = matvec(v)
        return w / jnp.maximum(jnp.linalg.norm(w), 1e-12)

    v = jax.lax.fori_loop(0, power_iters, pw, v0)
    lam = jnp.vdot(v, matvec(v))
    eta = (1.0 / jnp.maximum(lam, 1e-6)).astype(jnp.float32)

    def proj(a):
        return jnp.clip(a, 0.0, Cbox)

    def step(i, carry):
        a, z = carry
        g = 1.0 - matvec(z)  # ∇ of Σα − ½αᵀQα at the momentum point
        a_new = proj(z + eta * g)
        beta = i.astype(jnp.float32) / (i.astype(jnp.float32) + 3.0)
        z_new = a_new + beta * (a_new - a)
        return a_new, z_new

    a0 = jnp.zeros_like(t)
    a, _ = jax.lax.fori_loop(0, n_iters, step, (a0, a0))
    return a


def prepare_ovo(X, y, n_classes: int, C: float, gamma):
    """Host-side problem setup shared by the single-device and the
    pair-sharded distributed fits: resolve gamma, build the (N, N)
    kernel, and pack the padded per-pair (index, target, box) operands."""
    X = np.asarray(X, np.float64)
    y = np.asarray(y, np.int32)
    N, F = X.shape
    if gamma == "scale":  # sklearn: 1 / (F · Var(X))
        gamma = 1.0 / (F * X.var())
    gamma = float(gamma)

    K = rbf_kernel_matrix(X, gamma)

    pairs = [(i, j) for i in range(n_classes) for j in range(i + 1, n_classes)]
    members = [np.nonzero((y == i) | (y == j))[0] for i, j in pairs]
    Smax = max(len(m) for m in members)

    idx_all = np.zeros((len(pairs), Smax), np.int32)
    t_all = np.zeros((len(pairs), Smax), np.float32)
    for p, ((i, j), m) in enumerate(zip(pairs, members)):
        idx_all[p, : len(m)] = m
        t_all[p, : len(m)] = np.where(y[m] == i, 1.0, -1.0)
    Cbox_all = np.where(t_all != 0.0, np.float32(C), 0.0)
    return {
        "X": X, "gamma": gamma, "K": K, "pairs": pairs,
        "members": members, "idx": idx_all, "t": t_all, "Cbox": Cbox_all,
    }


def pack_params(prob: dict, alphas: np.ndarray, n_classes: int,
                sv_tol: float) -> svc.Params:
    """Dense (P, N) signed coefficients + recovered intercepts → Params
    (shared packing for both fit paths)."""
    pairs, members, t_all = prob["pairs"], prob["members"], prob["t"]
    X = prob["X"]
    N = X.shape[0]
    coef_dense = np.zeros((len(pairs), N), np.float64)
    at = np.asarray(alphas, np.float64)[: len(pairs)] * t_all
    for p in range(len(pairs)):
        m = members[p]
        coef_dense[p, m] = at[p, : len(m)]
    intercept = at.sum(axis=1)  # b from the K+1 augmentation

    sv_mask = np.abs(coef_dense).max(axis=0) > sv_tol
    sv_idx = np.nonzero(sv_mask)[0]
    sv_hi, sv_lo = svc.split_hilo(X[sv_idx])
    return svc.Params(
        sv_hi=sv_hi,
        sv_lo=sv_lo,
        pair_coef=jnp.asarray(coef_dense[:, sv_idx], jnp.float32),
        intercept=jnp.asarray(intercept, jnp.float32),
        vote_i=jnp.asarray([i for i, _ in pairs], jnp.int32),
        vote_j=jnp.asarray([j for _, j in pairs], jnp.int32),
        gamma=jnp.asarray(prob["gamma"], jnp.float32),
        n_classes=n_classes,
    )


def fit(
    X,
    y,
    n_classes: int,
    *,
    C: float = 1.0,
    gamma: float | str = "scale",
    n_iters: int = 800,
    power_iters: int = 24,
    sv_tol: float = 1e-6,
) -> svc.Params:
    """Fit ovo RBF-SVC on device; returns predict-ready Params."""
    prob = prepare_ovo(X, y, n_classes, C, gamma)
    solve = partial(_solve_pair, n_iters=n_iters, power_iters=power_iters)
    K = prob["K"]
    alphas = jax.lax.map(
        lambda args: solve(K, *args),
        (
            jnp.asarray(prob["idx"]),
            jnp.asarray(prob["t"]),
            jnp.asarray(prob["Cbox"]),
        ),
    )  # (P, Smax)
    return pack_params(prob, np.asarray(alphas), n_classes, sv_tol)
