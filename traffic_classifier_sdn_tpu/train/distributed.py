"""Data-parallel training: the flow batch sharded across the mesh, the
model state replicated — XLA inserts the cross-chip reductions from the
sharding annotations (the scaling-book recipe: pick a mesh, annotate
shardings, let XLA place the collectives on ICI).

The reference trains everything single-threaded inside sklearn's C
(SURVEY.md §2.3-2.4, no parallelism of any kind). Here the closed-form
fits (GNB moments, Lloyd iterations) and the SGD logreg step consume a
batch-sharded (N, F) matrix directly: per-class one-hot segment sums,
center updates, and gradients are all contractions over the sharded N
axis, which XLA lowers to local partial sums + ``psum`` over the data
axis. The returned params are replicated and bit-match the single-device
fit up to reduction-order rounding (tests gate argmax/assignment parity).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..models import gnb as gnb_model, kmeans as kmeans_model
from ..parallel.mesh import batch_sharded, shard_map
from . import gnb as gnb_train, kmeans as kmeans_train


def _data_size(mesh) -> int:
    return mesh.shape["data"]


def fit_gnb(mesh, X, y, n_classes: int, *,
            var_smoothing: float = 1e-9) -> gnb_model.Params:
    """Distributed GaussianNB fit: one pass of sharded segment moments.
    Same math as train/gnb.fit (two-pass centered variance, sklearn's
    global-variance smoothing), with N sharded over the data axis.

    N is padded to a multiple of the data-axis size with ``y = -1``
    sentinel rows: their one-hot is all zeros, so every segment sum
    excludes them, and the global-variance smoothing term masks them
    explicitly — the fit is exact, no row dropped or double-counted."""
    import numpy as np

    d = _data_size(mesh)
    X = np.asarray(X, np.float64)
    y = np.asarray(y, np.int32)
    pad = (-len(y)) % d
    if pad:
        X = np.concatenate([X, np.zeros((pad, X.shape[1]), X.dtype)],
                           axis=0)
        y = np.concatenate([y, np.full(pad, -1, np.int32)])
    Xs = jax.device_put(jnp.asarray(X), batch_sharded(mesh))
    ys = jax.device_put(jnp.asarray(y), batch_sharded(mesh))

    @jax.jit
    def _fit(X, y):
        # moments() is sentinel-safe: one_hot(-1) is a zero row, so
        # padding contributes to no count/sum/square (the mean[y] gather
        # wraps, but its rows are masked by the same zero one-hot)
        counts, theta, var = gnb_train.moments(X, y, n_classes)
        total = jnp.sum(counts)
        mask = (y >= 0).astype(X.dtype)
        # global mean straight from the masked rows — NOT from
        # theta·counts, where an absent class's 0/0 theta would
        # NaN-poison the smoothing term for every class
        mu_all = jnp.sum(mask[:, None] * X, axis=0) / total
        global_var = (
            jnp.sum(mask[:, None] * (X - mu_all) ** 2, axis=0) / total
        )
        var = var + var_smoothing * jnp.max(global_var)
        prior = counts / total
        return theta, var, prior

    theta, var, prior = _fit(Xs, ys)
    return gnb_model.from_numpy(
        {
            "theta": np.asarray(theta),
            "var": np.asarray(var),
            "class_prior": np.asarray(prior),
        }
    )


def fit_kmeans(mesh, X, k: int = 4, *, n_init: int = 10, n_iter: int = 50,
               seed: int = 0) -> tuple[kmeans_model.Params, float]:
    """Distributed Lloyd: assignments and center sums contract over the
    sharded N axis (local partials + psum); k-means++ seeding and the
    n_init tournament run replicated. Same implementation as
    train/kmeans — only the input sharding differs.

    N is trimmed to a multiple of the data-axis size (at most
    devices−1 rows — immaterial for Lloyd's center means; padding can't
    be made assignment-neutral without reweighting every step)."""
    import numpy as np

    d = _data_size(mesh)
    X = np.asarray(X)
    X = X[: len(X) - (len(X) % d)]
    Xs = jax.device_put(jnp.asarray(X, jnp.float32), batch_sharded(mesh))
    centers, inertia = kmeans_train._fit_impl(
        jax.random.key(seed), Xs, k, n_init, n_iter
    )
    params = kmeans_model.from_numpy({"cluster_centers": np.asarray(centers)})
    return params, float(inertia)


def fit_forest(mesh, X, y, n_classes: int, *, n_trees: int = 100,
               max_depth: int = 10, n_bins: int = 128,
               max_features: int | str = "sqrt", bootstrap: bool = True,
               seed: int = 0):
    """Distributed random-forest fit: rows sharded over the data axis,
    per-level class-count histograms psum'd, split decisions replicated —
    one collective per tree level (train/forest._build_tree with
    ``axis_name``). Counts are integer-valued f32 (exact under psum), and
    bootstrap/feature-subsample randomness derives from the replicated key
    over the GLOBAL row count, so the result is BIT-IDENTICAL to
    train/forest.fit on the gathered data (tested). Rows are padded to a
    multiple of the data axis with weight-0 sentinels.

    This is the flagship model's data-parallel training path — the
    scaling story for corpora that outgrow one chip's HBM (the binned
    matrix and the per-sample routing state stay sharded; only the
    (nodes, F, bins, C) histogram crosses ICI)."""
    import numpy as np

    from jax.sharding import PartitionSpec as P

    from ..models import forest as forest_model
    from ..parallel.mesh import DATA_AXIS
    from . import forest as forest_train

    X = np.asarray(X, np.float32)
    y = np.asarray(y, np.int32)
    F = X.shape[1]
    max_features = forest_train.resolve_max_features(max_features, F)
    n_real = X.shape[0]
    d = _data_size(mesh)

    edges = forest_train.make_bins(X, n_bins)  # global edges, host-side
    Xb = forest_train.bin_features(X, edges)
    pad = (-n_real) % d
    if pad:
        Xb = np.concatenate([Xb, np.zeros((pad, F), np.int32)])
        y = np.concatenate([y, np.zeros(pad, np.int32)])
    mask = np.concatenate(
        [np.ones(n_real, np.float32), np.zeros(pad, np.float32)]
    )

    from functools import partial

    build = partial(
        forest_train._build_tree,
        n_classes=n_classes,
        max_depth=max_depth,
        n_bins=n_bins,
        max_features=int(max_features),
        bootstrap=bootstrap,
        axis_name=DATA_AXIS,
        n_total_rows=n_real,
    )

    def local_fit(Xb, y, mask, edges):
        keys = jax.random.split(jax.random.PRNGKey(seed), n_trees)
        return jax.lax.map(lambda k: build(k, Xb, y, edges, mask), keys)

    # check_vma left ON: every output flows through a per-level psum, so
    # VMA inference proves the P() (replicated) out_specs — a dropped
    # psum in _build_tree becomes a trace-time error, not divergent trees
    shmapped = shard_map(
        local_fit,
        mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P()),
        out_specs=P(),
    )
    # host NumPy arrays go straight to device_put with the data-axis
    # sharding: no jnp.asarray staging copy on a single device first —
    # the OOM this path exists to avoid
    left, right, feature, threshold, values = jax.jit(shmapped)(
        jax.device_put(Xb, batch_sharded(mesh)),
        jax.device_put(y, batch_sharded(mesh)),
        jax.device_put(mask, batch_sharded(mesh)),
        jnp.asarray(edges),
    )
    return forest_model.Params(
        left=left, right=right, feature=feature, threshold=threshold,
        values=values, max_depth=max_depth,
    )


def fit_svc(mesh, X, y, n_classes: int, *, C: float = 1.0,
            gamma: float | str = "scale", n_iters: int = 800,
            power_iters: int = 24, sv_tol: float = 1e-6):
    """Distributed RBF-SVC fit: the C·(C−1)/2 one-vs-one box QPs shard
    over the STATE axis (the ovo problems are independent FISTA solves —
    expert-style parallelism over pairs), each against the replicated
    (N, N) kernel. No collectives until the final pair-axis gather, and
    each pair runs the identical solver — the result is BIT-IDENTICAL to
    train/svc.fit (tested). Pairs are padded to a multiple of the state
    axis with inert all-zero problems (their α clamps to the [0, 0] box).
    """
    import numpy as np

    from functools import partial

    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import STATE_AXIS
    from . import svc as svc_train

    prob = svc_train.prepare_ovo(X, y, n_classes, C, gamma)
    n_state = mesh.shape[STATE_AXIS]
    pad = (-prob["idx"].shape[0]) % n_state
    idx, t, Cbox = (
        np.concatenate(
            [prob[k], np.zeros((pad, prob[k].shape[1]), prob[k].dtype)]
        )
        for k in ("idx", "t", "Cbox")
    )

    solve = partial(
        svc_train._solve_pair, n_iters=n_iters, power_iters=power_iters
    )

    def local_solve(K, idx, t, Cbox):
        return jax.lax.map(lambda args: solve(K, *args), (idx, t, Cbox))

    shmapped = shard_map(
        local_solve,
        mesh=mesh,
        in_specs=(P(), P(STATE_AXIS), P(STATE_AXIS), P(STATE_AXIS)),
        out_specs=P(STATE_AXIS),
    )
    alphas = jax.jit(shmapped)(
        prob["K"], jnp.asarray(idx), jnp.asarray(t), jnp.asarray(Cbox)
    )
    return svc_train.pack_params(
        prob, np.asarray(alphas), n_classes, sv_tol
    )
