"""Gaussian naive Bayes training as closed-form segment moments.

Replaces sklearn's ``GaussianNB.fit`` (``5_GaussianNB.ipynb``; SURVEY.md §7
step 4): per-class counts, means, and variances computed as three one-hot
matmuls (MXU-friendly segment sums), plus sklearn's exact variance smoothing
``var += var_smoothing · max(var over features)``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..models import gnb

_HI = jax.lax.Precision.HIGHEST


def moments(X, y, n_classes: int):
    """Per-class (count, mean, var) via one-hot segment sums — the
    psum-able building block for the data-parallel fit."""
    onehot = jax.nn.one_hot(y, n_classes, dtype=X.dtype)  # (N, C)
    counts = jnp.sum(onehot, axis=0)  # (C,)
    sums = jnp.matmul(onehot.T, X, precision=_HI)  # (C, F)
    mean = sums / counts[:, None]
    # Two-pass variance: E[x²]−E[x]² cancels catastrophically on this data
    # (x ~ 1e8 → x² ~ 1e16 vs small within-class variance); centering first
    # keeps full relative precision and matches sklearn's np.var.
    # nan_to_num guards the gather: an empty class has 0/0 NaN mean, and a
    # row whose label gathers it (e.g. the distributed fit's padding
    # sentinel wrapping to an empty last class) would turn 0·NaN into NaN
    # inside the masked matmul, poisoning every class's variance. Rows
    # with real labels always gather a finite mean, so this changes
    # nothing for them.
    centered = X - jnp.nan_to_num(mean)[y]  # (N, F) class-mean subtraction
    sq_sums = jnp.matmul(onehot.T, centered * centered, precision=_HI)
    var = sq_sums / counts[:, None]
    return counts, mean, var


def fit(X, y, n_classes: int, *, var_smoothing: float = 1e-9) -> gnb.Params:
    X = jnp.asarray(X, jnp.float64)
    y = jnp.asarray(y, jnp.int32)
    counts, theta, var = moments(X, y, n_classes)
    # sklearn's epsilon_ is var_smoothing × the largest *global* per-feature
    # variance (GaussianNB.fit), not the largest per-class variance.
    mu_all = jnp.mean(X, axis=0)
    global_var = jnp.mean((X - mu_all) ** 2, axis=0)
    var = var + var_smoothing * jnp.max(global_var)
    prior = counts / jnp.sum(counts)
    import numpy as np

    return gnb.from_numpy(
        {
            "theta": np.asarray(theta),
            "var": np.asarray(var),
            "class_prior": np.asarray(prior),
        }
    )
