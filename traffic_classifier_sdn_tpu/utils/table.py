"""ASCII table rendering — the reference's PrettyTable output
(traffic_classifier.py:99-118) without the prettytable dependency.

Column set matches the reference exactly:
``Flow ID | Src MAC | Dest MAC | Traffic Type | Forward Status | Reverse
Status``.
"""

from __future__ import annotations

from collections.abc import Iterable, Sequence

CLASSIFIER_FIELDS = (
    "Flow ID",
    "Src MAC",
    "Dest MAC",
    "Traffic Type",
    "Forward Status",
    "Reverse Status",
)


def render_table(field_names: Sequence[str], rows: Iterable[Sequence]) -> str:
    rows = [[str(c) for c in r] for r in rows]
    widths = [len(f) for f in field_names]
    for r in rows:
        for i, c in enumerate(r):
            widths[i] = max(widths[i], len(c))
    sep = "+" + "+".join("-" * (w + 2) for w in widths) + "+"
    out = [sep]
    out.append(
        "|" + "|".join(f" {f:^{w}} " for f, w in zip(field_names, widths)) + "|"
    )
    out.append(sep)
    for r in rows:
        out.append(
            "|" + "|".join(f" {c:^{w}} " for c, w in zip(r, widths)) + "|"
        )
    out.append(sep)
    return "\n".join(out)


def status_str(active: bool) -> str:
    return "ACTIVE" if active else "INACTIVE"
