"""Crash-safe file publication, shared by every checkpoint writer.

One home for the temp-file + fsync + ``os.replace`` idiom so the two
durability layers (io/serving_checkpoint.py, io/checkpoint.py) cannot
drift: the final name only ever points at complete bytes, whatever kills
the writer. Fault sites (utils/faults.py) thread through here so the
chaos suite can kill a write at either hazard point:

- ``mid_write_site`` fires with the temp file HALF-written — the torn
  state a SIGKILL mid-write leaves behind;
- ``pre_rename_site`` fires with a complete, fsynced temp but no commit
  — crash between durability and visibility.

A real SIGKILL cannot run the ``finally`` cleanup, so writers that own a
directory should call ``sweep_stale_tmp`` at a quiet moment to collect
orphaned temp files from previous incarnations (single-writer model:
any ``.*.tmp.*`` present when no write is in flight is garbage).
"""

from __future__ import annotations

import os
import re

from .faults import fault_point

_TMP_RE = re.compile(r"^\..*\.tmp\.\d+$")


def atomic_write_bytes(path: str, payload: bytes, *,
                       mid_write_site: str | None = None,
                       pre_rename_site: str | None = None) -> None:
    """Write ``payload`` to ``path`` via temp file + fsync + rename in
    the same directory."""
    d = os.path.dirname(os.path.abspath(path))
    tmp = os.path.join(d, f".{os.path.basename(path)}.tmp.{os.getpid()}")
    try:
        with open(tmp, "wb") as f:
            if mid_write_site is not None:
                half = len(payload) // 2
                f.write(payload[:half])
                fault_point(mid_write_site)
                f.write(payload[half:])
            else:
                f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        if pre_rename_site is not None:
            fault_point(pre_rename_site)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def sweep_stale_tmp(directory: str) -> int:
    """Unlink orphaned temp files a killed writer left behind. Call only
    when no write is in flight (single-writer). Returns count removed."""
    removed = 0
    try:
        names = os.listdir(directory)
    except FileNotFoundError:
        return 0
    for name in names:
        if _TMP_RE.match(name):
            try:
                os.unlink(os.path.join(directory, name))
                removed += 1
            except OSError:
                pass
    return removed
