"""Profiling helpers: jax.profiler trace capture + on-device step timing.

The reference has no tracing/profiling at all (SURVEY.md §5); its nearest
artifact is Ryu debug logging. Here: ``trace()`` wraps
``jax.profiler.trace`` so any CLI run can drop a TensorBoard-compatible
trace of the XLA pipeline, and ``device_timer`` measures the median
on-device cost of a jitted callable the same careful way bench.py does
(chained dependent iterations inside one dispatch, round-trip subtracted)
— reliable even over a remote-TPU tunnel where naive wall-clock timing of
single calls lies.
"""

from __future__ import annotations

import contextlib
import time


@contextlib.contextmanager
def trace(log_dir: str | None):
    """Capture a jax.profiler trace into ``log_dir`` (no-op when None)."""
    if not log_dir:
        yield
        return
    import jax

    with jax.profiler.trace(log_dir):
        yield


def _sync(x) -> float:
    import numpy as np

    return float(np.asarray(x))


def roundtrip_seconds(repeats: int = 7) -> float:
    """Median dispatch + scalar-fetch cost of a trivial kernel."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    f = jax.jit(lambda a: jnp.sum(a) * 0.0)
    a = jnp.ones((8,), jnp.float32)
    _sync(f(a))
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        _sync(f(a))
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


def device_seconds_per_call(fn, args, iters: int = 16, repeats: int = 5,
                            perturb=None) -> float:
    """Median on-device seconds per ``fn(*args)`` call.

    Runs ``iters`` dependent iterations inside one jitted ``fori_loop``
    (a loop-carried perturbation defeats loop-invariant hoisting),
    reduces to a scalar, fetches it (a real sync), subtracts the measured
    empty-kernel round trip, divides by ``iters``. ``perturb(x, carry)``
    maps the loop carry into the first argument; default adds a scaled
    scalar."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    if perturb is None:
        def perturb(x, carry):
            return x + carry.astype(x.dtype) * 1e-6

    first, rest = args[0], tuple(args[1:])

    @jax.jit
    def loop(x0):
        def body(_, carry):
            acc, x = carry
            out = fn(perturb(x, acc), *rest)
            return acc + jnp.sum(out).astype(jnp.float32), x

        acc, _ = lax.fori_loop(0, iters, body, (jnp.float32(0.0), x0))
        return acc

    _sync(loop(first))  # compile + warm
    rtt = roundtrip_seconds()
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        _sync(loop(first))
        times.append(time.perf_counter() - t0)
    total = float(np.median(times))
    return max(total - rtt, 1e-12) / iters
